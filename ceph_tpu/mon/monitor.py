"""Monitor service + client (Monitor.cc / OSDMonitor.cc / MonClient.cc).

``Monitor`` owns the authoritative OSDMap.  Mutations arrive as
``Incremental``s (from commands, boot messages, or the failure
aggregator), are committed to the ``MonitorStore`` log, applied, and
pushed to every subscriber — the PaxosService propose→commit→notify
cycle with the quorum collapsed to one node (deviation documented in
the package docstring).

``MonitorStore`` is the MonitorDBStore role: a versioned blob log
("osdmap_full_<e>" / "osdmap_inc_<e>" keys) behind the ObjectStore
transaction API, so swapping in the persistent store gives mon-state
durability for free.

``MonClient`` keeps a daemon's local map current: subscribe from the
current epoch, apply pushed incrementals, surface epoch changes to a
callback (the OSD's handle_osd_map role).
"""

from __future__ import annotations

import json
import re as _re
import sys
import threading
import time
from collections import deque

from ..common.log_client import (
    CLOG_PRIOS as _clog_prios,
    MAX_CHANNEL_LEN as _MAX_CHANNEL_LEN,
    MAX_MESSAGE_LEN as _MAX_MESSAGE_LEN,
    MAX_NAME_LEN as _MAX_NAME_LEN,
)
from ..msg import (
    MLog,
    MOSDMap,
    Message,
    MessageError,
    Messenger,
)
from ..msg.message import (
    MMonCommand,
    MMonCommandReply,
    MMonSubscribe,
    MOSDBoot,
    MOSDFailure,
)
from ..msg.messenger import Connection, Dispatcher
from ..crush.types import PG_POOL_TYPE_ERASURE, PG_POOL_TYPE_REPLICATED
from ..osd.failure import FailureAggregator
from ..osd.osdmap import Incremental, OSDMap, PgPool
from ..store.objectstore import MemStore, ObjectStore, StoreError, Transaction

MON_COLL = "mon_store"

# cluster-log vocabulary accepted off the wire: the prio ladder is
# OWNED by common/log_client.py (one source — a prio added there must
# not be clamped away here); LogStore.add rewrites anything else.
# The channel rule excludes '/' so the "channel/prio" totals key
# stays unambiguous.
_CLOG_PRIOS = frozenset(_clog_prios)
_CHANNEL_RE = _re.compile(r"^[a-zA-Z][a-zA-Z0-9_.-]{0,63}$")

# health-mute bounds: mute codes are client-supplied strings stored
# until unmute/expiry — cap count and length or a loop of unique
# no-TTL mutes grows the mon without bound
MAX_HEALTH_MUTES = 64
MAX_MUTE_CODE_LEN = 64
# an osd stat report (~1 Hz when healthy) older than this stops
# feeding OSD_NEARFULL/OSD_FULL — a silent OSD must not pin HEALTH_ERR
STAT_REPORT_GRACE = 30.0


class MonitorStore:
    """Versioned map-blob log over an ObjectStore (MonitorDBStore role:
    every commit is one transaction; replay rebuilds the map chain)."""

    def __init__(self, store: ObjectStore | None = None):
        self.store = store or MemStore()
        try:
            self.store.queue_transaction(
                Transaction().create_collection(MON_COLL)
            )
        except StoreError:
            pass

    def put_commit(
        self, epoch: int, inc_blob: bytes | None, full_blob: bytes
    ) -> None:
        txn = Transaction()
        if inc_blob is not None:
            txn.touch(MON_COLL, f"osdmap_inc_{epoch}")
            txn.write(MON_COLL, f"osdmap_inc_{epoch}", 0, inc_blob)
        txn.touch(MON_COLL, f"osdmap_full_{epoch}")
        txn.write(MON_COLL, f"osdmap_full_{epoch}", 0, full_blob)
        txn.touch(MON_COLL, "meta")
        txn.setattr(
            MON_COLL, "meta", "last_committed", str(epoch).encode()
        )
        self.store.queue_transaction(txn)

    def last_committed(self) -> int:
        try:
            return int(self.store.getattr(MON_COLL, "meta", "last_committed"))
        except StoreError:
            return 0

    def get_inc(self, epoch: int) -> bytes | None:
        try:
            return self.store.read(MON_COLL, f"osdmap_inc_{epoch}")
        except StoreError:
            return None

    def get_full(self, epoch: int) -> bytes | None:
        try:
            return self.store.read(MON_COLL, f"osdmap_full_{epoch}")
        except StoreError:
            return None

    # -- generic blobs (the non-osdmap PaxosService keys: clog, ...) --------
    def put_blob(self, key: str, blob: bytes) -> None:
        txn = Transaction()
        txn.touch(MON_COLL, key)
        # truncate first: a shorter rewrite must not leave the old
        # tail glued onto the new blob
        txn.truncate(MON_COLL, key, 0)
        txn.write(MON_COLL, key, 0, blob)
        self.store.queue_transaction(txn)

    def get_blob(self, key: str) -> bytes | None:
        try:
            return self.store.read(MON_COLL, key)
        except StoreError:
            return None


class LogStore:
    """The LogMonitor role (src/mon/LogMonitor.{h,cc} reduced):
    cluster-log entries from MLog batches land in a bounded window
    with per-(channel, prio) running totals, persisted as one blob in
    the MonitorStore so a restarted mon keeps its health timeline.
    ``last`` serves ``ceph log last [n] [level] [channel]``."""

    KEY = "clog"
    MAX_TOTALS_KEYS = 64  # counter-cardinality bound (see add())

    def __init__(self, store: MonitorStore, max_entries: int = 500):
        self.store = store
        self.max_entries = max_entries
        # optional fanout hook: called with the ACCEPTED (coerced)
        # entries after every add — the `ceph -w` watch stream taps
        # here so subscribers see exactly what the window recorded
        self.notify = None
        self._entries: deque[dict] = deque(maxlen=max_entries)
        self._totals: dict[str, int] = {}  # "channel/prio" -> count
        self.total = 0
        # persistence is THROTTLED (the reference batches LogMonitor
        # commits through paxos the same way): the in-memory window is
        # authoritative for `log last`; a mon restart may lose the
        # last ~1s of entries
        self._last_persist = 0.0
        blob = store.get_blob(self.KEY)
        if blob:
            try:
                state = json.loads(blob)
                self._entries.extend(state.get("entries", []))
                self._totals = dict(state.get("totals", {}))
                self.total = int(state.get("total", 0))
            except (ValueError, TypeError):
                pass  # corrupt window: start fresh, never crash the mon

    def add(self, entries: list[dict]) -> int:
        added = 0
        accepted: list[dict] = []
        for raw in entries:
            if not isinstance(raw, dict) or "message" not in raw:
                continue
            # coerce EVERY field: entries arrive off the wire, and a
            # wrong-typed prio/stamp persisted into the window would
            # break `log last` until it ages out
            try:
                entry = {
                    "name": str(raw.get("name", "unknown"))[
                        :_MAX_NAME_LEN
                    ],
                    "stamp": float(raw.get("stamp", time.time())),
                    "channel": str(raw.get("channel", "cluster"))[
                        :_MAX_CHANNEL_LEN
                    ],
                    "prio": str(raw.get("prio", "info")),
                    "message": str(raw["message"])[
                        :_MAX_MESSAGE_LEN
                    ],
                    "seq": int(raw.get("seq", 0)),
                }
            except (TypeError, ValueError):
                continue  # unsalvageable entry: drop, never poison
            # channel and prio become _totals keys, prometheus label
            # values, and persisted state: clamp to a safe vocabulary
            # or an attacker looping `ceph log` with unique channels
            # grows mon memory and scrape size without bound (and a
            # '/' in a channel would corrupt the "channel/prio" key)
            if entry["prio"] not in _CLOG_PRIOS:
                entry["prio"] = "info"
            if not _CHANNEL_RE.match(entry["channel"]):
                entry["channel"] = "cluster"
            self._entries.append(entry)
            accepted.append(entry)
            key = f"{entry['channel']}/{entry['prio']}"
            if (
                key not in self._totals
                and len(self._totals) >= self.MAX_TOTALS_KEYS
            ):
                # bounded counter cardinality: overflow channels fold
                # into one bucket instead of growing forever
                key = f"other/{entry['prio']}"
            self._totals[key] = self._totals.get(key, 0) + 1
            self.total += 1
            added += 1
        now = time.time()
        if added and now - self._last_persist >= 1.0:
            self._last_persist = now
            self._persist()
        if accepted and self.notify is not None:
            try:
                self.notify(accepted)
            except Exception:  # noqa: BLE001 — fanout best-effort
                pass
        return added

    def last(
        self,
        n: int = 20,
        level: str | None = None,
        channel: str | None = None,
    ) -> list[dict]:
        from ..common.log_client import prio_rank

        if int(n) <= 0:
            return []
        entries = list(self._entries)
        if channel:
            entries = [
                e for e in entries if e.get("channel") == channel
            ]
        if level:
            floor = prio_rank(level)
            entries = [
                e
                for e in entries
                if prio_rank(e.get("prio", "info")) >= floor
            ]
        return entries[-max(0, int(n)):]

    def stat(self) -> dict:
        return {
            "total": self.total,
            "window": len(self._entries),
            "by_channel_prio": dict(self._totals),
        }

    def _persist(self) -> None:
        try:
            self.store.put_blob(
                self.KEY,
                json.dumps(
                    {
                        "entries": list(self._entries),
                        "totals": self._totals,
                        "total": self.total,
                    }
                ).encode(),
            )
        except StoreError:
            pass  # the in-memory window still serves `log last`


class Monitor(Dispatcher):
    """Single-node map authority (Monitor + OSDMonitor roles)."""

    def __init__(
        self,
        osdmap: OSDMap,
        store: MonitorStore | None = None,
        min_reporters: int = 2,
    ):
        self.store = store or MonitorStore()
        self._lock = threading.RLock()
        replay_to = self.store.last_committed()
        if replay_to > osdmap.epoch:
            # cold restart: adopt the highest committed map
            blob = self.store.get_full(replay_to)
            if blob is not None:
                osdmap = OSDMap.decode(blob)
        self.osdmap = osdmap
        if self.store.last_committed() < osdmap.epoch:
            self.store.put_commit(osdmap.epoch, None, osdmap.encode())
        # flap guard: the reporter threshold is config-gated
        # (mon_osd_min_down_reporters) with the constructor value as
        # the fallback, so an asymmetric partition's single live
        # reporter cannot keep re-downing a reachable OSD once the
        # operator raises the bar
        self._min_reporters_default = min_reporters
        self.failures = FailureAggregator(
            osdmap,
            min_reporters=self.min_down_reporters,
            mark_down_fn=self._commit_mark_down,
        )
        # subscribers: conn -> last epoch sent
        self._subs: dict[Connection, int] = {}
        # centralized config database (ConfigMonitor role)
        self.config_db: dict[str, dict[str, str]] = {}
        # SLOW_OPS reports (HealthMonitor's daemon-health role):
        # daemon -> (wallclock received, count, oldest_age).  Kept
        # in-memory per monitor, like mgr beacons — a count of 0
        # clears; stale reports age out of health after the grace
        self.slow_ops: dict[str, tuple[float, int, float]] = {}
        # cluster log (LogMonitor role): MLog batches + the mon's own
        # entries land here and serve `ceph log last`
        self.clog_store = LogStore(self.store)
        # health mutes (HealthMonitor mutes): code -> expiry wallclock
        # (inf = no TTL); muted codes leave the rollup, not the detail
        self.health_mutes: dict[str, float] = {}
        # un-archived recent crash count, pushed by the mgr crash
        # module ("crash report") — raises RECENT_CRASH
        self.recent_crashes = 0
        # scrub-error reports ("osd scrub errors" upcalls): daemon ->
        # (wallclock received, error count, damaged pgids, large-omap
        # object count).  Feeds OSD_SCRUB_ERRORS / PG_DAMAGED /
        # LARGE_OMAP_OBJECTS; an all-zero report clears, stale
        # reports age out like slow-op reports
        self.scrub_reports: dict[
            str, tuple[float, int, list, int]
        ] = {}
        # per-OSD space stats ("osd stat report" upcalls, the
        # osd_stat_t role): osd -> (wallclock received, kb, kb_used,
        # kb_avail).  Feeds OSD_NEARFULL / OSD_FULL
        self.osd_stats: dict[int, tuple[float, int, int, int]] = {}
        # per-OSD commit/apply latency (the osd_stat_t perf seat
        # `ceph osd perf` serves): osd -> (ts, commit_ms, apply_ms)
        self.osd_perf_stats: dict[int, tuple[float, float, float]] = {}
        # SLO burn-rate verdicts pushed by the mgr slo module ("slo
        # report", the RECENT_CRASH push idiom): code -> (wallclock
        # received, severity, summary).  An empty push clears; stale
        # reports age out with the slow-op grace (a dead mgr must not
        # pin SLO_LATENCY forever)
        self.slo_reports: dict[str, tuple[float, str, str]] = {}
        # PGMap digest pushed by the mgr pgmap module ("pgmap
        # report"): (wallclock received, digest dict).  Feeds the
        # `ceph status` pgmap section, `ceph df`, the grown `pg
        # dump`, and PG_DEGRADED / PG_AVAILABILITY; silence past the
        # stat-report grace drops it (dead mgr ≠ healthy PGs)
        self.pgmap: tuple[float, dict] | None = None
        # `ceph -w` watch subscribers: conn -> {level, debug,
        # dout_mark}; fed by the clog_store notify fanout below
        self._watch_subs: dict[Connection, dict] = {}
        self.clog_store.notify = self._push_watch
        # last health-check code set, so transitions (raise/clear)
        # write the cluster log — the health timeline
        self._prev_health: set[str] = set()

    def _config_float(self, key: str) -> float:
        """One mon option: the centralized config database overrides
        the schema default ('ceph config set mon <key> <v>')."""
        raw = self.config_db.get("mon", {}).get(key)
        if raw is not None:
            try:
                return float(raw)
            except ValueError:
                pass
        from ..common.config import SCHEMA

        return float(SCHEMA[key].default)

    def min_down_reporters(self) -> int:
        """mon_osd_min_down_reporters: config_db gates, the
        constructor value is the fallback (default 1 in the schema,
        so stand-alone monitors keep their constructed behavior)."""
        raw = self.config_db.get("mon", {}).get(
            "mon_osd_min_down_reporters"
        )
        if raw is not None:
            try:
                return max(1, int(raw))
            except ValueError:
                pass
        return max(1, int(self._min_reporters_default))

    def slow_op_report_grace(self) -> float:
        """mon_slow_op_report_grace: the centralized config database
        ('ceph config set mon mon_slow_op_report_grace N') overrides
        the schema default."""
        raw = self.config_db.get("mon", {}).get(
            "mon_slow_op_report_grace"
        )
        if raw is not None:
            try:
                return float(raw)
            except ValueError:
                pass
        from ..common.config import SCHEMA

        return float(SCHEMA["mon_slow_op_report_grace"].default)

    # -- commit cycle ------------------------------------------------------
    def commit(self, inc: Incremental) -> int:
        """propose_pending: apply + log + notify; returns new epoch."""
        with self._lock:
            blob = inc.encode()
            self.osdmap.apply_incremental(inc)
            self.store.put_commit(
                self.osdmap.epoch, blob, self.osdmap.encode()
            )
            self._push_maps()
            return self.osdmap.epoch

    def pending(self) -> Incremental:
        return self.osdmap.new_incremental()

    def _commit_mark_down(self, target: int) -> None:
        with self._lock:
            if not self.osdmap.is_up(target):
                return  # raced with a command; XOR must not re-up it
            inc = self.pending()
            inc.mark_down(target)
            self.commit(inc)
            self._clog(
                "warn",
                f"osd.{target} marked down after failure reports",
            )

    # -- cluster log (LogMonitor ingest + the mon's own channel) -----------
    def _clog(
        self, prio: str, message: str, channel: str = "cluster"
    ) -> None:
        """The mon's own cluster-log entry (no wire hop needed)."""
        self.clog_store.add(
            [
                {
                    "name": "mon.0",
                    "stamp": time.time(),
                    "channel": channel,
                    "prio": prio,
                    "message": message,
                    "seq": self.clog_store.total + 1,
                }
            ]
        )

    def pgmap_digest(self) -> dict | None:
        """The freshest mgr-pushed PGMap digest, or None when the
        mgr has gone silent past the stat-report grace (a dead mgr's
        last digest must not keep reporting healthy PGs)."""
        if self.pgmap is None:
            return None
        ts, digest = self.pgmap
        if time.time() - ts > STAT_REPORT_GRACE:
            return None
        return digest

    # -- health (HealthMonitor role) ---------------------------------------
    def health_checks(self) -> dict[str, dict]:
        """Every active health check, code -> {severity, summary} —
        BEFORE mutes.  State transitions against the previous
        evaluation are clogged, so the cluster log is the health
        timeline (LogMonitor's health-to-clog path)."""
        m = self.osdmap
        checks: dict[str, dict] = {}
        down = [
            o for o in range(m.max_osd)
            if m.exists(o) and not m.is_up(o)
        ]
        out = [
            o for o in range(m.max_osd)
            if m.exists(o) and m.osd_weight[o] == 0
        ]
        if down:
            checks["OSD_DOWN"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(down)} osds down",
            }
        if out:
            checks["OSD_OUT"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(out)} osds out",
            }
        # OSD_NEARFULL / OSD_FULL (OSDMonitor's full-flag checks,
        # src/mon/OSDMonitor.cc + PGMap::get_health fullness rows):
        # computed from the freshest per-OSD stat reports; a downed
        # reporter's stats stop counting (its data re-homes anyway)
        nearfull_ratio = self._config_float("mon_osd_nearfull_ratio")
        full_ratio = self._config_float("mon_osd_full_ratio")
        nearfull_osds: list[int] = []
        full_osds: list[int] = []
        stats_now = time.time()
        for osd, (ts, kb, kb_used, _kb_avail) in list(
            self.osd_stats.items()
        ):
            if not m.is_up(osd):
                del self.osd_stats[osd]
                continue
            if stats_now - ts > STAT_REPORT_GRACE:
                # an up-but-silent OSD's last report must not pin
                # OSD_FULL forever (same aging rule as slow-op and
                # scrub reports); reports flow at ~1 Hz when healthy
                del self.osd_stats[osd]
                continue
            ratio = (kb_used / kb) if kb else 0.0
            if ratio >= full_ratio:
                full_osds.append(osd)
            elif ratio >= nearfull_ratio:
                nearfull_osds.append(osd)
        if full_osds:
            checks["OSD_FULL"] = {
                "severity": "HEALTH_ERR",
                "summary": (
                    f"{len(full_osds)} full osd(s) "
                    f"{sorted(full_osds)}: writes blocked"
                ),
            }
        if nearfull_osds:
            checks["OSD_NEARFULL"] = {
                "severity": "HEALTH_WARN",
                "summary": (
                    f"{len(nearfull_osds)} nearfull osd(s) "
                    f"{sorted(nearfull_osds)}"
                ),
            }
        # SLOW_OPS: fresh nonzero reports only — a crashed daemon's
        # last report must not pin WARN forever
        now = time.time()
        grace = self.slow_op_report_grace()
        slow_total, oldest, reporters = 0, 0.0, []
        for daemon, (ts, count, age) in list(self.slow_ops.items()):
            if now - ts > grace:
                del self.slow_ops[daemon]
                continue
            if count > 0:
                slow_total += count
                oldest = max(oldest, age)
                reporters.append(daemon)
        if slow_total:
            checks["SLOW_OPS"] = {
                "severity": "HEALTH_WARN",
                "summary": (
                    f"{slow_total} slow ops, oldest one blocked for "
                    f"{oldest:.0f} sec, daemons {sorted(reporters)} "
                    "have slow ops (SLOW_OPS)"
                ),
            }
        # OSD_SCRUB_ERRORS / PG_DAMAGED (scrub findings).  Unlike
        # slow-op reports these must NOT age out on a timer — damage
        # stays damaged until a repair's zero-report clears it (the
        # reference keeps it in pg stats).  Only a reporter that left
        # the cluster drops its contribution (its PGs re-scrub under
        # their new primaries).
        err_total, damaged, large_total = 0, set(), 0
        for daemon, (_ts, count, pgs, large) in list(
            self.scrub_reports.items()
        ):
            try:
                osd_id = int(daemon.rsplit(".", 1)[1])
            except (IndexError, ValueError):
                osd_id = -1
            if osd_id >= 0 and not m.is_up(osd_id):
                del self.scrub_reports[daemon]
                continue
            if count > 0:
                err_total += count
                damaged.update(pgs)
            large_total += max(0, large)
        if err_total:
            checks["OSD_SCRUB_ERRORS"] = {
                "severity": "HEALTH_ERR",
                "summary": f"{err_total} scrub errors",
            }
        if damaged:
            checks["PG_DAMAGED"] = {
                "severity": "HEALTH_ERR",
                "summary": (
                    f"Possible data damage: {len(damaged)} pg"
                    f"{'s' if len(damaged) > 1 else ''} inconsistent"
                ),
            }
        if large_total:
            # LARGE_OMAP_OBJECTS (PGMap::get_health_checks): deep
            # scrub found omap objects past the key threshold — the
            # bucket-index reshard signal; cleared by the next deep
            # scrub after the index re-shards
            checks["LARGE_OMAP_OBJECTS"] = {
                "severity": "HEALTH_WARN",
                "summary": (
                    f"{large_total} large omap object"
                    f"{'s' if large_total > 1 else ''} found"
                ),
            }
        if self.recent_crashes:
            checks["RECENT_CRASH"] = {
                "severity": "HEALTH_WARN",
                "summary": (
                    f"{self.recent_crashes} daemons have recently "
                    "crashed"
                ),
            }
        # SLO_LATENCY (the mgr slo module's burn-rate verdicts): the
        # mgr re-pushes every tick while burning, so stale entries age
        # out on the slow-op grace — an evaluator that died mid-burn
        # cannot pin the check
        grace = self.slow_op_report_grace()
        for code, (ts, severity, summary) in list(
            self.slo_reports.items()
        ):
            if now - ts > grace:
                del self.slo_reports[code]
                continue
            checks[code] = {"severity": severity, "summary": summary}
        # PG_DEGRADED / PG_AVAILABILITY (PGMap::get_health_checks):
        # from the mgr's pgmap digest; a stale digest (dead mgr)
        # drops the checks rather than pinning them forever
        digest = self.pgmap_digest()
        if digest is not None:
            t = digest.get("totals", {})
            degraded = int(t.get("degraded", 0))
            unfound = int(t.get("unfound", 0))
            objects = max(int(t.get("objects", 0)), 1)
            if degraded or unfound:
                replicas = objects  # reported objects ≈ placements led
                checks["PG_DEGRADED"] = {
                    "severity": "HEALTH_WARN",
                    "summary": (
                        f"Degraded data redundancy: {degraded}/"
                        f"{replicas} objects degraded"
                        + (f", {unfound} unfound" if unfound else "")
                    ),
                }
            # inactive = reported pgs not in an active state; pools
            # whose primaries have not reported at all stay unknown,
            # not unavailable
            inactive = sum(
                1 for row in digest.get("pgs", {}).values()
                if not str(row.get("state", "")).startswith("active")
            )
            if inactive > 0:
                checks["PG_AVAILABILITY"] = {
                    "severity": "HEALTH_WARN",
                    "summary": (
                        "Reduced data availability: "
                        f"{inactive} pgs inactive"
                    ),
                }
        cur = set(checks)
        for code in sorted(cur - self._prev_health):
            self._clog(
                "warn",
                f"Health check failed: "
                f"{checks[code]['summary']} ({code})",
            )
        for code in sorted(self._prev_health - cur):
            self._clog("info", f"Health check cleared: {code}")
        self._prev_health = cur
        return checks

    # -- subscriber fan-out ------------------------------------------------
    def _map_message(self, since: int) -> MOSDMap:
        """Incremental run (since, current]; full map if a gap or a
        fresh subscriber (MOSDMap build semantics)."""
        cur = self.osdmap.epoch
        if since <= 0 or since >= cur:
            incs = []
        else:
            incs = [self.store.get_inc(e) for e in range(since + 1, cur + 1)]
        if since and incs and all(b is not None for b in incs):
            return MOSDMap(incrementals=incs)
        return MOSDMap(full=self.osdmap.encode())

    def _push_maps(self) -> None:
        for conn, sent in list(self._subs.items()):
            if conn.is_closed:
                del self._subs[conn]
                continue
            try:
                conn.send(self._map_message(sent))
                self._subs[conn] = self.osdmap.epoch
            except MessageError:
                del self._subs[conn]

    # -- dispatch ----------------------------------------------------------
    def ms_dispatch(self, conn: Connection, msg: Message) -> bool:
        if isinstance(msg, MMonSubscribe):
            if msg.from_osd >= 0 and getattr(
                conn, "peer_label", None
            ) is None:
                # stamp the subscriber's identity so directional
                # fault rules (netsplits) match the mon's map pushes
                # on this accepted connection too
                conn.peer_label = f"osd.{msg.from_osd}"
            with self._lock:
                self._subs[conn] = self.osdmap.epoch
                reply = self._map_message(msg.start_epoch)
                reply.tid = msg.tid
                conn.send(reply)
            return True
        if isinstance(msg, MOSDFailure):
            with self._lock:
                if msg.failed_for < 0:
                    self.failures.cancel_report(msg.target, msg.reporter)
                else:
                    self.failures.report_failure(
                        msg.target, msg.reporter, time.time()
                    )
            return True
        if isinstance(msg, MOSDBoot):
            with self._lock:
                inc = self.pending()
                inc.mark_up(msg.osd, addr=msg.addr)
                inc.mark_in(msg.osd)
                self.commit(inc)
                self._clog("info", f"osd.{msg.osd} boot")
            return True
        if isinstance(msg, MLog):
            try:
                entries = json.loads(msg.entries)
            except ValueError:
                entries = []
            if isinstance(entries, list):
                with self._lock:
                    self.clog_store.add(
                        [e for e in entries if isinstance(e, dict)]
                    )
            return True
        if isinstance(msg, MMonCommand):
            # "log subscribe" needs the CONNECTION (the watch stream
            # pushes back on it), which command handlers never see —
            # intercept here, before the handler table
            try:
                cmd = json.loads(msg.cmd)
            except ValueError:
                cmd = None
            if (
                isinstance(cmd, dict)
                and cmd.get("prefix") == "log subscribe"
            ):
                reply = self._watch_subscribe(conn, cmd)
            else:
                reply = self.handle_command(msg.cmd)
            reply.tid = msg.tid
            conn.send(reply)
            return True
        return False

    def ms_handle_reset(self, conn: Connection) -> None:
        self._subs.pop(conn, None)
        self._watch_subs.pop(conn, None)

    # -- `ceph -w` watch stream (the MLog subscription shape) --------------
    def _watch_subscribe(
        self, conn: Connection, cmd: dict
    ) -> MMonCommandReply:
        level = str(cmd.get("level", "info"))
        if level not in _CLOG_PRIOS:
            level = "info"
        with self._lock:
            self._watch_subs[conn] = {
                "level": level,
                "debug": bool(cmd.get("debug", False)),
                # dout watermark: the firehose streams only entries
                # newer than the subscription
                "dout_mark": time.time(),
            }
        return MMonCommandReply(
            outb=json.dumps({"subscribed": True, "level": level})
        )

    def _push_watch(self, entries: list[dict]) -> None:
        """clog fanout (LogStore.notify): every accepted entry
        streams to each subscriber that clears its level floor, as an
        MLog batch; ``--watch-debug`` subscribers additionally get
        the fresh dout-ring tail as channel="debug" entries."""
        if not self._watch_subs:
            return
        from ..common.log import log as _dout_ring
        from ..common.log_client import prio_rank

        for conn, sub in list(self._watch_subs.items()):
            if conn.is_closed:
                self._watch_subs.pop(conn, None)
                continue
            floor = prio_rank(sub["level"])
            batch = [
                e for e in entries
                if prio_rank(e.get("prio", "info")) >= floor
            ]
            if sub["debug"]:
                fresh = [
                    r for r in _dout_ring().dump_recent()
                    if r["stamp"] > sub["dout_mark"]
                ]
                if fresh:
                    sub["dout_mark"] = max(
                        r["stamp"] for r in fresh
                    )
                    batch.extend(
                        {
                            "name": "mon.0",
                            "stamp": r["stamp"],
                            "channel": "debug",
                            "prio": "debug",
                            "message": (
                                f"[{r['subsys']}:{r['level']}] "
                                f"{r['message']}"
                            ),
                            "seq": 0,
                        }
                        for r in fresh
                    )
            if not batch:
                continue
            try:
                conn.send(
                    MLog(name="mon.0", entries=json.dumps(batch))
                )
            except (MessageError, OSError):
                self._watch_subs.pop(conn, None)

    # -- command surface (MonCommands.h role) ------------------------------
    # read-only or high-rate periodic chatter: never audit-logged
    # (the reference's `mon debug` vs audit-channel split)
    _AUDIT_EXEMPT = frozenset(
        {
            "status", "health", "osd dump", "osd tree", "pg dump",
            "osd pool ls", "config get", "config dump", "mgr stat",
            "mds stat", "osd erasure-code-profile get",
            "osd erasure-code-profile ls",
            "log last", "log stat",
            # periodic daemon chatter
            "mds beacon", "mgr beacon", "osd slow ops",
            "crash report", "osd scrub errors", "osd stat report",
            "osd df", "osd perf", "slo report",
            "pgmap report", "df",
        }
    )

    def handle_command(self, cmd_json: str) -> MMonCommandReply:
        try:
            cmd = json.loads(cmd_json)
            prefix = cmd.get("prefix", "")
            handler = _COMMANDS.get(prefix)
            if handler is None:
                return MMonCommandReply(
                    rc=-22, outs=f"unknown command {prefix!r}"
                )
            with self._lock:
                if prefix not in self._AUDIT_EXEMPT:
                    # mutating operator commands hit the audit channel
                    # (the reference logs every dispatch to clog audit)
                    self._clog(
                        "info",
                        f"cmd={cmd_json[:512]}: dispatch",
                        channel="audit",
                    )
                return handler(self, cmd)
        except Exception as e:  # noqa: BLE001 — the RPC contract: a
            # command must ALWAYS produce a reply (a raised handler
            # would otherwise leave the caller blocked to timeout)
            if not isinstance(
                e, (KeyError, ValueError, TypeError, AttributeError)
            ):
                # those four are malformed-input shapes (missing,
                # bad, or wrong-typed fields — e.g. cmd='[]' makes
                # .get raise AttributeError) — operator error, not a
                # mon crash; filing reports for them would let any
                # client raise RECENT_CRASH with garbage commands.
                # Anything else is a real handler bug: file a report
                from ..common import crash as _crash

                _crash.capture(
                    "mon.0", e, extra_meta={"cmd": cmd_json[:512]}
                )
            return MMonCommandReply(rc=-22, outs=f"{type(e).__name__}: {e}")


def _cmd_status(mon: Monitor, cmd: dict) -> MMonCommandReply:
    m = mon.osdmap
    up = sum(1 for o in range(m.max_osd) if m.is_up(o))
    inn = sum(
        1
        for o in range(m.max_osd)
        if m.exists(o) and m.osd_weight[o] > 0
    )
    status = {
        "epoch": m.epoch,
        "num_osds": m.max_osd,
        "num_up_osds": up,
        "num_in_osds": inn,
        "num_pools": len(m.pools),
    }
    digest = mon.pgmap_digest()
    if digest is not None:
        # the reference's `ceph status` data/io section (PGMap::print_summary)
        status["pgmap"] = {
            "num_pgs": digest.get("num_pgs", 0),
            "pgs_by_state": digest.get("pg_states", {}),
            "data": digest.get("totals", {}),
            "io": digest.get("io", {}),
            "recovery": digest.get("recovery", {}),
        }
    return MMonCommandReply(outb=json.dumps(status))


def _cmd_pgmap_report(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """The mgr pgmap module's digest push.  Bounded validation (the
    slo-report idiom): the digest travels base64(binary) and must
    decode through the pinned codec or the push is rejected."""
    import base64 as _b64

    from ..mgr.pgmap import decode_pgmap_digest

    raw = cmd.get("digest")
    if not isinstance(raw, str) or len(raw) > 4 << 20:
        return MMonCommandReply(rc=-22, outs="bad digest")
    try:
        digest = decode_pgmap_digest(_b64.b64decode(raw))
    except Exception:  # noqa: BLE001 — reject, never crash the mon
        return MMonCommandReply(rc=-22, outs="undecodable digest")
    mon.pgmap = (time.time(), digest)
    return MMonCommandReply(outb=json.dumps({"ok": True}))


def _cmd_df(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """'ceph df': cluster fill from the per-OSD stat reports +
    per-pool stored/objects from the pgmap digest."""
    now = time.time()
    kb = kb_used = kb_avail = 0
    for _osd, (ts, k, ku, ka) in list(mon.osd_stats.items()):
        if now - ts > STAT_REPORT_GRACE:
            continue
        kb += k
        kb_used += ku
        kb_avail += ka
    digest = mon.pgmap_digest() or {}
    pools = []
    for pid in sorted(mon.osdmap.pools):
        p = (digest.get("pools") or {}).get(pid, {})
        pools.append(
            {
                "id": pid,
                "name": mon.osdmap.pool_names.get(pid, str(pid)),
                "stored": p.get("bytes", 0),
                "objects": p.get("objects", 0),
                "degraded": p.get("degraded", 0),
                "misplaced": p.get("misplaced", 0),
            }
        )
    return MMonCommandReply(
        outb=json.dumps(
            {
                "stats": {
                    "total_bytes": kb * 1024,
                    "total_used_bytes": kb_used * 1024,
                    "total_avail_bytes": kb_avail * 1024,
                },
                "pools": pools,
            }
        )
    )


def _cmd_osd_down(mon: Monitor, cmd: dict) -> MMonCommandReply:
    osd = int(cmd["id"])
    if not mon.osdmap.is_up(osd):
        # the state entry is an XOR: re-queueing it for a down OSD
        # would flip it back up (OSDMonitor guards with is_up too)
        return MMonCommandReply(outs=f"osd.{osd} is already down")
    inc = mon.pending()
    inc.mark_down(osd)
    epoch = mon.commit(inc)
    return MMonCommandReply(outs=f"marked down osd.{osd}", outb=json.dumps({"epoch": epoch}))


def _cmd_osd_out(mon: Monitor, cmd: dict) -> MMonCommandReply:
    osd = int(cmd["id"])
    inc = mon.pending()
    inc.mark_out(osd)
    epoch = mon.commit(inc)
    return MMonCommandReply(outs=f"marked out osd.{osd}", outb=json.dumps({"epoch": epoch}))


def _cmd_osd_in(mon: Monitor, cmd: dict) -> MMonCommandReply:
    osd = int(cmd["id"])
    inc = mon.pending()
    inc.mark_in(osd)
    epoch = mon.commit(inc)
    return MMonCommandReply(outs=f"marked in osd.{osd}", outb=json.dumps({"epoch": epoch}))


def _cmd_osd_reweight(mon: Monitor, cmd: dict) -> MMonCommandReply:
    osd = int(cmd["id"])
    weight = float(cmd["weight"])
    inc = mon.pending()
    inc.new_weight[osd] = int(weight * 0x10000)
    epoch = mon.commit(inc)
    return MMonCommandReply(outb=json.dumps({"epoch": epoch}))


def _cmd_osd_blocklist(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """Client fencing ("osd blocklist add/rm/ls", OSDMonitor's
    blocklist command, src/mon/OSDMonitor.cc prepare_command
    "osd blocklist").  ``addr`` is the client id the objecter stamps
    into every reqid; OSDs reject ops from blocklisted ids, which is
    what makes exclusive-lock break-lock and MDS failover safe."""
    op = cmd.get("blocklistop", "add")
    if op == "ls":
        now = time.time()
        live = {
            a: u for a, u in mon.osdmap.blocklist.items() if u > now
        }
        return MMonCommandReply(outb=json.dumps(live))
    addr = cmd["addr"]
    inc = mon.pending()
    if op == "add":
        expire = float(cmd.get("expire", 3600.0))
        inc.new_blocklist[addr] = time.time() + expire
        # trim dead entries while we are mutating anyway (the
        # reference expires them in OSDMonitor tick).  NEVER trim the
        # addr being re-added: apply_incremental applies new before
        # old, so the same addr in both would cancel the fresh fence
        now = time.time()
        for a, until in mon.osdmap.blocklist.items():
            if until <= now and a != addr:
                inc.old_blocklist.append(a)
        epoch = mon.commit(inc)
        return MMonCommandReply(
            outs=f"blocklisting {addr} for {expire}s",
            outb=json.dumps({"epoch": epoch}),
        )
    if op == "rm":
        if addr not in mon.osdmap.blocklist:
            return MMonCommandReply(
                outs=f"{addr} isn't blocklisted"
            )
        inc.old_blocklist.append(addr)
        epoch = mon.commit(inc)
        return MMonCommandReply(
            outs=f"un-blocklisting {addr}",
            outb=json.dumps({"epoch": epoch}),
        )
    return MMonCommandReply(rc=-22, outs=f"bad blocklistop {op!r}")


def _cmd_pool_create(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """Pool creation (OSDMonitor "osd pool create").  Erasure pools
    (pool_type=3) size themselves from the profile (size=k+m,
    min_size=k+1 — OSDMonitor::prepare_pool_size) and, when no
    crush_rule is given, get a profile-named indep rule created the
    way the plugin's create_rule would (OSDMonitor.cc:10928 flow)."""
    name = cmd["pool"]
    if name in mon.osdmap.pool_names.values():
        return MMonCommandReply(rc=-17, outs=f"pool {name!r} exists")
    pool_id = mon.osdmap.pool_max + 1
    ptype = int(cmd.get("pool_type", 1))
    size = int(cmd.get("size", 3))
    min_size = cmd.get("min_size")
    crush_rule = cmd.get("crush_rule")
    profile_name = cmd.get("erasure_code_profile", "")
    inc = mon.pending()
    if ptype == PG_POOL_TYPE_ERASURE:
        profile_name = profile_name or "default"
        profile = mon.osdmap.erasure_code_profiles.get(profile_name)
        if profile is None:
            return MMonCommandReply(
                rc=-2,
                outs=f"erasure-code-profile {profile_name!r} not found",
            )
        try:
            from ..osd.ec_pg import ECCodec

            codec = ECCodec(profile)
        except Exception as e:  # noqa: BLE001 — profile is user input
            return MMonCommandReply(
                rc=-22, outs=f"invalid profile {profile_name!r}: {e}"
            )
        size = codec.n
        min_size = (
            int(min_size) if min_size is not None else codec.k + 1
        )
        if crush_rule is None:
            # reuse a rule already named after the profile, else build
            # one on a crushmap copy and ship it in the incremental
            cmap = mon.osdmap.crush
            existing = [
                rid
                for rid, rname in cmap.rule_names.items()
                if rname == profile_name
            ]
            if existing:
                crush_rule = existing[0]
            else:
                import copy as _copy

                newmap = _copy.deepcopy(cmap)
                try:
                    crush_rule = newmap.add_simple_rule(
                        profile_name,
                        profile.get("crush-root", "default"),
                        profile.get("crush-failure-domain", "host"),
                        mode="indep",
                    )
                except (KeyError, AssertionError) as e:
                    return MMonCommandReply(
                        rc=-22,
                        outs=f"cannot create erasure rule: {e}",
                    )
                inc.crush = newmap
    pool = PgPool(
        pool_id=pool_id,
        type=ptype,
        size=size,
        pg_num=int(cmd.get("pg_num", 32)),
        crush_rule=int(crush_rule or 0),
        erasure_code_profile=profile_name,
    )
    if min_size is not None:
        pool.min_size = int(min_size)
    inc.new_pools[pool_id] = pool
    inc.new_pool_names[pool_id] = name
    inc.new_pool_max = pool_id
    epoch = mon.commit(inc)
    return MMonCommandReply(
        outs=f"pool '{name}' created",
        outb=json.dumps({"pool_id": pool_id, "epoch": epoch}),
    )


def _pool_by_name(mon: Monitor, name: str):
    for pid, pname in mon.osdmap.pool_names.items():
        if pname == name:
            return pid, mon.osdmap.pools[pid]
    return None, None


def _cmd_pool_mksnap(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """"osd pool mksnap" (OSDMonitor::prepare_command pool snaps):
    bump the pool's snap_seq and record the named snap; the new pool
    rides an incremental, and every write after this epoch clones."""
    pid, pool = _pool_by_name(mon, cmd["pool"])
    if pool is None:
        return MMonCommandReply(rc=-2, outs=f"pool {cmd['pool']!r} not found")
    snap = cmd["snap"]
    if snap in pool.snaps.values():
        return MMonCommandReply(rc=-17, outs=f"snap {snap!r} exists")
    import copy as _copy

    newpool = _copy.deepcopy(pool)
    newpool.snap_seq += 1
    newpool.snaps[newpool.snap_seq] = snap
    inc = mon.pending()
    inc.new_pools[pid] = newpool
    epoch = mon.commit(inc)
    return MMonCommandReply(
        outs=f"created pool {cmd['pool']} snap {snap}",
        outb=json.dumps(
            {"snapid": newpool.snap_seq, "epoch": epoch}
        ),
    )


def _cmd_pool_rmsnap(mon: Monitor, cmd: dict) -> MMonCommandReply:
    pid, pool = _pool_by_name(mon, cmd["pool"])
    if pool is None:
        return MMonCommandReply(rc=-2, outs=f"pool {cmd['pool']!r} not found")
    snap = cmd["snap"]
    sid = next(
        (k for k, v in pool.snaps.items() if v == snap), None
    )
    if sid is None:
        return MMonCommandReply(rc=-2, outs=f"snap {snap!r} not found")
    import copy as _copy

    newpool = _copy.deepcopy(pool)
    del newpool.snaps[sid]
    inc = mon.pending()
    inc.new_pools[pid] = newpool
    epoch = mon.commit(inc)
    return MMonCommandReply(
        outs=f"removed pool {cmd['pool']} snap {snap}",
        outb=json.dumps({"snapid": sid, "epoch": epoch}),
    )


def _cmd_pool_delete(mon: Monitor, cmd: dict) -> MMonCommandReply:
    name = cmd["pool"]
    ids = [i for i, n in mon.osdmap.pool_names.items() if n == name]
    if not ids:
        return MMonCommandReply(rc=-2, outs=f"pool {name!r} not found")
    inc = mon.pending()
    inc.old_pools.add(ids[0])
    epoch = mon.commit(inc)
    return MMonCommandReply(outb=json.dumps({"epoch": epoch}))


def _cmd_ec_profile_set(mon: Monitor, cmd: dict) -> MMonCommandReply:
    name = cmd["name"]
    profile = {}
    for kv in cmd.get("profile", []):
        k, _, v = kv.partition("=")
        profile[k] = v
    inc = mon.pending()
    inc.new_erasure_code_profiles[name] = profile
    epoch = mon.commit(inc)
    return MMonCommandReply(outb=json.dumps({"epoch": epoch}))


def _cmd_pg_upmap_items(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """"osd pg-upmap-items <pgid> <from> <to> [...]" — the balancer's
    commit surface (OSDMonitor's pg-upmap-items command)."""
    pgid = cmd["pgid"]
    try:
        pool_id, ps = (int(x) for x in pgid.split("."))
    except ValueError:
        return MMonCommandReply(rc=-22, outs=f"bad pgid {pgid!r}")
    if pool_id not in mon.osdmap.pools:
        return MMonCommandReply(rc=-2, outs=f"no pool {pool_id}")
    mappings = [
        (int(a), int(b)) for a, b in cmd.get("mappings", [])
    ]
    inc = mon.pending()
    if mappings:
        inc.new_pg_upmap_items[(pool_id, ps)] = mappings
    else:
        inc.old_pg_upmap_items.add((pool_id, ps))
    epoch = mon.commit(inc)
    return MMonCommandReply(outb=json.dumps({"epoch": epoch}))


def _cmd_osd_dump(mon: Monitor, cmd: dict) -> MMonCommandReply:
    m = mon.osdmap
    return MMonCommandReply(
        outb=json.dumps(
            {
                "epoch": m.epoch,
                "max_osd": m.max_osd,
                "osds": [
                    {
                        "osd": o,
                        "up": int(m.is_up(o)),
                        "in": int(m.exists(o) and m.osd_weight[o] > 0),
                        "weight": m.osd_weight[o] / 0x10000,
                    }
                    for o in range(m.max_osd)
                ],
                "pools": {
                    str(pid): {
                        "name": m.pool_names.get(pid, ""),
                        "size": p.size,
                        "pg_num": p.pg_num,
                        "type": p.type,
                    }
                    for pid, p in m.pools.items()
                },
            }
        )
    )


def _prune_mutes(mon: Monitor) -> None:
    """TTL expiry: a lapsed mute restores the check to the rollup."""
    now = time.time()
    for code, expiry in list(mon.health_mutes.items()):
        if expiry <= now:
            del mon.health_mutes[code]
            mon._clog("info", f"Health check unmuted: {code} (TTL)")


def _cmd_health(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """'ceph health' (HealthMonitor role): DOWN/OUT osds, fresh
    SLOW_OPS reports, and RECENT_CRASH degrade to WARN.  Muted codes
    leave the rollup (status + checks) but stay in checks_detail —
    mutes filter, they never lose detail."""
    checks = mon.health_checks()
    _prune_mutes(mon)
    muted = {c for c in checks if c in mon.health_mutes}
    active = {c: v for c, v in checks.items() if c not in muted}
    # the rollup takes the WORST active severity: scrub damage
    # (OSD_SCRUB_ERRORS/PG_DAMAGED) is HEALTH_ERR, not a warning
    if not active:
        status = "HEALTH_OK"
    elif any(
        v.get("severity") == "HEALTH_ERR" for v in active.values()
    ):
        status = "HEALTH_ERR"
    else:
        status = "HEALTH_WARN"
    return MMonCommandReply(
        outs=status,
        outb=json.dumps(
            {
                "status": status,
                "checks": [v["summary"] for v in active.values()],
                "checks_detail": {
                    code: {**v, "muted": code in muted}
                    for code, v in checks.items()
                },
                "muted": sorted(muted),
            }
        ),
    )


def _cmd_health_mute(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """'ceph health mute <code> [--ttl N]': drop a check code from
    the health rollup (HealthMonitor mutes)."""
    code = str(cmd.get("code", "")).strip()
    if not code or len(code) > MAX_MUTE_CODE_LEN:
        return MMonCommandReply(
            rc=-22, outs="missing or oversized code (-EINVAL)"
        )
    if (
        code not in mon.health_mutes
        and len(mon.health_mutes) >= MAX_HEALTH_MUTES
    ):
        return MMonCommandReply(
            rc=-7, outs="too many muted codes (-E2BIG)"
        )
    ttl = cmd.get("ttl")
    expiry = float("inf") if ttl is None else time.time() + float(ttl)
    mon.health_mutes[code] = expiry
    mon._clog(
        "info",
        f"Health check muted: {code}"
        + (f" (TTL {float(ttl):.0f}s)" if ttl is not None else ""),
        channel="audit",
    )
    return MMonCommandReply(
        outs=f"muted {code}",
        outb=json.dumps({"code": code, "ttl": ttl}),
    )


def _cmd_health_unmute(mon: Monitor, cmd: dict) -> MMonCommandReply:
    code = str(cmd.get("code", "")).strip()
    if code not in mon.health_mutes:
        return MMonCommandReply(
            rc=-2, outs=f"{code!r} is not muted (-ENOENT)"
        )
    del mon.health_mutes[code]
    mon._clog(
        "info", f"Health check unmuted: {code}", channel="audit"
    )
    return MMonCommandReply(outs=f"unmuted {code}")


def _cmd_crash_report(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """mgr crash module → mon: the current count of un-archived
    recent crashes (the mgr-raised health check surface).  Archiving
    pushes 0, which clears RECENT_CRASH."""
    mon.recent_crashes = max(0, int(cmd.get("num_recent", 0)))
    return MMonCommandReply(outb=json.dumps({"ok": True}))


def _cmd_log_last(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """'ceph log last [n] [level] [channel]' (LogMonitor's command)."""
    n = int(cmd.get("num", 20))
    level = cmd.get("level")
    channel = cmd.get("channel")
    entries = mon.clog_store.last(n, level=level, channel=channel)
    return MMonCommandReply(
        outs="\n".join(
            f"{e['stamp']:.6f} {e['name']} ({e['channel']}) "
            f"[{e['prio'].upper()}] {e['message']}"
            for e in entries
        ),
        outb=json.dumps(entries),
    )


def _cmd_log_stat(mon: Monitor, cmd: dict) -> MMonCommandReply:
    return MMonCommandReply(outb=json.dumps(mon.clog_store.stat()))


def _cmd_log_inject(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """'ceph log <text>': operator entry onto the cluster log (the
    reference's `ceph log` command)."""
    text = cmd.get("logtext", "")
    if isinstance(text, list):
        text = " ".join(str(t) for t in text)
    if not text:
        return MMonCommandReply(rc=-22, outs="missing logtext (-EINVAL)")
    mon.clog_store.add(
        [
            {
                "name": str(cmd.get("name", "client.admin")),
                "stamp": time.time(),
                "channel": str(cmd.get("channel", "cluster")),
                "prio": str(cmd.get("prio", "info")),
                "message": str(text),
                "seq": 0,
            }
        ]
    )
    return MMonCommandReply(outs="logged")


def _cmd_osd_slow_ops(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """Daemon → mon slow-op report (the OSD SLOW_OPS watchdog's
    upcall; MOSDBeacon's health payload in the reference).  A count
    of 0 withdraws the daemon's complaint immediately."""
    daemon = str(cmd.get("daemon", ""))
    if not daemon:
        return MMonCommandReply(rc=-22, outs="missing daemon")
    count = int(cmd.get("count", 0))
    oldest = float(cmd.get("oldest_age", 0.0))
    if count <= 0:
        mon.slow_ops.pop(daemon, None)
    else:
        mon.slow_ops[daemon] = (time.time(), count, oldest)
    return MMonCommandReply(rc=0, outb=json.dumps({"ok": True}))


def _cmd_osd_stat_report(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """Daemon → mon space-stat report (the osd_stat_t carry of
    MPGStats, reduced to the fullness fields): kb/kb_used/kb_avail
    from the OSD's store statfs.  Feeds OSD_NEARFULL/OSD_FULL."""
    try:
        osd = int(cmd["osd"])
    except (KeyError, TypeError, ValueError):
        return MMonCommandReply(rc=-22, outs="missing osd id")
    kb = max(0, int(cmd.get("kb", 0)))
    kb_used = max(0, int(cmd.get("kb_used", 0)))
    kb_avail = max(0, int(cmd.get("kb_avail", 0)))
    mon.osd_stats[osd] = (time.time(), kb, kb_used, kb_avail)
    # optional perf seat (commit/apply latency → `ceph osd perf`);
    # apply defaults to commit — the stores have no journal split
    if "commit_latency_ms" in cmd:
        try:
            commit = max(0.0, float(cmd["commit_latency_ms"]))
            apply_ = max(
                0.0, float(cmd.get("apply_latency_ms", commit))
            )
            mon.osd_perf_stats[osd] = (time.time(), commit, apply_)
        except (TypeError, ValueError):
            pass  # malformed perf seat: keep the space stats
    # the reply carries the EFFECTIVE ratios so the OSD's write gate
    # follows `ceph config set mon mon_osd_full_ratio ...` instead of
    # diverging from the health check on its local schema default
    return MMonCommandReply(
        rc=0,
        outb=json.dumps(
            {
                "ok": True,
                "nearfull_ratio": mon._config_float(
                    "mon_osd_nearfull_ratio"
                ),
                "full_ratio": mon._config_float(
                    "mon_osd_full_ratio"
                ),
            }
        ),
    )


def _cmd_osd_df(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """'ceph osd df' (reduced): per-OSD kb/kb_used/kb_avail from the
    latest stat reports, with the effective full ratios."""
    return MMonCommandReply(
        outb=json.dumps(
            {
                "nearfull_ratio": mon._config_float(
                    "mon_osd_nearfull_ratio"
                ),
                "full_ratio": mon._config_float("mon_osd_full_ratio"),
                "nodes": [
                    {
                        "osd": osd,
                        "kb": kb,
                        "kb_used": kb_used,
                        "kb_avail": kb_avail,
                        "utilization": (
                            kb_used / kb if kb else 0.0
                        ),
                    }
                    for osd, (_ts, kb, kb_used, kb_avail) in sorted(
                        mon.osd_stats.items()
                    )
                ],
            }
        )
    )


def _cmd_osd_perf(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """'ceph osd perf' (OSDMonitor's osd_stat_t perf view): per-OSD
    commit/apply latency from the freshest stat reports — the CLI
    table the reference prints from PGMap::dump_osd_perf_stats."""
    now = time.time()
    infos = []
    for osd, (ts, commit, apply_) in sorted(
        mon.osd_perf_stats.items()
    ):
        if not mon.osdmap.is_up(osd) or now - ts > STAT_REPORT_GRACE:
            del mon.osd_perf_stats[osd]
            continue
        infos.append(
            {
                "id": osd,
                "perf_stats": {
                    "commit_latency_ms": commit,
                    "apply_latency_ms": apply_,
                },
            }
        )
    return MMonCommandReply(
        outs="\n".join(
            ["osd  commit_latency(ms)  apply_latency(ms)"]
            + [
                f"{e['id']:>3}  "
                f"{e['perf_stats']['commit_latency_ms']:>18.3f}  "
                f"{e['perf_stats']['apply_latency_ms']:>17.3f}"
                for e in infos
            ]
        ),
        outb=json.dumps({"osd_perf_infos": infos}),
    )


_SLO_SEVERITIES = ("HEALTH_WARN", "HEALTH_ERR")
MAX_SLO_CHECKS = 32
MAX_SLO_SUMMARY = 512


def _cmd_slo_report(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """mgr slo module → mon: the current burn-rate verdicts (the
    mgr-raised health-check push, same idiom as "crash report").
    Each push REPLACES the set — an empty ``checks`` clears
    SLO_LATENCY immediately; entries are bounded and validated
    because they render into health summaries and the cluster log."""
    checks = cmd.get("checks", {})
    if not isinstance(checks, dict):
        return MMonCommandReply(rc=-22, outs="checks must be a dict")
    if len(checks) > MAX_SLO_CHECKS:
        return MMonCommandReply(
            rc=-7, outs="too many slo checks (-E2BIG)"
        )
    now = time.time()
    accepted: dict[str, tuple[float, str, str]] = {}
    for code, det in checks.items():
        code = str(code)
        if not code.startswith("SLO_") or len(code) > MAX_MUTE_CODE_LEN:
            return MMonCommandReply(
                rc=-22, outs=f"bad slo check code {code!r}"
            )
        severity = str(det.get("severity", "HEALTH_WARN"))
        if severity not in _SLO_SEVERITIES:
            return MMonCommandReply(
                rc=-22, outs=f"bad severity {severity!r}"
            )
        summary = str(det.get("summary", ""))[:MAX_SLO_SUMMARY]
        accepted[code] = (now, severity, summary)
    mon.slo_reports = accepted
    return MMonCommandReply(outb=json.dumps({"ok": True}))


def _cmd_tell(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """'ceph tell <daemon> <args...>' routing: the mon validates the
    target and names its address; the CLI dispatches the inner
    command there as an MCommand (the mon→daemon command route of
    the reference, collapsed to mon-names/client-dispatches exactly
    like the scrub orders)."""
    target = str(cmd.get("target", ""))
    kind, _, ident = target.partition(".")
    if kind != "osd" or not ident.isdigit():
        return MMonCommandReply(
            rc=-22, outs=f"bad tell target {target!r} (osd.N only)"
        )
    osd = int(ident)
    if not mon.osdmap.is_up(osd):
        return MMonCommandReply(
            rc=-11, outs=f"osd.{osd} is down (-EAGAIN)"
        )
    addr = mon.osdmap.osd_addrs.get(osd, "")
    if not addr:
        return MMonCommandReply(
            rc=-11, outs=f"osd.{osd} has no address (-EAGAIN)"
        )
    return MMonCommandReply(
        outb=json.dumps(
            {
                "target": target,
                "addr": addr,
                "args": cmd.get("args", {}),
            }
        )
    )


def _cmd_osd_scrub_errors(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """Daemon → mon scrub-findings report (the pg-stats path that
    feeds OSD_SCRUB_ERRORS/PG_DAMAGED in the reference).  A report of
    0 errors — what a successful repair sends — clears the daemon's
    contribution immediately."""
    daemon = str(cmd.get("daemon", ""))
    if not daemon:
        return MMonCommandReply(rc=-22, outs="missing daemon")
    errors = int(cmd.get("errors", 0))
    pgs = [str(p) for p in cmd.get("pgs", [])]
    large = int(cmd.get("large_omap", 0))
    if errors <= 0 and large <= 0:
        mon.scrub_reports.pop(daemon, None)
    else:
        mon.scrub_reports[daemon] = (
            time.time(), errors, pgs, large,
        )
    return MMonCommandReply(rc=0, outb=json.dumps({"ok": True}))


def _cmd_pg_scrub(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """'ceph pg scrub|deep-scrub|repair <pgid>': validate the pg and
    name its primary + address — the CLI dispatches the order to the
    primary OSD directly (the mon→mgr→OSD scrub-order route of the
    reference, collapsed to mon-names/client-dispatches)."""
    what = str(cmd.get("prefix", "pg scrub"))[3:]
    pgid = str(cmd.get("pgid", ""))
    try:
        pool_id, ps = (int(x) for x in pgid.split("."))
    except ValueError:
        return MMonCommandReply(rc=-22, outs=f"bad pgid {pgid!r}")
    pool = mon.osdmap.pools.get(pool_id)
    if pool is None or ps < 0 or ps >= pool.pg_num:
        return MMonCommandReply(rc=-2, outs=f"pg {pgid} dne")
    _up, _upp, _acting, primary = mon.osdmap.pg_to_up_acting_osds(
        pool_id, ps
    )
    if primary < 0 or not mon.osdmap.is_up(primary):
        return MMonCommandReply(
            rc=-11, outs=f"pg {pgid} has no live primary (-EAGAIN)"
        )
    return MMonCommandReply(
        outs=f"instructing pg {pgid} on osd.{primary} to {what}",
        outb=json.dumps(
            {
                "pgid": pgid,
                "op": what,
                "primary": primary,
                "addr": mon.osdmap.osd_addrs.get(primary, ""),
            }
        ),
    )


def _cmd_osd_tree(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """'ceph osd tree' (CrushTreeDumper role): the crush hierarchy
    with up/down + weight per device, shadow trees hidden."""
    m = mon.osdmap
    crush = m.crush
    shadows = {
        c for per in crush.class_bucket.values() for c in per.values()
    }
    lines = []

    def walk(item: int, depth: int, weight: int) -> None:
        indent = "    " * depth
        if item >= 0:
            state = "up" if m.is_up(item) else "down"
            reweight = (
                m.osd_weight[item] / 0x10000
                if item < m.max_osd
                else 0.0
            )
            cls = crush.class_names.get(
                crush.class_map.get(item, -1), ""
            )
            lines.append(
                f"{item:>4} {cls:>6} {weight / 0x10000:>8.5f} "
                f"{indent}osd.{item} {state:>6} {reweight:.5f}"
            )
            return
        b = crush.buckets[item]
        name = crush.item_names.get(item, f"bucket{-1 - item}")
        tname = crush.type_names.get(b.type, str(b.type))
        lines.append(
            f"{item:>4} {'':>6} {b.weight / 0x10000:>8.5f} "
            f"{indent}{tname} {name}"
        )
        for child, w in zip(b.items, b.item_weights):
            walk(child, depth + 1, w)

    for root in sorted(crush._roots(), reverse=True):
        if root in shadows:
            continue
        walk(root, 0, crush.buckets[root].weight)
    header = "  ID  CLASS   WEIGHT NAME/STATE"
    return MMonCommandReply(outb="\n".join([header] + lines))


def _cmd_pg_dump(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """'ceph pg dump': every pool PG with its up/acting sets (the
    OSDMonitor side of pg listing; per-PG I/O stats live on the mgr)."""
    m = mon.osdmap
    digest_pgs = (mon.pgmap_digest() or {}).get("pgs", {})
    pgs = []
    for pid, pool in m.pools.items():
        for ps in range(pool.pg_num):
            up, upp, acting, actingp = m.pg_to_up_acting_osds(pid, ps)
            row = {
                "pgid": f"{pid}.{ps}",
                "up": up,
                "up_primary": upp,
                "acting": acting,
                "acting_primary": actingp,
            }
            # states + counts from the mgr digest (the PGMap side of
            # pg dump); unreported pgs keep the map-only row
            st = digest_pgs.get(row["pgid"])
            if st is not None:
                row.update(
                    {
                        "state": st.get("state", "unknown"),
                        "num_objects": st.get("objects", 0),
                        "num_bytes": st.get("bytes", 0),
                        "num_objects_degraded": st.get("degraded", 0),
                        "num_objects_misplaced": st.get(
                            "misplaced", 0
                        ),
                        "num_objects_unfound": st.get("unfound", 0),
                        "recovery_progress": st.get(
                            "recovery_progress", 0.0
                        ),
                    }
                )
            pgs.append(row)
    return MMonCommandReply(outb=json.dumps({"pg_stats": pgs}))


def _cmd_pool_ls(mon: Monitor, cmd: dict) -> MMonCommandReply:
    names = [
        mon.osdmap.pool_names.get(pid, str(pid))
        for pid in sorted(mon.osdmap.pools)
    ]
    return MMonCommandReply(
        outs="\n".join(names), outb=json.dumps(names)
    )


def _cmd_ec_profile_get(mon: Monitor, cmd: dict) -> MMonCommandReply:
    name = cmd["name"]
    prof = mon.osdmap.erasure_code_profiles.get(name)
    if prof is None:
        return MMonCommandReply(rc=-2, outs=f"profile {name!r} not found")
    return MMonCommandReply(outb=json.dumps(prof))


def _cmd_ec_profile_ls(mon: Monitor, cmd: dict) -> MMonCommandReply:
    return MMonCommandReply(
        outb=json.dumps(sorted(mon.osdmap.erasure_code_profiles))
    )


def _cmd_config_set(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """ConfigMonitor role: centralized config database ('ceph config
    set <who> <key> <value>')."""
    who, key, value = cmd["who"], cmd["key"], str(cmd["value"])
    mon.config_db.setdefault(who, {})[key] = value
    return MMonCommandReply(outs=f"set {who}/{key}")


def _cmd_config_get(mon: Monitor, cmd: dict) -> MMonCommandReply:
    who = cmd["who"]
    key = cmd.get("key")
    section = mon.config_db.get(who, {})
    if key is not None:
        if key not in section:
            return MMonCommandReply(rc=-2, outs=f"no config {who}/{key}")
        return MMonCommandReply(outs=section[key], outb=json.dumps(section[key]))
    return MMonCommandReply(outb=json.dumps(section))


def _cmd_config_dump(mon: Monitor, cmd: dict) -> MMonCommandReply:
    return MMonCommandReply(outb=json.dumps(mon.config_db))


def _fence_mds(mon: Monitor, entry: dict | None) -> None:
    """Blocklist a demoted/replaced active's rados client id so a
    partitioned-but-alive daemon cannot flush journal or metadata the
    promoted standby's replay never saw (MDSMonitor fences the old
    gid via the OSDMap blocklist, src/mon/MDSMonitor.cc fail_mds_gid).
    Paxos-committed, so every OSD enforces it."""
    cid = (entry or {}).get("client")
    if not cid:
        return
    try:
        inc = mon.pending()
        inc.new_blocklist[cid] = time.time() + 3600.0
        mon.commit(inc)
    except Exception:  # noqa: BLE001 — a no-quorum window loses the
        # fence attempt, not the failover; the stale active still
        # demotes on its next beacon reply
        pass


def _mdsmap_of(mon: Monitor) -> dict:
    m = getattr(mon, "mdsmap", None)
    if m is None or "actives" not in m:
        m = mon.mdsmap = {
            "epoch": 0,
            "max_mds": 1,
            # rank (as str, JSON-stable) -> {name, addr, client}
            "actives": {},
            "standbys": [],
            "beacons": {},
            # subtree auth table: path prefix -> rank.  "subtrees" is
            # the LATEST table (what daemons must converge to);
            # "subtrees_stable" is what clients may route by — it
            # advances only once every active has flushed under the
            # new table and acked its epoch (the Migrator
            # export/import barrier, reduced to flush+ack)
            "subtrees": {"/": 0},
            "subtrees_stable": {"/": 0},
            "table_epoch": 0,
            "table_acks": {},  # name -> acked table_epoch
            # shrink-evicted ranks whose journals rank 0 must adopt
            # (replay + trim) before the re-pinned table stabilizes;
            # entries are [rank, gen] — the generation tag makes an
            # ack specific to ONE eviction, so a stale beacon ack
            # from before a re-grow→re-shrink cycle cannot drain a
            # NEWER eviction's un-replayed journal
            "stray_ranks": [],
            "stray_gen": 0,
        }
    return m


def _mds_promote_holes(mon: Monitor, m: dict) -> None:
    """Fill empty ranks (0..max_mds-1) from the standby pool.  A rank
    whose shrink-evicted journal is still queued for adoption
    (stray_ranks) is NOT refilled yet: promoting it mid-adoption
    would let the adopter's eventual trim() write a stale journal
    head over entries the fresh rank has already flushed — the rank
    re-grows only after its journal drained (rank 0 is never evicted,
    so adoption always makes progress)."""
    queued = {e[0] for e in m.get("stray_ranks", [])}
    for rank in range(m["max_mds"]):
        key = str(rank)
        if key in m["actives"] or rank in queued:
            continue
        if not m["standbys"]:
            break
        m["actives"][key] = m["standbys"].pop(0)
        m["epoch"] += 1


def _mds_table_maybe_stabilize(m: dict) -> None:
    """Expose the latest subtree table to clients once EVERY active
    has flushed under it (two-phase export: the old auth's dirty
    state must reach the backing omap before the new auth serves).
    Undrained stray journals (a shrink's evicted ranks, adopted by
    rank 0 — see _cmd_mds_set_max) hold the table back too: clients
    must not route to the new auth before it replayed the evicted
    rank's client-acked mutations."""
    te = m["table_epoch"]
    if m["subtrees_stable"] == m["subtrees"]:
        return
    if m.get("stray_ranks"):
        return
    if all(
        m["table_acks"].get(e["name"], -1) >= te
        for e in m["actives"].values()
    ):
        m["subtrees_stable"] = dict(m["subtrees"])


def _cmd_mds_beacon(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """MDSMonitor beacon handling (src/mon/MDSMonitor.cc reduced):
    max_mds active ranks + standbys, stale-beacon failover, subtree
    table distribution.  The mdsmap lives on the leader; a fresh
    leader rebuilds it from the next beacons (deviation: not
    paxos-committed — documented in mds package).  Replacing a stale
    active FENCES it (see _fence_mds)."""
    name = cmd["name"]
    addr = cmd["addr"]
    m = _mdsmap_of(mon)
    now = time.time()
    m["beacons"][name] = now
    if cmd.get("adopted_ranks") and m.get("stray_ranks"):
        # rank 0 replayed these evicted ranks' journals (shrink
        # adoption, _cmd_mds_set_max): drain the queue so the
        # re-pinned table can stabilize.  Acks are (rank, gen) pairs
        # — an ack for an OLDER eviction of the same rank does not
        # drain a newer one still awaiting replay
        done = {(int(e[0]), int(e[1])) for e in cmd["adopted_ranks"]}
        m["stray_ranks"] = [
            e for e in m["stray_ranks"] if tuple(e) not in done
        ]
    if "table_epoch" in cmd:
        m["table_acks"][name] = int(cmd["table_epoch"])
        _mds_table_maybe_stabilize(m)
    grace = getattr(mon, "mds_beacon_grace", 4.0)
    entry = {"name": name, "addr": addr,
             "client": cmd.get("client", "")}

    # evict stale actives (fenced) so their ranks become holes
    for rank, e in list(m["actives"].items()):
        if (
            e["name"] != name
            and now - m["beacons"].get(e["name"], 0) > grace
        ):
            _fence_mds(mon, e)
            del m["actives"][rank]
            m["table_acks"].pop(e["name"], None)
            m["epoch"] += 1

    my_rank = next(
        (
            int(r) for r, e in m["actives"].items()
            if e["name"] == name
        ),
        None,
    )
    if my_rank is not None:
        if m["actives"][str(my_rank)]["addr"] != addr:
            m["epoch"] += 1
        m["actives"][str(my_rank)] = entry
    elif entry["client"] and mon.osdmap.is_blocklisted(
        entry["client"]
    ):
        # a shrink/fail-evicted daemon still beaconing under its
        # FENCED identity must not become promotion-eligible:
        # parking it in standbys could re-promote it in this very
        # call (_mds_promote_holes below) while every rados op it
        # issues raises -EBLOCKLISTED — a wedged active that never
        # drains stray_ranks.  Keep it out; the standby reply makes
        # the daemon shed the identity (new_identity) and its next
        # beacon registers a fresh, unfenced standby.
        m["standbys"] = [
            s for s in m["standbys"] if s["name"] != name
        ]
    else:
        if all(s["name"] != name for s in m["standbys"]):
            m["standbys"].append(entry)
            m["epoch"] += 1
        else:
            m["standbys"] = [
                entry if s["name"] == name else s
                for s in m["standbys"]
            ]
    _mds_promote_holes(mon, m)
    _mds_table_maybe_stabilize(m)
    my_rank = next(
        (
            int(r) for r, e in m["actives"].items()
            if e["name"] == name
        ),
        None,
    )
    payload = {
        "state": "active" if my_rank is not None else "standby",
        "rank": -1 if my_rank is None else my_rank,
        "epoch": m["epoch"],
        "subtrees": m["subtrees"],
        "table_epoch": m["table_epoch"],
        "actives": {
            r: e["addr"] for r, e in m["actives"].items()
        },
    }
    if my_rank == 0 and m.get("stray_ranks"):
        # the shrink re-pin target: adopt these evicted ranks'
        # journals before serving their subtrees
        payload["adopt_ranks"] = sorted(m["stray_ranks"])
    return MMonCommandReply(rc=0, outb=json.dumps(payload))


def _cmd_mds_set_max(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """'mds set-max-mds' (fs set max_mds): grow/shrink the active
    rank count; standbys promote into new ranks on their next
    beacons.  Shrinking evicts the highest ranks exactly like
    ``mds fail`` does: the evicted daemon's client id is FENCED (a
    partitioned-but-alive rank must not flush stale state later), its
    subtrees re-pin to 0, and its rank joins ``stray_ranks`` — the
    journal-adoption queue rank 0 drains (replaying the evicted
    rank's unflushed, client-acked mutations) before the re-pinned
    table stabilizes for clients.  The evicted daemon re-registers as
    a standby via its next beacon, shedding the fenced identity on
    the way (mds/server.py demotion path)."""
    m = _mdsmap_of(mon)
    n = int(cmd["max_mds"])
    if n < 1:
        return MMonCommandReply(rc=-22, outs="max_mds >= 1 (-EINVAL)")
    strays = m.setdefault("stray_ranks", [])
    # a grow does NOT drop queued strays: _mds_promote_holes holds
    # the re-grown rank back until its journal adoption drains, so a
    # fresh promotee never races the adopter's replay+trim
    m["max_mds"] = n
    for rank in [r for r in m["actives"] if int(r) >= n]:
        gone = m["actives"].pop(rank)
        _fence_mds(mon, gone)
        m["beacons"].pop(gone["name"], None)
        m["table_acks"].pop(gone["name"], None)
        # one queue entry per rank (promotion is blocked while
        # queued, so the same rank cannot be evicted twice into the
        # queue — the filter is belt-and-suspenders), tagged with a
        # fresh generation so only an ack for THIS eviction drains it
        gen = m["stray_gen"] = m.get("stray_gen", 0) + 1
        strays[:] = [e for e in strays if e[0] != int(rank)]
        strays.append([int(rank), gen])
    changed = False
    for p, r in list(m["subtrees"].items()):
        if r >= n:
            m["subtrees"][p] = 0
            changed = True
    if changed:
        m["table_epoch"] += 1
    _mds_promote_holes(mon, m)
    m["epoch"] += 1
    return MMonCommandReply(
        rc=0, outb=json.dumps({"epoch": m["epoch"]})
    )


def _cmd_mds_pin(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """'mds pin <path> <rank>' — subtree auth delegation (the
    ceph.dir.pin xattr / export_dir surface, src/mds/MDCache.cc
    subtree auth + src/mds/Migrator.cc export, reduced to a table
    flip with a flush barrier): ops under <path> route to <rank>.
    Clients switch only after every active acks the new table
    (see _mds_table_maybe_stabilize)."""
    m = _mdsmap_of(mon)
    path = "/" + "/".join(p for p in cmd["path"].split("/") if p)
    rank = int(cmd["rank"])
    if rank >= m["max_mds"] or rank < 0:
        return MMonCommandReply(
            rc=-22, outs=f"rank {rank} out of range (-EINVAL)"
        )
    if m["subtrees"].get(path) == rank:
        return MMonCommandReply(rc=0, outs="no change")
    m["subtrees"][path] = rank
    m["table_epoch"] += 1
    m["epoch"] += 1
    return MMonCommandReply(
        rc=0,
        outb=json.dumps(
            {"epoch": m["epoch"], "table_epoch": m["table_epoch"]}
        ),
    )


def _cmd_mds_stat(mon: Monitor, cmd: dict) -> MMonCommandReply:
    m = _mdsmap_of(mon)
    return MMonCommandReply(
        rc=0,
        outb=json.dumps(
            {
                "epoch": m["epoch"],
                # rank-0 compat alias for single-MDS callers
                "active": m["actives"].get("0"),
                "actives": m["actives"],
                "standbys": m["standbys"],
                "max_mds": m["max_mds"],
                # clients route by the STABLE table only
                "subtrees": m["subtrees_stable"],
                "table_epoch": m["table_epoch"],
            }
        ),
    )


def _cmd_mds_fail(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """Operator-forced failover: demote (and fence) an active — by
    name, rank, or rank 0 by default; the next standby beacon claims
    the hole."""
    m = _mdsmap_of(mon)
    who = str(cmd.get("who", "0"))
    rank = None
    for r, e in m["actives"].items():
        if r == who or e["name"] == who:
            rank = r
            break
    if rank is None:
        return MMonCommandReply(rc=-2, outs=f"no active {who!r} (-ENOENT)")
    gone = m["actives"].pop(rank)
    _fence_mds(mon, gone)
    m["beacons"].pop(gone["name"], None)
    m["table_acks"].pop(gone["name"], None)
    _mds_promote_holes(mon, m)
    m["epoch"] += 1
    return MMonCommandReply(
        rc=0, outs=f"failed mds {gone['name']}",
        outb=json.dumps({"epoch": m["epoch"]}),
    )


def _pool_by_name(mon: Monitor, name: str):
    for pid, pname in mon.osdmap.pool_names.items():
        if pname == name:
            return pid, mon.osdmap.pools[pid]
    return None, None


def _tier_commit(mon: Monitor, *pools) -> int:
    inc = mon.pending()
    for pid, newp in pools:
        newp.last_change = mon.osdmap.epoch + 1
        inc.new_pools[pid] = newp
    return mon.commit(inc)


def _cmd_osd_tier(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """Cache-tier pool wiring (OSDMonitor's "osd tier add /
    cache-mode / set-overlay / remove-overlay / remove" commands,
    src/mon/OSDMonitor.cc): a CACHE pool fronts a BASE pool; once the
    overlay is set, clients route the base pool's ops to the cache
    (Objecter's read_tier/write_tier redirection)."""
    import copy as _copy

    op = cmd["tierop"]
    bid, base = _pool_by_name(mon, cmd["pool"])
    if base is None:
        return MMonCommandReply(rc=-2, outs=f"no pool {cmd['pool']!r}")
    if op in ("add", "remove", "cache-mode", "set-overlay"):
        cid_, cache = _pool_by_name(mon, cmd["tierpool"])
        if cache is None:
            return MMonCommandReply(
                rc=-2, outs=f"no pool {cmd['tierpool']!r}"
            )
    if op == "add":
        if cache.type != PG_POOL_TYPE_REPLICATED:
            return MMonCommandReply(
                rc=-22, outs="cache tier must be replicated (-EINVAL)"
            )
        if base.type != PG_POOL_TYPE_REPLICATED:
            # deviation: the promote path pulls whole objects via the
            # replicated recovery machinery; an EC base would need
            # per-shard reconstruction on fetch (reject loudly rather
            # than silently -ENOENT every cold read)
            return MMonCommandReply(
                rc=-22,
                outs="tiering over an erasure base pool unsupported "
                "(-EINVAL)",
            )
        nc = _copy.deepcopy(cache)
        nc.tier_of = bid
        epoch = _tier_commit(mon, (cid_, nc))
    elif op == "cache-mode":
        mode = cmd.get("mode", "writeback")
        if mode not in ("writeback", "none"):
            return MMonCommandReply(rc=-22, outs=f"bad mode {mode!r}")
        if mode == "none" and any(
            p.read_tier == cid_ or p.write_tier == cid_
            for p in mon.osdmap.pools.values()
        ):
            # disabling tiering under a live overlay would strand
            # redirected writes in the cache pool (real Ceph: -EBUSY)
            return MMonCommandReply(
                rc=-16, outs="remove the overlay first (-EBUSY)"
            )
        nc = _copy.deepcopy(cache)
        nc.cache_mode = "" if mode == "none" else mode
        epoch = _tier_commit(mon, (cid_, nc))
    elif op == "set-overlay":
        if cache.tier_of != bid:
            return MMonCommandReply(
                rc=-22,
                outs=f"{cmd['tierpool']} is not a tier of {cmd['pool']}",
            )
        nb = _copy.deepcopy(base)
        nb.read_tier = cid_
        nb.write_tier = cid_
        epoch = _tier_commit(mon, (bid, nb))
    elif op == "remove-overlay":
        nb = _copy.deepcopy(base)
        nb.read_tier = -1
        nb.write_tier = -1
        epoch = _tier_commit(mon, (bid, nb))
    elif op == "remove":
        if base.read_tier == cid_:
            return MMonCommandReply(
                rc=-16, outs="remove the overlay first (-EBUSY)"
            )
        nc = _copy.deepcopy(cache)
        nc.tier_of = -1
        nc.cache_mode = ""
        epoch = _tier_commit(mon, (cid_, nc))
    else:
        return MMonCommandReply(rc=-22, outs=f"bad tierop {op!r}")
    return MMonCommandReply(
        rc=0, outb=json.dumps({"epoch": epoch})
    )


def _cmd_mgr_beacon(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """MgrMonitor beacon (src/mon/MgrMonitor.cc reduced): one active
    mgr whose address daemons discover to push MMgrReports."""
    m = getattr(mon, "mgrmap", None)
    if m is None:
        m = mon.mgrmap = {"epoch": 0, "active": None}
    entry = {"name": cmd["name"], "addr": cmd["addr"]}
    if m["active"] != entry:
        m["active"] = entry
        m["epoch"] += 1
    return MMonCommandReply(
        rc=0, outb=json.dumps({"epoch": m["epoch"]})
    )


def _cmd_mgr_stat(mon: Monitor, cmd: dict) -> MMonCommandReply:
    m = getattr(mon, "mgrmap", None) or {"epoch": 0, "active": None}
    return MMonCommandReply(rc=0, outb=json.dumps(m))


def _cmd_pool_set(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """osd pool set <pool> pg_num <n> (OSDMonitor::prepare_command
    pg_num path): increase-only; primaries split their PGs when they
    observe the new map (object re-homing by stable_mod)."""
    name = cmd["pool"]
    var = cmd.get("var", "")
    pool_id = None
    for pid, pname in mon.osdmap.pool_names.items():
        if pname == name:
            pool_id = pid
            break
    if pool_id is None:
        return MMonCommandReply(rc=-2, outs=f"no pool {name!r} (-ENOENT)")
    if var == "target_max_objects":
        import copy as _copy

        newp = _copy.deepcopy(mon.osdmap.pools[pool_id])
        newp.target_max_objects = int(cmd["val"])
        newp.last_change = mon.osdmap.epoch + 1
        inc = mon.pending()
        inc.new_pools[pool_id] = newp
        epoch = mon.commit(inc)
        return MMonCommandReply(
            rc=0, outb=json.dumps({"epoch": epoch})
        )
    if var != "pg_num":
        return MMonCommandReply(rc=-22, outs=f"cannot set {var!r} (-EINVAL)")
    val = int(cmd["val"])
    pool = mon.osdmap.pools[pool_id]
    if val < pool.pg_num:
        return MMonCommandReply(
            rc=-22, outs="pg_num cannot shrink (-EINVAL)"
        )
    if val == pool.pg_num:
        return MMonCommandReply(rc=0, outs="no change")
    if pool.snap_seq or getattr(pool, "snaps", None):
        # splitting migrates heads through the client op path; snap
        # clones have no such path and would strand in the parent
        return MMonCommandReply(
            rc=-95,
            outs="pg_num change on pools with snapshots unsupported "
            "(-EOPNOTSUPP)",
        )
    import copy as _copy

    newp = _copy.deepcopy(pool)
    newp.pg_num = val
    newp.pgp_num = val
    newp.last_change = mon.osdmap.epoch + 1
    inc = mon.pending()
    inc.new_pools[pool_id] = newp
    epoch = mon.commit(inc)
    return MMonCommandReply(
        rc=0,
        outs=f"set pool {name} pg_num to {val}",
        outb=json.dumps({"epoch": epoch}),
    )


def _cmd_sm_snap_create(mon: Monitor, cmd: dict) -> MMonCommandReply:
    """Self-managed snap allocation (OSDMonitor / pg_pool_t
    add_unmanaged_snap): the id is live for clone resolution and
    trimming (recorded with an empty name), but only writers whose
    snapc carries it clone — the pool's named-snap machinery stays
    untouched."""
    pid, pool = _pool_by_name(mon, cmd["pool"])
    if pool is None:
        return MMonCommandReply(rc=-2, outs=f"pool {cmd['pool']!r} not found")
    import copy as _copy

    newpool = _copy.deepcopy(pool)
    newpool.snap_seq += 1
    newpool.snaps[newpool.snap_seq] = ""
    inc = mon.pending()
    inc.new_pools[pid] = newpool
    epoch = mon.commit(inc)
    return MMonCommandReply(
        outb=json.dumps({"snapid": newpool.snap_seq, "epoch": epoch})
    )


def _cmd_sm_snap_rm(mon: Monitor, cmd: dict) -> MMonCommandReply:
    pid, pool = _pool_by_name(mon, cmd["pool"])
    if pool is None:
        return MMonCommandReply(rc=-2, outs=f"pool {cmd['pool']!r} not found")
    snapid = int(cmd["snapid"])
    if snapid not in pool.snaps or pool.snaps[snapid] != "":
        return MMonCommandReply(
            rc=-2, outs=f"no self-managed snap {snapid} (-ENOENT)"
        )
    import copy as _copy

    newpool = _copy.deepcopy(pool)
    del newpool.snaps[snapid]
    inc = mon.pending()
    inc.new_pools[pid] = newpool
    epoch = mon.commit(inc)
    return MMonCommandReply(outb=json.dumps({"epoch": epoch}))


_COMMANDS = {
    "status": _cmd_status,
    "osd down": _cmd_osd_down,
    "osd out": _cmd_osd_out,
    "osd in": _cmd_osd_in,
    "osd reweight": _cmd_osd_reweight,
    "osd blocklist": _cmd_osd_blocklist,
    "osd dump": _cmd_osd_dump,
    "osd pool create": _cmd_pool_create,
    "osd pool delete": _cmd_pool_delete,
    "osd pool mksnap": _cmd_pool_mksnap,
    "osd pool rmsnap": _cmd_pool_rmsnap,
    "osd pg-upmap-items": _cmd_pg_upmap_items,
    "osd erasure-code-profile set": _cmd_ec_profile_set,
    "osd erasure-code-profile get": _cmd_ec_profile_get,
    "osd erasure-code-profile ls": _cmd_ec_profile_ls,
    "osd tree": _cmd_osd_tree,
    "osd pool ls": _cmd_pool_ls,
    "pg dump": _cmd_pg_dump,
    "pgmap report": _cmd_pgmap_report,
    "df": _cmd_df,
    "health": _cmd_health,
    "health mute": _cmd_health_mute,
    "health unmute": _cmd_health_unmute,
    "crash report": _cmd_crash_report,
    "log last": _cmd_log_last,
    "log stat": _cmd_log_stat,
    "log": _cmd_log_inject,
    "osd slow ops": _cmd_osd_slow_ops,
    "osd scrub errors": _cmd_osd_scrub_errors,
    "osd stat report": _cmd_osd_stat_report,
    "osd df": _cmd_osd_df,
    "osd perf": _cmd_osd_perf,
    "slo report": _cmd_slo_report,
    "tell": _cmd_tell,
    "pg scrub": _cmd_pg_scrub,
    "pg deep-scrub": _cmd_pg_scrub,
    "pg repair": _cmd_pg_scrub,
    "config set": _cmd_config_set,
    "config get": _cmd_config_get,
    "config dump": _cmd_config_dump,
    "mds beacon": _cmd_mds_beacon,
    "mds stat": _cmd_mds_stat,
    "mds fail": _cmd_mds_fail,
    "mds set-max-mds": _cmd_mds_set_max,
    "mds pin": _cmd_mds_pin,
    "mgr beacon": _cmd_mgr_beacon,
    "mgr stat": _cmd_mgr_stat,
    "osd pool set": _cmd_pool_set,
    "osd tier": _cmd_osd_tier,
    "osd pool selfmanaged-snap create": _cmd_sm_snap_create,
    "osd pool selfmanaged-snap rm": _cmd_sm_snap_rm,
}


class MonClient(Dispatcher):
    """Daemon-side map follower (MonClient role): subscribe, apply
    pushed full/incremental maps, notify ``on_map(epoch)``."""

    def __init__(self, messenger: Messenger, on_map=None, whoami: int = -1):
        self.messenger = messenger
        self.whoami = whoami
        self.on_map = on_map
        self.osdmap: OSDMap | None = None
        self._conn: Connection | None = None
        self._addrs: list[tuple[str, int]] = []
        self._reconnect_lock = threading.Lock()
        self._lock = threading.Lock()
        self._epoch_event = threading.Condition(self._lock)
        messenger.add_dispatcher(self)

    # -- session -----------------------------------------------------------
    def connect(self, host: str, port: int) -> None:
        if (host, int(port)) not in self._addrs:
            self._addrs.append((host, int(port)))
        self._conn = self.messenger.connect(host, int(port))
        reply = self._conn.call(
            MMonSubscribe(start_epoch=0, from_osd=self.whoami)
        )
        assert isinstance(reply, MOSDMap)
        self._apply(reply)

    def connect_any(self, addrs) -> None:
        """Session to the first reachable monitor of a quorum
        (MonClient::get_monmap_and_config's mon-list behavior)."""
        self._addrs = [(h, int(p)) for h, p in addrs]
        self.ensure_connected()

    def ensure_connected(self) -> None:
        """(Re)establish the mon session, cycling the known monitor
        addresses — the client half of monitor failover."""
        if self._conn is not None and not self._conn.is_closed:
            return
        with self._reconnect_lock:
            if self._conn is not None and not self._conn.is_closed:
                return
            last: Exception | None = None
            for host, port in self._addrs:
                try:
                    conn = self.messenger.connect(host, port)
                    reply = conn.call(
                        MMonSubscribe(
                            start_epoch=0, from_osd=self.whoami
                        )
                    )
                    assert isinstance(reply, MOSDMap)
                    self._conn = conn
                    self._apply(reply)
                    return
                except (MessageError, OSError, AssertionError) as e:
                    last = e
            raise MessageError(f"no monitor reachable: {last}")

    def ms_handle_reset(self, conn: Connection) -> None:
        """Session mon died: re-subscribe elsewhere EAGERLY — a
        client that only watches the map would otherwise go stale
        until its next command (MonClient::_reopen_session)."""
        if conn is not self._conn or not self._addrs:
            return
        if sys.is_finalizing():
            # interpreter teardown: connection resets fire as the GC
            # finalizes the messenger loop, and Thread.start() HANGS
            # during finalization (the new thread never bootstraps) —
            # a short-lived CLI would wedge on exit instead of exiting
            return
        threading.Thread(
            target=self._reconnect_bg,
            name="monc.reconnect",
            daemon=True,
        ).start()

    def _reconnect_bg(self) -> None:
        for _ in range(100):
            try:
                self.ensure_connected()
                return
            except (MessageError, OSError):
                time.sleep(0.2)

    def command(
        self, cmd: dict, timeout: float = 15.0
    ) -> MMonCommandReply:
        """Mon command with failover: retries across monitors on
        connection loss and waits out elections (-EAGAIN replies), the
        MonClient::start_mon_command resend behavior."""
        deadline = time.monotonic() + timeout
        payload = json.dumps(cmd)
        last_err: Exception | None = None
        while True:
            try:
                self.ensure_connected()
                # bound the in-flight call by the caller's deadline
                # too: a mon that accepts TCP but never replies must
                # not hold a timeout=2.0 caller for the default 30s
                reply = self._conn.call(
                    MMonCommand(cmd=payload),
                    timeout=max(
                        0.5, min(30.0, deadline - time.monotonic())
                    ),
                )
                assert isinstance(reply, MMonCommandReply)
                if reply.rc == -11 and "-EAGAIN" in reply.outs:
                    # electing: wait and resend
                    if time.monotonic() >= deadline:
                        return reply
                    time.sleep(0.2)
                    continue
                return reply
            except (MessageError, OSError, AssertionError) as e:
                last_err = e
                if self._conn is not None:
                    self._conn.close()
                if time.monotonic() >= deadline:
                    raise MessageError(
                        f"mon command failed: {last_err}"
                    ) from last_err
                time.sleep(0.2)

    def report_failure(self, target: int, failed_for: float) -> None:
        self.ensure_connected()
        self._conn.send(
            MOSDFailure(
                target=target,
                reporter=self.whoami,
                failed_for=failed_for,
                epoch=self.epoch,
            )
        )

    def send_log(self, entries: list[dict], name: str = "") -> None:
        """Ship a drained LogClient batch to the mon (MLog); raises
        MessageError/OSError on failure so the caller can requeue."""
        if not entries:
            return
        self.ensure_connected()
        self._conn.send(
            MLog(
                tid=self.messenger.new_tid(),
                name=name or (entries[0].get("name", "") if entries else ""),
                entries=json.dumps(entries),
            )
        )

    def boot(self, osd: int, addr: str = "") -> None:
        self.ensure_connected()
        self._conn.send(MOSDBoot(osd=osd, addr=addr))

    @property
    def epoch(self) -> int:
        with self._lock:
            return self.osdmap.epoch if self.osdmap else 0

    def wait_for_epoch(self, epoch: int, timeout: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout
        with self._epoch_event:
            while self.osdmap is None or self.osdmap.epoch < epoch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._epoch_event.wait(remaining)
            return True

    # -- map application ---------------------------------------------------
    def _apply(self, msg: MOSDMap) -> None:
        resubscribe = False
        with self._epoch_event:
            if msg.full:
                self.osdmap = OSDMap.decode(msg.full)
            for blob in msg.incrementals:
                inc = Incremental.decode(blob)
                if self.osdmap is None or inc.epoch > self.osdmap.epoch + 1:
                    resubscribe = True  # gap: need a fresh full map
                    break
                if inc.epoch <= self.osdmap.epoch:
                    continue  # dup push (already ahead)
                self.osdmap.apply_incremental(inc)
            self._epoch_event.notify_all()
        if resubscribe and self._conn is not None:
            # fire-and-forget: the reply dispatches as another MOSDMap
            # (we are on the read-loop thread here; call() would block it)
            self._conn.send(
                MMonSubscribe(
                    tid=self.messenger.new_tid(),
                    start_epoch=0,
                    from_osd=self.whoami,
                )
            )
            return
        if self.on_map is not None and self.osdmap is not None:
            self.on_map(self.osdmap.epoch)

    def ms_dispatch(self, conn: Connection, msg: Message) -> bool:
        if isinstance(msg, MOSDMap):
            self._apply(msg)
            return True
        return False
