"""AsyncMessenger — asyncio connection fabric behind the Messenger
contract (src/msg/Messenger.h:89,393-425; src/msg/async/AsyncMessenger.h).

A Messenger is a lightweight façade over the process-wide
``NetworkStack`` (msg/stack.py — the reference's NetworkStack/Worker
pool): at ``start()`` it checks out ONE shared event-loop worker by
least-connections, and every listener, connection, read loop and
timer of this messenger then multiplexes onto that worker's loop
alongside other daemons' messengers.  ``bind()`` starts a TCP
listener; ``connect()`` dials out.  Both directions speak the same
framed protocol (message.py): a fixed banner exchange, then
crc-framed typed messages.

Dispatch mirrors the reference: inbound messages walk the dispatcher
chain until one claims the type (ms_dispatch); connection teardown
notifies ms_handle_reset.  RPC-style request/reply (the sub-op
pattern) is provided by ``Connection.call`` — the reply is paired by
tid, exactly how ECBackend matches sub-op replies to in-flight ops.

Because the loop is SHARED, dispatch never runs on it: inbound
messages (and reset notifications) drain FIFO through a per-messenger
serial strand on the stack's elastic offload pool — a blocking
handler stalls only its own messenger's queue, never a worker, and
nested blocking RPC from handlers (which would deadlock a read loop
waiting on itself) is safe.  Tid-paired ``call`` replies resolve
directly on the read loop and never wait behind dispatch.

The API is synchronous on purpose: callers (stores, daemons, tests)
are plain Python; every sync call marshals onto the worker loop via
``run_coroutine_threadsafe``.  Per-messenger single-loop affinity is
what keeps the FaultInjector's seeded RNG single-threaded, so chaos
decision streams replay byte-identically on the shared stack.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import os
import threading
import time
import weakref

from .faults import FaultInjector
from .message import Message, MessageError
from .stack import NetworkStack

BANNER = b"ceph-tpu-msgr/2\n"
_CALL_TIMEOUT = 30.0
# bounded inbound dispatch queue (the ms_dispatch_throttle_bytes
# role, counted in messages): when a messenger's dispatch-strand
# backlog reaches the high watermark its socket reads PAUSE — TCP
# flow control pushes back on the senders — and resume once the
# strand drains to the low watermark.  Messages are never dropped;
# stalls are counted (l_msgr_dispatch_queue_stalls).
DISPATCH_QUEUE_HIGH_DEFAULT = 256
# largest ciphertext a peer may announce in secure mode; generous vs
# any legitimate message (multi-MB chunk writes) but far below the
# 4 GiB the u32 prefix could otherwise demand
MAX_FRAME_LEN = 1 << 28


class Dispatcher:
    """The Dispatcher contract (Messenger.h:89): return True from
    ms_dispatch to claim a message."""

    def ms_dispatch(self, conn: "Connection", msg: Message) -> bool:
        raise NotImplementedError

    def ms_handle_reset(self, conn: "Connection") -> None:
        pass


class SecureCtx:
    """Per-connection AEAD state for secure wire mode (the
    ProtocolV2 secure-mode role, src/msg/async/crypto_onwire.cc:1-309,
    with the framework's sha256-CTR+HMAC cipher — CryptoKey, the same
    implementation cephx tickets use — in the AES-GCM seat).

    Keys derive from the cephx session key plus both handshake nonces
    (fresh per connection); each direction gets its own key and an
    implicit strictly-increasing counter — the counter is NOT on the
    wire, so a spliced, replayed, or reordered record fails its MAC
    and drops the connection."""

    def __init__(self, session_key: bytes, challenge: bytes,
                 nonce: bytes, outgoing: bool):
        import hashlib
        import hmac as hmac_mod

        from ..auth.cephx import CryptoKey

        conn_key = hmac_mod.new(
            session_key, b"secure" + challenge + nonce, hashlib.sha256
        ).digest()
        c2s = CryptoKey(
            hmac_mod.new(conn_key, b"c2s", hashlib.sha256).digest()
        )
        s2c = CryptoKey(
            hmac_mod.new(conn_key, b"s2c", hashlib.sha256).digest()
        )
        self._send = c2s if outgoing else s2c
        self._recv = s2c if outgoing else c2s
        self.send_ctr = 0
        self.recv_ctr = 0

    def seal(self, frame: bytes) -> bytes:
        from ..auth.cephx import CryptoKey

        ctr8 = self.send_ctr.to_bytes(8, "little")
        ct = CryptoKey.xor(
            frame, self._send.keystream(ctr8, len(frame))
        )
        clen4 = len(ct).to_bytes(4, "little")
        # the length prefix is part of the MAC'd material: a tampered
        # length cannot steer the receiver even before the tag check
        tag = self._send.hmac(ctr8 + clen4 + ct)
        self.send_ctr += 1
        return clen4 + ct + tag

    def unseal(self, ct: bytes, tag: bytes) -> bytes:
        import hmac as hmac_mod

        from ..auth.cephx import CryptoKey

        ctr8 = self.recv_ctr.to_bytes(8, "little")
        want = self._recv.hmac(
            ctr8 + len(ct).to_bytes(4, "little") + ct
        )
        if not hmac_mod.compare_digest(tag, want):
            raise MessageError(
                "secure frame authentication failed (tampered or "
                "replayed) — dropping connection"
            )
        plain = CryptoKey.xor(
            ct, self._recv.keystream(ctr8, len(ct))
        )
        self.recv_ctr += 1
        return plain


class Connection:
    """One framed peer link (AsyncConnection role)."""

    def __init__(self, msgr: "Messenger", reader, writer, outgoing: bool):
        self.msgr = msgr
        self._reader = reader
        self._writer = writer
        self.outgoing = outgoing
        self.peer_addr = writer.get_extra_info("peername")
        self.peer_entity = ""  # authenticated cephx entity ('' = none)
        # fault-plane destination identity: "host:port" on dialed
        # connections; accepted connections start unlabeled and a
        # higher layer may stamp a daemon name (session handshakes,
        # mon subscriptions) so directional rules can match them
        self.peer_label: str | None = None
        # pending replies are concurrent futures: resolved from the
        # loop thread, awaited from caller threads (thread-safe both
        # ways, unlike asyncio futures)
        self._pending: dict[int, concurrent.futures.Future] = {}
        self._plock = threading.Lock()
        self._closed = False
        self._send_lock = asyncio.Lock()
        self.secure: SecureCtx | None = None

    # -- sync API ----------------------------------------------------------
    def send(self, msg: Message) -> None:
        """Fire-and-forget (Messenger::send_to)."""
        self.msgr._run(self._send(msg))

    def call(
        self, msg: Message, timeout: float = _CALL_TIMEOUT
    ) -> Message:
        """Send and wait for the tid-paired reply (sub-op pattern).
        Raises MessageError on connection loss or timeout.

        Request tids live in direction-disjoint spaces (dialer odd,
        acceptor even) so nested RPC initiated from BOTH ends of one
        socket can never collide in the tid-routed read loops."""
        if msg.tid == 0:
            msg.tid = (
                self.msgr.new_tid()
                if self.outgoing
                else self.msgr.new_even_tid()
            )
        cf: concurrent.futures.Future = concurrent.futures.Future()
        with self._plock:
            if self._closed:
                raise MessageError("connection closed")
            self._pending[msg.tid] = cf
        try:
            self.msgr._run(self._send(msg)).result(timeout)
            return cf.result(timeout)
        except MessageError:
            raise
        except concurrent.futures.TimeoutError as e:
            raise MessageError(f"call tid={msg.tid} timed out") from e
        except (Exception, concurrent.futures.CancelledError) as e:
            # CancelledError is a BaseException; shutdown()'s cancel-all
            # must surface as MessageError in caller threads, not escape
            raise MessageError(
                f"call tid={msg.tid} failed: {type(e).__name__}: {e}"
            ) from e
        finally:
            with self._plock:
                self._pending.pop(msg.tid, None)

    def close(self) -> None:
        if self.msgr._loop is not None and not self._closed:
            self.msgr._run(self._close())

    @property
    def is_closed(self) -> bool:
        return self._closed

    # -- loop-side ---------------------------------------------------------
    async def _send(self, msg: Message) -> None:
        if self._closed:
            raise MessageError("connection closed")
        plan = self.msgr.faults.plan(self)
        if plan.sockfail:
            # legacy ms_inject_socket_failures semantics: tear the
            # connection down instead of transmitting
            await self._close()
            raise MessageError(
                "injected socket failure (ms_inject_socket_failures)"
            )
        if plan.drop:
            return  # netem loss: the frame silently vanishes
        if plan.delay > 0.0:
            # deliver later off a task: ordering vs frames sent in
            # the meantime is deliberately NOT preserved (netem
            # delay/reorder semantics).  Tracked so shutdown cancels
            # it instead of leaving it pending on the SHARED loop.
            self.msgr._spawn(
                self._delayed_send(msg, plan.delay, plan.duplicate)
            )
            return
        await self._write_frame(msg, duplicate=plan.duplicate)

    async def _delayed_send(
        self, msg: Message, delay: float, duplicate: bool
    ) -> None:
        try:
            await asyncio.sleep(delay)
            if not self._closed:
                await self._write_frame(msg, duplicate=duplicate)
        except (asyncio.CancelledError, Exception):  # noqa: BLE001 —
            # a delayed frame racing shutdown/teardown is just lost
            pass

    async def _write_frame(
        self, msg: Message, duplicate: bool = False
    ) -> None:
        # duplication happens at MESSAGE level: each copy is sealed
        # with its own counter in secure mode, so both arrive as
        # valid frames and the receiver's dedup layers really work
        for _ in range(2 if duplicate else 1):
            frame = msg.to_frame()
            async with self._send_lock:
                # seal under the send lock: the implicit counter must
                # match the on-wire record order
                if self.secure is not None:
                    frame = self.secure.seal(frame)
                self._writer.write(frame)
                await self._writer.drain()

    async def _read_loop(self) -> None:
        try:
            while True:
                if self.secure is not None:
                    clen = int.from_bytes(
                        await self._reader.readexactly(4), "little"
                    )
                    # the prefix is plaintext; bound it before
                    # buffering so a tamperer can't force a multi-GiB
                    # allocation or an indefinite readexactly hang
                    # (it is also folded into the MAC, so a forged
                    # length never yields a valid frame)
                    if clen > MAX_FRAME_LEN:
                        raise MessageError(
                            f"secure frame length {clen} exceeds "
                            f"{MAX_FRAME_LEN}"
                        )
                    ct = await self._reader.readexactly(clen)
                    tag = await self._reader.readexactly(32)
                    frame = self.secure.unseal(ct, tag)
                    header = frame[: Message.HEADER_SIZE]
                    mtype, tid, plen = Message.parse_header(header)
                    body = frame[Message.HEADER_SIZE :]
                    if len(body) != plen + 4:
                        raise MessageError("secure frame length")
                else:
                    header = await self._reader.readexactly(
                        Message.HEADER_SIZE
                    )
                    mtype, tid, plen = Message.parse_header(header)
                    if plen > MAX_FRAME_LEN:
                        raise MessageError(
                            f"frame length {plen} exceeds "
                            f"{MAX_FRAME_LEN}"
                        )
                    body = await self._reader.readexactly(plen + 4)
                msg = Message.from_payload(
                    mtype,
                    tid,
                    body[:plen],
                    int.from_bytes(body[plen:], "little"),
                )
                with self._plock:
                    fut = self._pending.pop(tid, None)
                if fut is not None:
                    if not fut.set_running_or_notify_cancel():
                        continue  # caller gave up (timeout)
                    fut.set_result(msg)
                else:
                    self.msgr._dispatch(self, msg)
                    # bounded dispatch queue: past the watermark this
                    # connection stops reading (TCP pushes back on
                    # the peer) until the strand drains — backlog is
                    # bounded without ever dropping a message
                    await self.msgr._maybe_stall_reads()
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            MessageError,
            OSError,
        ):
            pass
        finally:
            await self._close()

    async def _close(self) -> None:
        if self._closed:
            return
        with self._plock:
            self._closed = True
            pending = list(self._pending.values())
            self._pending.clear()
        for fut in pending:
            if fut.set_running_or_notify_cancel():
                fut.set_exception(MessageError("connection reset"))
        try:
            self._writer.close()
        except Exception:
            pass
        else:
            # wait for connection_lost so the transport is truly dead
            # before the loop can be closed — an unfinished transport's
            # __del__ would otherwise call close() on the closed loop
            # (an unraisable "Event loop is closed" at pytest teardown)
            try:
                await asyncio.wait_for(
                    self._writer.wait_closed(), 1.0
                )
            except Exception:
                pass
        self.msgr._conn_reset(self)


class Messenger:
    """Messenger::create + bind/start/shutdown lifecycle.

    ``auth_server`` (a CephxServiceHandler) makes inbound connections
    demand a cephx authorizer after the banner; ``auth_client`` (a
    ticket-holding CephxClientHandler) satisfies such demands on
    outbound connections and verifies the server's proof back (mutual
    auth).  Both None = AUTH_NONE, the reference's
    auth_cluster_required=none mode (AuthRegistry negotiation)."""

    # every live messenger, weakly held — the fault-plane janitor
    # (tests/conftest.py) sweeps leaked rules/partitions off every
    # surviving instance between tests so one test's chaos cannot
    # shadow-fail the next
    _live: "weakref.WeakSet[Messenger]" = weakref.WeakSet()

    def __init__(
        self,
        name: str = "client",
        auth_server=None,
        auth_client=None,
        secure: bool = False,
    ):
        if secure and auth_server is None and auth_client is None:
            raise ValueError(
                "secure mode needs cephx (the session key is the "
                "wire key)"
            )
        self.secure = secure
        self.name = name
        self._loop: asyncio.AbstractEventLoop | None = None
        self._stack: NetworkStack | None = None
        self._worker = None  # the checked-out stack Worker
        self._start_lock = threading.Lock()
        # tasks THIS messenger created on the shared loop (read
        # loops, delayed sends, in-flight dials): shutdown cancels
        # exactly these — never another messenger's
        self._tasks: set = set()
        # dispatch-offload strand (created at start)
        self._dispatch_strand = None
        # bounded dispatch queue: backlog accounting + the read gate
        # every read loop of this messenger awaits while stalled
        self._dispatch_high = max(
            1,
            int(
                os.environ.get(
                    "CEPH_TPU_MSGR_DISPATCH_HIGH",
                    DISPATCH_QUEUE_HIGH_DEFAULT,
                )
            ),
        )
        self._dispatch_low = max(1, self._dispatch_high // 2)
        self._dispatch_depth = 0
        self._depth_lock = threading.Lock()
        self._read_gate: asyncio.Event | None = None
        self._shut = False  # shutdown() is terminal
        self._server: asyncio.AbstractServer | None = None
        self._dispatchers: list[Dispatcher] = []
        self._conns: set[Connection] = set()
        self._tid = 0
        self._tid_lock = threading.Lock()
        self.auth_server = auth_server
        self.auth_client = auth_client
        self.bound_addr: tuple[str, int] | None = None
        # lossless-peer sessions (msg/session.py), created lazily
        self._session_service = None
        self._session_conns: dict[tuple, object] = {}
        self._session_lock = threading.Lock()
        # fault-injection plane (msg/faults.py): netem-style rules,
        # partitions, and the legacy ms_inject_socket_failures knob
        self.faults = FaultInjector(name)
        Messenger._live.add(self)

    @property
    def inject_socket_failures(self) -> int:
        """Legacy knob (ms_inject_socket_failures,
        src/common/options.cc:1087): every Nth outbound frame PER
        CONNECTION tears the connection down instead of sending;
        0 = off.  Lives on the FaultInjector so both fault paths
        share one code path and counter set."""
        return self.faults.socket_failure_every

    @inject_socket_failures.setter
    def inject_socket_failures(self, n: int) -> None:
        self.faults.socket_failure_every = max(0, int(n))

    # -- lossless-peer sessions (ProtocolV2 reconnect/replay role) ---------
    def _sessions(self):
        if self._session_service is None:
            from .session import SessionService

            svc = SessionService(self)
            # envelopes must unwrap before application dispatchers
            self._dispatchers.insert(0, svc)
            self._session_service = svc
        return self._session_service

    def connect_session(self, host: str, port: int, name: str):
        """A lossless-peer connection: survives TCP drops, replays
        unacked messages on reconnect (src/msg/Policy.h
        lossless_peer).  One persistent object per (peer, name)."""
        from .session import SessionConnection

        self._sessions()  # inbound replies need the unwrapper
        key = (host, int(port), name)
        with self._session_lock:
            sc = self._session_conns.get(key)
            if sc is None or sc.is_closed:
                sc = SessionConnection(self, host, int(port), name)
                self._session_conns[key] = sc
            return sc

    def session_client_register(self, conn, sc) -> None:
        self._sessions().client_register(conn, sc)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        with self._start_lock:
            if self._worker is not None:
                return
            if self._shut:
                # TERMINAL shutdown: a background reconnect racing
                # teardown must not resurrect this messenger onto a
                # (possibly different) worker — half its state would
                # still be bound to the old loop
                raise MessageError("messenger shut down")
            while True:
                # a stack latching teardown between instance() and
                # checkout() hands back None: retry on the fresh
                # generation instead of adopting a dying loop
                stack = NetworkStack.instance()
                worker = stack.checkout(self)
                if worker is not None:
                    break
            self._stack = stack
            self._worker = worker
            self._loop = worker.loop
            self._dispatch_strand = stack.offload.strand()
            self._read_gate = asyncio.Event()

    # -- shared-loop task bookkeeping --------------------------------------
    def _track(self, task: asyncio.Task) -> None:
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def _spawn(self, coro) -> asyncio.Task:
        """create_task + track (loop thread only).  Falls back to
        the running loop when shutdown cleared self._loop under a
        task still in flight — the task is tracked either way, so it
        dies with the worker at the latest."""
        loop = self._loop
        if loop is None:
            loop = asyncio.get_running_loop()
        task = loop.create_task(coro)
        self._track(task)
        return task

    def _run_tracked(self, coro, timeout: float):
        """Run a coroutine on the worker loop as a TRACKED task and
        wait for its result — used for dials/binds so an in-flight
        attempt is cancelled by shutdown() instead of lingering on
        the shared loop."""
        loop = self._loop
        if loop is None:
            coro.close()
            raise MessageError("messenger not started")
        cf: concurrent.futures.Future = concurrent.futures.Future()

        def _schedule():
            task = loop.create_task(coro)
            self._track(task)

            def _transfer(t: asyncio.Task):
                if cf.set_running_or_notify_cancel():
                    try:
                        exc = t.exception()
                    except asyncio.CancelledError:
                        # task cancelled (shutdown raced the dial):
                        # surface a catchable error, not the
                        # BaseException-derived CancelledError
                        exc = MessageError("cancelled by shutdown")
                    if exc is not None:
                        cf.set_exception(exc)
                    else:
                        cf.set_result(t.result())

            task.add_done_callback(_transfer)

        try:
            loop.call_soon_threadsafe(_schedule)
        except RuntimeError as e:  # shared loop stopping under us
            coro.close()
            raise MessageError(f"messenger stopping: {e}") from e
        return cf.result(timeout)

    def bind(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        """Listen; returns the bound (host, port)."""
        if self.secure and self.auth_server is None:
            raise ValueError(
                "secure listener needs auth_server (cephx) — it "
                "would otherwise serve PLAINTEXT despite secure=True"
            )
        self.start()
        self._sessions()  # listeners serve lossless-peer handshakes

        async def _serve():
            self._server = await asyncio.start_server(
                self._accept, host, port
            )
            return self._server.sockets[0].getsockname()[:2]

        self.bound_addr = self._run_tracked(_serve(), 10)
        return self.bound_addr

    def connect(
        self, host: str, port: int, timeout: float = 10.0
    ) -> Connection:
        if self.secure and self.auth_client is None:
            raise MessageError(
                "secure dialer needs auth_client (cephx)"
            )
        self.start()

        async def _dial():
            reader, writer = await asyncio.open_connection(host, port)
            try:
                return await _negotiate(reader, writer)
            except BaseException:
                writer.close()
                raise

        async def _negotiate(reader, writer):
            writer.write(BANNER)
            await writer.drain()
            peer = await reader.readexactly(len(BANNER))
            if peer != BANNER:
                raise MessageError("banner mismatch")
            mode = await reader.readexactly(1)
            if self.secure and mode != b"S":
                # a secure-required dialer refuses the downgrade: an
                # on-path attacker rewriting 'S' to 'A'/'N' must not
                # yield a plaintext session
                raise MessageError(
                    "server did not offer secure mode (downgrade "
                    "refused)"
                )
            if mode in (b"A", b"S"):
                # server demands a cephx authorizer; its 16-byte
                # challenge follows (CEPHX_V2 anti-replay)
                challenge = await reader.readexactly(16)
                if self.auth_client is None:
                    raise MessageError(
                        "server requires cephx auth, no ticket held"
                    )
                blob, nonce = self.auth_client.build_authorizer(challenge)
                writer.write(len(blob).to_bytes(4, "little") + blob)
                await writer.drain()
                plen = int.from_bytes(await reader.readexactly(4), "little")
                if plen == 0:
                    raise MessageError("cephx authorizer rejected")
                proof = await reader.readexactly(plen)
                from ..auth.cephx import AuthError

                try:
                    self.auth_client.verify_server(challenge, nonce, proof)
                except AuthError as e:
                    raise MessageError(f"server auth failed: {e}")
            elif mode != b"N":
                raise MessageError("bad auth negotiation byte")
            conn = Connection(self, reader, writer, outgoing=True)
            conn.peer_label = f"{host}:{port}"
            if mode == b"S":
                conn.secure = SecureCtx(
                    self.auth_client.session.secret,
                    challenge,
                    nonce,
                    outgoing=True,
                )
            if self._shut:
                # a dial landing after shutdown's cancel sweep must
                # not register a connection nobody will ever read or
                # close (the fd would leak until stack teardown)
                writer.close()
                raise MessageError("messenger shut down")
            self._register_conn(conn)
            self._spawn(conn._read_loop())
            return conn

        try:
            return self._run_tracked(_dial(), timeout)
        except MessageError:
            raise
        except (Exception, concurrent.futures.CancelledError) as e:
            raise MessageError(
                f"connect {host}:{port} failed: {e}"
            ) from e

    def shutdown(self) -> None:
        with self._start_lock:
            self._shut = True
            if self._worker is None:
                return

        async def _stop():
            if self._server is not None:
                self._server.close()
            for conn in list(self._conns):
                await conn._close()
            if self._server is not None:
                # after the conns: on 3.12+ wait_closed blocks until
                # every connection handler returns, so waiting first
                # would always eat the full timeout
                try:
                    await asyncio.wait_for(
                        self._server.wait_closed(), 1.0
                    )
                except Exception:
                    pass
            # Cancel what THIS messenger still has in flight (dials
            # that never completed, lingering read loops, delayed
            # fault sends) — the loop is shared, so only our own
            # tracked tasks are fair game.
            me = asyncio.current_task()
            pending = [
                t for t in list(self._tasks)
                if t is not me and not t.done()
            ]
            for t in pending:
                t.cancel()
            if pending:
                # BOUNDED: a task slow to honor its cancellation (a
                # banner-less accepted socket mid-timeout, a wedged
                # transport) must not eat the caller's whole shutdown
                # budget — leftovers are already cancelled and die
                # with the worker at stack teardown
                await asyncio.wait(pending, timeout=5.0)

        try:
            self._run(_stop()).result(10)
        finally:
            with self._start_lock:
                stack, worker = self._stack, self._worker
                self._loop = None
                self._worker = None
                self._stack = None
                self._server = None
            if stack is not None:
                # last release tears the worker loops down
                stack.release(worker)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # -- dispatch ----------------------------------------------------------
    def add_dispatcher(self, d: Dispatcher) -> None:
        """add_dispatcher_head: earlier dispatchers see messages first."""
        self._dispatchers.append(d)

    def _dispatch(self, conn: Connection, msg: Message) -> None:
        """Queue one inbound message onto this messenger's dispatch
        strand (the dispatch-offload seam): handlers run FIFO on the
        stack's offload pool, never on the shared worker loop — a
        blocking handler stalls this messenger's queue, not a worker,
        and may safely make nested blocking RPC."""
        worker = self._worker
        if worker is not None:
            worker.count_dispatch()
        strand = self._dispatch_strand
        if strand is None:
            # racing shutdown: nobody left to deliver to
            return
        with self._depth_lock:
            self._dispatch_depth += 1
        stack = self._stack
        if stack is not None:
            stack.perf.inc("l_msgr_dispatch_queue_depth")

        def _run_one():
            try:
                self._dispatch_now(conn, msg)
            finally:
                self._dispatch_done()

        strand.submit(_run_one)

    def _dispatch_done(self) -> None:
        """Backlog drained by one (offload thread): below the low
        watermark, reopen this messenger's read gate so stalled
        socket reads resume."""
        wake = False
        with self._depth_lock:
            self._dispatch_depth -= 1
            gate = self._read_gate
            if (
                gate is not None
                and self._dispatch_depth <= self._dispatch_low
                and not gate.is_set()
            ):
                wake = True
        stack = self._stack
        if stack is not None:
            stack.perf.dec("l_msgr_dispatch_queue_depth")
        if wake:
            loop = self._loop
            if loop is not None:
                try:
                    # Event.set wakes loop futures — loop thread only
                    loop.call_soon_threadsafe(gate.set)
                except RuntimeError:
                    pass  # loop stopping: readers die with it

    @property
    def dispatch_backlog(self) -> int:
        with self._depth_lock:
            return self._dispatch_depth

    async def _maybe_stall_reads(self) -> None:
        """Read-loop side of the bounded dispatch queue (loop
        thread): at/over the high watermark, clear the gate and wait
        for the strand to drain.  Check-and-clear shares the depth
        lock with _dispatch_done's decrement, so a drain racing this
        stall can never strand the gate closed with an empty queue."""
        gate = self._read_gate
        if gate is None:
            return
        with self._depth_lock:
            if self._dispatch_depth < self._dispatch_high:
                return
            gate.clear()
        stack = self._stack
        if stack is not None:
            stack.perf.inc("l_msgr_dispatch_queue_stalls")
        await gate.wait()

    def _dispatch_now(self, conn, msg: Message) -> None:
        # trace propagation (the ZTracer trace-info handoff): a
        # message carrying a span/trace id makes it ambient for its
        # handlers, so spans they open join the sender's trace
        # without every handler re-plumbing the id
        trace = getattr(msg, "trace", "") or getattr(msg, "reqid", "")
        if trace:
            from ..common import tracing

            with tracing.propagate(trace):
                self._dispatch_inner(conn, msg)
        else:
            self._dispatch_inner(conn, msg)

    def _dispatch_inner(self, conn: Connection, msg: Message) -> None:
        for d in self._dispatchers:
            try:
                if d.ms_dispatch(conn, msg):
                    return
            except Exception:  # noqa: BLE001 — a dispatcher must not
                # kill the read loop; the reference logs and drops too
                import traceback

                traceback.print_exc()
                return

    def _register_conn(self, conn: Connection) -> None:
        """Loop-thread bookkeeping for a new live connection."""
        self._conns.add(conn)
        if self._worker is not None:
            self._worker.conn_opened()

    def _conn_reset(self, conn: Connection) -> None:
        if conn in self._conns:
            self._conns.discard(conn)
            if self._worker is not None:
                self._worker.conn_closed()
        # reset notifications ride the dispatch strand so dispatchers
        # observe them AFTER every message already queued from this
        # connection — the ordering inline dispatch used to give
        strand = self._dispatch_strand
        if strand is not None:
            strand.submit(lambda: self._conn_reset_now(conn))
        else:
            self._conn_reset_now(conn)

    def _conn_reset_now(self, conn: Connection) -> None:
        for d in self._dispatchers:
            try:
                d.ms_handle_reset(conn)
            except Exception:
                pass

    # -- internals ---------------------------------------------------------
    def new_tid(self) -> int:
        """Odd tid space: dialer-side requests and fire-and-forget."""
        with self._tid_lock:
            self._tid += 1
            return self._tid * 2 + 1

    def new_even_tid(self) -> int:
        """Even tid space: requests initiated from the ACCEPTING side
        of a connection (e.g. a replica's rollback re-pulls)."""
        with self._tid_lock:
            self._tid += 1
            return self._tid * 2

    def _run(self, coro):
        loop = self._loop
        if loop is None:
            coro.close()  # no loop: silence the never-awaited warning
            raise MessageError("messenger not started")
        try:
            return asyncio.run_coroutine_threadsafe(coro, loop)
        except RuntimeError as e:  # shared loop stopping under us
            coro.close()
            raise MessageError(f"messenger stopping: {e}") from e

    async def _accept(self, reader, writer) -> None:
        # the server spawned this handler as its own task on the
        # shared loop: track it so shutdown() cancels it with the
        # rest of this messenger's work
        self._track(asyncio.current_task())
        peer_entity = ""
        try:
            writer.write(BANNER)
            await writer.drain()
            peer = await asyncio.wait_for(
                reader.readexactly(len(BANNER)), 10
            )
            if peer != BANNER:
                writer.close()
                return
            secure_ctx = None
            if self.auth_server is not None:
                challenge = self.auth_server.make_challenge()
                # 'S' demands cephx AND switches the wire to sealed
                # frames after the handshake (ProtocolV2 secure
                # mode); 'A' is crc mode with cephx
                writer.write(
                    (b"S" if self.secure else b"A") + challenge
                )
                await writer.drain()
                blen = int.from_bytes(
                    await asyncio.wait_for(reader.readexactly(4), 10),
                    "little",
                )
                blob = await asyncio.wait_for(
                    reader.readexactly(blen), 10
                )
                from ..auth.cephx import AuthError

                try:
                    peer_entity, proof, session_key = (
                        self.auth_server.verify_authorizer(
                            blob, challenge
                        )
                    )
                except AuthError:
                    # reject: zero-length proof then close
                    writer.write((0).to_bytes(4, "little"))
                    await writer.drain()
                    writer.close()
                    return
                writer.write(
                    len(proof).to_bytes(4, "little") + proof
                )
                await writer.drain()
                if self.secure:
                    from ..common.encoding import Decoder as _D

                    d = _D(blob)
                    d.bytes()  # ticket blob
                    nonce = d.bytes()  # the client's handshake nonce
                    secure_ctx = SecureCtx(
                        session_key, challenge, nonce, outgoing=False
                    )
            else:
                writer.write(b"N")
                await writer.drain()
        except Exception:
            writer.close()
            return
        conn = Connection(self, reader, writer, outgoing=False)
        conn.secure = secure_ctx
        conn.peer_entity = peer_entity
        self._register_conn(conn)
        await conn._read_loop()


def wait_for(predicate, timeout: float, interval: float = 0.02) -> bool:
    """Poll helper for tests/daemons."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()
