"""Shared-event-loop network stack — the process-wide worker pool
every Messenger multiplexes onto (src/msg/async/Stack.{h,cc}
NetworkStack + Worker; src/msg/async/Event.cc EventCenter).

The reference's AsyncMessenger does NOT give each messenger its own
thread: one NetworkStack owns ``ms_async_op_threads`` epoll workers,
and every daemon's messenger binds/dials *through* a worker — which
is what lets one host run hundreds of daemons without hundreds of
reactor threads.  This module renders that shape over asyncio:

- ``Worker``     one asyncio loop on one daemon thread (the
                 EventCenter seat).  Messengers check out a worker at
                 ``start()`` by least-connections; every connection,
                 read loop, timer and send of that messenger then
                 lives on that worker's loop.  One-messenger-one-
                 worker (rather than per-connection scatter) is
                 deliberate: it keeps the FaultInjector's seeded RNG
                 single-threaded per messenger, so chaos decision
                 streams replay byte-identically (tests/chaos.py
                 scenario_lossy_link's contract).
- ``NetworkStack``  the process singleton: lazily spawns up to
                 ``CEPH_TPU_MSGR_WORKERS`` workers (default
                 ~min(cpu, 8)), refcounts live messengers, and tears
                 every loop down when the last messenger shuts down
                 (so pytest sessions never leak reactor threads).
- ``OffloadPool``  the dispatch-offload seam: inbound dispatch NEVER
                 runs on a worker loop (a blocking handler would
                 stall every messenger sharing that worker — the
                 exact cross-daemon coupling the per-messenger-loop
                 design never had).  Each messenger drains its
                 dispatch queue FIFO through a serial strand on this
                 pool, so a wedged handler stalls only its own
                 messenger's queue.  The pool is ELASTIC with idle
                 reaping: threads spawn when every existing one is
                 busy (nested blocking RPC between daemons can never
                 starve the pool into deadlock) and exit after
                 ``idle`` seconds, so steady-state thread count stays
                 small and independent of daemon count.
- ``Timers``     shared periodic callbacks riding the worker loops
                 (``loop.call_later``), fired onto the offload pool
                 with an overlap guard — the shared-services seat
                 daemon tick/report loops move onto at scale.

Telemetry: ``build_stack_perf`` declares the ``l_msgr_worker_*``
family (per-worker connections / dispatch counts / loop lag plus the
process aggregates); the live stack updates it and daemons merge
``stack_perf_dump()`` into their MMgrReport perf push, so the series
ride the existing perf → MMgrReport → prometheus pipe exactly like
the fault-plane counters.
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import os
import threading
import time

from ..common.perf_counters import PerfCountersBuilder

# worker count: ~cpu cores, capped — 8 loops already multiplex
# hundreds of daemons and the virtual-mesh CI boxes report many more
# cores than they schedule
MAX_WORKERS_DEFAULT = 8
OFFLOAD_MAX_DEFAULT = 512  # runaway backstop, not a working limit
OFFLOAD_IDLE_DEFAULT = 5.0  # seconds an offload thread waits for
# work before exiting (steady-state pool shrinks back after storms)

# loop-lag sampling period: cheap enough to always run, long enough
# to never matter
_LAG_PROBE_PERIOD = 0.5


def default_workers() -> int:
    env = os.environ.get("CEPH_TPU_MSGR_WORKERS")
    if env:
        return max(1, int(env))
    return max(2, min(os.cpu_count() or 4, MAX_WORKERS_DEFAULT))


def build_stack_perf(n_workers: int):
    """The shared-stack counter schema (l_msgr_worker_* family) —
    module-level so tools/check_metrics.py lints it without a live
    stack.  Per-worker series carry the worker index in the name
    (``l_msgr_worker0_connections``); the index-free names are the
    process aggregates the dashboards alert on."""
    b = (
        PerfCountersBuilder("msgr.stack")
        .add_u64_gauge(
            "l_msgr_workers", "event-loop workers started"
        )
        .add_u64_gauge(
            "l_msgr_worker_connections",
            "open connections across all workers",
        )
        .add_u64_counter(
            "l_msgr_worker_dispatch",
            "messages dispatched across all workers",
        )
        .add_u64_gauge(
            "l_msgr_worker_loop_lag",
            "worst worker event-loop lag (ms) at the last probe",
        )
        .add_u64_gauge(
            "l_msgr_offload_threads",
            "live dispatch-offload threads",
        )
        .add_u64_gauge(
            "l_msgr_offload_threads_peak",
            "dispatch-offload thread high-water mark",
        )
        .add_u64_gauge(
            "l_msgr_dispatch_queue_depth",
            "inbound messages queued on dispatch strands across "
            "all messengers",
        )
        .add_u64_counter(
            "l_msgr_dispatch_queue_stalls",
            "read-loop pauses: a messenger's dispatch backlog "
            "crossed the high watermark and its socket reads "
            "stalled until the strand drained",
        )
    )
    for i in range(n_workers):
        b.add_u64_gauge(
            f"l_msgr_worker{i}_connections",
            f"open connections on worker {i}",
        )
        b.add_u64_counter(
            f"l_msgr_worker{i}_dispatch",
            f"messages dispatched from worker {i}",
        )
        b.add_u64_gauge(
            f"l_msgr_worker{i}_loop_lag",
            f"event-loop lag (ms) on worker {i} at the last probe",
        )
    return b.create_perf_counters()


class Worker:
    """One asyncio loop on one daemon thread (the EventCenter /
    Worker seat).  Counters are mutated from the loop thread and the
    stack lock's owners; PerfCounters itself is lock-guarded."""

    def __init__(self, stack: "NetworkStack", idx: int):
        self.stack = stack
        self.idx = idx
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(
            target=self.loop.run_forever,
            name=f"msgr-worker-{idx}",
            daemon=True,
        )
        self.messengers = 0  # facades checked out here
        self.connections = 0  # open conns (least-connections metric)
        self.lag_ms = 0.0
        self._lag_handle = None

    def start(self) -> None:
        self.thread.start()
        self.loop.call_soon_threadsafe(self._arm_lag_probe)

    # -- loop-lag probe (loop thread) ---------------------------------------
    def _arm_lag_probe(self) -> None:
        expected = time.monotonic() + _LAG_PROBE_PERIOD
        self._lag_handle = self.loop.call_later(
            _LAG_PROBE_PERIOD, self._lag_probe, expected
        )

    def _lag_probe(self, expected: float) -> None:
        self.lag_ms = max(0.0, (time.monotonic() - expected) * 1000.0)
        perf = self.stack.perf
        perf.set(f"l_msgr_worker{self.idx}_loop_lag", self.lag_ms)
        perf.set(
            "l_msgr_worker_loop_lag",
            max(w.lag_ms for w in self.stack.workers),
        )
        self._arm_lag_probe()

    # -- accounting ---------------------------------------------------------
    def conn_opened(self) -> None:
        self.connections += 1
        perf = self.stack.perf
        perf.inc(f"l_msgr_worker{self.idx}_connections")
        perf.inc("l_msgr_worker_connections")

    def conn_closed(self) -> None:
        self.connections -= 1
        perf = self.stack.perf
        perf.dec(f"l_msgr_worker{self.idx}_connections")
        perf.dec("l_msgr_worker_connections")

    def count_dispatch(self) -> None:
        perf = self.stack.perf
        perf.inc(f"l_msgr_worker{self.idx}_dispatch")
        perf.inc("l_msgr_worker_dispatch")

    def stop(self) -> None:
        async def _halt():
            if self._lag_handle is not None:
                self._lag_handle.cancel()
            me = asyncio.current_task()
            tasks = [
                t for t in asyncio.all_tasks(self.loop) if t is not me
            ]
            for task in tasks:
                task.cancel()
            if tasks:
                # let cancellations actually deliver before the loop
                # dies — stopping in the same beat would strand them
                # as "Task was destroyed but it is pending"
                await asyncio.wait(tasks, timeout=1.0)

        try:
            asyncio.run_coroutine_threadsafe(
                _halt(), self.loop
            ).result(3.0)
        except (RuntimeError, concurrent.futures.TimeoutError,
                concurrent.futures.CancelledError):
            pass
        try:
            self.loop.call_soon_threadsafe(self.loop.stop)
        except RuntimeError:
            return  # already closed
        self.thread.join(timeout=5)
        try:
            self.loop.close()
        except RuntimeError:
            pass


class OffloadPool:
    """Elastic thread pool with idle reaping — the dispatch-offload
    seam.  Unlike a fixed ThreadPoolExecutor, a task submitted while
    every thread is blocked spawns a NEW thread (up to a runaway
    backstop far above any sane working set): daemons' dispatch
    handlers make nested blocking RPC to each other, and a fixed pool
    exhausted by blocked handlers could deadlock the whole cluster.
    Idle threads exit after ``idle`` seconds, so the pool's
    steady-state size tracks concurrent *blockage*, not daemon
    count."""

    def __init__(
        self,
        max_threads: int = OFFLOAD_MAX_DEFAULT,
        idle: float = OFFLOAD_IDLE_DEFAULT,
        perf=None,
    ):
        self.max_threads = max_threads
        self.idle = idle
        self.perf = perf
        self._lock = threading.Lock()
        self._work: collections.deque = collections.deque()
        # LIFO handoff: submit wakes the MOST-RECENTLY idled thread.
        # FIFO (a plain condvar) would rotate a steady trickle of
        # work across every thread, resetting all their idle timers —
        # a post-storm pool would then never shrink.  With LIFO a
        # small hot set serves the trickle and the cold surplus
        # actually times out.
        self._idle_stack: list[threading.Event] = []
        self._threads = 0
        self._peak = 0
        self._seq = 0
        self._shutdown = False

    @property
    def size(self) -> int:
        with self._lock:
            return self._threads

    @property
    def peak(self) -> int:
        with self._lock:
            return self._peak

    def submit(self, fn) -> None:
        with self._lock:
            if self._shutdown:
                return
            self._work.append(fn)
            if self._idle_stack:
                self._idle_stack.pop().set()  # newest waiter (LIFO)
                return
            if self._threads >= self.max_threads:
                return  # queued; a busy thread will get to it
            self._threads += 1
            self._peak = max(self._peak, self._threads)
            self._seq += 1
            name = f"msgr-offload-{self._seq}"
            if self.perf is not None:
                self.perf.set("l_msgr_offload_threads", self._threads)
                self.perf.set("l_msgr_offload_threads_peak", self._peak)
        threading.Thread(
            target=self._run, name=name, daemon=True
        ).start()

    def _run(self) -> None:
        ev = threading.Event()
        while True:
            fn = None
            with self._lock:
                if self._work:
                    fn = self._work.popleft()
                elif self._shutdown:
                    self._exit_locked()
                    return
                else:
                    ev.clear()
                    self._idle_stack.append(ev)
            if fn is None:
                signalled = ev.wait(self.idle)
                with self._lock:
                    if not signalled and not ev.is_set():
                        # true timeout: deregister and reap (submit
                        # sets the event under the lock, so is_set
                        # here is authoritative)
                        try:
                            self._idle_stack.remove(ev)
                        except ValueError:
                            pass
                        if not self._work:
                            self._exit_locked()
                            return
                continue
            try:
                fn()
            except Exception:  # noqa: BLE001 — an offload task must
                # never kill its carrier thread
                import traceback

                traceback.print_exc()

    def _exit_locked(self) -> None:
        self._threads -= 1
        if self.perf is not None:
            self.perf.set("l_msgr_offload_threads", self._threads)

    def strand(self) -> "Strand":
        return Strand(self)

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            self._work.clear()
            while self._idle_stack:
                self._idle_stack.pop().set()


class Strand:
    """Serial execution lane over an OffloadPool (the boost.asio
    strand idiom): tasks run FIFO, one at a time, but on whatever
    pool thread is free — per-daemon ordering without per-daemon
    threads."""

    def __init__(self, pool: OffloadPool):
        self._pool = pool
        self._lock = threading.Lock()
        self._q: collections.deque = collections.deque()
        self._busy = False

    def submit(self, fn) -> None:
        with self._lock:
            self._q.append(fn)
            if self._busy:
                return
            self._busy = True
        self._pool.submit(self._drain)

    def _drain(self) -> None:
        while True:
            with self._lock:
                if not self._q:
                    self._busy = False
                    return
                fn = self._q.popleft()
            try:
                fn()
            except Exception:  # noqa: BLE001 — a strand task must not
                # wedge the lane behind it
                import traceback

                traceback.print_exc()

    @property
    def idle(self) -> bool:
        with self._lock:
            return not self._busy and not self._q


class _TimerHandle:
    def __init__(self, timers: "Timers"):
        self._timers = timers
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class Timers:
    """Periodic callbacks on the shared worker loops, executed on the
    offload pool with an overlap guard (a slow callback skips beats
    instead of stacking) — the shared-services replacement for
    per-daemon tick/report threads."""

    def __init__(self, stack: "NetworkStack"):
        self._stack = stack
        self._rr = 0

    def _a_loop(self):
        workers = self._stack.workers
        if not workers:
            return None
        self._rr = (self._rr + 1) % len(workers)
        return workers[self._rr].loop

    def every(
        self, period: float, fn, fire_now: bool = False
    ) -> _TimerHandle:
        """Run ``fn`` on the offload pool every ``period`` seconds.
        A still-running previous firing makes the beat skip (never
        two concurrent runs of one registration)."""
        handle = _TimerHandle(self)
        running = {"flag": False}

        def fire():
            if handle.cancelled:
                return
            if not running["flag"]:
                running["flag"] = True

                def run():
                    try:
                        if not handle.cancelled:
                            fn()
                    finally:
                        running["flag"] = False

                self._stack.offload.submit(run)
            arm()

        def arm():
            loop = self._a_loop()
            if loop is None or handle.cancelled:
                return
            try:
                loop.call_soon_threadsafe(
                    loop.call_later, period, fire
                )
            except RuntimeError:
                pass  # stack torn down under us

        if fire_now:
            fire()
        else:
            arm()
        return handle

    def after(self, delay: float, fn) -> _TimerHandle:
        """One-shot: run ``fn`` on the offload pool after ``delay``."""
        handle = _TimerHandle(self)

        def fire():
            if not handle.cancelled:
                self._stack.offload.submit(fn)

        loop = self._a_loop()
        if loop is not None:
            try:
                loop.call_soon_threadsafe(
                    loop.call_later, delay, fire
                )
            except RuntimeError:
                pass
        return handle


class NetworkStack:
    """The process-wide stack singleton.  Messengers check workers
    out at start() and release them at shutdown(); the last release
    stops every worker loop and drops the singleton, so test
    processes never accumulate reactor threads across cases."""

    _instance: "NetworkStack | None" = None
    _instance_lock = threading.Lock()

    def __init__(self, n_workers: int | None = None):
        self.n_workers = n_workers or default_workers()
        self.perf = build_stack_perf(self.n_workers)
        self.workers: list[Worker] = []
        self.offload = OffloadPool(
            max_threads=int(
                os.environ.get(
                    "CEPH_TPU_MSGR_OFFLOAD_MAX", OFFLOAD_MAX_DEFAULT
                )
            ),
            perf=self.perf,
        )
        self.timers = Timers(self)
        self._lock = threading.Lock()
        self._refs = 0
        self._dead = False  # teardown latched; checkouts must retry

    # -- singleton ----------------------------------------------------------
    @classmethod
    def instance(cls) -> "NetworkStack":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def live(cls) -> "NetworkStack | None":
        """The current stack if any messenger holds it (telemetry
        readers must not create one as a side effect)."""
        with cls._instance_lock:
            return cls._instance

    # -- checkout / release -------------------------------------------------
    def checkout(self, _msgr) -> Worker | None:
        """Least-connections worker selection (the reference's
        Stack::get_worker policy): prefer an idle started worker,
        grow the pool while under the cap, else the worker carrying
        the fewest connections (messengers as tiebreak).  Returns
        None when this stack latched teardown between the caller's
        instance() and this call — the caller re-fetches a fresh
        instance and retries."""
        with self._lock:
            if self._dead:
                return None
            self._refs += 1
            idle = [w for w in self.workers if w.messengers == 0]
            if idle:
                worker = idle[0]
            elif len(self.workers) < self.n_workers:
                worker = Worker(self, len(self.workers))
                worker.start()
                self.workers.append(worker)
                self.perf.set("l_msgr_workers", len(self.workers))
            else:
                worker = min(
                    self.workers,
                    key=lambda w: (w.connections, w.messengers),
                )
            worker.messengers += 1
            return worker

    def release(self, worker: Worker | None) -> None:
        teardown = False
        with self._lock:
            if worker is not None:
                worker.messengers -= 1
            self._refs -= 1
            if self._refs <= 0:
                # latch: a concurrent checkout() racing this release
                # now gets None and retries against a FRESH instance
                # instead of checking out of a dying stack
                self._dead = True
                teardown = True
        if teardown:
            with NetworkStack._instance_lock:
                if NetworkStack._instance is self:
                    NetworkStack._instance = None
            self._teardown()

    def _teardown(self) -> None:
        self.offload.shutdown()
        for w in self.workers:
            w.stop()
        self.workers = []
        self.perf.set("l_msgr_workers", 0)

    # -- introspection ------------------------------------------------------
    def thread_count(self) -> int:
        """Worker + offload threads this stack currently owns — the
        messenger plane's entire thread bill."""
        with self._lock:
            n = len(self.workers)
        return n + self.offload.size


def stack_perf_dump() -> dict:
    """Flat l_msgr_worker_* entries for the MMgrReport perf merge
    (the kernel_stats().dump() idiom); {} when no stack is live."""
    stack = NetworkStack.live()
    if stack is None:
        return {}
    return stack.perf.dump()
