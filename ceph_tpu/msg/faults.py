"""Fault-injection network plane — netem-style, deterministic,
runtime-controlled (the ms_inject_* option family of
src/common/options.cc:1080-1100 grown into a rule engine; the
qa/tasks netem/partition thrashers' role in-process).

One ``FaultInjector`` hangs off every ``Messenger``; every outbound
frame consults it on the loop thread.  Rules are **directional**:
they apply to what THIS messenger sends toward a destination — a
one-way (asymmetric) lossy link is one rule on one messenger, a
symmetric netsplit is the same partition installed on every member.

Vocabulary (one ``FaultRule`` may combine all of them):

- ``drop``     probability a frame silently vanishes (netem loss);
- ``delay``    fixed per-frame latency, ``jitter`` adds U(0, jitter);
- ``reorder``  probability a frame is held back an extra window so it
               overtakes later frames (netem reorder);
- ``dup``      probability a frame is transmitted twice (netem
               duplicate — duplicated at MESSAGE level, so secure
               mode seals each copy with its own counter and the
               receiver's dedup layers are really exercised);
- partition groups: named sets of daemon names; a frame crossing
  group boundaries is dropped (a netsplit in one call).

Destinations are matched by the connection's ``peer_label`` — the
dialed ``host:port`` for outbound connections, a daemon name where a
higher layer stamped one (session handshakes carry the dialer's
name; the monitor stamps subscribers) — plus any name ``alias``-ed
to that address, so rules can say ``osd.1`` instead of a port.

Determinism: every probabilistic decision draws from ONE seeded RNG,
consumed only on the messenger loop thread, with a FIXED number of
draws per (rule, send) — so a chaos run with a pinned seed replays
the identical decision stream for the identical send sequence.  The
bounded ``decisions`` log makes that replay assertable.

Counters (``l_msgr_fault_dropped/_delayed/_duplicated``) flow through
the existing perf → MMgrReport → prometheus pipe; ``fault set/clear/
list`` is served over the admin socket and the ``ceph tell <daemon>
fault ...`` route.

The legacy ``ms_inject_socket_failures`` knob (every Nth send tears
the connection down) lives here too, as a special rule whose counter
is **per connection** — the old Messenger-global unlocked counter
made concurrent senders skip or double-fire injection windows.
"""

from __future__ import annotations

import itertools
import threading
import zlib
from collections import deque
from dataclasses import dataclass, field
from random import Random

from ..common.perf_counters import PerfCountersBuilder

# extra hold-back applied to a reordered frame when the rule carries
# no base delay (it must overtake SOMETHING)
REORDER_WINDOW = 0.05


def build_msgr_perf(name: str):
    """The messenger fault-plane counter schema (l_msgr_* block) —
    module-level so tools/check_metrics.py lints it without a
    messenger."""
    return (
        PerfCountersBuilder(f"msgr.{name}")
        .add_u64_counter("fault_dropped", "frames dropped by injection")
        .add_u64_counter("fault_delayed", "frames delayed by injection")
        .add_u64_counter(
            "fault_duplicated", "frames duplicated by injection"
        )
        .add_u64_counter(
            "fault_socket_failures",
            "connections torn down by ms_inject_socket_failures",
        )
        .create_perf_counters()
    )


@dataclass
class FaultRule:
    """One directional netem rule (what this messenger sends toward
    ``dst``; ``"*"`` matches every destination)."""

    rule_id: int
    dst: str = "*"
    drop: float = 0.0
    delay: float = 0.0
    jitter: float = 0.0
    dup: float = 0.0
    reorder: float = 0.0

    def describe(self) -> dict:
        return {
            "id": self.rule_id,
            "dst": self.dst,
            "drop": self.drop,
            "delay": self.delay,
            "jitter": self.jitter,
            "dup": self.dup,
            "reorder": self.reorder,
        }


@dataclass
class FaultAction:
    """The verdict for one send."""

    drop: bool = False
    sockfail: bool = False
    delay: float = 0.0
    duplicate: bool = False


@dataclass
class _Partition:
    name: str
    groups: list = field(default_factory=list)  # list[frozenset[str]]


class FaultInjector:
    """Per-messenger fault plane.  The RNG and counters are touched
    only on the messenger's loop thread (``plan`` runs inside
    ``Connection._send``); the configuration surface (rules/
    partitions/aliases) is mutated from OTHER threads (admin socket,
    `ceph tell`, test drivers) — ``_mut_lock`` guards it so ``plan``
    never iterates a container mid-mutation."""

    def __init__(
        self,
        name: str,
        seed: int | None = None,
        rng: Random | None = None,
    ):
        self.name = name
        self._mut_lock = threading.Lock()
        self._rule_seq = itertools.count(1)
        self._rules: dict[int, FaultRule] = {}
        self._partitions: dict[str, _Partition] = {}
        # name -> "host:port" (so rules/partitions can say "osd.1")
        self._aliases: dict[str, str] = {}
        self._names_by_addr: dict[str, set[str]] = {}
        # legacy ms_inject_socket_failures: every Nth send PER
        # CONNECTION tears the connection down (0 = off)
        self.socket_failure_every = 0
        self.perf = build_msgr_perf(name)
        # bounded decision trace — the replay-determinism witness
        self.decisions: deque = deque(maxlen=512)
        self.reseed(seed, rng=rng)

    # -- configuration ------------------------------------------------------
    def reseed(
        self, seed: int | None = None, rng: Random | None = None
    ) -> None:
        """Pin the decision stream.  The messenger name folds into
        the seed so every daemon draws an independent but
        reproducible stream from one cluster-wide seed.  A harness
        that wants to OWN the stream (the qa thrasher's
        single-source-of-randomness contract) can inject its
        ``rng`` instead; there is deliberately no module-global
        fallback anywhere in this file."""
        base = 0 if seed is None else int(seed)
        self.seed = base
        self._rng = (
            rng
            if rng is not None
            else Random((base << 32) ^ zlib.crc32(self.name.encode()))
        )
        self.decisions.clear()

    def alias(self, name: str, addr: str) -> None:
        """Register daemon name -> "host:port" so rules match names."""
        with self._mut_lock:
            old = self._aliases.get(name)
            if old is not None:
                self._names_by_addr.get(old, set()).discard(name)
            self._aliases[name] = addr
            self._names_by_addr.setdefault(addr, set()).add(name)

    def add_rule(
        self,
        dst: str = "*",
        drop: float = 0.0,
        delay: float = 0.0,
        jitter: float = 0.0,
        dup: float = 0.0,
        reorder: float = 0.0,
    ) -> int:
        rule = FaultRule(
            rule_id=next(self._rule_seq),
            dst=str(dst),
            drop=max(0.0, min(1.0, float(drop))),
            delay=max(0.0, float(delay)),
            jitter=max(0.0, float(jitter)),
            dup=max(0.0, min(1.0, float(dup))),
            reorder=max(0.0, min(1.0, float(reorder))),
        )
        with self._mut_lock:
            self._rules[rule.rule_id] = rule
        return rule.rule_id

    def clear(self, rule_id: int | None = None) -> int:
        """Remove one rule, or everything (rules AND partitions)."""
        with self._mut_lock:
            if rule_id is not None:
                return 1 if self._rules.pop(int(rule_id), None) else 0
            n = len(self._rules) + len(self._partitions)
            self._rules.clear()
            self._partitions.clear()
            return n

    def set_partition(self, name: str, groups) -> None:
        """A named netsplit: ``groups`` is a list of daemon-name
        lists; traffic between members of DIFFERENT groups drops.
        Install the same partition on every member messenger for a
        symmetric split."""
        part = _Partition(
            name=str(name),
            groups=[frozenset(str(m) for m in g) for g in groups],
        )
        with self._mut_lock:
            self._partitions[part.name] = part

    def clear_partition(self, name: str) -> int:
        with self._mut_lock:
            return 1 if self._partitions.pop(str(name), None) else 0

    def list_rules(self) -> dict:
        with self._mut_lock:
            return self._list_rules_locked()

    def _list_rules_locked(self) -> dict:
        return {
            "seed": self.seed,
            "rules": [
                r.describe() for r in self._rules.values()
            ],
            "partitions": {
                p.name: [sorted(g) for g in p.groups]
                for p in self._partitions.values()
            },
            "socket_failure_every": self.socket_failure_every,
            "aliases": dict(self._aliases),
        }

    @property
    def active(self) -> bool:
        return bool(
            self._rules
            or self._partitions
            or self.socket_failure_every
        )

    # -- matching -----------------------------------------------------------
    def _labels_of(self, conn) -> set[str]:
        label = getattr(conn, "peer_label", None)
        if not label:
            return set()
        labels = {label}
        labels |= self._names_by_addr.get(label, set())
        addr = self._aliases.get(label)
        if addr:
            labels.add(addr)
        return labels

    def _partition_blocks(self, labels: set[str]) -> bool:
        for part in self._partitions.values():
            mine = next(
                (g for g in part.groups if self.name in g), None
            )
            if mine is None:
                continue
            for g in part.groups:
                if g is mine:
                    continue
                if labels & g:
                    return True
        return False

    # -- the per-send verdict (loop thread only) ----------------------------
    def plan(self, conn) -> FaultAction:
        act = FaultAction()
        n = self.socket_failure_every
        if n:
            # per-connection counter: concurrent senders on OTHER
            # connections can no longer skip or double-fire this
            # connection's injection window (and the loop thread
            # serializes each connection's sends anyway)
            count = getattr(conn, "_sockfail_count", 0) + 1
            conn._sockfail_count = count
            if count % n == 0:
                act.sockfail = True
                self.perf.inc("fault_socket_failures")
                self._log(conn, "sockfail")
                return act
        if not self._rules and not self._partitions:
            return act
        # snapshot the configuration under the lock: admin-socket /
        # tell / test threads mutate these containers while the loop
        # thread plans
        with self._mut_lock:
            labels = self._labels_of(conn)
            blocked = self._partition_blocks(labels)
            rules = list(self._rules.values())
        if blocked:
            act.drop = True
            self.perf.inc("fault_dropped")
            self._log(conn, "partition-drop")
            return act
        rng = self._rng
        for rule in rules:
            if rule.dst != "*" and rule.dst not in labels:
                continue
            # one draw per declared facet, unconditionally — the
            # draw COUNT must not depend on earlier outcomes or the
            # seeded stream desynchronizes across replays
            if rule.drop and rng.random() < rule.drop:
                act.drop = True
            if rule.delay or rule.jitter:
                act.delay += rule.delay + (
                    rng.uniform(0.0, rule.jitter)
                    if rule.jitter
                    else 0.0
                )
            if rule.reorder and rng.random() < rule.reorder:
                act.delay += max(REORDER_WINDOW, act.delay)
            if rule.dup and rng.random() < rule.dup:
                act.duplicate = True
        if act.drop:
            act.delay = 0.0
            act.duplicate = False
            self.perf.inc("fault_dropped")
            self._log(conn, "drop")
            return act
        if act.delay > 0.0:
            self.perf.inc("fault_delayed")
        if act.duplicate:
            self.perf.inc("fault_duplicated")
        if act.delay > 0.0 or act.duplicate:
            self._log(
                conn,
                f"delay={act.delay:.6f}"
                + (" dup" if act.duplicate else ""),
            )
        return act

    def _log(self, conn, what: str) -> None:
        self.decisions.append(
            (getattr(conn, "peer_label", None) or "?", what)
        )

    # -- command surface (admin socket + `ceph tell <daemon> fault`) --------
    def command(self, args: dict) -> dict:
        """One `fault ...` command; ``args`` is the JSON command dict
        minus its prefix, plus ``op`` = set | clear | list | seed.
        Returns a JSON-able reply (raises ValueError on bad input)."""
        op = str(args.get("op", "list"))
        if op == "list":
            return self.list_rules()
        if op == "seed":
            self.reseed(int(args["seed"]))
            return {"seed": self.seed}
        if op == "set":
            if "partition" in args:
                groups = args.get("groups") or []
                if not isinstance(groups, list) or not all(
                    isinstance(g, (list, tuple)) for g in groups
                ):
                    raise ValueError(
                        "partition groups must be a list of lists"
                    )
                self.set_partition(args["partition"], groups)
                return {"partition": str(args["partition"])}
            rule_id = self.add_rule(
                dst=args.get("dst", "*"),
                drop=args.get("drop", 0.0),
                delay=args.get("delay", 0.0),
                jitter=args.get("jitter", 0.0),
                dup=args.get("dup", 0.0),
                reorder=args.get("reorder", 0.0),
            )
            return {"rule_id": rule_id}
        if op == "clear":
            if "partition" in args:
                return {
                    "cleared": self.clear_partition(args["partition"])
                }
            if "id" in args:
                return {"cleared": self.clear(int(args["id"]))}
            return {"cleared": self.clear()}
        raise ValueError(f"unknown fault op {op!r}")

    def register_admin_commands(self, asok) -> None:
        """`fault set/clear/list` over the admin socket (the
        `ceph daemon <name> fault ...` interaction)."""
        asok.register_command(
            "fault set",
            lambda args: self.command({**args, "op": "set"}),
            "install a fault rule or named partition",
        )
        asok.register_command(
            "fault clear",
            lambda args: self.command({**args, "op": "clear"}),
            "remove a fault rule / partition / everything",
        )
        asok.register_command(
            "fault list",
            lambda args: self.command({"op": "list"}),
            "dump active fault rules, partitions and the seed",
        )
