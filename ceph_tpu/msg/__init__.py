"""Messenger — the framework's wire layer (src/msg/, src/msg/async/).

The reference's Messenger is a connection-oriented dispatcher fabric:
daemons create a messenger, bind, register Dispatchers, and exchange
typed Messages over framed protocols (ProtocolV2: banner, segmented
frames, crc or secure mode).  This package re-renders that contract
small and async-native:

- ``Message`` subclasses declare a type id + payload encode/decode
  (the ECMsgTypes/MOSDPing/MOSDMap analog, src/osd/ECMsgTypes.h).
- ``Messenger`` owns an asyncio loop on a background thread, binds a
  TCP listener, and dispatches inbound messages to registered
  ``Dispatcher``s (Messenger::add_dispatcher_head, ms_dispatch).
- Frames are length-prefixed with crc32c over header and payload
  (ProtocolV2 crc mode; secure mode is out of scope — transport
  security would wrap the socket, not the frame format).

TPU note: this layer is deliberately host-only CPU code.  Bulk data
between chips rides XLA collectives inside jitted programs (SURVEY.md
§5.8); the messenger carries control-plane and shard-IO traffic
between *processes/hosts*, exactly the role the reference's
AsyncMessenger plays beneath the OSDs.
"""

from .faults import FaultInjector, FaultRule, build_msgr_perf
from .stack import NetworkStack, build_stack_perf, stack_perf_dump
from .message import (
    MCommand,
    MECSubRead,
    MLog,
    MMonElection,
    MMonPaxos,
    MECSubReadReply,
    MECSubWrite,
    MECSubWriteReply,
    MOSDBackoff,
    MOSDMap,
    MOSDOp,
    MOSDOpReply,
    MOSDRepOp,
    MOSDRepOpReply,
    MPGActivate,
    MPGLogReply,
    MPGLogReq,
    MPGNotify,
    MPGPull,
    MPGPush,
    MPGPushReply,
    MPGQuery,
    MPing,
    MRepScrub,
    MScrubCommand,
    MScrubMap,
    MWatchNotify,
    MWatchNotifyAck,
    Message,
    MessageError,
    register_message,
)
from .messenger import Connection, Dispatcher, Messenger

__all__ = [
    "Connection",
    "Dispatcher",
    "FaultInjector",
    "FaultRule",
    "MCommand",
    "MECSubRead",
    "MLog",
    "MECSubReadReply",
    "MECSubWrite",
    "MECSubWriteReply",
    "MMonElection",
    "MMonPaxos",
    "MOSDBackoff",
    "MOSDMap",
    "MOSDOp",
    "MOSDOpReply",
    "MOSDRepOp",
    "MOSDRepOpReply",
    "MPGActivate",
    "MPGLogReply",
    "MPGLogReq",
    "MPGNotify",
    "MPGPull",
    "MPGPush",
    "MPGPushReply",
    "MPGQuery",
    "MPing",
    "MRepScrub",
    "MScrubCommand",
    "MScrubMap",
    "MWatchNotify",
    "MWatchNotifyAck",
    "Message",
    "MessageError",
    "Messenger",
    "NetworkStack",
    "build_msgr_perf",
    "build_stack_perf",
    "register_message",
    "stack_perf_dump",
]
