"""Lossless-peer sessions — reconnect + replay over the messenger
(src/msg/async/ProtocolV2.cc session reconnect; src/msg/Policy.h
lossless_peer).

The reference's OSD↔OSD connections are *lossless peers*: a dropped
TCP connection is re-established and every message sent but not yet
acknowledged is replayed, with the receive side deduplicating by
sequence number — senders never observe the drop.  This module
renders that contract over the framework messenger without touching
the frame format:

- ``SessionConnection`` (the dialer half) owns what a raw Connection
  owns per-socket — the tid→future pending map, the send queue — plus
  the session state: out_seq, the unacked replay buffer, in_seq.
  TCP connections underneath are disposable transports: every
  send/call lazily (re)dials, performs the MSessionOpen handshake
  (exchanging last-received seqs), prunes acked messages, and replays
  the remainder.  Payload messages ride seq-stamped MSessionData
  envelopes.
- ``SessionService`` (the acceptor half) is registered FIRST on the
  server messenger's dispatcher chain.  It keeps per-session state
  (in_seq, its own out_seq + unacked buffer, the live socket),
  unwraps inbound envelopes (dropping seq <= in_seq — redelivered
  duplicates), and hands the inner message to the ordinary dispatcher
  chain wrapped in a ``_SessionPeerConn`` whose ``send`` re-wraps
  replies in the session's own envelopes so they replay too.
- Cumulative ``MSessionAck``s flow every ACK_EVERY messages in both
  directions to bound the replay buffers.

The exactly-once write guarantee this buys: a repop whose TCP
connection dies mid-flight is replayed to the replica (which dedups
if it already applied it) and the reply is replayed to the primary —
no -EAGAIN storm, no client-visible retry.
"""

from __future__ import annotations

import concurrent.futures
import threading
import time

from .message import (
    Message,
    MessageError,
    MSessionAck,
    MSessionData,
    MSessionOpen,
)
from .messenger import Connection, Dispatcher, Messenger

ACK_EVERY = 16
_CALL_TIMEOUT = 30.0


def _parse_inner(blob: bytes) -> Message:
    """Decode one complete inner frame (header+crc+payload+crc)."""
    hdr = blob[: Message.HEADER_SIZE]
    mtype, tid, plen = Message.parse_header(hdr)
    body = blob[Message.HEADER_SIZE :]
    payload, crc = body[:plen], int.from_bytes(
        body[plen : plen + 4], "little"
    )
    return Message.from_payload(mtype, tid, payload, crc)


class _SessionState:
    """One direction-agnostic session endpoint's bookkeeping."""

    def __init__(self, name: str):
        self.name = name
        self.lock = threading.RLock()
        self.out_seq = 0
        self.in_seq = 0
        self.unacked: list[tuple[int, bytes]] = []  # (seq, inner frame)
        self.since_ack = 0

    def send_wrapped(self, msg: Message, conn, new_tid) -> None:
        """Assign the seq and SCHEDULE the frame under one lock: the
        cumulative-seq dedup on the receive side requires FIFO, and
        concurrent senders that assigned seqs separately from the
        socket write could put a higher seq on the wire first — the
        reordered lower seq would then be dropped as a duplicate
        forever.  ``conn.send`` only schedules onto the loop (FIFO),
        so holding the lock across it is cheap."""
        if msg.tid == 0:
            msg.tid = new_tid()
        with self.lock:
            self.out_seq += 1
            seq = self.out_seq
            inner = msg.to_frame()
            self.unacked.append((seq, inner))
            if conn is not None:
                env = MSessionData(
                    tid=new_tid(), seq=seq, inner=inner
                )
                try:
                    conn.send(env)
                except (MessageError, OSError):
                    pass  # in unacked: replays on reconnect

    def prune(self, acked_seq: int) -> None:
        with self.lock:
            self.unacked = [
                (s, f) for (s, f) in self.unacked if s > acked_seq
            ]

    GAP = object()  # sentinel: out-of-order arrival, NACK needed

    def accept(self, env: MSessionData):
        """STRICT in-order acceptance: exactly in_seq+1 advances; a
        duplicate returns None; a gap returns GAP (the receiver never
        skips a seq — a skipped message could only be recovered by a
        reconnect that might never come)."""
        with self.lock:
            if env.seq <= self.in_seq:
                return None
            if env.seq > self.in_seq + 1:
                return self.GAP
            self.in_seq = env.seq
            self.since_ack += 1
        return _parse_inner(env.inner)

    def should_ack(self) -> bool:
        with self.lock:
            if self.since_ack >= ACK_EVERY:
                self.since_ack = 0
                return True
        return False

    def resend_after(self, acked_seq: int, conn, new_tid) -> None:
        """NACK recovery: prune then re-send the rest in order."""
        with self.lock:
            self.unacked = [
                (s, f) for (s, f) in self.unacked if s > acked_seq
            ]
            if conn is None:
                return
            for seq, inner in self.unacked:
                try:
                    conn.send(
                        MSessionData(
                            tid=new_tid(), seq=seq, inner=inner
                        )
                    )
                except (MessageError, OSError):
                    return


class SessionConnection:
    """Dialer half: the Connection API (send/call) surviving TCP
    drops with replay.  One instance per (messenger, peer, name)."""

    def __init__(
        self, msgr: Messenger, host: str, port: int, name: str
    ):
        import os

        self.msgr = msgr
        self.host, self.port = host, int(port)
        self.name = name
        self.nonce = os.urandom(8).hex()
        self._server_nonce: str | None = None
        self.state = _SessionState(name)
        self._conn: Connection | None = None
        self._dial_lock = threading.RLock()
        self._pending: dict[int, concurrent.futures.Future] = {}
        self._plock = threading.Lock()
        self._closed = False
        # proactive reconnect state: one redial attempt in flight at
        # a time, kicked by the transport's reset notification
        self._redial_lock = threading.Lock()
        self._redialing = False

    # -- Connection API ----------------------------------------------------
    @property
    def is_closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        self._closed = True
        with self._dial_lock:
            if self._conn is not None:
                self._conn.close()

    def send(self, msg: Message) -> None:
        try:
            conn = self._ensure()
        except (MessageError, OSError):
            conn = None  # queued in unacked: replays on reconnect
        self.state.send_wrapped(msg, conn, self.msgr.new_tid)

    def call(
        self, msg: Message, timeout: float = _CALL_TIMEOUT
    ) -> Message:
        if msg.tid == 0:
            msg.tid = self.msgr.new_tid()
        # fail fast when the peer is unreachable NOW and no session
        # socket survives — a dead peer must behave like a dead raw
        # connection for the caller's failure handling (the map-driven
        # re-peer paths), not burn the whole call timeout
        conn = None
        try:
            conn = self._ensure()
        except (MessageError, OSError):
            if self._conn is None or self._conn.is_closed:
                raise
        cf: concurrent.futures.Future = concurrent.futures.Future()
        with self._plock:
            self._pending[msg.tid] = cf
        deadline = time.monotonic() + timeout
        try:
            self.state.send_wrapped(msg, conn, self.msgr.new_tid)
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise MessageError(
                        f"session call tid={msg.tid} timed out"
                    )
                try:
                    return cf.result(min(0.1, remaining))
                except concurrent.futures.TimeoutError:
                    # reconnect only when the socket actually died —
                    # the handshake replays the request AND the reply
                    conn = self._conn
                    if conn is None or conn.is_closed:
                        try:
                            self._ensure()
                        except (MessageError, OSError):
                            time.sleep(0.05)
        finally:
            with self._plock:
                self._pending.pop(msg.tid, None)

    # -- transport management ----------------------------------------------
    def _ensure(self) -> Connection:
        with self._dial_lock:
            if self._closed:
                raise MessageError("session closed")
            if self._conn is not None and not self._conn.is_closed:
                return self._conn
            conn = self.msgr.connect(self.host, self.port)
            reply = conn.call(
                MSessionOpen(
                    session=self.name,
                    last_in_seq=self.state.in_seq,
                    nonce=self.nonce,
                ),
                timeout=2.0,
            )
            if not isinstance(reply, MSessionOpen):
                conn.close()
                raise MessageError("bad session handshake reply")
            first_contact = self._server_nonce is None
            if reply.nonce != self._server_nonce:
                # a NEW server incarnation: reset the dedup floor AND
                # renumber our own unacked backlog from seq 1 — a
                # fresh server expects 1, and replaying the old high
                # seqs would GAP/NACK forever
                self._server_nonce = reply.nonce
                with self.state.lock:
                    self.state.in_seq = 0
                    if not first_contact:
                        self.state.unacked = [
                            (i + 1, frame)
                            for i, (_s, frame) in enumerate(
                                self.state.unacked
                            )
                        ]
                        self.state.out_seq = len(self.state.unacked)
            self.state.prune(reply.last_in_seq)
            # hold the seq lock across the whole replay so a
            # concurrent new send cannot interleave a higher seq
            # ahead of the replayed ones
            with self.state.lock:
                for seq, inner in self.state.unacked:
                    conn.send(
                        MSessionData(
                            tid=self.msgr.new_tid(),
                            seq=seq,
                            inner=inner,
                        )
                    )
                self._conn = conn
            self.msgr.session_client_register(conn, self)
            return conn

    def on_transport_reset(self) -> None:
        """Event-driven reconnect (the replay-window determinism
        fix): the instant the transport dies with work outstanding —
        unacked frames to replay or calls awaiting replies — redial,
        re-handshake and replay ONCE, off the messenger loop.  The
        replay window is then exactly the death-to-redial handshake,
        not however long the caller's poll loop took to notice; each
        death triggers exactly one immediate replay attempt, and a
        failed attempt (peer really down) leaves recovery to the
        callers' retry loops as before."""
        if self._closed:
            return
        with self._plock:
            has_pending = bool(self._pending)
        if not has_pending and not self.state.unacked:
            return
        with self._redial_lock:
            if self._redialing:
                return
            self._redialing = True
        stack = self.msgr._stack

        def _redial():
            try:
                if not self._closed:
                    self._ensure()
            except (MessageError, OSError):
                pass
            finally:
                with self._redial_lock:
                    self._redialing = False

        if stack is not None:
            stack.offload.submit(_redial)
        else:  # messenger already torn down
            with self._redial_lock:
                self._redialing = False

    # -- inbound (called by the messenger's session dispatcher) -----------
    def handle_envelope(self, conn: Connection, env: MSessionData):
        msg = self.state.accept(env)
        if msg is _SessionState.GAP:
            # a seq went missing (e.g. scheduled onto a socket that
            # died mid-write): NACK so the peer resends in order
            try:
                conn.send(
                    MSessionAck(
                        tid=self.msgr.new_tid(),
                        session=self.name,
                        last_in_seq=self.state.in_seq,
                        nack=True,
                    )
                )
            except (MessageError, OSError):
                pass
            return
        if self.state.should_ack():
            try:
                conn.send(
                    MSessionAck(
                        tid=self.msgr.new_tid(),
                        session=self.name,
                        last_in_seq=self.state.in_seq,
                    )
                )
            except (MessageError, OSError):
                pass
        if msg is None:
            return
        with self._plock:
            fut = self._pending.get(msg.tid)
        if fut is not None:
            if fut.set_running_or_notify_cancel():
                fut.set_result(msg)
            return
        # not a reply: hand to the normal dispatcher chain with THIS
        # session as the reply path
        self.msgr._dispatch(_SessionPeerConn(self), msg)

    def handle_ack(self, ack: MSessionAck) -> None:
        if ack.nack:
            self.state.resend_after(
                ack.last_in_seq, self._conn, self.msgr.new_tid
            )
        else:
            self.state.prune(ack.last_in_seq)


class _SessionPeerConn:
    """The 'conn' handed to dispatchers for session traffic: replies
    ride the session (wrapped + replayable), not the raw socket."""

    def __init__(self, endpoint):
        self._ep = endpoint
        self.is_closed = False
        self._closed = False

    def send(self, msg: Message) -> None:
        self._ep.send(msg)

    def call(self, msg: Message, timeout: float = _CALL_TIMEOUT):
        return self._ep.call(msg, timeout)


class _ServerSession:
    """Acceptor half of one named session."""

    def __init__(self, svc: "SessionService", name: str):
        import os

        self.svc = svc
        self.name = name
        self.state = _SessionState(name)
        self.conn: Connection | None = None  # live socket
        self.nonce = ""
        self.my_nonce = os.urandom(8).hex()
        self._pending: dict[int, concurrent.futures.Future] = {}
        self._plock = threading.Lock()

    def send(self, msg: Message) -> None:
        conn = self.conn
        if conn is not None and conn.is_closed:
            conn = None  # replays when the dialer reconnects
        self.state.send_wrapped(
            msg, conn, self.svc.msgr.new_even_tid
        )

    def call(
        self, msg: Message, timeout: float = _CALL_TIMEOUT
    ) -> Message:
        if msg.tid == 0:
            msg.tid = self.svc.msgr.new_even_tid()
        cf: concurrent.futures.Future = concurrent.futures.Future()
        with self._plock:
            self._pending[msg.tid] = cf
        try:
            self.send(msg)
            return cf.result(timeout)
        except concurrent.futures.TimeoutError as e:
            raise MessageError(
                f"session call tid={msg.tid} timed out"
            ) from e
        finally:
            with self._plock:
                self._pending.pop(msg.tid, None)

    def handle_open(self, conn: Connection, msg: MSessionOpen):
        self.conn = conn
        if conn.peer_label is None and "-" in msg.session:
            # session names are "<dialer>-<peer id>" (osd._peer_conn):
            # stamp the dialer's identity so directional fault rules
            # match this accepted connection's replies too
            conn.peer_label = msg.session.rsplit("-", 1)[0]
        if msg.nonce != self.nonce:
            # a NEW dialer incarnation: BOTH seq spaces restart from
            # zero (keeping the old out_seq would make every reply a
            # permanent GAP against the fresh dialer's in_seq=0 — an
            # infinite NACK/resend loop) and the unacked backlog
            # belongs to a dead peer state
            self.nonce = msg.nonce
            with self.state.lock:
                self.state.in_seq = 0
                self.state.out_seq = 0
                self.state.unacked = []
        self.state.prune(msg.last_in_seq)
        conn.send(
            MSessionOpen(
                tid=msg.tid,  # tid-paired handshake reply
                session=self.name,
                last_in_seq=self.state.in_seq,
                nonce=self.my_nonce,
            )
        )
        # replay under the seq lock so no concurrent send interleaves
        # a newer seq ahead of the replayed backlog
        with self.state.lock:
            for seq, inner in self.state.unacked:
                conn.send(
                    MSessionData(
                        tid=self.svc.msgr.new_even_tid(),
                        seq=seq,
                        inner=inner,
                    )
                )

    def handle_envelope(self, conn: Connection, env: MSessionData):
        self.conn = conn
        inner = self.state.accept(env)
        if inner is _SessionState.GAP:
            try:
                conn.send(
                    MSessionAck(
                        tid=self.svc.msgr.new_even_tid(),
                        session=self.name,
                        last_in_seq=self.state.in_seq,
                        nack=True,
                    )
                )
            except (MessageError, OSError):
                pass
            return
        if self.state.should_ack():
            try:
                conn.send(
                    MSessionAck(
                        tid=self.svc.msgr.new_even_tid(),
                        session=self.name,
                        last_in_seq=self.state.in_seq,
                    )
                )
            except (MessageError, OSError):
                pass
        if inner is None:
            return
        with self._plock:
            fut = self._pending.get(inner.tid)
        if fut is not None:
            if fut.set_running_or_notify_cancel():
                fut.set_result(inner)
            return
        self.svc.msgr._dispatch(_SessionPeerConn(self), inner)


class SessionService(Dispatcher):
    """Acceptor-side session registry; registered first on the
    dispatcher chain by Messenger.__init__ so envelopes never reach
    application dispatchers raw."""

    def __init__(self, msgr: Messenger):
        self.msgr = msgr
        self._sessions: dict[str, _ServerSession] = {}
        self._by_conn: dict[int, object] = {}  # id(conn) → endpoint
        self._lock = threading.Lock()

    def client_register(self, conn: Connection, sc) -> None:
        with self._lock:
            self._by_conn[id(conn)] = sc

    def _session(self, name: str) -> _ServerSession:
        with self._lock:
            s = self._sessions.get(name)
            if s is None:
                s = self._sessions[name] = _ServerSession(self, name)
            return s

    def ms_dispatch(self, conn: Connection, msg: Message) -> bool:
        if isinstance(msg, MSessionOpen):
            s = self._session(msg.session)
            with self._lock:
                self._by_conn[id(conn)] = s
            s.handle_open(conn, msg)
            return True
        if isinstance(msg, MSessionData):
            with self._lock:
                ep = self._by_conn.get(id(conn))
            if ep is None:
                return True  # stray envelope on an unknown socket
            ep.handle_envelope(conn, msg)
            return True
        if isinstance(msg, MSessionAck):
            with self._lock:
                ep = self._by_conn.get(id(conn))
            if ep is not None:
                if isinstance(ep, _ServerSession):
                    if msg.nack:
                        ep.state.resend_after(
                            msg.last_in_seq, ep.conn,
                            self.msgr.new_even_tid,
                        )
                    else:
                        ep.state.prune(msg.last_in_seq)
                else:
                    ep.handle_ack(msg)
            return True
        return False

    def ms_handle_reset(self, conn: Connection) -> None:
        with self._lock:
            ep = self._by_conn.pop(id(conn), None)
        # a dialer-side endpoint reconnects/replays NOW rather than
        # waiting for a caller's poll to notice the dead socket
        kick = getattr(ep, "on_transport_reset", None)
        if kick is not None:
            kick()
