"""Typed messages + frame codec (the ECMsgTypes / MOSDPing / MOSDMap
roles, src/osd/ECMsgTypes.{h,cc}, src/messages/MOSDPing.h,
src/messages/MOSDMap.h) over the framework's versioned encoding.

Frame layout (ProtocolV2 crc-mode analog, src/msg/async/frames_v2.h):

    u32 magic | u16 type | u16 reserved | u64 tid | u32 payload_len
    u32 header_crc (crc32c over the 20 header bytes)
    payload bytes
    u32 payload_crc

Every message carries ``tid`` (transaction id) so replies pair with
requests across the connection, like the reference's sub-op tids.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from ..common.encoding import Decoder, Encoder
from ..native import ceph_crc32c
from ..store.objectstore import (
    Transaction,
    decode_transaction,
    encode_transaction,
)

FRAME_MAGIC = 0x43545546  # "CTUF"
_HEADER = struct.Struct("<IHHQI")


class MessageError(Exception):
    pass


_REGISTRY: dict[int, type["Message"]] = {}


def register_message(cls):
    """Class decorator: register a Message subclass by its TYPE id
    (the ceph_msg_type dispatch table role)."""
    if cls.TYPE in _REGISTRY:
        raise ValueError(f"message type {cls.TYPE} already registered")
    _REGISTRY[cls.TYPE] = cls
    return cls


@dataclass
class Message:
    """Base: subclasses set TYPE and implement encode_payload/
    decode_payload.  ``tid`` pairs replies with requests."""

    TYPE = 0
    tid: int = 0

    def encode_payload(self, e: Encoder) -> None:  # pragma: no cover
        pass

    @classmethod
    def decode_payload(cls, d: Decoder) -> "Message":
        return cls()

    # -- frame codec -------------------------------------------------------
    def to_frame(self) -> bytes:
        e = Encoder()
        self.encode_payload(e)
        payload = e.getvalue()
        header = _HEADER.pack(
            FRAME_MAGIC, self.TYPE, 0, self.tid, len(payload)
        )
        return b"".join(
            (
                header,
                ceph_crc32c(0, header).to_bytes(4, "little"),
                payload,
                ceph_crc32c(0, payload).to_bytes(4, "little"),
            )
        )

    @staticmethod
    def parse_header(buf: bytes) -> tuple[int, int, int]:
        """(type, tid, payload_len) from the 24-byte header block;
        raises MessageError on magic/crc mismatch."""
        if len(buf) != _HEADER.size + 4:
            raise MessageError("short header")
        magic, mtype, _res, tid, plen = _HEADER.unpack(
            buf[: _HEADER.size]
        )
        if magic != FRAME_MAGIC:
            raise MessageError(f"bad magic {magic:#x}")
        crc = int.from_bytes(buf[_HEADER.size :], "little")
        if ceph_crc32c(0, buf[: _HEADER.size]) != crc:
            raise MessageError("header crc mismatch")
        return mtype, tid, plen

    @staticmethod
    def from_payload(mtype: int, tid: int, payload: bytes, crc: int):
        if ceph_crc32c(0, payload) != crc:
            raise MessageError("payload crc mismatch")
        cls = _REGISTRY.get(mtype)
        if cls is None:
            raise MessageError(f"unknown message type {mtype}")
        msg = cls.decode_payload(Decoder(payload))
        msg.tid = tid
        return msg

    HEADER_SIZE = _HEADER.size + 4


# -- concrete messages -----------------------------------------------------


@register_message
@dataclass
class MPing(Message):
    """Heartbeat (MOSDPing): PING or PING_REPLY with sender id and a
    timestamp echoed back for rtt accounting."""

    TYPE = 1
    from_osd: int = 0
    stamp: float = 0.0
    is_reply: bool = False

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.from_osd).f64(self.stamp).bool(self.is_reply)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MPing":
        return cls(
            from_osd=d.s32(), stamp=d.f64(), is_reply=d.bool()
        )


@register_message
@dataclass
class MECSubWrite(Message):
    """Primary → shard sub-write (ECSubWrite, src/osd/ECMsgTypes.h:37):
    one object-store transaction to apply atomically, tagged with the
    sender and the map epoch it was planned under."""

    TYPE = 2
    from_osd: int = 0
    epoch: int = 0
    txn: Transaction = field(default_factory=Transaction)
    trace: str = ""  # span id (ECBackend.cc:886: sub-ops carry trace)

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.from_osd).u32(self.epoch)
        encode_transaction(e, self.txn)
        e.string(self.trace)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MECSubWrite":
        return cls(
            from_osd=d.s32(), epoch=d.u32(),
            txn=decode_transaction(d), trace=d.string(),
        )


@register_message
@dataclass
class MECSubWriteReply(Message):
    """Shard → primary commit ack (ECSubWriteReply)."""

    TYPE = 3
    from_osd: int = 0
    ok: bool = True
    error: str = ""

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.from_osd).bool(self.ok).string(self.error)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MECSubWriteReply":
        return cls(from_osd=d.s32(), ok=d.bool(), error=d.string())


# read op kinds (the shard-side handle_sub_read switch)
READ_DATA = 0  # (cid, oid, off, len) -> bytes
READ_ATTR = 1  # (cid, oid, attr) -> bytes
READ_STAT = 2  # (cid, oid) -> size
READ_EXISTS = 3  # (cid, oid) -> bool
READ_LIST = 4  # (cid,) -> [oid]
READ_ATTRS = 5  # (cid, oid) -> encoded {name: value} map
READ_OMAP = 6  # (cid, oid) -> encoded {key: value} map


@register_message
@dataclass
class MECSubRead(Message):
    """Primary → shard sub-read (ECSubRead, src/osd/ECMsgTypes.h:96):
    a batch of read ops [(kind, cid, oid, arg1, arg2)]."""

    TYPE = 4
    from_osd: int = 0
    ops: list[tuple] = field(default_factory=list)

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.from_osd)
        e.u32(len(self.ops))
        for kind, cid, oid, a1, a2 in self.ops:
            e.u8(kind).string(cid).string(oid).u64(a1).string(a2)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MECSubRead":
        msg = cls(from_osd=d.s32())
        for _ in range(d.u32()):
            msg.ops.append(
                (d.u8(), d.string(), d.string(), d.u64(), d.string())
            )
        return msg


@register_message
@dataclass
class MECSubReadReply(Message):
    """Shard → primary read results (ECSubReadReply): per-op
    (ok, bytes) pairs; failed ops carry the error text."""

    TYPE = 5
    from_osd: int = 0
    results: list[tuple[bool, bytes]] = field(default_factory=list)

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.from_osd)
        e.u32(len(self.results))
        for ok, data in self.results:
            e.bool(ok).bytes(data)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MECSubReadReply":
        msg = cls(from_osd=d.s32())
        for _ in range(d.u32()):
            msg.results.append((d.bool(), d.bytes()))
        return msg


@register_message
@dataclass
class MOSDMap(Message):
    """Map distribution (MOSDMap): full map blob and/or a run of
    incremental blobs, by epoch."""

    TYPE = 6
    full: bytes = b""  # OSDMap.encode() or empty
    incrementals: list[bytes] = field(default_factory=list)

    def encode_payload(self, e: Encoder) -> None:
        e.bytes(self.full)
        e.list(self.incrementals, lambda e2, b: e2.bytes(b))

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MOSDMap":
        return cls(
            full=d.bytes(),
            incrementals=d.list(lambda d2: d2.bytes()),
        )


@register_message
@dataclass
class MMonSubscribe(Message):
    """Client → mon map subscription (MonClient subscribe flow,
    src/mon/MonClient.cc): "send me osdmaps starting at start_epoch"."""

    TYPE = 7
    what: str = "osdmap"
    start_epoch: int = 0  # 0 = send the full current map
    from_osd: int = -1

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.what).u32(self.start_epoch).s32(self.from_osd)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MMonSubscribe":
        return cls(
            what=d.string(), start_epoch=d.u32(), from_osd=d.s32()
        )


@register_message
@dataclass
class MOSDFailure(Message):
    """OSD → mon failure report (MOSDFailure; OSD::send_failures,
    src/osd/OSD.cc:5889).  ``failed_for`` seconds of silence; a report
    with failed_for < 0 withdraws a previous report (the recovery
    cancel path)."""

    TYPE = 8
    target: int = -1
    reporter: int = -1
    failed_for: float = 0.0
    epoch: int = 0

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.target).s32(self.reporter)
        e.f64(self.failed_for).u32(self.epoch)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MOSDFailure":
        return cls(
            target=d.s32(), reporter=d.s32(),
            failed_for=d.f64(), epoch=d.u32(),
        )


@register_message
@dataclass
class MMonCommand(Message):
    """CLI → mon command (MMonCommand: the `ceph` CLI speaks JSON
    command dicts per src/mon/MonCommands.h)."""

    TYPE = 9
    cmd: str = "{}"  # JSON dict, e.g. {"prefix": "osd pool create", ...}

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.cmd)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MMonCommand":
        return cls(cmd=d.string())


@register_message
@dataclass
class MMonCommandReply(Message):
    """Mon → CLI reply: rc + human text + JSON payload."""

    TYPE = 10
    rc: int = 0
    outs: str = ""
    outb: str = ""  # JSON

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.rc).string(self.outs).string(self.outb)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MMonCommandReply":
        return cls(rc=d.s32(), outs=d.string(), outb=d.string())


@register_message
@dataclass
class MOSDBoot(Message):
    """OSD → mon boot announcement (MOSDBoot): mark me up at addr."""

    TYPE = 11
    osd: int = -1
    addr: str = ""

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.osd).string(self.addr)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MOSDBoot":
        return cls(osd=d.s32(), addr=d.string())


# -- OSD daemon: client ops, replication, peering, recovery ----------------

# client op kinds (the do_osd_ops switch, PrimaryLogPG.cc)
OSD_OP_WRITEFULL = 0
OSD_OP_WRITE = 1
OSD_OP_READ = 2
OSD_OP_DELETE = 3
OSD_OP_STAT = 4
OSD_OP_SETXATTR = 5  # oid attr (in .oid/.attr), value in .data
OSD_OP_GETXATTR = 6
OSD_OP_LIST = 7  # list this PG's objects (the pgls op)
OSD_OP_APPEND = 8  # atomic append (offset resolved on the primary)
OSD_OP_CALL = 9  # object-class call (attr='cls.method', data=indata)
OSD_OP_OMAPSET = 10  # data = encoded {key: value} map
OSD_OP_OMAPGET = 11  # attr = start_after, length = max_return
OSD_OP_OMAPRM = 12  # data = encoded [key] list
OSD_OP_OMAPCLEAR = 13
OSD_OP_WATCH = 14  # offset = client cookie
OSD_OP_UNWATCH = 15  # offset = client cookie
OSD_OP_NOTIFY = 16  # data = payload; reply.data = encoded ack list

# MOSDOp.flags bits (the CEPH_OSD_FLAG_* seat)
OSD_FLAG_FULL_TRY = 1  # attempt the write even on a full OSD/pool
# (repair/delete traffic that FREES space must still land;
# CEPH_OSD_FLAG_FULL_TRY, src/include/rados.h)


@register_message
@dataclass
class MOSDOp(Message):
    """Client → primary object op (MOSDOp): targeted at a pg, carrying
    one op (the reference batches a vector; one is enough for the
    librados surface here)."""

    TYPE = 12
    pool: int = 0
    pgid: str = ""
    oid: str = ""
    op: int = OSD_OP_READ
    offset: int = 0
    length: int = 0
    data: bytes = b""
    attr: str = ""
    reqid: str = ""  # stable across retries (osd_reqid_t role)
    epoch: int = 0  # client's map epoch (primary checks staleness)
    snapid: int = 0  # read snapshot (0 = head, CEPH_NOSNAP role)
    # writer SnapContext seq (SnapContext::seq, PrimaryLogPG.h:632):
    # self-managed snaps — make_writeable clones against THIS, not
    # the pool's snap_seq, when the writer provides one
    snap_seq: int = 0
    # op flags (OSD_FLAG_*): FULL_TRY lets repair/delete traffic land
    # on a full OSD instead of parking on backoff
    flags: int = 0
    # QoS class (the dmclock client-class tag): the primary enqueues
    # this op under the named scheduler class when its profile is
    # registered, else under the default client class; empty = client
    qos: str = ""

    def encode_payload(self, e: Encoder) -> None:
        e.s64(self.pool).string(self.pgid).string(self.oid)
        e.u8(self.op).u64(self.offset).s64(self.length)
        e.bytes(self.data).string(self.attr).string(self.reqid)
        e.u32(self.epoch).u64(self.snapid).u64(self.snap_seq)
        e.u32(self.flags).string(self.qos)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MOSDOp":
        return cls(
            pool=d.s64(), pgid=d.string(), oid=d.string(),
            op=d.u8(), offset=d.u64(), length=d.s64(),
            data=d.bytes(), attr=d.string(), reqid=d.string(),
            epoch=d.u32(), snapid=d.u64(), snap_seq=d.u64(),
            # versioned-decode tolerance: frames from before the
            # backoff plane carry no flags word, pre-SLO ones no qos
            flags=d.u32() if d.remaining() else 0,
            qos=d.string() if d.remaining() else "",
        )


@register_message
@dataclass
class MOSDOpReply(Message):
    """Primary → client result (MOSDOpReply)."""

    TYPE = 13
    ok: bool = True
    error: str = ""
    data: bytes = b""
    names: list = field(default_factory=list)
    size: int = 0
    epoch: int = 0  # primary's epoch (client refreshes when ahead)

    def encode_payload(self, e: Encoder) -> None:
        e.bool(self.ok).string(self.error).bytes(self.data)
        e.list(self.names, lambda e2, n: e2.string(n))
        e.u64(self.size).u32(self.epoch)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MOSDOpReply":
        return cls(
            ok=d.bool(), error=d.string(), data=d.bytes(),
            names=d.list(lambda d2: d2.string()),
            size=d.u64(), epoch=d.u32(),
        )


@register_message
@dataclass
class MOSDRepOp(Message):
    """Primary → replica: one transaction + its log entry (MOSDRepOp /
    sub_op_modify: data and pg log ride the same atomic apply)."""

    TYPE = 14
    pgid: str = ""
    epoch: int = 0
    txn: "Transaction" = None  # type: ignore[assignment]
    entry_blob: bytes = b""  # encoded LogEntry
    trace: str = ""  # span id (the client reqid; ECBackend.cc:886 role)

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.pgid).u32(self.epoch)
        encode_transaction(e, self.txn)
        e.bytes(self.entry_blob)
        e.string(self.trace)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MOSDRepOp":
        return cls(
            pgid=d.string(), epoch=d.u32(),
            txn=decode_transaction(d), entry_blob=d.bytes(),
            trace=d.string(),
        )


@register_message
@dataclass
class MOSDRepOpReply(Message):
    TYPE = 15
    from_osd: int = 0
    ok: bool = True
    error: str = ""

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.from_osd).bool(self.ok).string(self.error)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MOSDRepOpReply":
        return cls(from_osd=d.s32(), ok=d.bool(), error=d.string())


@register_message
@dataclass
class MPGQuery(Message):
    """Primary → peer: send me your pg_info (the GetInfo query,
    PeeringState's pg_query_t)."""

    TYPE = 16
    pgid: str = ""
    epoch: int = 0

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.pgid).u32(self.epoch)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MPGQuery":
        return cls(pgid=d.string(), epoch=d.u32())


@register_message
@dataclass
class MPGNotify(Message):
    """Peer → primary: pg_info + recent log suffix (MNotifyRec role;
    the log rides along so the primary can locate the divergence
    point, the proc_replica_log input)."""

    TYPE = 17
    from_osd: int = 0
    info_blob: bytes = b""  # encoded PGInfo ('' = pg unknown here)
    entry_blobs: list = field(default_factory=list)

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.from_osd).bytes(self.info_blob)
        e.list(self.entry_blobs, lambda e2, b: e2.bytes(b))

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MPGNotify":
        return cls(
            from_osd=d.s32(), info_blob=d.bytes(),
            entry_blobs=d.list(lambda d2: d2.bytes()),
        )


@register_message
@dataclass
class MPGLogReq(Message):
    """Primary → authoritative peer: entries after ``since`` (the
    GetLog request)."""

    TYPE = 18
    pgid: str = ""
    epoch: int = 0
    since: tuple = (0, 0)

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.pgid).u32(self.epoch)
        e.u32(self.since[0]).u64(self.since[1])

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MPGLogReq":
        return cls(
            pgid=d.string(), epoch=d.u32(), since=(d.u32(), d.u64())
        )


@register_message
@dataclass
class MPGLogReply(Message):
    """Authoritative peer → primary: log entries + info (MLogRec)."""

    TYPE = 19
    from_osd: int = 0
    info_blob: bytes = b""
    entry_blobs: list = field(default_factory=list)

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.from_osd).bytes(self.info_blob)
        e.list(self.entry_blobs, lambda e2, b: e2.bytes(b))

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MPGLogReply":
        return cls(
            from_osd=d.s32(), info_blob=d.bytes(),
            entry_blobs=d.list(lambda d2: d2.bytes()),
        )


@register_message
@dataclass
class MPGPush(Message):
    """Primary → recovering peer: one whole object at a version (the
    recovery push, ReplicatedBackend::prep_push; None data = the
    object was deleted)."""

    TYPE = 20
    pgid: str = ""
    epoch: int = 0
    oid: str = ""
    exists: bool = True
    data: bytes = b""
    attrs: dict = field(default_factory=dict)
    omap: dict = field(default_factory=dict)
    entry_blob: bytes = b""  # the log entry that names this version

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.pgid).u32(self.epoch).string(self.oid)
        e.bool(self.exists).bytes(self.data)
        e.map(
            self.attrs,
            lambda e2, k: e2.string(k),
            lambda e2, v: e2.bytes(v),
        )
        e.map(
            self.omap,
            lambda e2, k: e2.string(k),
            lambda e2, v: e2.bytes(v),
        )
        e.bytes(self.entry_blob)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MPGPush":
        return cls(
            pgid=d.string(), epoch=d.u32(), oid=d.string(),
            exists=d.bool(), data=d.bytes(),
            attrs=d.map(lambda d2: d2.string(), lambda d2: d2.bytes()),
            omap=d.map(lambda d2: d2.string(), lambda d2: d2.bytes()),
            entry_blob=d.bytes(),
        )


@register_message
@dataclass
class MPGPushReply(Message):
    TYPE = 21
    from_osd: int = 0
    ok: bool = True

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.from_osd).bool(self.ok)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MPGPushReply":
        return cls(from_osd=d.s32(), ok=d.bool())


@register_message
@dataclass
class MWatchNotify(Message):
    """OSD → watcher: a notify fired on an object you watch
    (MWatchNotify); the client acks with MWatchNotifyAck carrying the
    same notify_id."""

    TYPE = 26
    oid: str = ""
    notify_id: int = 0
    cookie: int = 0  # the watcher's registration cookie
    payload: bytes = b""

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.oid).u64(self.notify_id).u64(self.cookie)
        e.bytes(self.payload)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MWatchNotify":
        return cls(
            oid=d.string(), notify_id=d.u64(), cookie=d.u64(),
            payload=d.bytes(),
        )


@register_message
@dataclass
class MWatchNotifyAck(Message):
    TYPE = 27
    notify_id: int = 0
    cookie: int = 0
    reply: bytes = b""

    def encode_payload(self, e: Encoder) -> None:
        e.u64(self.notify_id).u64(self.cookie).bytes(self.reply)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MWatchNotifyAck":
        return cls(
            notify_id=d.u64(), cookie=d.u64(), reply=d.bytes()
        )


# -- lossless-peer sessions (ProtocolV2 session reconnect/replay) ----------


@register_message
@dataclass
class MSessionOpen(Message):
    """Session handshake (ProtocolV2 RECONNECT frame role): names the
    logical session and reports the sender's last received seq so the
    peer can prune acked messages and replay the rest."""

    TYPE = 28
    session: str = ""
    last_in_seq: int = 0
    # dialer incarnation id: a changed nonce tells the acceptor the
    # client's session state reset (fresh daemon), so stale in_seq
    # must not dedup-drop the new incarnation's messages
    nonce: str = ""

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.session).u64(self.last_in_seq)
        e.string(self.nonce)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MSessionOpen":
        return cls(
            session=d.string(), last_in_seq=d.u64(),
            nonce=d.string(),
        )


@register_message
@dataclass
class MSessionData(Message):
    """Seq-stamped envelope: ``inner`` is a complete message frame.
    The receiver drops seq <= its in_seq (redelivery after replay)
    and otherwise processes the inner frame as if it arrived bare."""

    TYPE = 29
    seq: int = 0
    inner: bytes = b""

    def encode_payload(self, e: Encoder) -> None:
        e.u64(self.seq).bytes(self.inner)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MSessionData":
        return cls(seq=d.u64(), inner=d.bytes())


@register_message
@dataclass
class MSessionAck(Message):
    """Cumulative ack (bounds the sender's replay buffer); with
    ``nack`` set it reports a sequence GAP — the receiver saw a seq
    beyond last_in_seq+1 — and the sender must resend everything
    after last_in_seq in order."""

    TYPE = 30
    session: str = ""
    last_in_seq: int = 0
    nack: bool = False

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.session).u64(self.last_in_seq)
        e.bool(self.nack)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MSessionAck":
        return cls(
            session=d.string(), last_in_seq=d.u64(), nack=d.bool()
        )


# election ops (Elector.cc / ElectionLogic.cc roles)
ELECT_PROPOSE = 0
ELECT_ACK = 1
ELECT_VICTORY = 2


@register_message
@dataclass
class MMonElection(Message):
    """Monitor election (MMonElection): PROPOSE carries the
    candidate's (last_committed, rank) so peers defer to the most
    up-to-date, lowest-rank candidate; ACK endorses a proposal epoch;
    VICTORY announces the leader + quorum."""

    TYPE = 24
    op: int = ELECT_PROPOSE
    epoch: int = 0
    rank: int = -1
    last_committed: int = 0
    quorum: list = field(default_factory=list)

    def encode_payload(self, e: Encoder) -> None:
        e.u8(self.op).u32(self.epoch).s32(self.rank)
        e.u64(self.last_committed)
        e.list(self.quorum, lambda e2, r: e2.s32(r))

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MMonElection":
        return cls(
            op=d.u8(), epoch=d.u32(), rank=d.s32(),
            last_committed=d.u64(),
            quorum=d.list(lambda d2: d2.s32()),
        )


# paxos ops (Paxos.cc collect/begin/accept/commit/lease)
PAXOS_COLLECT = 0
PAXOS_LAST = 1
PAXOS_BEGIN = 2
PAXOS_ACCEPT = 3
PAXOS_COMMIT = 4
PAXOS_LEASE = 5
PAXOS_SYNC = 6  # lagging peon asks the leader for missing commits


@register_message
@dataclass
class MMonPaxos(Message):
    """Paxos round message (MMonPaxos): ``epoch`` is the election
    epoch guarding against deposed leaders (the pn role), ``version``
    the map epoch being proposed/committed.  ``entries`` carries
    catch-up runs of (version, inc_blob, full_blob)."""

    TYPE = 25
    op: int = PAXOS_COLLECT
    epoch: int = 0
    version: int = 0
    last_committed: int = 0
    ok: bool = True
    rank: int = -1
    inc_blob: bytes = b""
    full_blob: bytes = b""
    entries: list = field(default_factory=list)

    def encode_payload(self, e: Encoder) -> None:
        e.u8(self.op).u32(self.epoch).u64(self.version)
        e.u64(self.last_committed).bool(self.ok).s32(self.rank)
        e.bytes(self.inc_blob).bytes(self.full_blob)
        e.u32(len(self.entries))
        for v, inc, full in self.entries:
            e.u64(v).bytes(inc).bytes(full)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MMonPaxos":
        msg = cls(
            op=d.u8(), epoch=d.u32(), version=d.u64(),
            last_committed=d.u64(), ok=d.bool(), rank=d.s32(),
            inc_blob=d.bytes(), full_blob=d.bytes(),
        )
        for _ in range(d.u32()):
            msg.entries.append((d.u64(), d.bytes(), d.bytes()))
        return msg


@register_message
@dataclass
class MPGActivate(Message):
    """Primary → peer: peering finished — rewind divergent entries
    past ``rewind_to``, adopt the authoritative log suffix, go active
    (the MOSDPGLog activation message with the merge_log divergence
    point)."""

    TYPE = 22
    pgid: str = ""
    epoch: int = 0
    info_blob: bytes = b""  # primary's (authoritative) info
    rewind_to: tuple = (0, 0)  # newest version shared with the auth log
    entry_blobs: list = field(default_factory=list)

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.pgid).u32(self.epoch).bytes(self.info_blob)
        e.u32(self.rewind_to[0]).u64(self.rewind_to[1])
        e.list(self.entry_blobs, lambda e2, b: e2.bytes(b))

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MPGActivate":
        return cls(
            pgid=d.string(), epoch=d.u32(), info_blob=d.bytes(),
            rewind_to=(d.u32(), d.u64()),
            entry_blobs=d.list(lambda d2: d2.bytes()),
        )


@register_message
@dataclass
class MPGPull(Message):
    """Recovering primary → authoritative peer: send me this object
    (the pull side of recovery, ReplicatedBackend::prepare_pull);
    answered by a tid-paired MPGPush.  For erasure pools ``shard`` is
    the requester's acting-set position — the server reconstructs that
    shard's bytes (ECBackend recovery reads); -1 = whole object
    (replicated pools)."""

    TYPE = 23
    pgid: str = ""
    epoch: int = 0
    oid: str = ""
    shard: int = -1

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.pgid).u32(self.epoch).string(self.oid)
        e.s32(self.shard)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MPGPull":
        return cls(
            pgid=d.string(), epoch=d.u32(), oid=d.string(),
            shard=d.s32(),
        )


@register_message
@dataclass
class MClientRequest(Message):
    """FS client → MDS metadata op (MClientRequest: op name + JSON
    args; src/messages/MClientRequest.h role).  ``reqid`` lets the
    session dedup retries across reconnects."""

    TYPE = 40
    op: str = ""
    args: str = "{}"
    reqid: str = ""

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.op).string(self.args).string(self.reqid)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MClientRequest":
        return cls(op=d.string(), args=d.string(), reqid=d.string())


@register_message
@dataclass
class MClientReply(Message):
    """MDS → client op reply (MClientReply role)."""

    TYPE = 41
    rc: int = 0
    outs: str = ""
    outb: str = "{}"

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.rc).string(self.outs).string(self.outb)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MClientReply":
        return cls(rc=d.s32(), outs=d.string(), outb=d.string())


@register_message
@dataclass
class MClientCaps(Message):
    """Capability traffic between MDS and client (MClientCaps role):
    the MDS revokes a session's cap on an inode before a conflicting
    mutation commits; the client invalidates its cached state and
    acks on the same tid."""

    TYPE = 42
    action: str = ""  # "revoke" | "ack"
    ino: int = 0

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.action).s64(self.ino)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MClientCaps":
        return cls(action=d.string(), ino=d.s64())


@register_message
@dataclass
class MRecoveryReserve(Message):
    """Two-sided recovery/backfill reservation handshake
    (src/messages/MRecoveryReserve.h + MBackfillReserve.h, the
    doc/dev/osd_internals/backfill_reservation.rst protocol): the
    primary REQUESTs a slot at the replica before pushing, the
    replica GRANTs or DENYs against its own osd_max_backfills cap,
    and a RELEASE returns the slot when recovery finishes (or
    fails).  Denied primaries retry on a later tick instead of
    overrunning a busy peer."""

    TYPE = 44
    op: str = ""  # "request" | "grant" | "deny" | "release"
    pgid: str = ""
    epoch: int = 0
    from_osd: int = -1

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.op).string(self.pgid)
        e.u32(self.epoch).s64(self.from_osd)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MRecoveryReserve":
        return cls(
            op=d.string(), pgid=d.string(), epoch=d.u32(),
            from_osd=d.s64(),
        )


@register_message
@dataclass
class MMgrReport(Message):
    """Daemon → mgr perf-counter report (src/messages/MMgrReport.h
    role): the daemon name plus a JSON perf dump, pushed on the
    daemon's tick so the mgr's stats plane sees live counters.

    ``spans`` piggybacks the daemon's drained trace spans (a JSON
    list, common/tracing.py shape) on the same report — the mgr
    ``tracing`` module ingests them, so distributed tracing rides the
    existing stats plane instead of needing its own session.

    ``crashes`` piggybacks pending crash reports (a JSON list,
    common/crash.py shape) the same way — the mgr ``crash`` module
    ingests them and raises RECENT_CRASH."""

    TYPE = 43
    daemon: str = ""
    perf: str = "{}"
    spans: str = "[]"
    crashes: str = "[]"

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.daemon).string(self.perf).string(self.spans)
        e.string(self.crashes)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MMgrReport":
        return cls(
            daemon=d.string(), perf=d.string(), spans=d.string(),
            # versioned-decode tolerance: frames from before the
            # crash plane carry no 4th string
            crashes=d.string() if d.remaining() else "[]",
        )


@register_message
@dataclass
class MRepScrub(Message):
    """Primary → acting-set member scrub traffic (the MOSDRepScrub +
    scrub-reservation roles, src/messages/MOSDRepScrub.h and the
    ScrubReserver handshake):

    - ``op="reserve"``/``"release"``: the osd_max_scrubs reservation
      handshake — the replica grants or denies a scrub slot against
      its own cap before the primary starts digesting chunks.
    - ``op="ls"``: list this PG's object names, so the primary scrubs
      objects it has itself lost.
    - ``op="scan"``: build a digest map over ``oids`` (size + omap +
      xattr digests; payload crc32c when ``deep``) — the MOSDRepScrub
      → ScrubMap round, answered by MScrubMap."""

    TYPE = 46
    op: str = "scan"  # reserve | release | ls | scan
    pgid: str = ""
    epoch: int = 0
    from_osd: int = -1
    deep: bool = False
    oids: list = field(default_factory=list)

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.op).string(self.pgid).u32(self.epoch)
        e.s32(self.from_osd).bool(self.deep)
        e.list(self.oids, lambda e2, o: e2.string(o))

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MRepScrub":
        return cls(
            op=d.string(), pgid=d.string(), epoch=d.u32(),
            from_osd=d.s32(), deep=d.bool(),
            oids=d.list(lambda d2: d2.string()),
        )


@register_message
@dataclass
class MScrubMap(Message):
    """Acting-set member → primary scrub answer (the ScrubMap carry
    of MOSDRepScrubMap): ``map_json`` is the JSON digest map for
    ``scan`` (oid → {size, omap_digest, attrs_digest, data_digest,
    hinfo}), the JSON name list for ``ls``, and empty for the
    reservation verdicts, where ``ok`` is grant/deny."""

    TYPE = 47
    pgid: str = ""
    from_osd: int = -1
    ok: bool = True
    error: str = ""
    map_json: str = ""

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.pgid).s32(self.from_osd).bool(self.ok)
        e.string(self.error).string(self.map_json)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MScrubMap":
        return cls(
            pgid=d.string(), from_osd=d.s32(), ok=d.bool(),
            error=d.string(), map_json=d.string(),
        )


@register_message
@dataclass
class MScrubCommand(Message):
    """Client/CLI → primary OSD scrub-plane command (the path `ceph
    pg (deep-)scrub`, `ceph pg repair`, and `rados
    list-inconsistent-obj` take after the mon names the primary —
    the mgr→OSD scrub order of DaemonServer::handle_command).
    Answered with an MMonCommandReply (rc/outs/outb)."""

    TYPE = 48
    op: str = "scrub"  # scrub | deep-scrub | repair | list-inconsistent-obj
    pgid: str = ""

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.op).string(self.pgid)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MScrubCommand":
        return cls(op=d.string(), pgid=d.string())


@register_message
@dataclass
class MLog(Message):
    """Daemon → mon cluster-log batch (src/messages/MLog.h): the
    LogClient's drained entries (common/log_client.py shape, a JSON
    list) bound for the monitor's LogMonitor store, where they become
    ``ceph log last``."""

    TYPE = 45
    name: str = ""  # sending daemon identity
    entries: str = "[]"

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.name).string(self.entries)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MLog":
        return cls(name=d.string(), entries=d.string())


# MOSDBackoff ops (src/messages/MOSDBackoff.h CEPH_OSD_BACKOFF_OP_*)
BACKOFF_OP_BLOCK = "block"
BACKOFF_OP_UNBLOCK = "unblock"


@register_message
@dataclass
class MOSDBackoff(Message):
    """OSD → client backoff protocol (src/messages/MOSDBackoff.h +
    the Backoff struct of src/osd/osd_types.h): when a PG cannot take
    an op (peering after a partition, OSD full), the OSD answers the
    op with a tid-paired BLOCK — the Objecter PARKS every op bound
    for that PG instead of hammering resends — and later sends an
    un-paired UNBLOCK (same pgid + id) that releases them.  ``reason``
    ("peering" | "full") is advisory, for dump_backoffs."""

    TYPE = 49
    op: str = BACKOFF_OP_BLOCK
    pgid: str = ""
    id: int = 0
    reason: str = ""
    epoch: int = 0

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.op).string(self.pgid).u64(self.id)
        e.string(self.reason).u32(self.epoch)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MOSDBackoff":
        return cls(
            op=d.string(), pgid=d.string(), id=d.u64(),
            reason=d.string(), epoch=d.u32(),
        )


@register_message
@dataclass
class MCommand(Message):
    """CLI → daemon command (src/messages/MCommand.h): the `ceph
    tell <daemon> ...` surface — the mon resolves the daemon's
    address, the CLI dispatches the JSON command dict here, and the
    daemon answers with MMonCommandReply.  Carries the fault-plane
    commands (`fault set/clear/list`) and `dump_backoffs`."""

    TYPE = 50
    cmd: str = "{}"

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.cmd)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MCommand":
        return cls(cmd=d.string())


@register_message
@dataclass
class MPGStats(Message):
    """OSD → mgr per-PG statistics (src/messages/MPGStats.h): every
    stat-report tick the OSD sends the PG-stat dicts for the PGs it
    leads (state string, object/byte counts, degraded / misplaced /
    unfound accounting, recovery watermark) plus any in-flight
    progress events (scrub/repair chunks).  ``stats`` and ``events``
    are JSON lists — the mgr folds them into the PGMap digest it
    pushes to the mon."""

    TYPE = 51
    osd: int = 0
    epoch: int = 0
    stats: str = "[]"
    events: str = "[]"

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.osd).u32(self.epoch)
        e.string(self.stats).string(self.events)

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MPGStats":
        return cls(
            osd=d.s32(), epoch=d.u32(),
            stats=d.string(), events=d.string(),
        )
