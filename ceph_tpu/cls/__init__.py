"""Object classes (cls) — in-OSD stored procedures
(src/cls/, src/objclass/class_api.cc, src/osd/ClassHandler.cc).

The reference loads ``libcls_*.so`` modules into the OSD; pools call
their methods through CEPH_OSD_OP_CALL (PrimaryLogPG::do_osd_ops →
ClassHandler dispatch).  Here classes self-register with the
``ClassHandler`` registry (the dlopen role, same pattern as the EC
and compressor registries) and methods declare RD/WR flags exactly
like cls_register_cxx_method.

A method receives a ``MethodContext`` exposing the object primitives
(cls_cxx_read/stat/getxattr/...); WRITE methods stage mutations
(write_full / setxattr / remove) that the OSD folds into the SAME
replicated, logged transaction as any client write — a failed method
aborts with no side effects, matching the reference's all-or-nothing
op semantics.

Built-ins mirror the reference's most-used classes: ``hello``
(cls_hello), ``lock`` (cls_lock: exclusive/shared cooperative locks),
``version`` (cls_version: monotone object versions), ``log``
(cls_log: timestamped appends with trim).
"""

from __future__ import annotations

import json
import time

__all__ = [
    "ClassError",
    "ClassHandler",
    "MethodContext",
    "RD",
    "WR",
    "default_handler",
]

RD = 1  # CLS_METHOD_RD
WR = 2  # CLS_METHOD_WR


class ClassError(Exception):
    """Method failure — surfaces to the client as an op error."""


class MethodContext:
    """The objclass API surface handed to methods (class_api.cc):
    reads hit the live object; writes stage into the op's transaction."""

    def __init__(
        self,
        read_fn,
        attrs: dict[str, bytes],
        exists: bool,
        omap_fn=None,
    ):
        self._read = read_fn
        self._attrs = dict(attrs)
        self._omap_fn = omap_fn
        self._omap_cache: dict[str, bytes] | None = None
        self.exists = exists
        # staged mutations the OSD materializes into the txn
        self.new_data: bytes | None = None
        self.new_attrs: dict[str, bytes] = {}
        self.new_omap: dict[str, bytes] = {}
        self.rm_omap: set[str] = set()
        self.removed = False
        # payloads to deliver to the object's watchers AFTER the op
        # commits (cls_cxx_notify; cls_lock's unlock broadcast)
        self.notifies: list[bytes] = []

    # -- reads (cls_cxx_read / stat / getxattr) ----------------------------
    def read(self) -> bytes:
        if self.new_data is not None:
            return self.new_data
        return self._read() if self.exists else b""

    def stat(self) -> int:
        return len(self.read())

    def getxattr(self, name: str) -> bytes | None:
        if name in self.new_attrs:
            return self.new_attrs[name]
        return self._attrs.get(name)

    # -- omap (cls_cxx_map_get_val / get_vals / set_val / remove_key) ------
    def _omap_base(self) -> dict[str, bytes]:
        if self._omap_cache is None:
            self._omap_cache = (
                dict(self._omap_fn())
                if self._omap_fn is not None and self.exists
                else {}
            )
        return self._omap_cache

    def omap_get(self) -> dict[str, bytes]:
        """Merged view: stored omap + staged writes of THIS op."""
        merged = dict(self._omap_base())
        for k in self.rm_omap:
            merged.pop(k, None)
        merged.update(self.new_omap)
        return merged

    def omap_get_val(self, key: str) -> bytes | None:
        return self.omap_get().get(key)

    def omap_set(self, kv: dict[str, bytes]) -> None:
        for k, v in kv.items():
            self.new_omap[k] = bytes(v)
            self.rm_omap.discard(k)

    def omap_rm(self, keys) -> None:
        for k in keys:
            self.rm_omap.add(k)
            self.new_omap.pop(k, None)

    # -- staged writes (cls_cxx_write_full / setxattr / remove) ------------
    def write_full(self, data: bytes) -> None:
        self.new_data = bytes(data)
        self.removed = False

    def setxattr(self, name: str, value: bytes) -> None:
        self.new_attrs[name] = bytes(value)

    def remove(self) -> None:
        self.removed = True
        self.new_data = None

    def notify(self, payload: bytes) -> None:
        """Queue a watcher notification delivered once the op commits."""
        self.notifies.append(bytes(payload))

    @property
    def has_staged_writes(self) -> bool:
        return bool(
            self.new_data is not None
            or self.new_attrs
            or self.new_omap
            or self.rm_omap
            or self.removed
        )


class ClassHandler:
    """class/method registry (ClassHandler.cc + cls_register)."""

    def __init__(self):
        self._classes: dict[str, dict[str, tuple[int, object]]] = {}

    def register(self, cls: str, method: str, flags: int, fn) -> None:
        self._classes.setdefault(cls, {})[method] = (flags, fn)

    def cls_method(self, cls: str, method: str, flags: int):
        def deco(fn):
            self.register(cls, method, flags, fn)
            return fn

        return deco

    def flags_of(self, cls: str, method: str) -> int:
        entry = self._classes.get(cls, {}).get(method)
        if entry is None:
            raise ClassError(
                f"class {cls!r} method {method!r} not found (-EOPNOTSUPP)"
            )
        return entry[0]

    def call(
        self, cls: str, method: str, ctx: MethodContext, indata: bytes
    ) -> bytes:
        flags, fn = self._classes.get(cls, {}).get(method, (0, None))
        if fn is None:
            raise ClassError(
                f"class {cls!r} method {method!r} not found (-EOPNOTSUPP)"
            )
        return fn(ctx, indata) or b""

    def classes(self) -> list[str]:
        return sorted(self._classes)


default_handler = ClassHandler()


# -- built-in classes ------------------------------------------------------

_LOCK_ATTR = "cls_lock"


@default_handler.cls_method("hello", "say_hello", RD)
def _hello(ctx: MethodContext, indata: bytes) -> bytes:
    """cls_hello's say_hello (src/cls/hello/cls_hello.cc)."""
    name = indata.decode() or "world"
    return f"Hello, {name}!".encode()


@default_handler.cls_method("hello", "record_hello", WR)
def _record_hello(ctx: MethodContext, indata: bytes) -> bytes:
    ctx.write_full(b"Hello, " + (indata or b"world") + b"!")
    return b""


def _lock_state(ctx: MethodContext) -> dict:
    raw = ctx.getxattr(_LOCK_ATTR)
    return json.loads(raw) if raw else {"type": "", "holders": {}}


@default_handler.cls_method("lock", "lock", WR)
def _lock(ctx: MethodContext, indata: bytes) -> bytes:
    """cls_lock lock_op: exclusive or shared cooperative lock."""
    req = json.loads(indata)
    name, typ = req["cookie"], req.get("type", "exclusive")
    state = _lock_state(ctx)
    if state["holders"]:
        if typ == "exclusive":
            # exclusive needs to be the SOLE holder (an upgrade while
            # other shared holders remain would not be exclusive)
            if set(state["holders"]) != {name}:
                raise ClassError("object is locked (-EBUSY)")
        elif state["type"] == "exclusive":
            if name not in state["holders"]:
                raise ClassError("object is locked (-EBUSY)")
    state["type"] = typ
    state["holders"][name] = time.time()
    ctx.setxattr(_LOCK_ATTR, json.dumps(state).encode())
    return b""


@default_handler.cls_method("lock", "unlock", WR)
def _unlock(ctx: MethodContext, indata: bytes) -> bytes:
    req = json.loads(indata)
    state = _lock_state(ctx)
    if req["cookie"] not in state["holders"]:
        raise ClassError("no such lock holder (-ENOENT)")
    del state["holders"][req["cookie"]]
    if not state["holders"]:
        state["type"] = ""
    ctx.setxattr(_LOCK_ATTR, json.dumps(state).encode())
    # waiters watch the object and retry on this broadcast
    # (cls_lock's unlock → watch/notify wakeup pattern)
    ctx.notify(
        json.dumps({"event": "unlocked", "cookie": req["cookie"]}).encode()
    )
    return b""


@default_handler.cls_method("lock", "get_info", RD)
def _lock_info(ctx: MethodContext, indata: bytes) -> bytes:
    return json.dumps(_lock_state(ctx)).encode()


@default_handler.cls_method("version", "set", WR)
def _version_set(ctx: MethodContext, indata: bytes) -> bytes:
    ctx.setxattr("cls_version", indata)
    return b""


@default_handler.cls_method("version", "inc", WR)
def _version_inc(ctx: MethodContext, indata: bytes) -> bytes:
    cur = int(ctx.getxattr("cls_version") or b"0")
    ctx.setxattr("cls_version", str(cur + 1).encode())
    return str(cur + 1).encode()


@default_handler.cls_method("version", "read", RD)
def _version_read(ctx: MethodContext, indata: bytes) -> bytes:
    return ctx.getxattr("cls_version") or b"0"


# cls_log (src/cls/log/cls_log.cc): entries live in the OMAP keyed by
# zero-padded "<stamp>.<seq>" so listing pages in time order and trim
# is a ranged key removal — the index-style workload omap exists for.

_LOG_SEQ_ATTR = "cls_log_seq"


def _log_key(stamp: float, seq: int) -> str:
    return f"{stamp:020.6f}.{seq:012d}"


@default_handler.cls_method("log", "add", WR)
def _log_add(ctx: MethodContext, indata: bytes) -> bytes:
    """cls_log add: one omap entry per line, timestamp-ordered keys."""
    seq = int(ctx.getxattr(_LOG_SEQ_ATTR) or b"0")
    entries = json.loads(indata) if indata.startswith(b"[") else [
        indata.decode()
    ]
    now = time.time()
    staged: dict[str, bytes] = {}
    for entry in entries:
        seq += 1
        staged[_log_key(now, seq)] = json.dumps(
            {"stamp": now, "entry": entry}
        ).encode()
    ctx.omap_set(staged)
    ctx.setxattr(_LOG_SEQ_ATTR, str(seq).encode())
    return b""


@default_handler.cls_method("log", "list", RD)
def _log_list(ctx: MethodContext, indata: bytes) -> bytes:
    """cls_log list: [from_key, max] page of entries in key order."""
    req = json.loads(indata) if indata else {}
    start = req.get("from", "")
    limit = int(req.get("max", -1))
    omap = ctx.omap_get()
    out = []
    for key in sorted(omap):
        if start and key <= start:
            continue
        out.append({"key": key, **json.loads(omap[key])})
        if 0 <= limit <= len(out):
            break
    return json.dumps(out).encode()


@default_handler.cls_method("log", "trim", WR)
def _log_trim(ctx: MethodContext, indata: bytes) -> bytes:
    """cls_log trim: remove entries with key <= to_key (or keep the
    newest N when indata is a bare integer)."""
    omap = ctx.omap_get()
    keys = sorted(omap)
    if indata.isdigit():
        keep = int(indata)
        doomed = keys[: max(0, len(keys) - keep)]
    else:
        req = json.loads(indata) if indata else {}
        to_key = req.get("to", "")
        doomed = [k for k in keys if k <= to_key]
    ctx.omap_rm(doomed)
    return b""
