"""Deterministic fault schedules (the thrashosds config surface:
``chance_down``, ``chance_test_map_discontinuity``, ``timeout`` knobs
of qa/tasks/thrashosds, collapsed to one seeded generator).

A ``Schedule`` is a flat, time-ordered list of ``ScheduleEvent``s —
the *entire* chaos plan for a run.  ``Schedule.from_seed`` derives it
from ONE ``random.Random(seed)`` with a fixed draw pattern, so the
same seed always yields the byte-identical event list (and JSON), and
a different seed yields different weather.  Nothing here touches a
cluster: generation is pure, which is what makes replay and shrinking
(qa/shrink.py) trivial — a run is ``execute(schedule)``, a repro is
the schedule JSON, a shrunk repro is a subset of the same list.

Event grammar (args per kind):

========  ==========================================================
kind      effect (executed by qa/thrasher.py)
========  ==========================================================
kill      SIGKILL-equivalent OSD death (WAL abandoned un-flushed)
revive    remount the WAL (crash replay) + reboot the OSD
wal_kill  kill + revive in one step (crash-restart in place)
out       ``ceph osd out`` — CRUSH stops mapping to it
in        ``ceph osd in``
reweight  ``ceph osd reweight`` to args["weight"] (0.5..1.0)
netsplit  isolate osd args["osd"] from every other OSD (symmetric
          partition via msg/faults.py)
heal_netsplit  clear the partition everywhere
lossy     delay+jitter+dup netem rule on the client->osd.N path
clear_faults   clear every rule and partition on every messenger
power_loss     whole-cluster crash: every OSD's WAL abandoned, then
               every OSD remounted (replay) and rebooted
fill_pressure  shrink one OSD's store capacity until it is
               args["ratio"] full (drives OSD_FULL + backoff parks)
fill_release   restore every shrunk capacity
scrub     order an on-demand (deep-)scrub on a random live PG
settle    quiet gap — no fault injected
========  ==========================================================

Events that leave lasting damage are generated in *pairs* (kill ->
revive, netsplit -> heal_netsplit, out -> in, fill_pressure ->
fill_release) a few seconds apart, and the executor runs an
unconditional epilogue regardless — so ANY subset of a schedule (the
shrinker's probes) still converges to a healthy cluster.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from random import Random

SCHEDULE_VERSION = 1

# relative pick weights for the initiating event kinds (the closers —
# revive/in/heal/release — are generated as pairs, never picked)
DEFAULT_WEIGHTS: dict[str, float] = {
    "kill": 3.0,
    "wal_kill": 2.0,
    "out": 1.5,
    "reweight": 1.5,
    "netsplit": 2.0,
    "lossy": 3.0,
    "clear_faults": 1.0,
    "power_loss": 0.75,
    "fill_pressure": 0.75,
    "scrub": 2.0,
    "settle": 2.0,
}

# how long a paired fault stays open: U(lo, hi) seconds
_PAIR_WINDOW = {
    "kill": (2.5, 5.0),
    "out": (2.5, 5.0),
    "netsplit": (2.0, 4.0),
    "fill_pressure": (1.5, 3.0),
}
_CLOSER = {
    "kill": "revive",
    "out": "in",
    "netsplit": "heal_netsplit",
    "fill_pressure": "fill_release",
}


def _r(x: float) -> float:
    """Round for byte-stable JSON (ms resolution is plenty)."""
    return round(float(x), 3)


@dataclass
class ScheduleEvent:
    """One planned fault at offset ``t`` seconds from run start."""

    t: float
    kind: str
    args: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"t": _r(self.t), "kind": self.kind, "args": self.args}

    @classmethod
    def from_dict(cls, d: dict) -> "ScheduleEvent":
        return cls(
            t=float(d["t"]),
            kind=str(d["kind"]),
            args=dict(d.get("args", {})),
        )


@dataclass
class Schedule:
    """A full chaos plan: pure data, replayable, shrinkable."""

    seed: int
    duration: float
    osds: int
    events: list[ScheduleEvent] = field(default_factory=list)

    @classmethod
    def from_seed(
        cls,
        seed: int,
        duration: float = 30.0,
        osds: int = 3,
        weights: dict[str, float] | None = None,
        pace: float = 1.0,
    ) -> "Schedule":
        """The generator: ONE Random(seed), a FIXED draw pattern per
        event (kind pick, target pick, per-kind args, pair window) —
        the determinism contract the acceptance criteria assert.
        ``pace`` scales the mean gap between events (>1 = calmer)."""
        rng = Random(int(seed))
        w = dict(DEFAULT_WEIGHTS if weights is None else weights)
        unknown = set(w) - set(DEFAULT_WEIGHTS)
        if unknown:
            raise ValueError(
                f"unknown event kinds: {sorted(unknown)}"
            )
        kinds = sorted(w)  # sorted: dict order must not matter
        cum, total = [], 0.0
        for k in kinds:
            total += max(0.0, float(w[k]))
            cum.append(total)
        events: list[ScheduleEvent] = []
        t = 0.0
        while True:
            t += rng.uniform(0.6, 1.8) * float(pace)
            if t >= duration or total <= 0.0:
                break
            x = rng.uniform(0.0, total)
            kind = next(
                k for k, c in zip(kinds, cum) if x <= c
            )
            ev = ScheduleEvent(t=_r(t), kind=kind, args={})
            # fixed draws per kind — never conditional on state
            osd = rng.randrange(max(1, int(osds)))
            if kind in (
                "kill", "wal_kill", "out", "netsplit",
                "fill_pressure",
            ):
                ev.args = {"osd": osd}
            elif kind == "reweight":
                ev.args = {
                    "osd": osd,
                    "weight": round(rng.uniform(0.5, 1.0), 2),
                }
            elif kind == "lossy":
                ev.args = {
                    "osd": osd,
                    "delay": round(rng.uniform(0.005, 0.03), 3),
                    "jitter": round(rng.uniform(0.0, 0.03), 3),
                    "dup": round(rng.uniform(0.1, 0.4), 2),
                }
            elif kind == "scrub":
                ev.args = {"deep": rng.random() < 0.5}
            if kind == "fill_pressure":
                ev.args["ratio"] = round(rng.uniform(0.955, 0.97), 3)
            events.append(ev)
            closer = _CLOSER.get(kind)
            if closer is not None:
                lo, hi = _PAIR_WINDOW[kind]
                close_args = (
                    {"osd": osd}
                    if closer in ("revive", "in")
                    else {}
                )
                events.append(
                    ScheduleEvent(
                        t=_r(min(t + rng.uniform(lo, hi), duration)),
                        kind=closer,
                        args=close_args,
                    )
                )
        events.sort(key=lambda e: e.t)
        return cls(
            seed=int(seed),
            duration=_r(duration),
            osds=int(osds),
            events=events,
        )

    # -- serialization (the repro/replay surface) ---------------------------
    def to_dict(self) -> dict:
        return {
            "version": SCHEDULE_VERSION,
            "seed": self.seed,
            "duration": self.duration,
            "osds": self.osds,
            "events": [e.to_dict() for e in self.events],
        }

    def to_json(self) -> str:
        """Canonical bytes: sorted keys, no whitespace — the
        byte-identical-across-runs artifact format."""
        return json.dumps(
            self.to_dict(), sort_keys=True, separators=(",", ":")
        )

    @classmethod
    def from_dict(cls, d: dict) -> "Schedule":
        return cls(
            seed=int(d["seed"]),
            duration=float(d["duration"]),
            osds=int(d["osds"]),
            events=[
                ScheduleEvent.from_dict(e) for e in d["events"]
            ],
        )

    @classmethod
    def from_json(cls, text: str) -> "Schedule":
        return cls.from_dict(json.loads(text))

    def subset(self, events: list[ScheduleEvent]) -> "Schedule":
        """The shrinker's probe: same metadata, fewer events."""
        return Schedule(
            seed=self.seed,
            duration=self.duration,
            osds=self.osds,
            events=list(events),
        )
