"""The thrasher — qa/tasks/thrashosds + ceph_manager.py's Thrasher
loop: execute a deterministic fault ``Schedule`` against a LIVE
cluster while the consistency oracle (qa/oracle.py) watches every
client op, then force convergence and audit.

Two cluster harnesses implement the same small surface:

- ``ThrashCluster`` — in-process: one Monitor + mgr(PgMap) + N OSDs
  over ``WALStore(MemStore)``, all on the shared-event-loop stack.
  Daemon "death" abandons the WALStore exactly as a SIGKILL would
  (no close, no flush — the tests/test_wal_store.py crash idiom) and
  revival remounts the SAME wal dir, so acked-write durability is
  really carried by crash replay, not by Python object lifetime.
- ``ProcThrashCluster`` — multi-process: the PR 19 Supervisor fleet;
  kill is a real SIGKILL via the kill-on-request hold API, revival a
  supervisor respawn, and network faults ride ``ceph tell <osd>
  fault ...``.

``Thrasher.run`` executes one schedule: events fire at their offsets
(optionally time-compressed), guarded so ANY subset keeps the cluster
above min_size (that is what makes shrink probes safe), followed by
an unconditional epilogue (heal everything, revive everything, mark
everything in) and a bounded HEALTH_OK convergence check + final
audit.  ``Thrasher.run_with_shrink`` ddmin-minimizes a violating
schedule and emits ``repro_<seed>.json``.

``mutation="suppress_replay"`` deliberately breaks the durability
invariant — every WAL remount first truncates the log — to prove the
oracle fires (the mutation-testing gate: an oracle nobody has seen
fail is an oracle nobody can trust).
"""

from __future__ import annotations

import pathlib
import shutil
import tempfile
import threading
import time
from random import Random

from ..common.perf_counters import PerfCountersBuilder
from .oracle import ConsistencyOracle, HistoryRecorder
from .schedule import Schedule, ScheduleEvent

DEFAULT_SEED = 20260806
MIN_LIVE_IN = 2  # never drop below min_size usable OSDs


def _map_up_in(osdmap, i: int) -> bool:
    """up AND in (weight > 0) per the client's map view."""
    return (
        osdmap.is_up(i)
        and 0 <= i < len(osdmap.osd_weight)
        and osdmap.osd_weight[i] > 0
    )


def build_thrash_perf():
    """The thrasher counter schema (l_thrash_* block) — module-level
    so tools/check_metrics.py lints it without a run."""
    return (
        PerfCountersBuilder("qa.thrasher")
        .add_u64_counter(
            "l_thrash_events", "schedule events executed"
        )
        .add_u64_counter(
            "l_thrash_skipped_events",
            "events skipped by safety guards / capability set",
        )
        .add_u64_counter(
            "l_thrash_violations", "oracle violations recorded"
        )
        .add_u64_counter(
            "l_thrash_shrink_steps", "shrink probe runs executed"
        )
        .create_perf_counters()
    )


# -- fault-plane primitives (shared with tests/chaos.py) --------------------
def addr_str(addr) -> str:
    host, port = addr
    return f"{host}:{port}"


def install_aliases(messengers, aliases: dict[str, str]) -> None:
    """Teach every injector the daemon-name -> address map so rules
    and partitions can say ``osd.1`` / ``mon.2``."""
    for m in messengers:
        for name, addr in aliases.items():
            m.faults.alias(name, addr)


def install_partition(
    messengers, groups, aliases, name="netsplit", seed=DEFAULT_SEED
) -> None:
    """One symmetric netsplit: the same named partition (and seed) on
    every member messenger."""
    for m in messengers:
        m.faults.reseed(seed)
    install_aliases(messengers, aliases)
    for m in messengers:
        m.faults.set_partition(name, groups)


def install_lossy(
    messenger, dst: str, delay=0.02, jitter=0.03, dup=0.4
) -> int:
    """One netem-style delay+jitter+dup rule toward ``dst`` (no
    drops: nothing times out, so a seeded run replays exactly)."""
    return messenger.faults.add_rule(
        dst=dst, delay=delay, jitter=jitter, dup=dup
    )


def heal(messengers, name: str | None = None) -> None:
    for m in messengers:
        if name is not None:
            m.faults.clear_partition(name)
        else:
            m.faults.clear()


def fault_counters(messenger) -> dict:
    return messenger.faults.perf.dump()


def _base_map(n: int):
    """The canonical n-host replicated CRUSH map every harness uses
    (one OSD per straw2 host under "default", a firstn host rule)."""
    from ..crush.builder import CrushMap
    from ..crush.types import CRUSH_BUCKET_STRAW2, Tunables
    from ..osd.osdmap import OSDMap

    cmap = CrushMap(tunables=Tunables())
    hosts = []
    for h in range(n):
        hosts.append(
            cmap.add_bucket(
                CRUSH_BUCKET_STRAW2, 1, [h], [0x10000],
                name=f"host{h}",
            )
        )
    cmap.add_bucket(
        CRUSH_BUCKET_STRAW2, 3, hosts,
        [cmap.buckets[b].weight for b in hosts], name="default",
    )
    cmap.add_simple_rule("rep", "default", "host", mode="firstn")
    return OSDMap.build(cmap, n)


class ThrashCluster:
    """In-process live cluster with crash-real OSD death.

    Every OSD runs over ``WALStore(MemStore(), <wal dir>)``: the wal
    dir on disk is the daemon's only durable state, so kill/revive
    exercises the actual replay path and ``mutation`` can corrupt it.
    """

    caps = frozenset(
        {
            "kill", "revive", "wal_kill", "out", "in", "reweight",
            "netsplit", "heal_netsplit", "lossy", "clear_faults",
            "power_loss", "fill_pressure", "fill_release", "scrub",
            "settle",
        }
    )

    def __init__(
        self,
        n_osds: int = 3,
        seed: int = DEFAULT_SEED,
        workdir: str | None = None,
        pg_num: int = 4,
        mutation: str | None = None,
    ):
        from ..mgr import Manager
        from ..mgr.pgmap import PgMapModule
        from ..mon.monitor import Monitor
        from ..msg import Messenger
        from ..rados import Rados

        self.n = int(n_osds)
        self.seed = int(seed)
        self.mutation = mutation
        self.pool = "qapool"
        self._own_workdir = workdir is None
        self.workdir = pathlib.Path(
            workdir
            if workdir is not None
            else tempfile.mkdtemp(prefix="qa-thrash-")
        )
        self.mon = Monitor(_base_map(self.n), min_reporters=2)
        self.mon_msgr = Messenger("mon")
        self.mon_msgr.add_dispatcher(self.mon)
        self.mon_addr = self.mon_msgr.bind()
        self.mgr = Manager(modules=[PgMapModule], name="qa-mgr")
        self.mgr.start(self.mon_addr)
        self.osds: dict[int, object] = {}
        self.wal_replays: dict[int, int] = {}
        for i in range(self.n):
            self._boot_osd(i)
        self.client = Rados(f"qa-{seed}").connect(*self.mon_addr)
        self.client.objecter.op_timeout = 30.0
        self.pool_id = self.client.pool_create(
            self.pool, pg_num=int(pg_num), size=3, min_size=2
        )
        self.io = self.client.open_ioctx(self.pool)
        self.refresh_aliases()
        self._wait_boot()

    # -- plumbing -----------------------------------------------------------
    def _wal_dir(self, i: int) -> pathlib.Path:
        return self.workdir / f"osd{i}-wal"

    def _make_store(self, i: int):
        from ..store.objectstore import MemStore
        from ..store.wal_store import WALStore

        if self.mutation == "suppress_replay":
            # the deliberate invariant break: throw the log away
            # before every mount, so "crash replay" replays nothing
            shutil.rmtree(self._wal_dir(i), ignore_errors=True)
        return WALStore(MemStore(), self._wal_dir(i))

    def _boot_osd(self, i: int):
        from ..osd.daemon import OSD

        store = self._make_store(i)
        self.wal_replays[i] = (
            self.wal_replays.get(i, 0) + store.replayed_records
        )
        osd = OSD(
            i, store=store, tick_interval=0.2, heartbeat_grace=1.0
        )
        osd.log_keep = 4096  # thrash windows must stay log-recoverable
        osd.boot(*self.mon_addr)
        self.osds[i] = osd
        return osd

    def _wait_boot(self, timeout: float = 20.0):
        from ..msg.messenger import wait_for

        assert wait_for(
            lambda: all(
                self.client.monc.osdmap.is_up(i)
                for i in self.osds
            ),
            timeout,
        ), "OSDs never booted into the map"

    def refresh_aliases(self) -> None:
        """(Re)install osd-name aliases everywhere — revived OSDs
        bind fresh ports, so partitions must re-learn addresses."""
        aliases = {
            f"osd.{i}": addr_str(o.addr)
            for i, o in self.osds.items()
            if getattr(o, "addr", None) is not None
        }
        install_aliases(self.messengers(), aliases)

    def messengers(self) -> list:
        return [self.mon_msgr, self.client.messenger] + [
            o.messenger for o in self.osds.values()
        ]

    def osd_messengers(self) -> list:
        return [o.messenger for o in self.osds.values()]

    # -- daemon lifecycle ---------------------------------------------------
    def kill_osd(self, i: int) -> None:
        """SIGKILL-equivalent: abandon the WAL un-flushed (no close,
        no drain — in-flight acks die with it), then tear the daemon
        down.  The wal dir on disk is all that survives."""
        osd = self.osds.pop(i)
        osd.store._closed = True  # the crash: nothing flushes
        osd._stop.set()
        osd._workq.put(None)
        osd.messenger.shutdown()

    def revive_osd(self, i: int) -> int:
        """Remount the wal dir (crash replay) and reboot the OSD.
        Returns the number of replayed records."""
        before = self.wal_replays.get(i, 0)
        self._boot_osd(i)
        self.refresh_aliases()
        return self.wal_replays[i] - before

    def crash_restart_osd(self, i: int) -> int:
        self.kill_osd(i)
        return self.revive_osd(i)

    def power_loss(self) -> int:
        """Whole-cluster crash: every OSD's WAL abandoned at the same
        instant, then every OSD remounted and rebooted.  Zero acked
        loss across this is the WAL group-commit contract."""
        for i in list(self.osds):
            self.kill_osd(i)
        replayed = 0
        for i in range(self.n):
            replayed += self.revive_osd(i)
        return replayed

    # -- mon surface --------------------------------------------------------
    def mon_command(self, cmd: dict):
        return self.client.mon_command(cmd)

    def mark_out(self, i: int) -> None:
        self.mon_command({"prefix": "osd out", "id": i})

    def mark_in(self, i: int) -> None:
        self.mon_command({"prefix": "osd in", "id": i})

    def reweight(self, i: int, weight: float) -> None:
        self.mon_command(
            {"prefix": "osd reweight", "id": i, "weight": weight}
        )

    def health(self) -> tuple[str, dict]:
        import json

        rc, outb, _outs = self.mon_command({"prefix": "health"})
        if rc != 0:
            return "UNKNOWN", {}
        doc = json.loads(outb)
        return doc.get("status", "UNKNOWN"), doc.get(
            "checks_detail", {}
        )

    def wait_healthy(self, timeout: float = 60.0) -> bool:
        from ..msg.messenger import wait_for

        def ok():
            if not all(
                _map_up_in(self.client.monc.osdmap, i)
                for i in range(self.n)
            ):
                return False
            return self.health()[0] == "HEALTH_OK"

        return wait_for(ok, timeout, interval=0.25)

    # -- fault hooks --------------------------------------------------------
    def scrub_random(self, rng: Random, deep: bool) -> str | None:
        """Order an on-demand scrub on a deterministic-random live
        PG (the scrub-during-fault composition)."""
        for i in sorted(self.osds):
            pgid = self.osds[i].scrubber.request_random(
                rng, deep=deep
            )
            if pgid is not None:
                return pgid
        return None

    def reset_failure_reports(self) -> None:
        """Heal hook: a partition leaves half-counted failure reports
        pending on the mon; a later unrelated report must not tip a
        healthy OSD down with stale counts."""
        self.mon.failures.reset()

    def set_fill(self, i: int, ratio: float):
        """Shrink osd.i's capacity until it is ``ratio`` full (the
        OSD_FULL / backoff-park pressure).  Returns the restore
        value, or None when the osd is down."""
        osd = self.osds.get(i)
        if osd is None:
            return None
        inner = osd.store.inner
        original = inner.total_bytes
        used = max(1, int(inner.statfs()["used"]))
        inner.total_bytes = max(used + 4096, int(used / ratio))
        return original

    def restore_fill(self, i: int, total: int) -> None:
        osd = self.osds.get(i)
        if osd is not None:
            osd.store.inner.total_bytes = total

    # -- teardown -----------------------------------------------------------
    def shutdown(self) -> None:
        for i in list(self.osds):
            try:
                self.kill_osd(i)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for closer in (
            self.client.shutdown,
            self.mgr.shutdown,
            self.mon_msgr.shutdown,
        ):
            try:
                closer()
            except Exception:  # noqa: BLE001
                pass
        if self._own_workdir:
            shutil.rmtree(self.workdir, ignore_errors=True)


class ProcThrashCluster:
    """Multi-process harness: the PR 19 supervised fleet.  Kill is a
    real SIGKILL held against auto-respawn (the kill-on-request API),
    revive a supervisor respawn (real WAL replay in the readiness
    report), and network faults ride ``ceph tell osd.N fault ...``."""

    caps = frozenset(
        {
            "kill", "revive", "wal_kill", "out", "in", "reweight",
            "netsplit", "heal_netsplit", "lossy", "clear_faults",
            "scrub", "settle",
        }
    )

    def __init__(
        self,
        n_osds: int = 3,
        seed: int = DEFAULT_SEED,
        workdir: str | None = None,
        pg_num: int = 4,
        mutation: str | None = None,
    ):
        from ..proc import ClusterSpec, Supervisor
        from ..rados import Rados

        if mutation is not None:
            raise ValueError(
                "mutation modes are in-process only (the proc "
                "harness cannot reach inside a child's store)"
            )
        self.n = int(n_osds)
        self.seed = int(seed)
        self.pool = "qapool"
        self._own_workdir = workdir is None
        self.workdir = pathlib.Path(
            workdir
            if workdir is not None
            else tempfile.mkdtemp(prefix="qa-proc-thrash-")
        )
        self.spec = ClusterSpec.plan(
            str(self.workdir),
            mons=1,
            osds=self.n,
            mgrs=1,
            memstore=True,
            wal=True,
        )
        self.sup = Supervisor(self.spec, min_uptime=0.5)
        self.sup.start(ready_timeout=120)
        self.client = Rados(f"qa-proc-{seed}").connect_any(
            self.spec.mon_addrs
        )
        self.client.objecter.op_timeout = 30.0
        self.pool_id = self.client.pool_create(
            self.pool, pg_num=int(pg_num), size=3, min_size=2
        )
        self.io = self.client.open_ioctx(self.pool)
        self._lossy_rules: list[int] = []

    # -- daemon lifecycle ---------------------------------------------------
    def kill_osd(self, i: int) -> None:
        self.sup.kill(f"osd.{i}", hold=True)

    def revive_osd(self, i: int) -> int:
        role = f"osd.{i}"
        self.sup.respawn(role)
        self.sup.wait_ready([role], timeout=60)
        try:
            return int(self.sup.ready_info(role)["replayed"])
        except (KeyError, TypeError, ValueError):
            return 0

    def crash_restart_osd(self, i: int) -> int:
        self.kill_osd(i)
        return self.revive_osd(i)

    def refresh_aliases(self) -> None:
        pass  # proc rules are address-based (osdmap is authoritative)

    # -- mon / tell surface -------------------------------------------------
    def mon_command(self, cmd: dict):
        return self.client.mon_command(cmd)

    def tell(self, target: str, args: dict):
        """``ceph tell osd.N ...``: the mon names the address, we
        dispatch the MCommand there (the CLI route)."""
        import json

        from ..msg.message import MCommand

        rc, outb, outs = self.mon_command(
            {"prefix": "tell", "target": target, "args": args}
        )
        if rc != 0:
            return rc, "", outs
        t = json.loads(outb)
        host, _, port = t["addr"].rpartition(":")
        conn = self.client.messenger.connect(host, int(port))
        reply = conn.call(
            MCommand(
                tid=self.client.messenger.new_tid(),
                cmd=json.dumps(t["args"]),
            )
        )
        return reply.rc, reply.outb, reply.outs

    def mark_out(self, i: int) -> None:
        self.mon_command({"prefix": "osd out", "id": i})

    def mark_in(self, i: int) -> None:
        self.mon_command({"prefix": "osd in", "id": i})

    def reweight(self, i: int, weight: float) -> None:
        self.mon_command(
            {"prefix": "osd reweight", "id": i, "weight": weight}
        )

    def health(self) -> tuple[str, dict]:
        import json

        rc, outb, _outs = self.mon_command({"prefix": "health"})
        if rc != 0:
            return "UNKNOWN", {}
        doc = json.loads(outb)
        return doc.get("status", "UNKNOWN"), doc.get(
            "checks_detail", {}
        )

    def archive_crashes(self) -> None:
        """SIGKILLed children ride MMgrReport into RECENT_CRASH —
        expected deaths, archived so convergence can reach
        HEALTH_OK."""
        import json

        from ..msg.message import MMonCommand

        rc, outb, _outs = self.mon_command({"prefix": "mgr stat"})
        if rc != 0 or not outb:
            return
        active = json.loads(outb).get("active")
        if not active:
            return
        host, _, port = active["addr"].rpartition(":")
        try:
            conn = self.client.messenger.connect(host, int(port))
            conn.call(
                MMonCommand(
                    cmd=json.dumps(
                        {"prefix": "crash archive", "id": "all"}
                    )
                )
            )
        except Exception:  # noqa: BLE001 — convergence retries
            pass

    def wait_healthy(self, timeout: float = 90.0) -> bool:
        from ..msg.messenger import wait_for

        def ok():
            if not all(
                _map_up_in(self.client.monc.osdmap, i)
                for i in range(self.n)
            ):
                return False
            status, checks = self.health()
            if "RECENT_CRASH" in checks:
                self.archive_crashes()
                return False
            return status == "HEALTH_OK"

        return wait_for(ok, timeout, interval=0.5)

    # -- fault hooks --------------------------------------------------------
    def _osd_addr(self, i: int) -> str | None:
        return self.client.monc.osdmap.osd_addrs.get(i)

    def install_lossy(self, i: int, delay, jitter, dup) -> None:
        addr = self._osd_addr(i)
        if addr:
            self._lossy_rules.append(
                install_lossy(
                    self.client.messenger, addr, delay, jitter, dup
                )
            )

    def install_netsplit(self, victim: int) -> None:
        """Symmetric victim isolation with address-based drop rules
        installed over ``tell`` on every live daemon."""
        vaddr = self._osd_addr(victim)
        if vaddr is None:
            return
        for j in range(self.n):
            if j == victim:
                continue
            jaddr = self._osd_addr(j)
            if jaddr is None:
                continue
            self.tell(
                f"osd.{j}",
                {"prefix": "fault set", "dst": vaddr, "drop": 1.0},
            )
            self.tell(
                f"osd.{victim}",
                {"prefix": "fault set", "dst": jaddr, "drop": 1.0},
            )

    def clear_faults(self) -> None:
        for i in range(self.n):
            try:
                self.tell(f"osd.{i}", {"prefix": "fault clear"})
            except Exception:  # noqa: BLE001 — daemon may be down
                pass
        self.client.messenger.faults.clear()
        self._lossy_rules.clear()

    def scrub_random(self, rng: Random, deep: bool) -> str | None:
        import json

        from ..msg.message import MScrubCommand

        pg_num = self.client.monc.osdmap.pools[
            self.pool_id
        ].pg_num
        pgid = f"{self.pool_id}.{rng.randrange(pg_num)}"
        rc, outb, _outs = self.mon_command(
            {
                "prefix": (
                    "pg deep-scrub" if deep else "pg scrub"
                ),
                "pgid": pgid,
            }
        )
        if rc != 0 or not outb:
            return None
        t = json.loads(outb)
        host, _, port = t["addr"].rpartition(":")
        conn = self.client.messenger.connect(host, int(port))
        conn.call(
            MScrubCommand(
                tid=self.client.messenger.new_tid(),
                op=t["op"],
                pgid=t["pgid"],
            )
        )
        return pgid

    def reset_failure_reports(self) -> None:
        pass  # mon is out-of-process; its aggregator self-heals

    def shutdown(self) -> None:
        for closer in (
            self.client.shutdown,
            self.sup.stop,
        ):
            try:
                closer()
            except Exception:  # noqa: BLE001
                pass
        if self._own_workdir:
            shutil.rmtree(self.workdir, ignore_errors=True)


class Thrasher:
    """Execute a Schedule against a live cluster under the oracle.

    The executor tracks its OWN alive/in sets (a pure function of the
    events applied, never of cluster timing) and guards every event
    so at least MIN_LIVE_IN OSDs stay alive AND in — which is what
    makes arbitrary shrink subsets safe to execute.  Whatever the
    events did, the epilogue heals faults, revives the dead, marks
    everything in, restores weights and capacity, then demands
    HEALTH_OK within ``convergence_timeout`` and runs the final
    audit."""

    def __init__(
        self,
        schedule: Schedule,
        mode: str = "inprocess",
        mutation: str | None = None,
        time_scale: float = 1.0,
        convergence_timeout: float = 60.0,
        workload_clients: int = 2,
        objects_per_client: int = 4,
        perf=None,
        workdir: str | None = None,
    ):
        if mutation not in (None, "suppress_replay"):
            raise ValueError(f"unknown mutation: {mutation!r}")
        self.schedule = schedule
        self.mode = mode
        self.mutation = mutation
        self.time_scale = max(0.1, float(time_scale))
        self.convergence_timeout = float(convergence_timeout)
        self.workload_clients = int(workload_clients)
        self.objects_per_client = int(objects_per_client)
        self.perf = perf if perf is not None else build_thrash_perf()
        self.workdir = workdir

    def _make_cluster(self):
        cls = (
            ProcThrashCluster
            if self.mode == "proc"
            else ThrashCluster
        )
        return cls(
            n_osds=self.schedule.osds,
            seed=self.schedule.seed,
            mutation=self.mutation,
            workdir=self.workdir,
        )

    # -- one run ------------------------------------------------------------
    def run(self, events: list[ScheduleEvent] | None = None) -> dict:
        events = (
            list(self.schedule.events)
            if events is None
            else list(events)
        )
        cluster = self._make_cluster()
        oracle = ConsistencyOracle(perf=self.perf)
        recorder = HistoryRecorder(
            cluster.io,
            oracle,
            seed=self.schedule.seed,
            clients=self.workload_clients,
            objects_per_client=self.objects_per_client,
        )
        trace: list[dict] = []
        state = _ExecState(self.schedule.osds)
        try:
            recorder.start()
            time.sleep(0.5 / self.time_scale)
            t0 = time.monotonic()
            for idx, ev in enumerate(events):
                delay = (
                    t0 + ev.t / self.time_scale - time.monotonic()
                )
                if delay > 0:
                    time.sleep(delay)
                applied, note = self._apply(
                    cluster, state, ev, idx
                )
                self.perf.inc(
                    "l_thrash_events"
                    if applied
                    else "l_thrash_skipped_events"
                )
                trace.append(
                    {
                        "t": ev.t,
                        "kind": ev.kind,
                        "applied": applied,
                        "note": note,
                    }
                )
            self._epilogue(cluster, state)
            recorder.stop()
            converged = cluster.wait_healthy(
                self.convergence_timeout
            )
            if not converged:
                status, checks = cluster.health()
                oracle.add_violation(
                    "no_health_convergence",
                    {
                        "status": status,
                        "checks": sorted(checks),
                        "timeout": self.convergence_timeout,
                    },
                )
            audited = recorder.final_audit()
            return {
                "seed": self.schedule.seed,
                "mode": self.mode,
                "mutation": self.mutation,
                "events": len(events),
                "events_applied": sum(
                    1 for e in trace if e["applied"]
                ),
                "trace": trace,
                "ops": recorder.ops,
                "op_errors": recorder.errors,
                "audited": audited,
                "converged": converged,
                "wal_replays": dict(
                    getattr(cluster, "wal_replays", {})
                ),
                "violations": [
                    v.to_dict() for v in oracle.violations
                ],
            }
        finally:
            recorder.stop(timeout=5.0)
            cluster.shutdown()

    # -- event execution ----------------------------------------------------
    def _apply(self, cluster, state, ev, idx) -> tuple[bool, str]:
        if ev.kind not in cluster.caps:
            return False, "unsupported by harness"
        # per-event deterministic rng (scrub target picks etc.):
        # a pure function of (seed, event index), independent of
        # which other events a shrink subset kept
        rng = Random((self.schedule.seed << 20) ^ (idx + 1))
        a = ev.args
        osd = a.get("osd")
        if ev.kind == "kill":
            if osd not in state.alive:
                return False, "already down"
            if not state.safe_without(osd):
                return False, "guard: would drop below min live"
            cluster.kill_osd(osd)
            state.alive.discard(osd)
            return True, ""
        if ev.kind == "revive":
            if osd in state.alive:
                return False, "already up"
            replayed = cluster.revive_osd(osd)
            state.alive.add(osd)
            return True, f"replayed={replayed}"
        if ev.kind == "wal_kill":
            if osd not in state.alive:
                return False, "down"
            if state.netsplit is not None:
                return False, "guard: netsplit active"
            replayed = cluster.crash_restart_osd(osd)
            return True, f"replayed={replayed}"
        if ev.kind == "out":
            if osd not in state.in_set:
                return False, "already out"
            if not state.safe_without(osd):
                return False, "guard: would drop below min live"
            cluster.mark_out(osd)
            state.in_set.discard(osd)
            return True, ""
        if ev.kind == "in":
            if osd in state.in_set:
                return False, "already in"
            cluster.mark_in(osd)
            state.in_set.add(osd)
            return True, ""
        if ev.kind == "reweight":
            cluster.reweight(osd, a["weight"])
            state.reweighted.add(osd)
            return True, ""
        if ev.kind == "netsplit":
            if state.netsplit is not None:
                return False, "already split"
            if osd not in state.alive or not state.safe_without(
                osd
            ):
                return False, "guard: victim down or min live"
            self._install_netsplit(cluster, osd)
            state.netsplit = osd
            return True, ""
        if ev.kind == "heal_netsplit":
            if state.netsplit is None:
                return False, "no split"
            self._heal_netsplit(cluster)
            state.netsplit = None
            return True, ""
        if ev.kind == "lossy":
            self._install_lossy(cluster, a)
            state.lossy = True
            return True, ""
        if ev.kind == "clear_faults":
            self._clear_faults(cluster, state)
            return True, ""
        if ev.kind == "power_loss":
            replayed = cluster.power_loss()
            state.alive = set(range(self.schedule.osds))
            state.netsplit = None
            return True, f"replayed={replayed}"
        if ev.kind == "fill_pressure":
            if osd in state.fills or osd not in state.alive:
                return False, "already filled or down"
            original = cluster.set_fill(osd, a["ratio"])
            if original is None:
                return False, "store unavailable"
            state.fills[osd] = original
            return True, ""
        if ev.kind == "fill_release":
            if not state.fills:
                return False, "nothing filled"
            for i, total in list(state.fills.items()):
                cluster.restore_fill(i, total)
            state.fills.clear()
            return True, ""
        if ev.kind == "scrub":
            pgid = cluster.scrub_random(rng, bool(a.get("deep")))
            return (
                (True, f"pg={pgid}")
                if pgid is not None
                else (False, "no scrubbable pg")
            )
        if ev.kind == "settle":
            return True, ""
        return False, f"unknown kind {ev.kind!r}"

    def _install_netsplit(self, cluster, victim: int) -> None:
        if isinstance(cluster, ProcThrashCluster):
            cluster.install_netsplit(victim)
            return
        cluster.refresh_aliases()
        groups = [
            [f"osd.{victim}"],
            [
                f"osd.{j}"
                for j in cluster.osds
                if j != victim
            ],
        ]
        aliases = {
            f"osd.{j}": addr_str(o.addr)
            for j, o in cluster.osds.items()
        }
        install_partition(
            cluster.osd_messengers(),
            groups,
            aliases,
            name="qa-netsplit",
            seed=self.schedule.seed,
        )

    def _heal_netsplit(self, cluster) -> None:
        if isinstance(cluster, ProcThrashCluster):
            cluster.clear_faults()
        else:
            heal(cluster.osd_messengers(), "qa-netsplit")
            cluster.reset_failure_reports()

    def _install_lossy(self, cluster, a: dict) -> None:
        if isinstance(cluster, ProcThrashCluster):
            cluster.install_lossy(
                a["osd"], a["delay"], a["jitter"], a["dup"]
            )
            return
        osd = cluster.osds.get(a["osd"])
        if osd is None:
            return
        cluster.client.messenger.faults.alias(
            f"osd.{a['osd']}", addr_str(osd.addr)
        )
        install_lossy(
            cluster.client.messenger,
            f"osd.{a['osd']}",
            a["delay"],
            a["jitter"],
            a["dup"],
        )

    def _clear_faults(self, cluster, state) -> None:
        if isinstance(cluster, ProcThrashCluster):
            cluster.clear_faults()
        else:
            heal(cluster.messengers())
            cluster.reset_failure_reports()
        state.netsplit = None
        state.lossy = False

    def _epilogue(self, cluster, state) -> None:
        """Unconditional convergence path — runs the same whatever
        subset of events executed (the shrinkability contract)."""
        self._clear_faults(cluster, state)
        for i, total in list(state.fills.items()):
            cluster.restore_fill(i, total)
        state.fills.clear()
        for i in sorted(
            set(range(self.schedule.osds)) - state.alive
        ):
            cluster.revive_osd(i)
            state.alive.add(i)
        for i in sorted(
            set(range(self.schedule.osds)) - state.in_set
        ):
            cluster.mark_in(i)
            state.in_set.add(i)
        for i in sorted(state.reweighted):
            cluster.reweight(i, 1.0)
        state.reweighted.clear()

    # -- shrink -------------------------------------------------------------
    def run_with_shrink(
        self,
        artifact_dir: str | None = None,
        max_shrink_runs: int = 24,
    ) -> dict:
        """One full run; on violation, ddmin the event list to a
        minimal reproducing subset and emit ``repro_<seed>.json``."""
        from .shrink import shrink_events, write_repro

        report = self.run()
        if not report["violations"]:
            return report
        kinds = {v["kind"] for v in report["violations"]}

        def reproduces(subset) -> bool:
            r = self.run(events=list(subset))
            return any(
                v["kind"] in kinds for v in r["violations"]
            )

        minimal, runs = shrink_events(
            self.schedule.events,
            reproduces,
            perf=self.perf,
            max_runs=max_shrink_runs,
        )
        report["minimal_events"] = [
            e.to_dict() for e in minimal
        ]
        report["shrink_runs"] = runs
        if artifact_dir is not None:
            report["repro_path"] = str(
                write_repro(
                    artifact_dir,
                    self.schedule,
                    minimal,
                    report["violations"],
                    runs,
                    mutation=self.mutation,
                )
            )
        return report


class _ExecState:
    """The executor's own bookkeeping — a pure function of the
    applied events, so guards behave identically across replays and
    shrink probes."""

    def __init__(self, n: int):
        self.n = n
        self.alive = set(range(n))
        self.in_set = set(range(n))
        self.netsplit: int | None = None
        self.lossy = False
        self.fills: dict[int, int] = {}
        self.reweighted: set[int] = set()

    def safe_without(self, osd: int) -> bool:
        usable = (self.alive & self.in_set) - {osd}
        return len(usable) >= MIN_LIVE_IN


def replay_repro(
    path, mode: str = "inprocess", time_scale: float = 1.0
) -> dict:
    """Re-execute the MINIMAL schedule from a repro artifact (the
    standalone-reproduction contract: the artifact alone restarts
    the investigation — including the mutation, when the violation
    was a deliberate oracle proof)."""
    from .shrink import load_repro

    doc = load_repro(path)
    minimal = Schedule.from_dict(doc["minimal_schedule"])
    thr = Thrasher(
        minimal,
        mode=mode,
        mutation=doc.get("mutation"),
        time_scale=time_scale,
        convergence_timeout=30.0,
    )
    return thr.run()


# make `python -m ceph_tpu.qa.thrasher --seed N --duration S` a
# standalone smoke driver
def main(argv=None) -> int:
    import argparse
    import json

    p = argparse.ArgumentParser(description="deterministic thrasher")
    p.add_argument("--seed", type=int, default=DEFAULT_SEED)
    p.add_argument("--duration", type=float, default=30.0)
    p.add_argument("--osds", type=int, default=3)
    p.add_argument(
        "--mode", choices=("inprocess", "proc"), default="inprocess"
    )
    p.add_argument("--time-scale", type=float, default=1.0)
    p.add_argument("--mutation", default=None)
    p.add_argument("--artifact-dir", default=None)
    p.add_argument("--pace", type=float, default=1.0)
    p.add_argument(
        "--weight",
        action="append",
        default=[],
        metavar="KIND=W",
        help="override an event weight (repeatable); kinds absent "
        "from any --weight set are excluded",
    )
    args = p.parse_args(argv)
    try:
        weights = None
        if args.weight:
            weights = {}
            for spec in args.weight:
                kind, _, w = spec.partition("=")
                weights[kind] = float(w)
        sched = Schedule.from_seed(
            args.seed,
            duration=args.duration,
            osds=args.osds,
            weights=weights,
            pace=args.pace,
        )
        thr = Thrasher(
            sched,
            mode=args.mode,
            mutation=args.mutation,
            time_scale=args.time_scale,
        )
    except ValueError as e:
        p.error(str(e))
    report = thr.run_with_shrink(artifact_dir=args.artifact_dir)
    print(json.dumps(report, indent=2, sort_keys=True))
    return 1 if report["violations"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
