"""qa — the randomized robustness plane (qa/tasks/thrashosds +
ceph_manager.py's Thrasher loop, in-repo and deterministic).

Hand-scripted chaos scenarios (tests/chaos.py) prove exactly the
failure modes someone thought to write down.  This package *generates*
them: a weighted, seed-deterministic schedule of composed faults
(schedule.py) drives a live cluster (thrasher.py) while a continuous
consistency oracle (oracle.py) checks every client op against the
acked history; a violating run shrinks itself to a minimal repro
artifact (shrink.py).  Every run is a pure function of its seed.
"""

from .oracle import ConsistencyOracle, HistoryRecorder, Violation
from .schedule import Schedule, ScheduleEvent
from .shrink import shrink_events, write_repro
from .thrasher import ThrashCluster, Thrasher, build_thrash_perf

__all__ = [
    "ConsistencyOracle",
    "HistoryRecorder",
    "Violation",
    "Schedule",
    "ScheduleEvent",
    "ThrashCluster",
    "Thrasher",
    "build_thrash_perf",
    "shrink_events",
    "write_repro",
]
