"""Failure shrinking — ddmin over the schedule's event list (the
delta-debugging minimizer QuickCheck/hypothesis apply to inputs,
applied to fault schedules).

A violating run hands us (events, run_fn) where ``run_fn(subset) ->
bool`` replays the SAME seed/workload under only ``subset`` of the
events and reports whether the violation still reproduces.  Because
every run is a pure function of (schedule subset, seed) and the
executor's epilogue makes any subset convergent, subsets are safe to
probe in any order.  Classic ddmin: try dropping large chunks first,
re-granulate on failure, stop when no single-chunk removal
reproduces — the result is 1-minimal (removing any one remaining
chunk of the final granularity loses the bug).

``write_repro`` emits the standalone artifact (``repro_<seed>.json``)
plus a ``build_process_report``-style summary so a violation reads
like any other crash in the fleet's telemetry.
"""

from __future__ import annotations

import json
import pathlib


def shrink_events(
    events: list,
    run_fn,
    perf=None,
    max_runs: int = 64,
):
    """ddmin: minimize ``events`` while ``run_fn(subset)`` stays
    True.  Returns (minimal_events, runs_used).  ``run_fn`` is only
    trusted, never inspected; a False on the full list returns it
    unshrunk (nothing to minimize against).  ``perf`` counts probes
    on ``l_thrash_shrink_steps``."""
    runs = 0

    def probe(subset) -> bool:
        nonlocal runs
        runs += 1
        if perf is not None:
            perf.inc("l_thrash_shrink_steps")
        return bool(run_fn(subset))

    current = list(events)
    if not current:
        return current, runs
    n = 2  # granularity: number of chunks
    while len(current) >= 2 and runs < max_runs:
        chunk = max(1, len(current) // n)
        reduced = False
        for start in range(0, len(current), chunk):
            if runs >= max_runs:
                break
            subset = current[:start] + current[start + chunk:]
            if not subset:
                continue
            if probe(subset):
                current = subset
                n = max(2, n - 1)
                reduced = True
                break
        if not reduced:
            if n >= len(current):
                break
            n = min(len(current), n * 2)
    # final pass: try every single-event removal once (1-minimality
    # at event granularity, bounded by max_runs)
    i = 0
    while i < len(current) and len(current) > 1 and runs < max_runs:
        subset = current[:i] + current[i + 1:]
        if probe(subset):
            current = subset
        else:
            i += 1
    return current, runs


def build_thrash_report(
    seed: int,
    violations: list,
    original_events: int,
    minimal_events: int,
    shrink_runs: int,
) -> dict:
    """The build_process_report-shaped summary: a thrash violation
    surfaces through the same telemetry vocabulary as a daemon
    death."""
    kinds = sorted({v["kind"] for v in violations})
    return {
        "role": "qa.thrasher",
        "reason": "ConsistencyViolation: " + ", ".join(kinds),
        "meta": {
            "seed": seed,
            "violations": len(violations),
            "schedule_events": original_events,
            "minimal_events": minimal_events,
            "shrink_runs": shrink_runs,
        },
    }


def write_repro(
    directory,
    schedule,
    minimal_events: list,
    violations: list,
    shrink_runs: int,
    mutation: str | None = None,
) -> pathlib.Path:
    """Emit ``repro_<seed>.json``: everything a later session needs
    to replay the violation — the full schedule, the minimal subset,
    the violations it produced, the mutation (if the run was a
    deliberate oracle proof), and the report summary."""
    directory = pathlib.Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    vio = [
        v.to_dict() if hasattr(v, "to_dict") else dict(v)
        for v in violations
    ]
    doc = {
        "schedule": schedule.to_dict(),
        "minimal_schedule": schedule.subset(
            minimal_events
        ).to_dict(),
        "violations": vio,
        "mutation": mutation,
        "report": build_thrash_report(
            schedule.seed,
            vio,
            len(schedule.events),
            len(minimal_events),
            shrink_runs,
        ),
    }
    path = directory / f"repro_{schedule.seed}.json"
    tmp = path.with_suffix(".tmp")
    tmp.write_text(
        json.dumps(doc, sort_keys=True, separators=(",", ":"))
    )
    tmp.replace(path)
    return path


def load_repro(path) -> dict:
    return json.loads(pathlib.Path(path).read_text())
