"""Continuous consistency oracle — the RadosModel/ceph_test_rados
role (src/test/osd/RadosModel.h: every op records what it was told,
and every read is checked against the set of states the history
permits), grown into an online checker the thrasher runs *during*
the fault schedule.

Model.  Each object has exactly ONE writer (its owning workload
client issues sync ops sequentially), and every mutation carries a
per-object monotonically increasing version stamped INTO the payload.
That makes the permitted-state set tiny and exact:

    possible(oid) = { last acked mutation }
                  ∪ { lost-ack mutations NEWER than the last ack }

A mutation whose ack was lost (timeout / connection reset mid-fault)
is *indeterminate*: it may or may not have landed, so both outcomes
stay permitted until a later acked mutation supersedes it, or a read
OBSERVES it — observation collapses the indeterminacy (the state
provably advanced) and anything older becomes a violation.

Checked invariants, op by op:

- **acked-write durability** — a read may never miss the last acked
  mutation (absent object after an acked write = ``lost_acked_write``);
- **read-your-writes / monotonicity** — an observed version below the
  proven floor is ``stale_read`` (or ``resurrected_delete`` when an
  acked delete sits between); versions never issued are
  ``phantom_version``; payload bytes that do not match the
  deterministic content for their stamped version are
  ``corrupt_payload``;
- **no resurrected deletes** — data observed after an acked delete
  with no newer indeterminate write to explain it.

``ConsistencyOracle`` is pure bookkeeping (unit-testable on
hand-built histories); ``HistoryRecorder`` is the live workload that
feeds it from N client threads against a real IoCtx.
"""

from __future__ import annotations

import threading
import time
import zlib
from dataclasses import dataclass, field
from random import Random

_MAGIC = "QA1"


# -- payload codec (self-describing, self-verifying) ------------------------
def encode_payload(oid: str, version: int, size: int) -> bytes:
    """Deterministic bytes for (oid, version): header + a seeded
    filler stream — a reader can reconstruct and verify every byte
    from the header alone."""
    header = f"{_MAGIC}|{oid}|{version}|".encode()
    fill = max(0, int(size) - len(header))
    return header + _filler(oid, version, fill)


def _filler(oid: str, version: int, n: int) -> bytes:
    rng = Random(zlib.crc32(f"{oid}|{version}".encode()))
    return rng.randbytes(n)


def parse_payload(data: bytes):
    """-> (version, ok) — ok False when the bytes are not a valid
    payload for the version they claim."""
    try:
        magic, oid, version, _rest = data.split(b"|", 3)
        if magic != _MAGIC.encode():
            return None, False
        v = int(version)
    except (ValueError, TypeError):
        return None, False
    return v, data == encode_payload(
        oid.decode(), v, len(data)
    )


@dataclass
class Violation:
    """One oracle finding — the unit the shrinker minimizes toward."""

    kind: str
    oid: str
    client: str
    detail: dict = field(default_factory=dict)
    t: float = 0.0

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "oid": self.oid,
            "client": self.client,
            "detail": self.detail,
            "t": round(self.t, 3),
        }


class _ObjState:
    __slots__ = ("acked", "indeterminate", "floor", "issued")

    def __init__(self):
        # (version, deleted) of the last ACKED mutation, or None
        self.acked: tuple[int, bool] | None = None
        # version -> deleted, for lost-ack mutations newer than acked
        self.indeterminate: dict[int, bool] = {}
        # highest version PROVEN applied (acked or observed)
        self.floor = 0
        # every version ever issued -> deleted (phantom detection)
        self.issued: dict[int, bool] = {}


class ConsistencyOracle:
    """Op-by-op history checker.  Feed it every mutation outcome via
    ``note_mutation`` and every read via ``note_read``; violations
    accumulate in ``self.violations`` (and bump the thrasher's
    ``l_thrash_violations`` counter when one is attached)."""

    def __init__(self, perf=None, clock=time.monotonic):
        self._lock = threading.Lock()
        self._objs: dict[str, _ObjState] = {}
        self.violations: list[Violation] = []
        self.perf = perf
        self._clock = clock
        self._t0 = clock()

    # -- recording ----------------------------------------------------------
    def note_mutation(
        self,
        client: str,
        oid: str,
        version: int,
        acked: bool,
        delete: bool = False,
    ) -> None:
        """One write/delete outcome.  ``acked`` False = the ack was
        lost (timeout, reset): the op becomes indeterminate, not
        forgotten."""
        with self._lock:
            st = self._objs.setdefault(oid, _ObjState())
            st.issued[version] = delete
            if acked:
                self._settle(st, version, delete)
            elif st.acked is None or version > st.acked[0]:
                st.indeterminate[version] = delete

    def _settle(self, st: _ObjState, version: int, delete: bool):
        """An outcome at ``version`` is now proven: it supersedes
        every indeterminate at or below it."""
        if st.acked is None or version >= st.acked[0]:
            st.acked = (version, delete)
        st.floor = max(st.floor, version)
        for v in [
            v for v in st.indeterminate if v <= version
        ]:
            del st.indeterminate[v]

    def note_read(
        self,
        client: str,
        oid: str,
        version: int | None,
        payload_ok: bool = True,
    ) -> Violation | None:
        """One completed read: ``version`` is the payload's stamped
        version, or None when the object was absent (-ENOENT).
        Returns the violation, if the observation is impossible."""
        with self._lock:
            st = self._objs.setdefault(oid, _ObjState())
            v = self._check_read_locked(
                client, oid, st, version, payload_ok
            )
            if v is not None:
                self._record(v)
            return v

    def _check_read_locked(
        self, client, oid, st, version, payload_ok
    ) -> Violation | None:
        def vio(kind, **detail):
            return Violation(
                kind=kind,
                oid=oid,
                client=client,
                detail={
                    "observed": version,
                    "acked": st.acked,
                    "indeterminate": sorted(st.indeterminate),
                    "floor": st.floor,
                    **detail,
                },
                t=self._clock() - self._t0,
            )

        if version is None:
            # absent is fine while nothing durable exists, after an
            # acked delete, or while a lost-ack delete may have landed
            if st.acked is None or st.acked[1]:
                return None
            newer_del = [
                v
                for v, d in st.indeterminate.items()
                if d and v > st.acked[0]
            ]
            if newer_del:
                # the delete provably landed: collapse to the newest
                self._settle(st, max(newer_del), True)
                return None
            return vio("lost_acked_write")
        if version not in st.issued:
            return vio("phantom_version")
        if not payload_ok:
            return vio("corrupt_payload")
        if st.issued[version]:
            # a delete's version can never be read back as data
            return vio("phantom_version", note="delete version")
        if st.acked is not None and version == st.acked[0]:
            return None if not st.acked[1] else vio(
                "resurrected_delete"
            )
        if version in st.indeterminate:
            # the lost-ack write landed; the state provably advanced
            self._settle(st, version, st.indeterminate[version])
            return None
        # not the last ack, not a live indeterminate: the state is
        # provably past this version — classify by what superseded it
        over_delete = any(
            d and v > version
            for v, d in st.issued.items()
            if v <= st.floor
        )
        return vio(
            "resurrected_delete" if over_delete else "stale_read"
        )

    def add_violation(
        self, kind: str, detail: dict | None = None
    ) -> Violation:
        """Harness-level findings (e.g. health never converged)."""
        v = Violation(
            kind=kind,
            oid="-",
            client="harness",
            detail=detail or {},
            t=self._clock() - self._t0,
        )
        with self._lock:
            self._record(v)
        return v

    def _record(self, v: Violation) -> None:
        self.violations.append(v)
        if self.perf is not None:
            self.perf.inc("l_thrash_violations")

    # -- summaries ----------------------------------------------------------
    def objects(self) -> list[str]:
        with self._lock:
            return sorted(self._objs)

    def expected_present(self, oid: str) -> bool | None:
        """Final-audit helper: True = data must exist, False = must
        be absent, None = indeterminate either way."""
        with self._lock:
            st = self._objs.get(oid)
            if st is None or st.acked is None:
                return None if st and st.indeterminate else False
            if st.indeterminate:
                return None
            return not st.acked[1]

    def summary(self) -> dict:
        with self._lock:
            return {
                "objects": len(self._objs),
                "violations": [
                    v.to_dict() for v in self.violations
                ],
            }


class HistoryRecorder:
    """The history-recording client workload: N threads, each the
    single writer of its own object set, sync ops only, every outcome
    fed to the oracle the instant it is known (ceph_test_rados'
    write/read/delete mix against a thrashing cluster)."""

    def __init__(
        self,
        io,
        oracle: ConsistencyOracle,
        seed: int,
        clients: int = 2,
        objects_per_client: int = 4,
        op_gap: float = 0.03,
        max_payload: int = 2048,
    ):
        self.io = io
        self.oracle = oracle
        self.seed = int(seed)
        self.n_clients = int(clients)
        self.objects_per_client = int(objects_per_client)
        self.op_gap = float(op_gap)
        self.max_payload = int(max_payload)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.ops = 0
        self.errors = 0
        self._stat_lock = threading.Lock()

    def oids_of(self, client: int) -> list[str]:
        return [
            f"qa-c{client}-o{k}"
            for k in range(self.objects_per_client)
        ]

    def start(self) -> None:
        for c in range(self.n_clients):
            t = threading.Thread(
                target=self._client_loop,
                args=(c,),
                name=f"qa-client-{c}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    def stop(self, timeout: float = 60.0) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=timeout)

    def _client_loop(self, c: int) -> None:
        from ..osdc.objecter import ObjectNotFound, RadosError

        name = f"client.{c}"
        rng = Random((self.seed << 16) ^ (c + 1))
        oids = self.oids_of(c)
        versions = {oid: 0 for oid in oids}
        while not self._stop.is_set():
            oid = oids[rng.randrange(len(oids))]
            roll = rng.random()
            with self._stat_lock:
                self.ops += 1
            try:
                if roll < 0.55:
                    versions[oid] += 1
                    v = versions[oid]
                    data = encode_payload(
                        oid, v, rng.randrange(64, self.max_payload)
                    )
                    try:
                        self.io.write_full(oid, data)
                        self.oracle.note_mutation(
                            name, oid, v, acked=True
                        )
                    except RadosError:
                        self.oracle.note_mutation(
                            name, oid, v, acked=False
                        )
                        self._err()
                elif roll < 0.85:
                    try:
                        data = self.io.read(oid)
                        ver, ok = parse_payload(data)
                        self.oracle.note_read(name, oid, ver, ok)
                    except ObjectNotFound:
                        self.oracle.note_read(name, oid, None)
                    except RadosError:
                        self._err()  # read outcome unknown: no claim
                else:
                    versions[oid] += 1
                    v = versions[oid]
                    try:
                        self.io.remove(oid)
                        self.oracle.note_mutation(
                            name, oid, v, acked=True, delete=True
                        )
                    except ObjectNotFound:
                        # definite: nothing was there (counts as an
                        # acked transition to absent)
                        self.oracle.note_mutation(
                            name, oid, v, acked=True, delete=True
                        )
                    except RadosError:
                        self.oracle.note_mutation(
                            name, oid, v, acked=False, delete=True
                        )
                        self._err()
            except Exception:  # noqa: BLE001 — a workload thread
                # must never die silently mid-run; count and continue
                self._err()
            self._stop.wait(self.op_gap)

    def _err(self) -> None:
        with self._stat_lock:
            self.errors += 1

    def final_audit(self, retries: int = 3) -> int:
        """After faults cease and health converges: read EVERY object
        once more through the oracle.  Returns the number of audit
        reads performed."""
        from ..osdc.objecter import ObjectNotFound, RadosError

        audited = 0
        for c in range(self.n_clients):
            for oid in self.oids_of(c):
                for attempt in range(retries):
                    try:
                        data = self.io.read(oid)
                        ver, ok = parse_payload(data)
                        self.oracle.note_read(
                            "audit", oid, ver, ok
                        )
                        audited += 1
                        break
                    except ObjectNotFound:
                        self.oracle.note_read("audit", oid, None)
                        audited += 1
                        break
                    except RadosError:
                        if attempt == retries - 1:
                            self.oracle.add_violation(
                                "audit_read_failed",
                                {"oid": oid},
                            )
                        else:
                            time.sleep(1.0)
        return audited
