"""``rbd`` CLI — block-image admin (src/tools/rbd/rbd.cc reduced to
the daily-driver verbs over the librbd analog):

    python -m ceph_tpu.tools.rbd_cli -m HOST:PORT -p POOL create NAME --size BYTES \\
        [--object-size N] [--stripe-unit N] [--stripe-count N] \\
        [--features exclusive-lock,object-map,journaling]
    ... ls | info NAME | rm NAME | resize NAME --size BYTES
    ... export NAME FILE | import FILE NAME [--size BYTES]
    ... snap create NAME@SNAP | snap ls NAME | snap rm NAME@SNAP
    ... clone PARENT@SNAP CHILD | flatten NAME
    ... diff NAME [--from-snap SNAP]   (object-map fast-diff)
    ... du NAME                        (object-map, no scan)
    ... lock status NAME
    ... mirror NAME --target-mon HOST:PORT --target-pool POOL [--once]
"""

from __future__ import annotations

import argparse
import json
import sys

from ..rados import Rados
from ..rbd import RBD, Image, RBDError


def _create(rbd, io, name: str, size: int, args) -> None:
    rbd.create(
        io, name, size,
        stripe_unit=args.stripe_unit or args.object_size,
        stripe_count=args.stripe_count,
        object_size=args.object_size,
        features=args.features,
    )


def _info(io, name: str) -> dict:
    img = Image(io, name)
    try:
        st = img.stat()
        st["name"] = name
        st["features"] = sorted(img.features)
        if img.parent is not None:
            st["parent"] = (
                f"{img.parent['name']}@{img.parent['snap']}"
            )
        st["snaps"] = img.snap_list()
        return st
    finally:
        img.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="rbd", description=__doc__)
    p.add_argument("-m", "--mon", required=True, metavar="HOST:PORT")
    p.add_argument("-p", "--pool", required=True)
    p.add_argument("--size", type=int, default=None)
    p.add_argument("--object-size", type=int, default=1 << 22)
    p.add_argument("--stripe-unit", type=int, default=None)
    p.add_argument("--stripe-count", type=int, default=1)
    p.add_argument("--features", default="")
    p.add_argument("--from-snap", default=None)
    p.add_argument("--target-mon", default=None)
    p.add_argument("--target-pool", default=None)
    p.add_argument("--once", action="store_true")
    p.add_argument("command", nargs="+")
    args = p.parse_args(argv)
    host, _, port = args.mon.partition(":")
    cmd, rest = args.command[0], args.command[1:]
    r = Rados("rbd-cli").connect(host, int(port))
    try:
        io = r.open_ioctx(args.pool)
        rbd = RBD()
        if cmd == "create":
            if args.size is None:
                p.error("create needs --size")
            _create(rbd, io, rest[0], args.size, args)
        elif cmd == "ls":
            for name in rbd.list(io):
                print(name)
        elif cmd == "info":
            print(json.dumps(_info(io, rest[0]), indent=2))
        elif cmd == "rm":
            rbd.remove(io, rest[0])
        elif cmd == "resize":
            if args.size is None:
                p.error("resize needs --size")
            img = Image(io, rest[0])
            try:
                img.resize(args.size)
            finally:
                img.close()
        elif cmd == "export":
            img = Image(io, rest[0])
            try:
                out = (
                    sys.stdout.buffer
                    if rest[1] == "-"
                    else open(rest[1], "wb")
                )
                step = 4 << 20
                for off in range(0, img.size(), step):
                    out.write(
                        img.read(off, min(step, img.size() - off))
                    )
                if rest[1] != "-":
                    out.close()
            finally:
                img.close()
        elif cmd == "import":
            import os as _os

            if rest[0] == "-":
                fh, size = sys.stdin.buffer, args.size
                if size is None:
                    p.error("import from stdin needs --size")
            else:
                fh = open(rest[0], "rb")
                size = args.size or _os.fstat(fh.fileno()).st_size
            _create(rbd, io, rest[1], size, args)
            img = Image(io, rest[1])
            try:
                # stream in 4MB steps — a multi-GB image must not
                # materialize in RAM (export already streams)
                off = 0
                while off < size:
                    chunk = fh.read(min(4 << 20, size - off))
                    if not chunk:
                        break
                    img.write(off, chunk)
                    off += len(chunk)
            finally:
                img.close()
                if rest[0] != "-":
                    fh.close()
        elif cmd == "snap":
            sub = rest[0]
            if sub == "ls":
                img = Image(io, rest[1])
                try:
                    for s in img.snap_list():
                        print(s)
                finally:
                    img.close()
            else:
                name, _, snap = rest[1].partition("@")
                if not snap:
                    p.error("need NAME@SNAP")
                img = Image(io, name)
                try:
                    if sub == "create":
                        img.snap_create(snap)
                    elif sub == "rm":
                        img.snap_remove(snap)
                    else:
                        p.error(f"unknown snap op {sub!r}")
                finally:
                    img.close()
        elif cmd == "clone":
            parent, _, snap = rest[0].partition("@")
            if not snap:
                p.error("need PARENT@SNAP")
            rbd.clone(io, parent, snap, rest[1])
        elif cmd == "flatten":
            img = Image(io, rest[0])
            try:
                img.flatten()
            finally:
                img.close()
        elif cmd == "diff":
            img = Image(io, rest[0])
            try:
                objs = img.diff_objects(args.from_snap)
                osz = img.layout.object_size
                for o in objs:
                    print(f"{o * osz}\t{osz}\tobject {o}")
            finally:
                img.close()
        elif cmd == "du":
            img = Image(io, rest[0])
            try:
                used = img.used_objects() * img.layout.object_size
                print(
                    f"{rest[0]}\tprovisioned {img.size()}\t"
                    f"used <= {used}"
                )
            finally:
                img.close()
        elif cmd == "lock" and rest[0] == "status":
            img = Image(io, rest[1])
            try:
                try:
                    print(img.lock_holder() or "unlocked")
                except RBDError as e:
                    print(e)
            finally:
                img.close()
        elif cmd == "mirror":
            if not (args.target_mon and args.target_pool):
                p.error("mirror needs --target-mon and --target-pool")
            from ..rbd.mirror import MirrorDaemon

            th, _, tp = args.target_mon.partition(":")
            tr = Rados("rbd-mirror-cli").connect(th, int(tp))
            try:
                dst = tr.open_ioctx(args.target_pool)
                d = MirrorDaemon(
                    io, dst, interval=0.0 if args.once else 0.5
                )
                try:
                    if args.once:
                        d.replay_once()
                    else:
                        print(
                            "mirroring; Ctrl-C to stop",
                            file=sys.stderr,
                        )
                        import time

                        while True:
                            time.sleep(1)
                except KeyboardInterrupt:
                    pass
                finally:
                    d.stop()
            finally:
                tr.shutdown()
        else:
            p.error(f"unknown command {cmd!r}")
        return 0
    except RBDError as e:
        print(f"rbd: {e}", file=sys.stderr)
        return 1
    finally:
        r.shutdown()


if __name__ == "__main__":
    sys.exit(main())
