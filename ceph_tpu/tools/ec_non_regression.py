"""Byte-exactness corpus tool
(src/test/erasure-code/ceph_erasure_code_non_regression.cc:113,304-324
and the ceph-erasure-code-corpus layout).

--create archives the encoded chunks of a deterministic payload for a
plugin/profile; --check re-encodes and compares byte-for-byte, and
verifies every single-erasure decode against the archived chunks.  The
reference's corpus submodule is empty in the mount, so this corpus is
self-generated — it pins today's outputs as the contract for every
future backend/kernel change (the role SURVEY.md §4.4 assigns it).
"""

from __future__ import annotations

import argparse
import base64
import hashlib
import json
import pathlib
import sys

import numpy as np

from ..ec import ErasureCodeProfile, registry_instance


def default_payload(size: int) -> bytes:
    """Deterministic, content-addressable payload (the reference uses
    SP(seed) strings; any fixed generator works as long as it never
    changes)."""
    out = bytearray()
    counter = 0
    while len(out) < size:
        out += hashlib.sha256(f"ceph-tpu-corpus-{counter}".encode()).digest()
        counter += 1
    return bytes(out[:size])


def profile_from_args(params: list[str]) -> ErasureCodeProfile:
    profile = ErasureCodeProfile()
    for kv in params:
        key, _, value = kv.partition("=")
        profile[key] = value
    return profile


def corpus_name(plugin: str, profile: ErasureCodeProfile, size: int) -> str:
    """Readable prefix + digest of the full (factory-completed) profile."""
    canon = json.dumps(
        {k: v for k, v in sorted(profile.items()) if k != "backend"},
        sort_keys=True,
    )
    digest = hashlib.sha256(canon.encode()).hexdigest()[:10]
    brief = "_".join(
        f"{key}{profile[key]}"
        for key in ("technique", "k", "m", "l", "c", "d", "w")
        if key in profile
    )
    return f"{plugin}_{brief}_s{size}_{digest}"


def create(args) -> int:
    profile = profile_from_args(args.parameter)
    # snapshot before factory(): init fills generated keys (lrc's
    # mapping/layers, defaults) that must not be re-fed to parse
    original = {k: v for k, v in profile.items() if k != "backend"}
    ec = registry_instance().factory(args.plugin, profile)
    data = default_payload(args.size)
    encoded = ec.encode(set(range(ec.get_chunk_count())), data)
    entry = {
        "plugin": args.plugin,
        "profile": original,
        "size": args.size,
        "chunks": {
            str(i): base64.b64encode(bytes(c)).decode()
            for i, c in sorted(encoded.items())
        },
    }
    directory = pathlib.Path(args.directory)
    directory.mkdir(parents=True, exist_ok=True)
    path = directory / (corpus_name(args.plugin, original, args.size) + ".json")
    path.write_text(json.dumps(entry, indent=1))
    print(f"created {path}")
    return 0


def check(args) -> int:
    directory = pathlib.Path(args.directory)
    failures = 0
    entries = sorted(directory.glob("*.json"))
    if not entries:
        print(f"no corpus entries under {directory}", file=sys.stderr)
        return 1
    for path in entries:
        entry = json.loads(path.read_text())
        profile = ErasureCodeProfile(entry["profile"])
        if args.backend:
            profile["backend"] = args.backend
        ec = registry_instance().factory(entry["plugin"], profile)
        data = default_payload(entry["size"])
        n = ec.get_chunk_count()
        encoded = ec.encode(set(range(n)), data)
        archived = {
            int(i): np.frombuffer(
                base64.b64decode(c), dtype=np.uint8
            )
            for i, c in entry["chunks"].items()
        }
        ok = True
        for i in range(n):
            if not np.array_equal(encoded[i], archived[i]):
                print(f"{path.name}: chunk {i} DIFFERS", file=sys.stderr)
                ok = False
        # single-erasure decodes must reproduce the archived chunk
        for lost in range(n):
            avail = {i: c for i, c in archived.items() if i != lost}
            decoded = ec._decode({lost}, avail)
            if not np.array_equal(decoded[lost], archived[lost]):
                print(
                    f"{path.name}: decode of chunk {lost} DIFFERS",
                    file=sys.stderr,
                )
                ok = False
        print(f"{path.name}: {'ok' if ok else 'FAILED'}")
        failures += not ok
    return 1 if failures else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ec_non_regression", description=__doc__)
    mode = p.add_mutually_exclusive_group(required=True)
    mode.add_argument("--create", action="store_true")
    mode.add_argument("--check", action="store_true")
    p.add_argument("--directory", default="corpus")
    p.add_argument("--plugin", default="jerasure")
    p.add_argument("-P", "--parameter", action="append", default=[])
    p.add_argument("--size", type=int, default=65536)
    p.add_argument("--backend", default="",
                   help="override backend when checking (jax vs numpy)")
    args = p.parse_args(argv)
    return create(args) if args.create else check(args)


if __name__ == "__main__":
    sys.exit(main())
