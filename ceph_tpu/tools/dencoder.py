"""ceph-dencoder analog — the encoding-corpus regression gate
(src/tools/ceph-dencoder/ceph_dencoder.cc + the ceph-object-corpus
workflow).

The reference pins sample encodings of every versioned struct in a
corpus repository and re-checks decode+re-encode on every build, so a
format change that breaks old blobs is caught at CI time rather than
at mixed-version upgrade time.  Same machinery here:

- ``TYPES`` registers every versioned wire/disk struct with a
  deterministic sample builder, an encoder, and a decoder.
- ``generate`` writes the sample encodings into ``corpus/dencoder/``.
- ``check`` decodes every PINNED blob with today's code and
  re-encodes it; any byte difference (or decode failure) is a format
  regression against data already in the wild.
- CLI: ``list`` / ``generate`` / ``check`` / ``decode -t TYPE FILE``.

A NEW field appended to a struct re-encodes pinned blobs differently
— that is exactly the signal: regenerate the corpus DELIBERATELY
(``generate --force``) in the same change that bumps the format, the
review showing both.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from ..common.encoding import Decoder, Encoder

CORPUS_DIR = (
    pathlib.Path(__file__).resolve().parents[2] / "corpus" / "dencoder"
)


def _sample_messages():
    """Deterministic sample instances of every registered message."""
    from ..msg import message as M

    samples = {
        "MPing": M.MPing(from_osd=3, stamp=12.5, is_reply=True),
        "MOSDOp": M.MOSDOp(
            pool=7, pgid="7.3", oid="obj-1", op=M.OSD_OP_WRITE,
            offset=4096, length=11, data=b"hello world",
            attr="k", reqid="client.9", epoch=42, snapid=5,
            snap_seq=6, flags=M.OSD_FLAG_FULL_TRY, qos="gold",
        ),
        "MOSDOpReply": M.MOSDOpReply(
            ok=True, error="", data=b"payload", names=["a", "b"],
            size=11, epoch=42,
        ),
        "MMonCommand": M.MMonCommand(cmd='{"prefix": "status"}'),
        "MMonCommandReply": M.MMonCommandReply(
            rc=-22, outs="bad", outb='{"x": 1}'
        ),
        "MMonSubscribe": M.MMonSubscribe(start_epoch=9),
        "MOSDBoot": M.MOSDBoot(osd=2, addr="127.0.0.1:6800"),
        "MOSDFailure": M.MOSDFailure(
            target=1, reporter=0, failed_for=30
        ),
        "MClientRequest": M.MClientRequest(
            op="mkdir", args='{"path": "/d"}', reqid="c.1"
        ),
        "MClientReply": M.MClientReply(rc=0, outs="", outb='{"ino": 5}'),
        "MClientCaps": M.MClientCaps(action="revoke", ino=77),
        "MMgrReport": M.MMgrReport(
            daemon="osd.1", perf='{"op": 4}',
            spans='[{"trace_id": "t", "span_id": "s"}]',
            crashes='[{"crash_id": "c", "entity_name": "osd.1"}]',
        ),
        "MLog": M.MLog(
            name="osd.1",
            entries='[{"name": "osd.1", "channel": "cluster", '
            '"prio": "warn", "message": "m", "seq": 1, '
            '"stamp": 1.5}]',
        ),
        "MRepScrub": M.MRepScrub(
            op="scan", pgid="1.3", epoch=42, from_osd=0,
            deep=True, oids=["o_a", "o_b"],
        ),
        "MScrubMap": M.MScrubMap(
            pgid="1.3", from_osd=2, ok=True, error="",
            map_json='{"o_a": {"exists": true, "size": 11, '
            '"data_digest": 7}}',
        ),
        "MScrubCommand": M.MScrubCommand(
            op="deep-scrub", pgid="1.3"
        ),
        "MOSDBackoff": M.MOSDBackoff(
            op=M.BACKOFF_OP_BLOCK, pgid="7.3", id=4,
            reason="full", epoch=42,
        ),
        "MCommand": M.MCommand(
            cmd='{"prefix": "fault list"}'
        ),
        # the recovery protocol (ISSUE 11): pull/push/reply + the
        # two-sided reservation handshake — pinned so a
        # recovery-message format drift fails the corpus gate
        "MPGPull": M.MPGPull(
            pgid="7.3", epoch=42, oid="obj-1", shard=2
        ),
        "MPGPush": M.MPGPush(
            pgid="7.3", epoch=42, oid="obj-1", exists=True,
            data=b"shard-bytes",
            attrs={"hinfo_key": b'{"size": 11}', "u_color": b"teal"},
            omap={"k1": b"v1"},
            entry_blob=b"entry",
        ),
        "MPGPushReply": M.MPGPushReply(from_osd=2, ok=True),
        "MRecoveryReserve": M.MRecoveryReserve(
            op="request", pgid="7.3", epoch=42, from_osd=1
        ),
        # the PG-stats plane (ISSUE 16): OSD → mgr per-PG accounting
        # + piggybacked progress events
        "MPGStats": M.MPGStats(
            osd=1, epoch=42,
            stats='[{"pgid": "7.3", "state": "active+clean", '
            '"num_objects": 4, "num_bytes": 4096, '
            '"num_objects_degraded": 0}]',
            events='[{"id": "scrub pg 7.3 (osd.1)", '
            '"message": "scrub pg 7.3 (osd.1)", '
            '"fraction": 0.5, "done": false}]',
        ),
    }
    for name, msg in samples.items():
        msg.tid = 99
    return samples


def _build_types():
    """name -> (sample_bytes_builder, roundtrip) where roundtrip
    decodes a blob and re-encodes it with TODAY's code."""
    from ..crush.builder import CrushMap
    from ..crush.encode import decode_crush_map, encode_crush_map
    from ..crush.types import CRUSH_BUCKET_STRAW2, Tunables
    from ..msg import message as M
    from ..osd.daemon import (
        _decode_entry,
        _decode_info,
        _encode_entry,
        _encode_info,
    )
    from ..osd.osdmap import Incremental, OSDMap, PgPool
    from ..osd.pg_log import LogEntry, PGInfo
    from ..store.objectstore import (
        Transaction,
        decode_transaction,
        encode_transaction,
    )

    def crush_sample() -> CrushMap:
        m = CrushMap(tunables=Tunables())
        hosts = [
            m.add_bucket(
                CRUSH_BUCKET_STRAW2, 1, [h * 2, h * 2 + 1],
                [0x10000, 0x18000], name=f"host{h}",
            )
            for h in range(3)
        ]
        m.add_bucket(
            CRUSH_BUCKET_STRAW2, 3, hosts,
            [m.buckets[b].weight for b in hosts], name="default",
        )
        m.add_simple_rule("data", "default", "host", mode="firstn")
        return m

    def osdmap_sample() -> OSDMap:
        om = OSDMap.build(crush_sample(), 6)
        om.pools[1] = PgPool(
            pool_id=1, size=3, min_size=2, pg_num=8,
            crush_rule=0, last_change=3,
        )
        om.pool_names[1] = "data"
        om.pg_upmap_items[(1, 3)] = ((0, 4),)
        om.epoch = 7
        return om

    def inc_sample() -> Incremental:
        inc = osdmap_sample().new_incremental()
        inc.mark_down(2)
        inc.new_weight[3] = 0x8000
        return inc

    types = {}

    # messages pin their full FRAME (header + crcs + payload)
    for name, msg in _sample_messages().items():
        mtype = msg.TYPE

        def build(msg=msg) -> bytes:
            return msg.to_frame()

        def roundtrip(blob: bytes, mtype=mtype) -> bytes:
            hdr = blob[: M.Message.HEADER_SIZE]
            got_type, tid, plen = M.Message.parse_header(hdr)
            assert got_type == mtype, f"type moved: {got_type}"
            body = blob[M.Message.HEADER_SIZE :]
            decoded = M.Message.from_payload(
                got_type, tid, body[:plen],
                int.from_bytes(body[plen:], "little"),
            )
            return decoded.to_frame()

        types[f"msg_{name}"] = (build, roundtrip)

    types["osdmap_full"] = (
        lambda: osdmap_sample().encode(),
        lambda blob: OSDMap.decode(blob).encode(),
    )
    types["osdmap_incremental"] = (
        lambda: inc_sample().encode(),
        lambda blob: Incremental.decode(blob).encode(),
    )
    types["crush_map"] = (
        lambda: encode_crush_map(crush_sample()),
        lambda blob: encode_crush_map(decode_crush_map(blob)),
    )

    entry = LogEntry(
        op=0, oid="obj", version=(7, 21), prior_version=(7, 20),
        reqid="client.4",
    )
    types["pg_log_entry"] = (
        lambda: _encode_entry(entry),
        lambda blob: _encode_entry(_decode_entry(blob)),
    )
    info = PGInfo(
        pgid="1.3", last_update=(7, 21), log_tail=(6, 2),
        last_epoch_started=7,
    )
    types["pg_info"] = (
        lambda: _encode_info(info),
        lambda blob: _encode_info(_decode_info(blob)),
    )

    txn = (
        Transaction()
        .create_collection("c")
        .touch("c", "o")
        .write("c", "o", 128, b"bytes")
        .truncate("c", "o", 64)
        .setattr("c", "o", "a", b"v")
        .omap_setkeys("c", "o", {"k": b"v"})
        .omap_rmkeys("c", "o", ["dead"])
        .clone("c", "o", "o2")
        .remove("c", "o2")
    )

    def txn_build() -> bytes:
        e = Encoder()
        encode_transaction(e, txn)
        return e.getvalue()

    def txn_roundtrip(blob: bytes) -> bytes:
        e = Encoder()
        encode_transaction(e, decode_transaction(Decoder(blob)))
        return e.getvalue()

    types["objectstore_transaction"] = (txn_build, txn_roundtrip)

    # WAL plane (store/wal_store.py): the on-log record (seq + crc
    # over the transaction payload) and the replay-base checkpoint are
    # durable formats — a log written by one build must replay under
    # every later one
    from ..store.wal_store import (
        WALCheckpoint,
        decode_wal_checkpoint,
        decode_wal_record,
        encode_wal_checkpoint,
        encode_wal_record,
        make_wal_record,
    )

    def wal_record_build() -> bytes:
        e = Encoder()
        encode_wal_record(e, make_wal_record(42, txn_build()))
        return e.getvalue()

    def wal_record_roundtrip(blob: bytes) -> bytes:
        e = Encoder()
        encode_wal_record(e, decode_wal_record(Decoder(blob)))
        return e.getvalue()

    types["wal_record"] = (wal_record_build, wal_record_roundtrip)

    def wal_ckpt_build() -> bytes:
        e = Encoder()
        encode_wal_checkpoint(e, WALCheckpoint(1337))
        return e.getvalue()

    def wal_ckpt_roundtrip(blob: bytes) -> bytes:
        e = Encoder()
        encode_wal_checkpoint(e, decode_wal_checkpoint(Decoder(blob)))
        return e.getvalue()

    types["wal_checkpoint"] = (wal_ckpt_build, wal_ckpt_roundtrip)

    # latency-histogram snapshots (the SLO plane's wire/artifact
    # shapes, common/histogram.py): the 1D log2 histogram and the 2D
    # latency×size grid both pin their binary snapshot encoding
    from ..common.histogram import LogHistogram, PerfHistogram2D

    def hist_sample() -> LogHistogram:
        h = LogHistogram()
        for v in (1e-5, 3e-4, 3e-4, 0.002, 0.05, 1.7, 900.0, 1e9):
            h.add(v)
        return h

    def grid_sample() -> PerfHistogram2D:
        g = PerfHistogram2D()
        for lat, size in (
            (1e-4, 4096.0), (0.003, 65536.0), (0.2, 1.0),
            (9.0, 1 << 26),
        ):
            g.add(lat, size)
        return g

    types["perf_histogram"] = (
        lambda: hist_sample().encode(),
        lambda blob: LogHistogram.decode(blob).encode(),
    )
    types["perf_histogram_2d"] = (
        lambda: grid_sample().encode(),
        lambda blob: PerfHistogram2D.decode(blob).encode(),
    )

    # sharded bucket-index plane (rgw/index.py): the bucket metadata
    # record (index layout + live reshard descriptor) and the
    # reshard-queue entry pin their canonical encodings — a record
    # shape drift would strand every bucket written before it
    from ..rgw.index import (
        decode_bucket_record,
        decode_reshard_entry,
        encode_bucket_record,
        encode_reshard_entry,
    )

    bucket_rec = {
        "ctime": 1700000000.0,
        "owner": "alice",
        "acl": {
            "owner": "alice",
            "grants": [
                {"grantee": "alice", "permission": "FULL_CONTROL"}
            ],
        },
        "index": {"gen": 2, "num_shards": 8},
        "reshard": {
            "status": "in_progress",
            "target_gen": 3,
            "target_shards": 16,
            "stamp": 1700000001.5,
        },
    }
    types["rgw_bucket_record"] = (
        lambda: encode_bucket_record(bucket_rec),
        lambda blob: encode_bucket_record(
            decode_bucket_record(blob)
        ),
    )
    reshard_ent = {
        "bucket": "photos",
        "target_shards": 16,
        "reason": "threshold",
        "queued_at": 1700000002.25,
    }
    types["rgw_reshard_entry"] = (
        lambda: encode_reshard_entry(reshard_ent),
        lambda blob: encode_reshard_entry(
            decode_reshard_entry(blob)
        ),
    )

    # the PGMap digest (mgr/pgmap.py): the mgr→mon rollup the status
    # / df / health surfaces read — sorted-map encoding, so the same
    # digest is always the same bytes
    from ..mgr.pgmap import decode_pgmap_digest, encode_pgmap_digest

    digest_sample = {
        "version": 1,
        "num_pgs": 8,
        "num_pools": 1,
        "pg_states": {"active+clean": 7, "active+degraded": 1},
        "pools": {
            1: {
                "name": "data", "num_pgs": 8, "active_pgs": 8,
                "objects": 24, "bytes": 49152, "degraded": 3,
                "misplaced": 0, "unfound": 0,
            }
        },
        "totals": {
            "objects": 24, "bytes": 49152, "degraded": 3,
            "misplaced": 0, "unfound": 0,
        },
        "io": {
            "ops_sec": 12.5, "read_ops_sec": 4.5,
            "write_ops_sec": 8.0,
        },
        "recovery": {"objects_sec": 2.0, "bytes_sec": 4096.0},
        "pgs": {
            "1.3": {
                "state": "active+degraded", "objects": 3,
                "bytes": 6144, "degraded": 3, "misplaced": 0,
                "unfound": 0, "up": [0, 1, 2], "acting": [0, 1],
                "reported_epoch": 7, "recovery_progress": 0.25,
            }
        },
    }
    types["pgmap_digest"] = (
        lambda: encode_pgmap_digest(digest_sample),
        lambda blob: encode_pgmap_digest(
            decode_pgmap_digest(blob)
        ),
    )
    return types


def list_types() -> list[str]:
    return sorted(_build_types())


def generate(force: bool = False) -> list[str]:
    """Pin missing sample encodings (all of them with --force)."""
    CORPUS_DIR.mkdir(parents=True, exist_ok=True)
    written = []
    for name, (build, _rt) in sorted(_build_types().items()):
        path = CORPUS_DIR / f"{name}.bin"
        if path.exists() and not force:
            continue
        path.write_bytes(build())
        written.append(name)
    return written


def check() -> dict[str, str]:
    """Decode+re-encode every pinned blob; returns {type: error}
    (empty = the formats still read everything in the wild)."""
    errors: dict[str, str] = {}
    types = _build_types()
    for name, (_build, roundtrip) in sorted(types.items()):
        path = CORPUS_DIR / f"{name}.bin"
        if not path.exists():
            errors[name] = "not pinned (run dencoder generate)"
            continue
        blob = path.read_bytes()
        try:
            again = roundtrip(blob)
        except Exception as e:  # noqa: BLE001 — any decode failure
            # IS the regression being hunted
            errors[name] = f"decode failed: {type(e).__name__}: {e}"
            continue
        if again != blob:
            errors[name] = (
                f"re-encode differs ({len(blob)} -> {len(again)} "
                "bytes): format changed against pinned data"
            )
    for path in sorted(CORPUS_DIR.glob("*.bin")):
        if path.stem not in types:
            errors[path.stem] = "pinned but no longer registered"
    return errors


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="dencoder", description=__doc__.splitlines()[0]
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list")
    g = sub.add_parser("generate")
    g.add_argument("--force", action="store_true")
    sub.add_parser("check")
    d = sub.add_parser("decode")
    d.add_argument("-t", "--type", required=True)
    d.add_argument("file")
    args = p.parse_args(argv)
    if args.cmd == "list":
        print("\n".join(list_types()))
        return 0
    if args.cmd == "generate":
        for name in generate(force=args.force):
            print(f"pinned {name}")
        return 0
    if args.cmd == "check":
        errors = check()
        for name, err in errors.items():
            print(f"{name}: {err}", file=sys.stderr)
        ok = sum(1 for t in list_types() if t not in errors)
        print(f"{ok} ok, {len(errors)} bad")
        return 1 if errors else 0
    if args.cmd == "decode":
        types = _build_types()
        if args.type not in types:
            print(f"unknown type {args.type}", file=sys.stderr)
            return 2
        blob = pathlib.Path(args.file).read_bytes()
        again = types[args.type][1](blob)
        same = again == blob
        print(
            f"{args.type}: {len(blob)} bytes, re-encode "
            f"{'identical' if same else 'DIFFERS'}"
        )
        return 0 if same else 1
    return 2


if __name__ == "__main__":
    sys.exit(main())
