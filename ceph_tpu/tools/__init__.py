"""CLI tools mirroring the reference harnesses (crushtool, osdmaptool,
ceph_erasure_code_benchmark) flag-for-flag where it matters."""
