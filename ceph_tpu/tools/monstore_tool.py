"""ceph-monstore-tool analog — offline mon-store surgery
(src/tools/ceph_monstore_tool.cc).

Operates on a STOPPED monitor's MonitorStore (the MonitorDBStore
role: versioned osdmap blobs behind an ObjectStore — KStore or
BlockStore on disk).  The rescue walk the reference supports:

- ``status``            — last_committed + which full/incremental
                          epochs the store actually holds
- ``dump [--epoch N]``  — JSON summary of a committed map
- ``export/import``     — raw full-map blobs out of / into the store
                          (get-osdmap / rebuild inputs)
- ``set-last-committed``— rewind/advance the committed pointer to an
                          epoch the store holds (the
                          rebuild/rewrite-crush class of rescue)
- ``prune --keep K``    — drop history below last_committed-K

Every mutation goes through the store's transaction API, so the
repair itself is crash-safe.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from ..mon.monitor import MON_COLL, MonitorStore
from ..osd.osdmap import OSDMap
from ..store.objectstore import StoreError, Transaction


def open_store(path: str):
    """Mount the on-disk store backing a stopped monitor (KStore or
    BlockStore, detected by their files)."""
    p = pathlib.Path(path)
    if (p / "block.dev").exists() or (p / "kv.log").exists():
        from ..store.blockstore import BlockStore

        return BlockStore(p)
    if (p / "wal.log").exists() or (p / "snap.bin").exists():
        from ..store import KStore

        return KStore(p)
    raise SystemExit(f"{path}: no KStore or BlockStore found")


class MonStore:
    """The tool's view over a MonitorStore's key layout."""

    def __init__(self, store):
        self.store = store
        self.ms = MonitorStore(store)

    def epochs(self) -> tuple[list[int], list[int]]:
        fulls, incs = [], []
        try:
            names = self.store.list_objects(MON_COLL)
        except StoreError:
            return [], []
        for n in names:
            if n.startswith("osdmap_full_"):
                fulls.append(int(n[len("osdmap_full_"):]))
            elif n.startswith("osdmap_inc_"):
                incs.append(int(n[len("osdmap_inc_"):]))
        return sorted(fulls), sorted(incs)

    def status(self) -> dict:
        fulls, incs = self.epochs()
        lc = self.ms.last_committed()
        return {
            "last_committed": lc,
            "full_epochs": fulls,
            "incremental_epochs": incs,
            "consistent": lc in fulls if fulls else lc == 0,
        }

    def get_map(self, epoch: int | None = None) -> OSDMap:
        epoch = epoch or self.ms.last_committed()
        blob = self.ms.get_full(epoch)
        if blob is None:
            raise SystemExit(f"no full map for epoch {epoch}")
        return OSDMap.decode(blob)

    def dump(self, epoch: int | None = None) -> dict:
        m = self.get_map(epoch)
        return {
            "epoch": m.epoch,
            "max_osd": m.max_osd,
            "up_osds": [o for o in range(m.max_osd) if m.is_up(o)],
            "pools": {
                m.pool_names.get(pid, str(pid)): {
                    "id": pid,
                    "type": p.type,
                    "size": p.size,
                    "pg_num": p.pg_num,
                    "snap_seq": p.snap_seq,
                }
                for pid, p in m.pools.items()
            },
            "pg_upmap_items": len(m.pg_upmap_items),
        }

    def export_map(self, epoch: int | None, out: str) -> int:
        epoch = epoch or self.ms.last_committed()
        blob = self.ms.get_full(epoch)
        if blob is None:
            raise SystemExit(f"no full map for epoch {epoch}")
        pathlib.Path(out).write_bytes(blob)
        return epoch

    def import_map(self, path: str) -> int:
        """Install a full-map blob at ITS OWN epoch (rebuild input);
        advances last_committed when the blob is newer."""
        blob = pathlib.Path(path).read_bytes()
        m = OSDMap.decode(blob)  # validates before any write
        txn = Transaction()
        txn.touch(MON_COLL, f"osdmap_full_{m.epoch}")
        txn.truncate(MON_COLL, f"osdmap_full_{m.epoch}", 0)
        txn.write(MON_COLL, f"osdmap_full_{m.epoch}", 0, blob)
        if m.epoch > self.ms.last_committed():
            txn.touch(MON_COLL, "meta")
            txn.setattr(
                MON_COLL, "meta", "last_committed",
                str(m.epoch).encode(),
            )
        self.store.queue_transaction(txn)
        return m.epoch

    def set_last_committed(self, epoch: int) -> None:
        fulls, _ = self.epochs()
        if epoch not in fulls:
            raise SystemExit(
                f"store holds no full map for epoch {epoch} "
                f"(have {fulls})"
            )
        txn = Transaction()
        txn.touch(MON_COLL, "meta")
        txn.setattr(
            MON_COLL, "meta", "last_committed", str(epoch).encode()
        )
        self.store.queue_transaction(txn)

    def prune(self, keep: int) -> list[int]:
        """Drop full+inc blobs below last_committed - keep (the
        reference's compaction/prune rescue)."""
        lc = self.ms.last_committed()
        cutoff = lc - max(keep, 0)
        fulls, incs = self.epochs()
        dropped = []
        txn = Transaction()
        for e in fulls:
            if e < cutoff:
                txn.remove(MON_COLL, f"osdmap_full_{e}")
                dropped.append(e)
        for e in incs:
            if e < cutoff:
                txn.remove(MON_COLL, f"osdmap_inc_{e}")
        if txn.ops:
            self.store.queue_transaction(txn)
        return dropped


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="monstore-tool", description=__doc__.splitlines()[0]
    )
    p.add_argument("path", help="stopped monitor's store directory")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status")
    d = sub.add_parser("dump")
    d.add_argument("--epoch", type=int)
    e = sub.add_parser("export")
    e.add_argument("--epoch", type=int)
    e.add_argument("--out", required=True)
    i = sub.add_parser("import")
    i.add_argument("--in", dest="infile", required=True)
    slc = sub.add_parser("set-last-committed")
    slc.add_argument("epoch", type=int)
    pr = sub.add_parser("prune")
    pr.add_argument("--keep", type=int, default=32)
    args = p.parse_args(argv)

    store = open_store(args.path)
    try:
        t = MonStore(store)
        if args.cmd == "status":
            print(json.dumps(t.status(), indent=2))
        elif args.cmd == "dump":
            print(json.dumps(t.dump(args.epoch), indent=2))
        elif args.cmd == "export":
            epoch = t.export_map(args.epoch, args.out)
            print(f"exported epoch {epoch} to {args.out}")
        elif args.cmd == "import":
            epoch = t.import_map(args.infile)
            print(f"imported full map at epoch {epoch}")
        elif args.cmd == "set-last-committed":
            t.set_last_committed(args.epoch)
            print(f"last_committed = {args.epoch}")
        elif args.cmd == "prune":
            dropped = t.prune(args.keep)
            print(f"pruned {len(dropped)} full maps")
    finally:
        close = getattr(store, "close", None)
        if close is not None:
            close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
