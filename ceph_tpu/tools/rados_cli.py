"""``rados`` CLI — object-level admin I/O + bench
(src/tools/rados/rados.cc: put/get/rm/ls/stat/omap ops and the
``rados bench`` load generator).

    python -m ceph_tpu.tools.rados_cli -m HOST:PORT -p POOL put OBJ FILE
    ... get OBJ FILE | rm OBJ | ls | stat OBJ
    ... setomapval OBJ KEY VALUE | listomapvals OBJ | rmomapkey OBJ KEY
    ... mksnap NAME | rmsnap NAME | lssnap
    ... list-inconsistent-obj PGID
    ... bench SECONDS write|read [--obj-size N] [--concurrent N]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from ..rados import Rados


def _bench(io, rados, seconds: int, mode: str, obj_size: int, conc: int):
    """rados bench: timed write (then read) of sequential objects;
    prints the reference tool's headline numbers (bandwidth, IOPS,
    average latency)."""
    payload = bytes(range(256)) * (obj_size // 256 + 1)
    payload = payload[:obj_size]
    t_end = time.monotonic() + seconds
    lat: list[float] = []
    done = 0
    inflight = []
    i = 0
    while time.monotonic() < t_end or inflight:
        while (
            len(inflight) < conc and time.monotonic() < t_end
        ):
            oid = f"bench_{i:08d}"
            t0 = time.monotonic()
            fut = (
                io.aio_write_full(oid, payload)
                if mode == "write"
                else io.aio_read(f"bench_{i % max(done, 1):08d}")
            )
            inflight.append((t0, fut))
            i += 1
        t0, fut = inflight.pop(0)
        fut.result()
        lat.append(time.monotonic() - t0)
        done += 1
    total = done * obj_size
    dt = max(sum(lat) / max(conc, 1), 1e-9)
    wall = seconds if seconds else dt
    print(
        json.dumps(
            {
                "mode": mode,
                "ops": done,
                "bytes": total,
                "seconds": wall,
                "bandwidth_MBps": round(total / wall / 2**20, 2),
                "iops": round(done / wall, 1),
                "avg_latency_ms": round(
                    1000 * sum(lat) / max(len(lat), 1), 2
                ),
            }
        )
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="rados", description=__doc__)
    p.add_argument("-m", "--mon", required=True, metavar="HOST:PORT")
    p.add_argument("-p", "--pool", required=True)
    p.add_argument("command", nargs=argparse.REMAINDER)
    p.add_argument("--obj-size", type=int, default=1 << 20)
    p.add_argument("--concurrent", type=int, default=4)
    args = p.parse_args(argv)
    if not args.command:
        p.error("no command")
    host, _, port = args.mon.partition(":")
    cmd, rest = args.command[0], args.command[1:]
    r = Rados("rados-cli").connect(host, int(port))
    try:
        io = r.open_ioctx(args.pool)
        if cmd == "put":
            oid, path = rest
            data = (
                sys.stdin.buffer.read()
                if path == "-"
                else open(path, "rb").read()
            )
            io.write_full(oid, data)
        elif cmd == "get":
            oid, path = rest
            data = io.read(oid)
            if path == "-":
                sys.stdout.buffer.write(data)
            else:
                open(path, "wb").write(data)
        elif cmd == "rm":
            io.remove(rest[0])
        elif cmd == "ls":
            for name in io.list_objects():
                print(name)
        elif cmd == "stat":
            print(
                json.dumps({"oid": rest[0], "size": io.stat(rest[0])})
            )
        elif cmd == "setomapval":
            oid, key, value = rest
            io.omap_set(oid, {key: value.encode()})
        elif cmd == "listomapvals":
            for k, v in sorted(io.omap_get_vals(rest[0]).items()):
                print(f"{k}: {v.decode('latin-1')}")
        elif cmd == "rmomapkey":
            io.omap_rm_keys(rest[0], [rest[1]])
        elif cmd == "mksnap":
            print(io.snap_create(rest[0]))
        elif cmd == "rmsnap":
            io.snap_remove(rest[0])
        elif cmd == "lssnap":
            for sid, name in sorted(io.snap_list().items()):
                print(f"{sid}\t{name}")
        elif cmd == "list-inconsistent-obj":
            # the pg's persisted ScrubStore findings, served by its
            # primary (src/tools/rados/rados.cc do_get_inconsistent)
            print(
                json.dumps(
                    {
                        "epoch": r.monc.epoch,
                        "inconsistents": r.list_inconsistent_obj(
                            rest[0]
                        ),
                    },
                    indent=2,
                )
            )
        elif cmd == "bench":
            seconds, mode = int(rest[0]), rest[1]
            _bench(
                io, r, seconds, mode, args.obj_size, args.concurrent
            )
        else:
            print(f"unknown command {cmd!r}", file=sys.stderr)
            return 2
        return 0
    finally:
        r.shutdown()


if __name__ == "__main__":
    sys.exit(main())
