"""Flag-compatible ceph_erasure_code_benchmark
(src/test/erasure-code/ceph_erasure_code_benchmark.cc).

Same options (-s/-i/-p/-w/-e/--erased/-E/-P), same output contract —
one line ``<seconds>\\t<KB>`` so qa/workunits/erasure-code/bench.sh's
GB/s conversion works unchanged.  Extension: ``--batch B`` encodes B
stripes per iteration through the hoisted batched path (the TPU seam,
ECUtil::encode's per-stripe loop in one device call).
"""

from __future__ import annotations

import argparse
import random
import sys
import time

import numpy as np

from ..ec import ErasureCodeProfile, registry_instance


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="ec_benchmark", description=__doc__.splitlines()[0]
    )
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("-s", "--size", type=int, default=1024 * 1024,
                   help="size of the buffer to be encoded")
    p.add_argument("-i", "--iterations", type=int, default=1,
                   help="number of encode/decode runs")
    p.add_argument("-p", "--plugin", default="jerasure",
                   help="erasure code plugin name")
    p.add_argument("-w", "--workload", default="encode",
                   choices=["encode", "decode"])
    p.add_argument("-e", "--erasures", type=int, default=1,
                   help="number of erasures when decoding")
    p.add_argument("--erased", type=int, action="append", default=[],
                   help="erased chunk (repeat for more than one)")
    p.add_argument("-E", "--erasures-generation", default="random",
                   choices=["random", "exhaustive"])
    p.add_argument("-P", "--parameter", action="append", default=[],
                   help="add key=value to the erasure code profile")
    p.add_argument("--batch", type=int, default=1,
                   help="stripes per device call (TPU batched path)")
    return p.parse_args(argv)


def make_code(args):
    profile = ErasureCodeProfile()
    for kv in args.parameter:
        if kv.count("=") != 1:
            print(f"--parameter {kv} ignored: not exactly one =",
                  file=sys.stderr)
            continue
        key, value = kv.split("=")
        profile[key] = value
    profile.setdefault("k", "7")
    profile.setdefault("m", "3")
    return registry_instance().factory(args.plugin, profile)


def run_encode(args, ec) -> tuple[float, int]:
    data = b"X" * args.size
    want = set(range(ec.get_chunk_count()))
    if args.batch > 1:
        # hoisted path: B identical-geometry stripes in one call
        chunk = ec.get_chunk_size(args.size)
        k = ec.get_data_chunk_count()
        stripes = np.frombuffer(
            data.ljust(chunk * k, b"\0"), dtype=np.uint8
        ).reshape(1, k, chunk)
        stripes = np.broadcast_to(
            stripes, (args.batch, k, chunk)
        ).copy()
        backend = ec.backend
        matrix = getattr(ec, "matrix", None)
        if matrix is None or not hasattr(backend, "matrix_stripes"):
            raise SystemExit(
                "--batch needs a matrix technique (reed_sol_*, isa)"
            )
        begin = time.perf_counter()
        for _ in range(args.iterations):
            backend.matrix_stripes(matrix, stripes, ec.w)
        elapsed = time.perf_counter() - begin
        kb = args.iterations * args.batch * (args.size // 1024)
        return elapsed, kb
    begin = time.perf_counter()
    for _ in range(args.iterations):
        ec.encode(want, data)
    elapsed = time.perf_counter() - begin
    return elapsed, args.iterations * (args.size // 1024)


def _display_chunks(chunks, count):
    out = "chunks "
    for c in range(count):
        out += f"({c})  " if c not in chunks else f" {c}   "
    print(out + "(X) is an erased chunk")


def _decode_exhaustive(ec, all_chunks, chunks, start, want, verbose):
    """Recursive exhaustive erasure sweep with content verification
    (decode_erasures, ceph_erasure_code_benchmark.cc:202-249)."""
    n = ec.get_chunk_count()
    if want == 0:
        if verbose:
            _display_chunks(chunks, n)
        want_to_read = {c for c in range(n) if c not in chunks}
        decoded = ec.decode(want_to_read, chunks)
        for c in want_to_read:
            if not np.array_equal(decoded[c], all_chunks[c]):
                raise SystemExit(
                    f"chunk {c}: recovered content differs"
                )
        return
    for i in range(start, n):
        if i not in chunks:
            continue
        one_less = {c: v for c, v in chunks.items() if c != i}
        _decode_exhaustive(ec, all_chunks, one_less, i + 1, want - 1,
                           verbose)


def run_decode(args, ec) -> tuple[float, int]:
    data = b"X" * args.size
    n = ec.get_chunk_count()
    want = set(range(n))
    encoded = ec.encode(want, data)
    if args.erased:
        for c in args.erased:
            encoded.pop(c, None)
        _display_chunks(encoded, n)
    rng = random.Random()
    begin = time.perf_counter()
    for _ in range(args.iterations):
        if args.erasures_generation == "exhaustive":
            _decode_exhaustive(
                ec, encoded, dict(encoded), 0, args.erasures, args.verbose
            )
        elif args.erased:
            ec.decode(want, encoded)
        else:
            chunks = dict(encoded)
            for _ in range(args.erasures):
                while True:
                    erasure = rng.randrange(n)
                    if erasure in chunks:
                        break
                chunks.pop(erasure)
            ec.decode(want, chunks)
    elapsed = time.perf_counter() - begin
    return elapsed, args.iterations * (args.size // 1024)


def main(argv=None) -> int:
    args = parse_args(argv)
    ec = make_code(args)
    if args.workload == "encode":
        elapsed, kb = run_encode(args, ec)
    else:
        elapsed, kb = run_decode(args, ec)
    print(f"{elapsed:.6f}\t{kb}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
