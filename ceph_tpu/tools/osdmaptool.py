"""osdmaptool --test-map-pgs equivalent (src/tools/osdmaptool.cc:41-53,
147-218): bulk-map every PG of every pool, print distribution stats and
timing — the full-map-recompute benchmark (ParallelPGMapper's job, done
as one batched device call per pool)."""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from ..crush.types import (
    CRUSH_ITEM_NONE,
    PG_POOL_TYPE_ERASURE,
    PG_POOL_TYPE_REPLICATED,
)
from ..osd import OSDMap, OSDMapMapping, PgPool
from .crushtool import build_hierarchy


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="osdmaptool", description=__doc__)
    p.add_argument("--test-map-pgs", action="store_true", required=True)
    p.add_argument("--build", metavar="OSDS:PER_HOST[:HOSTS_PER_RACK]",
                   default="64:4")
    p.add_argument("--pg-num", type=int, default=1024)
    p.add_argument("--pool-type", default="replicated",
                   choices=["replicated", "erasure"])
    p.add_argument("--size", type=int, default=0,
                   help="pool size (default 3 replicated / 5 erasure)")
    p.add_argument("--backend", default="jax", choices=["jax", "oracle"])
    p.add_argument("--dump", action="store_true",
                   help="print per-osd pg counts")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    parts = [int(v) for v in args.build.split(":")]
    num_osds, per_host = parts[0], parts[1]
    hpr = parts[2] if len(parts) > 2 else 0
    crush = build_hierarchy(num_osds, per_host, hpr)
    om = OSDMap.build(crush, num_osds)
    if args.pool_type == "replicated":
        pool = PgPool(
            pool_id=1, type=PG_POOL_TYPE_REPLICATED,
            size=args.size or 3, pg_num=args.pg_num, crush_rule=0,
        )
    else:
        pool = PgPool(
            pool_id=1, type=PG_POOL_TYPE_ERASURE,
            size=args.size or 5, pg_num=args.pg_num, crush_rule=1,
        )
    om.add_pool(pool)

    mapping = OSDMapMapping()
    use_device = args.backend == "jax"
    mapping.update(om, use_device=use_device)  # warm-up incl. compile
    t0 = time.perf_counter()
    mapping.update(om, use_device=use_device)
    elapsed = time.perf_counter() - t0

    up = mapping.up[1]
    valid = up != CRUSH_ITEM_NONE
    per_osd = np.bincount(up[valid].astype(np.int64), minlength=num_osds)
    total = int(valid.sum())
    print(
        f"pool 1 pg_num {pool.pg_num} size {pool.size} "
        f"({args.pool_type}): mapped {total} osd slots over "
        f"{num_osds} osds in {elapsed:.4f}s = "
        f"{pool.pg_num / elapsed:.0f} pg mappings/sec [{args.backend}]"
    )
    print(
        f"  per-osd pgs: min {per_osd.min()} max {per_osd.max()} "
        f"avg {per_osd.mean():.1f} stddev {per_osd.std():.1f}"
    )
    if args.dump:
        for osd, cnt in enumerate(per_osd):
            print(f"  osd.{osd}\t{cnt}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
