"""ceph-objectstore-tool analog — offline store surgery
(src/tools/ceph_objectstore_tool.cc): inspect/export/import/remove
objects in a STOPPED OSD's KStore directory.

    python -m ceph_tpu.tools.objectstore_tool --data-path DIR <op>

    ops: list-collections | list [COLL] | info COLL OID
         export COLL OID FILE | import COLL OID FILE
         remove COLL OID | export-pg COLL FILE | import-pg FILE
         fsck

Export blobs carry data + xattrs + omap (the tool's object dump
format); ``export-pg``/``import-pg`` move a whole collection, the
offline-PG-surgery use case (e.g. rescuing a PG from a dead OSD's
store into a replacement).
"""

from __future__ import annotations

import argparse
import json
import sys

from ..common.encoding import Decoder, Encoder
from ..store.kstore import KStore
from ..store.objectstore import StoreError, Transaction

_MAGIC = 0x4F535442  # "OSTB"


def _export_obj(store, cid: str, oid: str) -> bytes:
    e = Encoder()
    e.u32(_MAGIC).string(cid).string(oid)
    e.bytes(store.read(cid, oid))
    e.map(
        store.list_attrs(cid, oid),
        lambda e2, k: e2.string(k),
        lambda e2, v: e2.bytes(v),
    )
    e.map(
        store.omap_get(cid, oid),
        lambda e2, k: e2.string(k),
        lambda e2, v: e2.bytes(v),
    )
    return e.getvalue()


def _import_obj(store, blob: bytes, cid=None, oid=None) -> tuple[str, str]:
    d = Decoder(blob)
    if d.u32() != _MAGIC:
        raise StoreError("bad export magic")
    b_cid, b_oid = d.string(), d.string()
    cid, oid = cid or b_cid, oid or b_oid
    data = d.bytes()
    attrs = d.map(lambda d2: d2.string(), lambda d2: d2.bytes())
    omap = d.map(lambda d2: d2.string(), lambda d2: d2.bytes())
    txn = Transaction()
    if cid not in store.list_collections():
        txn.create_collection(cid)
    elif store.exists(cid, oid):
        txn.remove(cid, oid)
    txn.touch(cid, oid)
    if data:
        txn.write(cid, oid, 0, data)
    for k, v in attrs.items():
        txn.setattr(cid, oid, k, v)
    if omap:
        txn.omap_setkeys(cid, oid, omap)
    store.queue_transaction(txn)
    return cid, oid


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="objectstore_tool", description=__doc__
    )
    p.add_argument("--data-path", required=True)
    p.add_argument("op", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if not args.op:
        p.error("no op")
    store = KStore(args.data_path)
    try:
        op, rest = args.op[0], args.op[1:]
        if op == "list-collections":
            for cid in store.list_collections():
                print(cid)
        elif op == "list":
            colls = rest or store.list_collections()
            for cid in colls:
                for oid in store.list_objects(cid):
                    print(f"{cid}\t{oid}")
        elif op == "info":
            cid, oid = rest
            print(
                json.dumps(
                    {
                        "collection": cid,
                        "oid": oid,
                        "size": store.stat(cid, oid),
                        "xattrs": sorted(store.list_attrs(cid, oid)),
                        "omap_keys": len(store.omap_get(cid, oid)),
                    }
                )
            )
        elif op == "export":
            cid, oid, path = rest
            blob = _export_obj(store, cid, oid)
            (sys.stdout.buffer.write(blob) if path == "-"
             else open(path, "wb").write(blob))
        elif op == "import":
            cid, oid, path = rest
            _import_obj(store, open(path, "rb").read(), cid, oid)
            store.compact()
        elif op == "remove":
            cid, oid = rest
            store.queue_transaction(Transaction().remove(cid, oid))
            store.compact()
        elif op == "export-pg":
            cid, path = rest
            e = Encoder()
            oids = store.list_objects(cid)
            e.u32(len(oids))
            for oid in oids:
                e.bytes(_export_obj(store, cid, oid))
            open(path, "wb").write(e.getvalue())
        elif op == "import-pg":
            (path,) = rest
            d = Decoder(open(path, "rb").read())
            n = d.u32()
            for _ in range(n):
                _import_obj(store, d.bytes())
            store.compact()
            print(f"imported {n} objects")
        elif op == "fsck":
            # the KStore mount already replays + validates the WAL and
            # snapshot crc; walk everything to force full reads
            objs = 0
            for cid in store.list_collections():
                for oid in store.list_objects(cid):
                    store.read(cid, oid)
                    store.list_attrs(cid, oid)
                    store.omap_get(cid, oid)
                    objs += 1
            print(
                json.dumps(
                    {
                        "collections": len(store.list_collections()),
                        "objects": objs,
                        "ok": True,
                    }
                )
            )
        else:
            print(f"unknown op {op!r}", file=sys.stderr)
            return 2
        return 0
    finally:
        store.close()


if __name__ == "__main__":
    sys.exit(main())
