"""``ceph`` CLI — the admin command surface (src/ceph.in).

The reference CLI translates argv into JSON command objects described
by MonCommands.h and ships them to the monitor; replies carry a text
``outs`` and a data ``outb``.  This CLI does exactly that over the
framework's MMonCommand path:

    python -m ceph_tpu.tools.ceph_cli -m HOST:PORT status
    ... osd tree | osd dump | osd pool ls | pg dump | health
    ... osd pool create NAME [PG_NUM] [--size N] [--pool-type N]
    ... osd pool delete NAME
    ... osd down/out/in ID | osd reweight ID WEIGHT
    ... osd erasure-code-profile set NAME k=4 m=2 [...]
    ... osd erasure-code-profile get NAME | ls
    ... config set WHO KEY VALUE | config get WHO [KEY] | config dump

``--format json`` prints outb; the default prints outs (or pretty
outb when there is no outs), like the reference's -f handling.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..mon.monitor import MonClient
from ..msg import Messenger


def _coerce(v: str):
    """key=value coercion for tell/fault arguments."""
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        return v


def _build_tell_args(args: list[str]) -> dict:
    """The inner `ceph tell osd.N <cmd>` grammar: `fault set
    [dst=X] [drop=P] [delay=S] [jitter=S] [dup=P] [reorder=P]` /
    `fault set partition=NAME groups=a,b;c,d` / `fault clear
    [id=N | partition=NAME]` / `fault list` / `fault seed N` /
    `dump_backoffs` / `perf dump`."""
    if not args:
        raise SystemExit("tell: missing daemon command")
    if args[0] == "fault":
        if len(args) < 2:
            raise SystemExit("tell: fault set|clear|list|seed ...")
        cmd: dict = {"prefix": f"fault {args[1]}"}
        if args[1] == "seed" and len(args) > 2:
            cmd["seed"] = int(args[2])
            cmd["prefix"] = "fault seed"
            return cmd
        for kv in args[2:]:
            k, _, v = kv.partition("=")
            if k == "groups":
                # a,b;c,d → [["a","b"],["c","d"]]
                cmd[k] = [
                    [m for m in grp.split(",") if m]
                    for grp in v.split(";")
                ]
            else:
                cmd[k] = _coerce(v)
        return cmd
    # generic daemon commands (`perf histogram dump`,
    # `dump_historic_slow_ops threshold=1 qos_class=gold`, ...):
    # bare words join into the prefix, k=v tokens become arguments
    words = [a for a in args if "=" not in a]
    cmd = {"prefix": " ".join(words)}
    for kv in args:
        if "=" in kv:
            k, _, v = kv.partition("=")
            cmd[k] = _coerce(v)
    return cmd


def _build_command(args: list[str]) -> dict:
    """argv tail → JSON command (the MonCommands.h translation)."""
    joined = " ".join(args)
    # longest-prefix match over the known command table shapes
    if args[0] == "tell" and len(args) >= 3:
        # `ceph tell osd.N ...`: the mon validates the target and
        # names its address; main() dispatches the inner command
        # there as an MCommand
        return {
            "prefix": "tell",
            "target": args[1],
            "args": _build_tell_args(args[2:]),
        }
    if joined.startswith("osd df"):
        return {"prefix": "osd df"}
    if joined.startswith("osd pool create"):
        rest = args[3:]
        cmd = {"prefix": "osd pool create", "pool": rest[0]}
        if len(rest) > 1 and rest[1].isdigit():
            cmd["pg_num"] = int(rest[1])
        for kv in rest[1:]:
            if "=" in kv:
                k, _, v = kv.partition("=")
                cmd[k.replace("-", "_")] = v
        return cmd
    if joined.startswith("osd pool delete"):
        return {"prefix": "osd pool delete", "pool": args[3]}
    if joined.startswith("osd pool ls"):
        return {"prefix": "osd pool ls"}
    if joined.startswith("osd erasure-code-profile set"):
        # monitor-side _cmd_ec_profile_set expects the raw list of
        # "k=v" strings (the MonCommands.h CephString[] shape)
        return {
            "prefix": "osd erasure-code-profile set",
            "name": args[3],
            "profile": list(args[4:]),
        }
    if joined.startswith("osd erasure-code-profile get"):
        return {"prefix": "osd erasure-code-profile get", "name": args[3]}
    if joined.startswith("osd erasure-code-profile ls"):
        return {"prefix": "osd erasure-code-profile ls"}
    if joined.startswith(("osd down", "osd out", "osd in")):
        return {"prefix": f"osd {args[1]}", "id": int(args[2])}
    if joined.startswith("osd reweight"):
        return {
            "prefix": "osd reweight",
            "id": int(args[2]),
            "weight": float(args[3]),
        }
    if joined.startswith("osd blocklist"):
        # osd blocklist add|rm|ls [ADDR] [EXPIRE]
        cmd = {"prefix": "osd blocklist", "blocklistop": args[2]}
        if len(args) > 3:
            cmd["addr"] = args[3]
        if len(args) > 4:
            cmd["expire"] = float(args[4])
        return cmd
    if joined.startswith("osd tier"):
        # osd tier add|remove|cache-mode|set-overlay BASE CACHE
        # osd tier cache-mode BASE CACHE MODE
        # osd tier remove-overlay BASE
        op = args[2]
        cmd = {"prefix": "osd tier", "tierop": op, "pool": args[3]}
        if op in ("add", "remove", "cache-mode", "set-overlay"):
            if len(args) < 5:
                raise SystemExit(
                    f"osd tier {op} needs BASE CACHE"
                )
            cmd["tierpool"] = args[4]
        if op == "cache-mode" and len(args) > 5:
            cmd["mode"] = args[5]
        return cmd
    if joined.startswith("mds pin"):
        return {"prefix": "mds pin", "path": args[2],
                "rank": int(args[3])}
    if joined.startswith("mds set-max-mds"):
        return {"prefix": "mds set-max-mds", "max_mds": int(args[2])}
    if joined.startswith("mds fail"):
        return {"prefix": "mds fail", "who": args[2]}
    if joined.startswith("mds stat"):
        return {"prefix": "mds stat"}
    if joined.startswith("osd pool set"):
        return {"prefix": "osd pool set", "pool": args[3],
                "var": args[4], "val": args[5]}
    if joined.startswith("osd tree"):
        return {"prefix": "osd tree"}
    if joined.startswith("osd dump"):
        return {"prefix": "osd dump"}
    if joined.startswith("pg dump"):
        return {"prefix": "pg dump"}
    if joined.startswith(("pg scrub", "pg deep-scrub", "pg repair")):
        # pg scrub|deep-scrub|repair PGID — the mon validates and
        # names the primary; main() dispatches the order to it
        if len(args) < 3:
            raise SystemExit(f"pg {args[1]} needs a PGID")
        return {"prefix": f"pg {args[1]}", "pgid": args[2]}
    if joined.startswith("config set"):
        return {
            "prefix": "config set",
            "who": args[2],
            "key": args[3],
            "value": " ".join(args[4:]),
        }
    if joined.startswith("config get"):
        cmd = {"prefix": "config get", "who": args[2]}
        if len(args) > 3:
            cmd["key"] = args[3]
        return cmd
    if joined.startswith("config dump"):
        return {"prefix": "config dump"}
    # exact-token match, NOT joined.startswith: `log "last words"`
    # (one quoted arg) must inject an entry, never run the query
    if args[0] == "log" and len(args) > 1 and args[1] == "last":
        # log last [n] [level] [channel]
        from ..common.log_client import CLOG_PRIOS

        cmd = {"prefix": "log last"}
        for a in args[2:]:
            if a.isdigit():
                cmd["num"] = int(a)
            elif a in CLOG_PRIOS:
                cmd["level"] = a
            else:
                cmd["channel"] = a
        return cmd
    if args[0] == "log" and len(args) > 1 and args[1] == "stat":
        return {"prefix": "log stat"}
    if args[0] == "log" and len(args) > 1:
        return {"prefix": "log", "logtext": " ".join(args[1:])}
    if joined.startswith(("health mute", "health unmute")):
        if len(args) < 3:
            raise SystemExit(f"health {args[1]} needs a check CODE")
        if args[1] == "unmute":
            return {"prefix": "health unmute", "code": args[2]}
        # health mute CODE [--ttl SECONDS]
        cmd = {"prefix": "health mute", "code": args[2]}
        rest = args[3:]
        if rest:
            try:
                raw = rest[1] if rest[0] == "--ttl" else rest[0]
                cmd["ttl"] = float(raw)
            except (IndexError, ValueError):
                raise SystemExit(
                    "health mute --ttl needs a number of seconds"
                ) from None
        return cmd
    if args[0] == "crash":
        # mgr-targeted (routed to the active mgr by main()):
        # crash ls | info ID | stat | archive ID|all
        sub = args[1] if len(args) > 1 else "ls"
        if sub in ("ls", "stat"):
            return {"prefix": f"crash {sub}"}
        if sub == "info":
            if len(args) < 3:
                raise SystemExit("crash info needs a crash id")
            return {"prefix": "crash info", "id": args[2]}
        if sub == "archive":
            if len(args) < 3:
                # NEVER default to archive-all: clearing every crash
                # (and RECENT_CRASH) from a missing argument is a
                # destructive surprise — demand it by name
                raise SystemExit(
                    "crash archive needs an id (or the literal 'all')"
                )
            return {"prefix": "crash archive", "id": args[2]}
        raise SystemExit(f"unknown crash subcommand {sub!r}")
    if args[0] == "tracing":
        # mgr-targeted: tracing dump [qos_class=X] | tracing summary
        sub = args[1] if len(args) > 1 else "summary"
        cmd = {"prefix": f"tracing {sub}"}
        for kv in args[2:]:
            if "=" in kv:
                k, _, v = kv.partition("=")
                cmd[k] = v
        return cmd
    if args[0] == "slo":
        # mgr-targeted (routed to the active mgr by main()):
        # slo status | slo targets | slo targets set SPEC...
        if len(args) >= 3 and args[1] == "targets" and args[2] == "set":
            return {
                "prefix": "slo targets set",
                "targets": " ".join(args[3:]),
            }
        sub = args[1] if len(args) > 1 else "status"
        return {"prefix": f"slo {sub}"}
    if args[0] == "progress":
        # mgr-targeted: progress | progress json | progress clear |
        # progress event id=X fraction=F [message=...] [done=1]
        sub = args[1] if len(args) > 1 else ""
        if sub == "event":
            cmd = {"prefix": "progress event"}
            for kv in args[2:]:
                if "=" in kv:
                    k, _, v = kv.partition("=")
                    cmd[k] = _coerce(v)
            return cmd
        return {"prefix": f"progress {sub}".strip()}
    if args[0] == "df":
        return {"prefix": "df"}
    if args[0] in ("status", "health"):
        return {"prefix": args[0]}
    # pass-through: let the monitor reject unknowns (same as the
    # reference's validation living mon-side)
    return {"prefix": joined}


def _mgr_command(msgr, mc, cmd: dict):
    """Send a command to the active mgr (mgr-module surface)."""
    from ..msg.message import MMonCommand, MMonCommandReply

    reply = mc.command({"prefix": "mgr stat"})
    active = json.loads(reply.outb).get("active") if reply.rc == 0 else None
    if not active or not active.get("addr"):
        raise SystemExit("no active mgr (is one running?)")
    host, _, port = active["addr"].rpartition(":")
    conn = msgr.connect(host, int(port))
    out = conn.call(MMonCommand(cmd=json.dumps(cmd)))
    assert isinstance(out, MMonCommandReply)
    return out


def _watch(msgr, mc, level: str, debug: bool) -> int:
    """`ceph -w`: subscribe to the mon's cluster-log stream and
    print entries as they commit, until interrupted.  The mon pushes
    MLog batches on the subscribed connection (the MLog subscription
    shape); ``--watch-debug`` adds the mon's dout-ring firehose as
    channel="debug" lines."""
    import queue
    import time as _time

    from ..msg.message import MLog
    from ..msg.messenger import Dispatcher

    q: queue.Queue = queue.Queue()

    class _WatchSink(Dispatcher):
        def ms_dispatch(self, conn, msg):
            if isinstance(msg, MLog):
                q.put(msg)
                return True
            return False

        def ms_handle_reset(self, conn):
            q.put(None)

    msgr.add_dispatcher(_WatchSink())
    reply = mc.command(
        {"prefix": "log subscribe", "level": level, "debug": debug}
    )
    if reply.rc != 0:
        raise SystemExit(f"log subscribe failed: {reply.outs}")
    st = mc.command({"prefix": "status"})
    if st.rc == 0 and st.outb:
        print(
            json.dumps(json.loads(st.outb), indent=2), flush=True
        )
    try:
        while True:
            msg = q.get()
            if msg is None:
                print("connection to mon lost", file=sys.stderr)
                return 1
            try:
                entries = json.loads(msg.entries)
            except ValueError:
                continue
            for e in entries:
                if not isinstance(e, dict):
                    continue
                stamp = _time.strftime(
                    "%Y-%m-%d %H:%M:%S",
                    _time.localtime(float(e.get("stamp", 0))),
                )
                print(
                    f"{stamp} {e.get('name', '?')} "
                    f"[{e.get('channel', 'cluster')}:"
                    f"{e.get('prio', 'info')}] "
                    f"{e.get('message', '')}",
                    flush=True,
                )
    except KeyboardInterrupt:
        return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="ceph", description=__doc__, add_help=True
    )
    p.add_argument(
        "-m", "--mon", required=True, metavar="HOST:PORT",
        help="monitor address",
    )
    p.add_argument(
        "-f", "--format", choices=["plain", "json"], default="plain"
    )
    # explicit flags, declared BEFORE the REMAINDER command so
    # argparse claims them (a REMAINDER would swallow `-w`)
    p.add_argument(
        "-w", "--watch", action="store_true",
        help="stream the cluster log live (the `ceph -w` surface)",
    )
    p.add_argument(
        "--watch-debug", action="store_true",
        help="watch, including the mon's dout-ring firehose",
    )
    p.add_argument(
        "--watch-level", default="debug",
        help="minimum clog priority to stream (default: debug)",
    )
    p.add_argument("command", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    watching = args.watch or args.watch_debug
    if not args.command and not watching:
        p.error("no command given")
    host, _, port = args.mon.partition(":")

    msgr = Messenger("ceph-cli")
    try:
        mc = MonClient(msgr, whoami=-1)
        mc.connect(host, int(port))
        if watching:
            return _watch(
                msgr, mc, args.watch_level, args.watch_debug
            )
        cmd = _build_command(args.command)
        prefix = cmd["prefix"]
        if prefix == "progress" or prefix.startswith("progress "):
            # mgr-module command (the progress module's surface)
            reply = _mgr_command(msgr, mc, cmd)
        elif prefix == "slo" or prefix.startswith(("slo ", "tracing ")):
            # mgr-module commands, like crash: the owning module
            # (first prefix word) serves them on the active mgr
            reply = _mgr_command(msgr, mc, cmd)
        elif prefix == "crash" or prefix.startswith("crash "):
            # mgr-module command: discover the active mgr through the
            # monitor and send there (the reference CLI routes
            # MgrCommands to the active mgr the same way)
            reply = _mgr_command(msgr, mc, cmd)
        elif prefix in ("pg scrub", "pg deep-scrub", "pg repair"):
            # scrub-plane order: the mon validates the pg and names
            # the primary; the CLI dispatches the order there
            reply = mc.command(cmd)
            if reply.rc == 0 and reply.outb:
                from ..msg.message import MScrubCommand

                target = json.loads(reply.outb)
                host, _, port = target["addr"].rpartition(":")
                conn = msgr.connect(host, int(port))
                reply = conn.call(
                    MScrubCommand(
                        tid=msgr.new_tid(),
                        op=target["op"], pgid=target["pgid"],
                    )
                )
        elif prefix == "tell":
            # mon names the daemon's address; the CLI dispatches the
            # inner command there as an MCommand (`ceph tell` route)
            reply = mc.command(cmd)
            if reply.rc == 0 and reply.outb:
                from ..msg.message import MCommand

                target = json.loads(reply.outb)
                host, _, port = target["addr"].rpartition(":")
                conn = msgr.connect(host, int(port))
                reply = conn.call(
                    MCommand(
                        tid=msgr.new_tid(),
                        cmd=json.dumps(target["args"]),
                    )
                )
        else:
            reply = mc.command(cmd)
    finally:
        msgr.shutdown()

    if args.format == "json":
        print(reply.outb or json.dumps({"status": reply.outs}))
    else:
        if reply.outs:
            print(reply.outs)
        if reply.outb and not reply.outs:
            try:
                print(json.dumps(json.loads(reply.outb), indent=2))
            except (ValueError, TypeError):
                print(reply.outb)
    if reply.rc != 0 and not reply.outs:
        print(f"Error: rc={reply.rc}", file=sys.stderr)
    return 0 if reply.rc == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
