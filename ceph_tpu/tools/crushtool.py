"""crushtool equivalent (src/tools/crushtool.cc:200-231,535 and
src/crush/CrushTester.{h,cc}, src/crush/CrushCompiler.cc).

Modes:
- ``-c map.txt -o out``     compile a text crushmap to reference binary
- ``-d map.bin [-o out]``   decompile a reference binary to text
- ``-i map.bin --test``     test a real (reference-format) binary map
- ``--build --test``        test a synthetic straw2 hierarchy

--test maps x ∈ [min-x, max-x) through a rule and reports utilization,
chi-squared uniformity and bad mappings — plus mappings/sec, which is
the PG-mapping benchmark surface (BASELINE.md).

Backends: ``jax`` (batched device kernel) or ``oracle`` (exact scalar);
jax falls back to the oracle on maps outside the device kernel's scope
(e.g. list/tree/straw buckets).
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from ..crush.builder import CrushMap
from ..crush.types import (
    CRUSH_BUCKET_STRAW2,
    CRUSH_ITEM_NONE,
    Tunables,
)


def build_hierarchy(
    num_osds: int,
    per_host: int,
    hosts_per_rack: int = 0,
    weight_fn=None,
) -> CrushMap:
    """root -> [racks ->] hosts -> osds, all straw2 (the benchmark
    hierarchy: 10k OSDs via --build's layered buckets)."""
    m = CrushMap(tunables=Tunables())
    weight_fn = weight_fn or (lambda osd: 0x10000)
    hosts = []
    for h in range((num_osds + per_host - 1) // per_host):
        items = list(range(h * per_host, min((h + 1) * per_host, num_osds)))
        if not items:
            break
        weights = [weight_fn(i) for i in items]
        hosts.append(
            m.add_bucket(CRUSH_BUCKET_STRAW2, 1, items, weights,
                         name=f"host{h}")
        )
    level = hosts
    if hosts_per_rack:
        racks = []
        for r in range((len(hosts) + hosts_per_rack - 1) // hosts_per_rack):
            sub = hosts[r * hosts_per_rack : (r + 1) * hosts_per_rack]
            racks.append(
                m.add_bucket(
                    CRUSH_BUCKET_STRAW2,
                    2,
                    sub,
                    [m.buckets[b].weight for b in sub],
                    name=f"rack{r}",
                )
            )
        level = racks
    m.add_bucket(
        CRUSH_BUCKET_STRAW2,
        3,
        level,
        [m.buckets[b].weight for b in level],
        name="default",
    )
    m.add_simple_rule("replicated_rule", "default", "host", mode="firstn")
    m.add_simple_rule("ec_rule", "default", "host", mode="indep")
    return m


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="crushtool", description=__doc__)
    p.add_argument("--test", action="store_true")
    p.add_argument("-c", "--compile", metavar="MAP.TXT",
                   help="compile text crushmap to reference binary")
    p.add_argument("-d", "--decompile", metavar="MAP.BIN",
                   help="decompile reference binary crushmap to text")
    p.add_argument("-i", "--input", metavar="MAP.BIN",
                   help="reference binary crushmap to --test")
    p.add_argument("-o", "--output", metavar="FILE",
                   help="output file for -c/-d")
    p.add_argument("--build", metavar="OSDS:PER_HOST[:HOSTS_PER_RACK]",
                   default="64:4",
                   help="synthesize a straw2 hierarchy")
    p.add_argument("--min-x", type=int, default=0)
    p.add_argument("--max-x", type=int, default=1024)
    p.add_argument("--num-rep", type=int, default=3)
    p.add_argument("--rule", type=int, default=0)
    p.add_argument("--backend", default="jax", choices=["jax", "oracle"])
    p.add_argument("--show-utilization", action="store_true")
    p.add_argument("--show-statistics", action="store_true")
    p.add_argument("--show-bad-mappings", action="store_true")
    p.add_argument("--weight", type=str, action="append", default=[],
                   metavar="OSD:W", help="reweight osd, e.g. 3:0.5")
    args = p.parse_args(argv)
    if not (args.test or args.compile or args.decompile or args.input):
        p.error("no action specified (use -c, -d, -i and/or --test)")
    return args


def run_test(m: CrushMap, args) -> dict:
    n = args.max_x - args.min_x
    xs = np.arange(args.min_x, args.max_x, dtype=np.int64)
    num_osds = m.max_devices
    weights = [0x10000] * num_osds
    for spec in args.weight:
        osd, sep, w = spec.partition(":")
        if not sep:
            raise SystemExit(
                f"crushtool: --weight expects OSD:W, got {spec!r}"
            )
        osd = int(osd)
        if osd >= len(weights):
            # ids past max_devices are tolerated like the reference's
            # weight map (crushtool.cc:822); they can't match anyway
            weights.extend([0x10000] * (osd + 1 - len(weights)))
        weights[osd] = int(float(w) * 0x10000)

    t0 = time.perf_counter()
    backend = args.backend
    if backend == "jax":
        from ..crush import jaxmap

        try:
            cm = jaxmap.compile_map(m)
        except jaxmap.UnsupportedMap as e:
            print(f"# map outside device kernel ({e}); using oracle",
                  file=sys.stderr)
            backend = "oracle"
    if backend == "jax":
        res, counts = jaxmap.batch_do_rule(
            cm, args.rule, xs, args.num_rep, weights
        )
        res = np.asarray(res)
        counts = np.asarray(counts)
        # time a second, compile-free pass for the throughput figure
        t0 = time.perf_counter()
        res2, _ = jaxmap.batch_do_rule(
            cm, args.rule, xs, args.num_rep, weights
        )
        np.asarray(res2)
        elapsed = time.perf_counter() - t0
    else:
        rows = []
        counts = []
        for x in xs:
            r = m.do_rule(args.rule, int(x), args.num_rep, weights)
            counts.append(len(r))
            rows.append(r + [CRUSH_ITEM_NONE] * (args.num_rep - len(r)))
        res = np.asarray(rows, dtype=np.int64)
        counts = np.asarray(counts)
        elapsed = time.perf_counter() - t0
    args.backend = backend  # report the backend that actually ran

    valid = (res != CRUSH_ITEM_NONE) & (
        np.arange(args.num_rep)[None, :] < counts[:, None]
    )
    per_osd = np.bincount(
        res[valid].astype(np.int64), minlength=num_osds
    )
    bad = int((counts < args.num_rep).sum())
    total = int(valid.sum())
    expected = total / num_osds if num_osds else 0.0
    chi2 = (
        float((((per_osd - expected) ** 2) / expected).sum())
        if expected
        else 0.0
    )
    return {
        "n": n,
        "elapsed": elapsed,
        "mappings_per_sec": n / elapsed if elapsed else float("inf"),
        "per_osd": per_osd,
        "bad": bad,
        "chi2": chi2,
        "expected": expected,
    }


def main(argv=None) -> int:
    args = parse_args(argv)
    from ..crush import compiler

    if args.compile:
        with open(args.compile) as f:
            m = compiler.compile_crushmap(f.read())
        blob = compiler.encode_crushmap(m)
        out = args.output or (args.compile + ".compiled")
        with open(out, "wb") as f:
            f.write(blob)
    elif args.decompile:
        with open(args.decompile, "rb") as f:
            m = compiler.decode_crushmap(f.read())
        text = compiler.decompile_crushmap(m)
        if args.output:
            with open(args.output, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
        return 0
    elif args.input:
        with open(args.input, "rb") as f:
            m = compiler.decode_crushmap(f.read())
    else:
        parts = [int(v) for v in args.build.split(":")]
        num_osds, per_host = parts[0], parts[1]
        hpr = parts[2] if len(parts) > 2 else 0
        m = build_hierarchy(num_osds, per_host, hpr)
    if not args.test:
        return 0
    stats = run_test(m, args)
    print(
        f"rule {args.rule} x [{args.min_x},{args.max_x}) num_rep "
        f"{args.num_rep}: {stats['n']} mappings in "
        f"{stats['elapsed']:.4f}s = {stats['mappings_per_sec']:.0f} "
        f"mappings/sec [{args.backend}]"
    )
    if args.show_bad_mappings or stats["bad"]:
        print(f"bad mappings (short of {args.num_rep}): {stats['bad']}")
    if args.show_utilization:
        for osd, cnt in enumerate(stats["per_osd"]):
            print(f"  device {osd}:\t{cnt}")
    if args.show_statistics:
        print(
            f"chi-squared = {stats['chi2']:.2f} "
            f"(expected per device {stats['expected']:.1f})"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
