"""crushtool equivalent (src/tools/crushtool.cc:200-231,535 and
src/crush/CrushTester.{h,cc}, src/crush/CrushCompiler.cc).

Modes:
- ``-c map.txt -o out``     compile a text crushmap to reference binary
- ``-d map.bin [-o out]``   decompile a reference binary to text
- ``-i map.bin --test``     test a real (reference-format) binary map
- ``--build --test``        test a synthetic straw2 hierarchy

--test maps x ∈ [min-x, max-x) through a rule and reports utilization,
chi-squared uniformity and bad mappings — plus mappings/sec, which is
the PG-mapping benchmark surface (BASELINE.md).

Backends: ``jax`` (batched device kernel) or ``oracle`` (exact scalar);
jax falls back to the oracle on maps outside the device kernel's scope
(e.g. list/tree/straw buckets).
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from ..crush.builder import CrushMap
from ..crush.types import (
    CRUSH_BUCKET_STRAW2,
    CRUSH_ITEM_NONE,
    Tunables,
)


def build_hierarchy(
    num_osds: int,
    per_host: int,
    hosts_per_rack: int = 0,
    weight_fn=None,
) -> CrushMap:
    """root -> [racks ->] hosts -> osds, all straw2 (the benchmark
    hierarchy: 10k OSDs via --build's layered buckets)."""
    m = CrushMap(tunables=Tunables())
    weight_fn = weight_fn or (lambda osd: 0x10000)
    hosts = []
    for h in range((num_osds + per_host - 1) // per_host):
        items = list(range(h * per_host, min((h + 1) * per_host, num_osds)))
        if not items:
            break
        weights = [weight_fn(i) for i in items]
        hosts.append(
            m.add_bucket(CRUSH_BUCKET_STRAW2, 1, items, weights,
                         name=f"host{h}")
        )
    level = hosts
    if hosts_per_rack:
        racks = []
        for r in range((len(hosts) + hosts_per_rack - 1) // hosts_per_rack):
            sub = hosts[r * hosts_per_rack : (r + 1) * hosts_per_rack]
            racks.append(
                m.add_bucket(
                    CRUSH_BUCKET_STRAW2,
                    2,
                    sub,
                    [m.buckets[b].weight for b in sub],
                    name=f"rack{r}",
                )
            )
        level = racks
    m.add_bucket(
        CRUSH_BUCKET_STRAW2,
        3,
        level,
        [m.buckets[b].weight for b in level],
        name="default",
    )
    m.add_simple_rule("replicated_rule", "default", "host", mode="firstn")
    m.add_simple_rule("ec_rule", "default", "host", mode="indep")
    return m


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="crushtool", description=__doc__)
    p.add_argument("--test", action="store_true")
    p.add_argument("-c", "--compile", metavar="MAP.TXT",
                   help="compile text crushmap to reference binary")
    p.add_argument("-d", "--decompile", metavar="MAP.BIN",
                   help="decompile reference binary crushmap to text")
    p.add_argument("-i", "--input", metavar="MAP.BIN",
                   help="reference binary crushmap to --test")
    p.add_argument("-o", "--output", metavar="FILE",
                   help="output file for -c/-d")
    p.add_argument("--build", metavar="OSDS:PER_HOST[:HOSTS_PER_RACK]",
                   default="64:4",
                   help="synthesize a straw2 hierarchy")
    p.add_argument("--min-x", type=int, default=0)
    p.add_argument("--max-x", type=int, default=1024)
    p.add_argument("--num-rep", type=int, default=3)
    p.add_argument("--rule", type=int, default=0)
    p.add_argument("--backend", default="jax", choices=["jax", "oracle"])
    p.add_argument("--show-utilization", action="store_true")
    p.add_argument("--show-statistics", action="store_true")
    p.add_argument("--show-bad-mappings", action="store_true")
    p.add_argument("--weight", type=str, action="append", default=[],
                   metavar="OSD:W", help="reweight osd, e.g. 3:0.5")
    p.add_argument("--compare", metavar="MAP2.BIN",
                   help="mapping-delta report vs a second binary map "
                        "over the --test x range (crushtool.cc:231)")
    p.add_argument("--tree", action="store_true",
                   help="print the bucket hierarchy as a tree")
    args = p.parse_args(argv)
    if not (args.test or args.compile or args.decompile or args.input
            or args.compare or args.tree):
        p.error("no action specified (use -c, -d, -i, --test, "
                "--compare and/or --tree)")
    return args


def _parse_weights(m: CrushMap, args) -> list[int]:
    weights = [0x10000] * m.max_devices
    for spec in args.weight:
        osd, sep, w = spec.partition(":")
        if not sep:
            raise SystemExit(
                f"crushtool: --weight expects OSD:W, got {spec!r}"
            )
        osd = int(osd)
        if osd >= len(weights):
            # ids past max_devices are tolerated like the reference's
            # weight map (crushtool.cc:822); they can't match anyway
            weights.extend([0x10000] * (osd + 1 - len(weights)))
        weights[osd] = int(float(w) * 0x10000)
    return weights


def _map_range(m: CrushMap, args, weights, timed: bool = True):
    """Map x ∈ [min-x, max-x) through ``--rule`` on the selected
    backend.  Returns (res, counts, elapsed, backend) with ``elapsed``
    from a compile-free pass (the throughput figure).  ``timed=False``
    skips that second pass for callers that discard elapsed
    (--compare maps both inputs; no point doubling the device work)."""
    xs = np.arange(args.min_x, args.max_x, dtype=np.int64)
    t0 = time.perf_counter()
    backend = args.backend
    if backend == "jax":
        from ..crush import jaxmap

        try:
            cm = jaxmap.compile_map(m)
        except jaxmap.UnsupportedMap as e:
            print(f"# map outside device kernel ({e}); using oracle",
                  file=sys.stderr)
            backend = "oracle"
    if backend == "jax":
        res, counts = jaxmap.batch_do_rule(
            cm, args.rule, xs, args.num_rep, weights
        )
        res = np.asarray(res)
        counts = np.asarray(counts)
        elapsed = time.perf_counter() - t0
        if timed:
            # time a second, compile-free pass for the throughput figure
            t0 = time.perf_counter()
            res2, _ = jaxmap.batch_do_rule(
                cm, args.rule, xs, args.num_rep, weights
            )
            np.asarray(res2)
            elapsed = time.perf_counter() - t0
    else:
        rows = []
        counts = []
        for x in xs:
            r = m.do_rule(args.rule, int(x), args.num_rep, weights)
            counts.append(len(r))
            rows.append(r + [CRUSH_ITEM_NONE] * (args.num_rep - len(r)))
        res = np.asarray(rows, dtype=np.int64)
        counts = np.asarray(counts)
        elapsed = time.perf_counter() - t0
    return res, counts, elapsed, backend


def run_test(m: CrushMap, args) -> dict:
    n = args.max_x - args.min_x
    num_osds = m.max_devices
    weights = _parse_weights(m, args)
    res, counts, elapsed, backend = _map_range(m, args, weights)
    args.backend = backend  # report the backend that actually ran

    valid = (res != CRUSH_ITEM_NONE) & (
        np.arange(args.num_rep)[None, :] < counts[:, None]
    )
    per_osd = np.bincount(
        res[valid].astype(np.int64), minlength=num_osds
    )
    bad = int((counts < args.num_rep).sum())
    total = int(valid.sum())
    expected = total / num_osds if num_osds else 0.0
    chi2 = (
        float((((per_osd - expected) ** 2) / expected).sum())
        if expected
        else 0.0
    )
    return {
        "n": n,
        "elapsed": elapsed,
        "mappings_per_sec": n / elapsed if elapsed else float("inf"),
        "per_osd": per_osd,
        "bad": bad,
        "chi2": chi2,
        "expected": expected,
    }


def run_compare(m1: CrushMap, m2: CrushMap, args) -> dict:
    """Mapping-delta report between two maps (crushtool.cc:231
    --compare, the balancer-validation workflow): map the same x
    range through ``--rule`` on BOTH maps and count changed mappings
    — whole-x changes (any position differs) and moved slots (data
    that would migrate).  Output is deterministic for a given
    (maps, range, rule, weights): stable field order, fixed float
    formatting — so workflows can diff it (dencoder-stable)."""
    n = args.max_x - args.min_x
    w1 = _parse_weights(m1, args)
    w2 = _parse_weights(m2, args)
    res1, counts1, _, b1 = _map_range(m1, args, w1, timed=False)
    res2, counts2, _, b2 = _map_range(m2, args, w2, timed=False)
    args.backend = b1 if b1 == b2 else "mixed"
    row_changed = (res1 != res2).any(axis=1) | (counts1 != counts2)
    valid = (res1 != CRUSH_ITEM_NONE) & (
        np.arange(args.num_rep)[None, :] < counts1[:, None]
    )
    slots = int(valid.sum())
    moved = int((valid & (res1 != res2)).sum())
    changed = int(row_changed.sum())
    return {
        "n": n,
        "changed": changed,
        "changed_ratio": changed / n if n else 0.0,
        "slots": slots,
        "moved": moved,
        "moved_ratio": moved / slots if slots else 0.0,
        "equivalent": changed == 0,
    }


def format_compare(stats: dict, args) -> str:
    lines = [
        (
            f"rule {args.rule} x [{args.min_x},{args.max_x}) num_rep "
            f"{args.num_rep}: {stats['changed']}/{stats['n']} "
            f"mappings changed "
            f"(ratio {stats['changed_ratio']:.6f})"
        ),
        (
            f"moved slots: {stats['moved']}/{stats['slots']} "
            f"(ratio {stats['moved_ratio']:.6f})"
        ),
        (
            "maps appear equivalent"
            if stats["equivalent"]
            else "warning: maps are NOT equivalent"
        ),
    ]
    return "\n".join(lines)


def format_tree(m: CrushMap) -> str:
    """``crushtool --tree``-shaped hierarchy dump: one row per item,
    roots first, children indented under their parent in bucket item
    order.  Deterministic for a given map (stable root ordering,
    fixed-point weights printed at 5 decimals) so the output is
    diffable (dencoder-stable)."""
    lines = ["ID\tWEIGHT\tTYPE NAME"]

    def type_name(t: int) -> str:
        return m.type_names.get(t, f"type{t}")

    def item_name(item: int) -> str:
        if item >= 0:
            return f"osd.{item}"
        return m.item_names.get(item, f"bucket{item}")

    def walk(item: int, weight: int, depth: int) -> None:
        indent = "    " * depth
        if item >= 0:
            lines.append(
                f"{item}\t{weight / 0x10000:.5f}\t"
                f"{indent}{type_name(0)} {item_name(item)}"
            )
            return
        b = m.buckets[item]
        lines.append(
            f"{item}\t{b.weight / 0x10000:.5f}\t"
            f"{indent}{type_name(b.type)} {item_name(item)}"
        )
        for child, w in zip(b.items, b.item_weights):
            walk(child, w, depth + 1)

    for root in sorted(m._roots()):
        walk(root, m.buckets[root].weight, 0)
    return "\n".join(lines)


def main(argv=None) -> int:
    args = parse_args(argv)
    from ..crush import compiler

    if args.compile:
        with open(args.compile) as f:
            m = compiler.compile_crushmap(f.read())
        blob = compiler.encode_crushmap(m)
        out = args.output or (args.compile + ".compiled")
        with open(out, "wb") as f:
            f.write(blob)
    elif args.decompile:
        with open(args.decompile, "rb") as f:
            m = compiler.decode_crushmap(f.read())
        text = compiler.decompile_crushmap(m)
        if args.output:
            with open(args.output, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
        # -d composes with --tree/--compare/--test on the decoded map
        # (parse_args advertises "and/or"); plain -d is done here
        if not (args.tree or args.compare or args.test):
            return 0
    elif args.input:
        with open(args.input, "rb") as f:
            m = compiler.decode_crushmap(f.read())
    else:
        parts = [int(v) for v in args.build.split(":")]
        num_osds, per_host = parts[0], parts[1]
        hpr = parts[2] if len(parts) > 2 else 0
        m = build_hierarchy(num_osds, per_host, hpr)
    if args.tree:
        print(format_tree(m))
    rc = 0
    if args.compare:
        with open(args.compare, "rb") as f:
            m2 = compiler.decode_crushmap(f.read())
        stats = run_compare(m, m2, args)
        print(format_compare(stats, args))
        # non-equivalence is the exit status even when --test also
        # runs below (the flags compose "and/or", parse_args)
        rc = 0 if stats["equivalent"] else 1
    if not args.test:
        return rc
    stats = run_test(m, args)
    print(
        f"rule {args.rule} x [{args.min_x},{args.max_x}) num_rep "
        f"{args.num_rep}: {stats['n']} mappings in "
        f"{stats['elapsed']:.4f}s = {stats['mappings_per_sec']:.0f} "
        f"mappings/sec [{args.backend}]"
    )
    if args.show_bad_mappings or stats["bad"]:
        print(f"bad mappings (short of {args.num_rep}): {stats['bad']}")
    if args.show_utilization:
        for osd, cnt in enumerate(stats["per_osd"]):
            print(f"  device {osd}:\t{cnt}")
    if args.show_statistics:
        print(
            f"chi-squared = {stats['chi2']:.2f} "
            f"(expected per device {stats['expected']:.1f})"
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
