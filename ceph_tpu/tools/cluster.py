"""``ceph-tpu-cluster`` — the vstart.sh/cephadm-role launcher
(src/vstart.sh:1, reduced to its working core): stand up a whole
mon+mgr+N-OSD(+MDS+RGW) cluster OUTSIDE pytest, from one command,
with persistent stores under a cluster directory.

    ceph-tpu-cluster start --osds 3 --mds 1 --rgw 1 -d /tmp/c1
    ceph-tpu-cluster status -d /tmp/c1
    ceph-tpu-cluster stop -d /tmp/c1

``start`` runs the daemons in THIS process (they are thread-hosted,
like vstart's standalone daemons collapsed onto one host) and writes
``<dir>/cluster.json`` — mon address, pools, rgw port — which the
``ceph``/``rados`` CLIs and librados clients consume:

``start --processes`` instead boots the REAL process model: a mon
trio + mgr + OSDs (+MDS/RGW), each daemon its own OS process under
the crash-respawning :class:`~ceph_tpu.proc.Supervisor`, traffic on
real sockets — vstart the way the reference actually runs, and the
only mode whose throughput can exceed one core.  ``--mons`` sizes
the quorum; per-child logs land in ``<dir>/<role>.log``.

    python -m ceph_tpu.tools.ceph_cli -m $(ceph-tpu-cluster addr -d /tmp/c1) status

``--daemonize`` forks into the background with a pidfile so ``stop``
(SIGTERM) and ``status`` work from other shells — the vstart
lifecycle.  OSD data lives in <dir>/osd.N (BlockStore), so a stopped
cluster restarts with its objects (``--memstore`` opts out).
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import signal
import sys
import time


def _build_map(n_osd: int):
    from ..crush.builder import CrushMap
    from ..crush.types import CRUSH_BUCKET_STRAW2, Tunables
    from ..osd.osdmap import OSDMap

    cmap = CrushMap(tunables=Tunables())
    hosts = []
    for h in range(n_osd):
        hosts.append(
            cmap.add_bucket(
                CRUSH_BUCKET_STRAW2, 1, [h], [0x10000],
                name=f"host{h}",
            )
        )
    cmap.add_bucket(
        CRUSH_BUCKET_STRAW2, 3, hosts,
        [cmap.buckets[b].weight for b in hosts], name="default",
    )
    cmap.add_simple_rule("replicated_rule", "default", "host",
                         mode="firstn")
    return OSDMap.build(cmap, n_osd)


class Cluster:
    """One running cluster (every daemon thread-hosted here)."""

    def __init__(self, spec: dict):
        self.spec = spec
        self.dir = pathlib.Path(spec["dir"])
        self.mon = None
        self.mon_msgr = None
        self.osds = []
        self.mgr = None
        self.mds = []
        self.rgw = None
        self._clients = []

    # -- bring-up (the vstart order: mon, mgr, osds, mds, rgw) ---------
    def start(self) -> dict:
        from ..mgr import Manager
        from ..mon.monitor import Monitor
        from ..msg import Messenger
        from ..osd.daemon import OSD
        from ..rados import Rados

        self.dir.mkdir(parents=True, exist_ok=True)
        n = int(self.spec["osds"])
        mon_store = None
        if not self.spec.get("memstore"):
            # persistent mon store: a restarted cluster replays its
            # committed map chain (pools/epochs survive with the OSD
            # data, the vstart dev-cluster restart contract)
            from ..mon.monitor import MonitorStore
            from ..store import BlockStore

            mon_store = MonitorStore(
                BlockStore(self.dir / "mon", sync=False)
            )
        self.mon = Monitor(
            _build_map(n), store=mon_store,
            min_reporters=min(2, n),
        )
        self.mon_msgr = Messenger("mon")
        self.mon_msgr.add_dispatcher(self.mon)
        mon_addr = self.mon_msgr.bind(
            "127.0.0.1", int(self.spec.get("mon_port", 0))
        )

        self.mgr = Manager(name="x")
        self.mgr.start(mon_addr)

        for i in range(n):
            store = self._store_for(i)
            osd = OSD(
                i, store=store,
                wal_dir=(
                    str(self.dir / f"osd.{i}-wal")
                    if self.spec.get("wal") else None
                ),
                admin_socket_path=str(self.dir / f"osd.{i}.asok"),
                # big clusters ride the shared network stack's
                # strands/timers instead of 3 threads per daemon
                shared_services=bool(
                    self.spec.get("shared_services")
                ) or None,
            )
            osd.boot(*mon_addr)
            self.osds.append(osd)

        conf = {
            "mon_addr": list(mon_addr),
            "osds": n,
            "pools": [],
            "dir": str(self.dir),
            "pid": os.getpid(),
        }

        admin = Rados("cluster-admin").connect(*mon_addr)
        self._clients.append(admin)
        existing = set(admin.monc.osdmap.pool_names.values())

        def pool(name, **kw):
            if name not in existing:
                admin.pool_create(name, **kw)
            conf["pools"].append(name)

        if int(self.spec.get("mds", 0)) > 0:
            from ..mds import MDSDaemon

            size = min(3, max(1, n))
            pool("fsmeta", pg_num=4, size=size)
            pool("fsdata", pg_num=8, size=size)
            for j in range(int(self.spec["mds"])):
                r = Rados(f"mds-{j}").connect(*mon_addr)
                self._clients.append(r)
                self.mds.append(
                    MDSDaemon(f"mds{j}", r, "fsmeta")
                )
            conf["mds"] = int(self.spec["mds"])
        if int(self.spec.get("rgw", 0)) > 0:
            from ..rgw import RGW

            pool("rgwpool", pg_num=8, size=min(3, max(1, n)))
            r = Rados("rgw-0").connect(*mon_addr)
            self._clients.append(r)
            self.rgw = RGW(
                r.open_ioctx("rgwpool"),
                auth=bool(self.spec.get("rgw_auth", False)),
                name="rgw.0",
            )
            conf["rgw_port"] = self.rgw.serve(
                int(self.spec.get("rgw_port", 0))
            )
            # production posture: the dynamic-reshard worker drains
            # the threshold queue, and index/reshard counters flow
            # to the mgr like every other daemon's
            self.rgw.start_reshard()
            self.rgw.start_mgr_reports()
        # atomic publish: the daemonize parent polls for this file
        # and reads it immediately — a partial write would crash it
        tmp = self.dir / "cluster.json.tmp"
        tmp.write_text(json.dumps(conf))
        os.replace(tmp, self.dir / "cluster.json")
        return conf

    def _store_for(self, i: int):
        if self.spec.get("memstore"):
            return None  # the OSD defaults to MemStore
        from ..store import BlockStore

        return BlockStore(self.dir / f"osd.{i}", sync=False)

    def wait_healthy(self, timeout: float = 30.0) -> bool:
        from ..rados import Rados

        deadline = time.monotonic() + timeout
        admin = self._clients[0]
        while time.monotonic() < deadline:
            rc, outb, _ = admin.mon_command({"prefix": "status"})
            if rc == 0:
                st = json.loads(outb)
                if st["num_up_osds"] == st["num_osds"]:
                    return True
            time.sleep(0.3)
        return False

    def stop(self) -> None:
        if self.rgw is not None:
            self.rgw.shutdown()
        if self.mgr is not None:
            try:
                self.mgr.shutdown()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for d in self.mds:
            d.shutdown()
        for osd in self.osds:
            osd.shutdown()
        for c in self._clients:
            try:
                c.shutdown()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        if self.mon_msgr is not None:
            self.mon_msgr.shutdown()
        try:
            (self.dir / "cluster.json").unlink()
        except OSError:
            pass


def _load_conf(d: pathlib.Path) -> dict:
    f = d / "cluster.json"
    if not f.exists():
        raise SystemExit(f"no running cluster at {d} (no cluster.json)")
    return json.loads(f.read_text())


def _daemonize(args) -> int | None:
    """Fork into the background with readiness polling.  Returns the
    parent's exit code, or None in the detached child (which carries
    on to boot the cluster)."""
    pid = os.fork()
    if pid:
        # parent: wait for the child to report readiness
        for _ in range(200):
            if (pathlib.Path(args.dir) / "cluster.json").exists():
                conf = _load_conf(pathlib.Path(args.dir))
                print(json.dumps(conf))
                return 0
            time.sleep(0.3)
        print("cluster failed to start", file=sys.stderr)
        return 1
    os.setsid()
    # drop the inherited stdio: a caller capturing our pipes would
    # otherwise wait forever for EOF the daemon never sends; daemon
    # output goes to <dir>/cluster.log
    logdir = pathlib.Path(args.dir)
    logdir.mkdir(parents=True, exist_ok=True)
    log = open(logdir / "cluster.log", "ab", buffering=0)
    devnull = open(os.devnull, "rb")
    os.dup2(devnull.fileno(), 0)
    os.dup2(log.fileno(), 1)
    os.dup2(log.fileno(), 2)
    return None


def _start_processes(args) -> int:
    """``start --processes``: supervised one-daemon-per-OS-process
    fleet (the tentpole runtime) behind the same cluster.json
    contract the thread-hosted mode publishes."""
    from ..proc import ClusterSpec, Supervisor
    from ..rados import Rados

    cdir = pathlib.Path(args.dir)
    # a previous run that died uncleanly may have left daemon
    # process groups squatting the pinned ports
    Supervisor.reap_orphans(cdir)
    spec = ClusterSpec.plan(
        args.dir,
        mons=args.mons,
        osds=args.osds,
        mgrs=1,
        mds=args.mds,
        rgw=args.rgw,
        memstore=args.memstore,
        wal=args.wal,
        mon_port=args.mon_port,
        rgw_port=args.rgw_port,
    )
    sup = Supervisor(spec)
    sup.start()
    conf = {
        "mode": "processes",
        "mon_addr": list(spec.mon_addrs[0]),
        "mon_addrs": [list(a) for a in spec.mon_addrs],
        "osds": int(args.osds),
        "pools": [],
        "dir": str(cdir),
        "pid": os.getpid(),
    }
    if args.mds:
        conf["mds"] = int(args.mds)
        conf["pools"] += ["fsmeta", "fsdata"]
    if args.rgw:
        conf["rgw_port"] = int(spec.data["rgw_ports"][0])
        conf["pools"].append("rgwpool")

    admin = Rados("cluster-admin").connect_any(spec.mon_addrs)
    healthy = False
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        rc, outb, _ = admin.mon_command({"prefix": "status"})
        if rc == 0:
            st = json.loads(outb)
            if st["num_up_osds"] == st["num_osds"]:
                healthy = True
                break
        time.sleep(0.3)
    admin.shutdown()

    tmp = cdir / "cluster.json.tmp"
    tmp.write_text(json.dumps(conf))
    os.replace(tmp, cdir / "cluster.json")
    if not args.daemonize:
        print(json.dumps(conf))
        print(
            f"cluster {'healthy' if healthy else 'DEGRADED'} "
            f"({len(spec.roles())} processes); Ctrl-C to stop",
            file=sys.stderr,
        )
    stop = {"flag": False}

    def _sig(_s, _f):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    try:
        while not stop["flag"]:
            time.sleep(0.5)
    finally:
        sup.stop()
        try:
            (cdir / "cluster.json").unlink()
        except OSError:
            pass
    return 0


def _cmd_start(args) -> int:
    if args.daemonize:
        rc = _daemonize(args)
        if rc is not None:
            return rc
    if args.processes:
        return _start_processes(args)
    spec = {
        "dir": args.dir,
        "osds": args.osds,
        "mds": args.mds,
        "rgw": args.rgw,
        "memstore": args.memstore,
        "wal": args.wal,
        "mon_port": args.mon_port,
        "rgw_port": args.rgw_port,
        "shared_services": args.shared_services,
    }
    c = Cluster(spec)
    conf = c.start()
    healthy = c.wait_healthy()
    if not args.daemonize:
        print(json.dumps(conf))
        print(
            f"cluster {'healthy' if healthy else 'DEGRADED'}; "
            "Ctrl-C to stop",
            file=sys.stderr,
        )
    stop = {"flag": False}

    def _sig(_s, _f):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    try:
        while not stop["flag"]:
            time.sleep(0.5)
    finally:
        c.stop()
    return 0


def _cmd_status(args) -> int:
    from ..mon.monitor import MonClient
    from ..msg import Messenger

    conf = _load_conf(pathlib.Path(args.dir))
    msgr = Messenger("cluster-status")
    try:
        monc = MonClient(msgr, whoami=-1)
        if conf.get("mon_addrs"):
            # multi-mon (--processes): any quorum member answers
            monc.connect_any(
                [tuple(a) for a in conf["mon_addrs"]]
            )
        else:
            monc.connect(*conf["mon_addr"])
        reply = monc.command({"prefix": "status"})
        print(reply.outb if reply.rc == 0 else reply.outs)
        return 0 if reply.rc == 0 else 1
    finally:
        msgr.shutdown()


def _cmd_stop(args) -> int:
    from ..proc import Supervisor

    cdir = pathlib.Path(args.dir)
    conf = _load_conf(cdir)
    pid = conf.get("pid")
    if pid is None:
        return 1
    try:
        # the daemonized launcher is a setsid group leader: signal
        # the whole GROUP, so helpers it forked (and, in --processes
        # mode, the supervisor thread's machinery) die with it — a
        # single os.kill used to strand them
        os.killpg(pid, signal.SIGTERM)
    except ProcessLookupError:
        print("already gone", file=sys.stderr)
    except PermissionError:
        os.kill(pid, signal.SIGTERM)
    for _ in range(150):
        if not (cdir / "cluster.json").exists():
            return 0
        time.sleep(0.2)
    # launcher wedged: reap the recorded daemon process groups
    # directly, then put the launcher group down hard
    reaped = Supervisor.reap_orphans(cdir)
    try:
        os.killpg(pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    try:
        (cdir / "cluster.json").unlink()
    except OSError:
        pass
    print(
        f"cluster did not stop cleanly; force-killed "
        f"(reaped {len(reaped)} daemon groups)",
        file=sys.stderr,
    )
    return 1


def _cmd_addr(args) -> int:
    conf = _load_conf(pathlib.Path(args.dir))
    host, port = conf["mon_addr"]
    print(f"{host}:{port}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ceph-tpu-cluster")
    sub = p.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("start")
    sp.add_argument("--osds", type=int, default=3)
    sp.add_argument("--mds", type=int, default=0)
    sp.add_argument("--rgw", type=int, default=0)
    sp.add_argument("--memstore", action="store_true",
                    help="RAM stores (no persistence)")
    sp.add_argument(
        "--wal", action="store_true",
        help="front each OSD store with the write-ahead log "
        "(deferred small writes, group commit, crash replay)",
    )
    sp.add_argument(
        "--shared-services", action="store_true",
        help="OSD tick/report/op-queue on the shared network "
        "stack (zero per-daemon threads; for large --osds)",
    )
    sp.add_argument(
        "--processes", "-P", action="store_true",
        help="one OS process per daemon under the crash-respawning "
        "supervisor (real mon quorum, real sockets, scales past "
        "one core)",
    )
    sp.add_argument(
        "--mons", type=int, default=3,
        help="monitor quorum size (--processes mode only)",
    )
    sp.add_argument("--mon-port", type=int, default=0)
    sp.add_argument("--rgw-port", type=int, default=0)
    sp.add_argument("-d", "--dir", default="./ceph-tpu-cluster")
    sp.add_argument("--daemonize", "-D", action="store_true")
    sp.set_defaults(fn=_cmd_start)
    for name, fn in (
        ("status", _cmd_status), ("stop", _cmd_stop),
        ("addr", _cmd_addr),
    ):
        s = sub.add_parser(name)
        s.add_argument("-d", "--dir", default="./ceph-tpu-cluster")
        s.set_defaults(fn=fn)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
