"""``radosgw-admin`` analog — bucket-index / reshard administration
(src/rgw/rgw_admin.cc reduced to the sharded-index plane).

    python -m ceph_tpu.tools.rgw_admin -m HOST:PORT -p POOL \
        bucket stats --bucket B
    ... bucket reshard --bucket B --num-shards N
    ... reshard status --bucket B
    ... reshard list
    ... reshard process

Every command prints one JSON document (the reference tool's
formatter::flush shape).
"""

from __future__ import annotations

import argparse
import json
import sys

from ..rados import Rados
from ..rgw import RGW, RGWError


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="radosgw-admin", description=__doc__.splitlines()[0]
    )
    p.add_argument("-m", "--mon", required=True, metavar="HOST:PORT")
    p.add_argument("-p", "--pool", required=True)
    sub = p.add_subparsers(dest="cmd", required=True)

    b = sub.add_parser("bucket")
    bsub = b.add_subparsers(dest="sub", required=True)
    bs = bsub.add_parser("stats")
    bs.add_argument("--bucket", required=True)
    br = bsub.add_parser("reshard")
    br.add_argument("--bucket", required=True)
    br.add_argument("--num-shards", type=int, required=True)

    r = sub.add_parser("reshard")
    rsub = r.add_subparsers(dest="sub", required=True)
    rst = rsub.add_parser("status")
    rst.add_argument("--bucket", required=True)
    rsub.add_parser("list")
    rsub.add_parser("process")

    args = p.parse_args(argv)
    host, _, port = args.mon.rpartition(":")
    rados = Rados("rgw-admin").connect(host, int(port))
    try:
        gw = RGW(rados.open_ioctx(args.pool))
        if args.cmd == "bucket" and args.sub == "stats":
            st = gw.reshard_status(args.bucket)
            fills = gw.index.shard_counts(args.bucket)
            st["shard_fill"] = fills
            st["entries"] = sum(fills)
            out = st
        elif args.cmd == "bucket" and args.sub == "reshard":
            out = gw.bucket_reshard(args.bucket, args.num_shards)
        elif args.cmd == "reshard" and args.sub == "status":
            out = gw.reshard_status(args.bucket)
        elif args.cmd == "reshard" and args.sub == "list":
            out = gw.reshard_list()
        else:  # reshard process
            out = {"resharded": gw.reshard_process()}
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0
    except RGWError as e:
        print(f"radosgw-admin: {e}", file=sys.stderr)
        return 1
    finally:
        rados.shutdown()


if __name__ == "__main__":
    sys.exit(main())
