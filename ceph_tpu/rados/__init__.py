"""librados analog — the public client library
(src/librados/librados_cxx.cc, RadosClient.cc, IoCtxImpl.cc).

``Rados`` opens a cluster session (mon connect + map subscription,
the RadosClient role); ``IoCtx`` is the per-pool I/O handle with the
librados core surface: write_full/write/append/read/remove/stat,
xattrs, object listing, and aio_* variants returning
``concurrent.futures.Future`` (the librados completion model).

All data ops route through the Objecter (osdc/) to the PG primary
with retry-on-map-change; pool management routes through the monitor
command surface exactly like the reference's pool ops.
"""

from __future__ import annotations

import concurrent.futures
import json

from ..common.encoding import Decoder, Encoder
from ..mon.monitor import MonClient
from ..msg import Messenger
from ..msg.message import (
    OSD_FLAG_FULL_TRY,
    OSD_OP_APPEND,
    OSD_OP_CALL,
    OSD_OP_DELETE,
    OSD_OP_GETXATTR,
    OSD_OP_LIST,
    OSD_OP_NOTIFY,
    OSD_OP_OMAPCLEAR,
    OSD_OP_OMAPGET,
    OSD_OP_OMAPRM,
    OSD_OP_OMAPSET,
    OSD_OP_READ,
    OSD_OP_SETXATTR,
    OSD_OP_STAT,
    OSD_OP_UNWATCH,
    OSD_OP_WATCH,
    OSD_OP_WRITE,
    OSD_OP_WRITEFULL,
)
from ..osdc import Objecter, ObjecterError, ObjectNotFound, RadosError

__all__ = [
    "IoCtx",
    "ObjectNotFound",
    "Rados",
    "RadosError",
]


class Rados:
    """Cluster handle (rados_t / RadosClient)."""

    def __init__(self, name: str = "client"):
        self.messenger = Messenger(name)
        self.monc = MonClient(
            self.messenger, on_map=self._on_map, whoami=-1
        )
        self.objecter = Objecter(self.monc, self.messenger)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=4, thread_name_prefix=f"{name}.aio"
        )
        self._connected = False
        # watch callbacks by cookie (librados watch handles)
        self._watch_cbs: dict[int, object] = {}
        self._watch_seq = __import__("itertools").count(1)
        self.messenger.add_dispatcher(_WatchDispatcher(self))

    def _on_map(self, epoch: int) -> None:
        # linger re-registration does blocking RPC — never on the
        # messenger loop thread (the map push arrives there)
        if self.objecter._lingers:
            self._pool.submit(self.objecter.handle_map_change, epoch)

    def connect(self, mon_host: str, mon_port: int) -> "Rados":
        self.monc.connect(mon_host, mon_port)
        self._connected = True
        return self

    def connect_any(self, mon_addrs) -> "Rados":
        """Connect to the first reachable monitor of a quorum; the
        session fails over between monitors afterwards."""
        self.monc.connect_any(mon_addrs)
        self._connected = True
        return self

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)
        self.messenger.shutdown()

    # -- pool surface (rados_pool_*) ---------------------------------------
    def pool_lookup(self, name: str) -> int:
        for pool_id, pname in self.monc.osdmap.pool_names.items():
            if pname == name:
                return pool_id
        raise RadosError(f"pool {name!r} does not exist (-ENOENT)")

    def pool_list(self) -> list[str]:
        return sorted(self.monc.osdmap.pool_names.values())

    def pool_create(self, name: str, **kwargs) -> int:
        reply = self.monc.command(
            {"prefix": "osd pool create", "pool": name, **kwargs}
        )
        if reply.rc != 0:
            raise RadosError(reply.outs)
        out = json.loads(reply.outb)
        # generous: on a loaded box the subscription push carrying
        # the new pool can trail the command reply by many seconds
        self.monc.wait_for_epoch(out["epoch"], timeout=30.0)
        return out["pool_id"]

    def pool_delete(self, name: str) -> None:
        reply = self.monc.command(
            {"prefix": "osd pool delete", "pool": name}
        )
        if reply.rc != 0:
            raise RadosError(reply.outs)

    def mon_command(self, cmd: dict):
        """Raw mon command pass-through (rados_mon_command)."""
        reply = self.monc.command(cmd)
        return reply.rc, reply.outb, reply.outs

    @property
    def client_id(self) -> str:
        """This client's cluster identity — the entity-addr analog
        the OSDMap blocklist fences on (rados_get_addrs role)."""
        return self.objecter._client_id

    def blocklist_add(self, client_id: str, expire: float = 3600.0) -> None:
        """Fence another client (rados_blocklist_add): every OSD
        rejects its ops once the map propagates."""
        reply = self.monc.command({
            "prefix": "osd blocklist", "blocklistop": "add",
            "addr": client_id, "expire": expire,
        })
        if reply.rc != 0:
            raise RadosError(reply.outs)
        self.monc.wait_for_epoch(json.loads(reply.outb)["epoch"])

    # -- scrub plane (the `ceph pg *` / `rados list-inconsistent-*`
    # surface: mon names the primary, client dispatches to it) -------------
    def pg_command(self, pgid: str, op: str, timeout: float = 15.0):
        """Send a scrub-plane command (scrub | deep-scrub | repair |
        list-inconsistent-obj) to the pg's primary OSD, retrying
        across -EAGAIN (re-peering / moved primary) like any op."""
        import time as _time

        from ..msg.message import (
            MessageError,
            MMonCommandReply,
            MScrubCommand,
        )

        try:
            pool_id, ps = (int(x) for x in pgid.split("."))
        except ValueError:
            raise RadosError(f"bad pgid {pgid!r} (-EINVAL)") from None
        if pool_id < 0 or ps < 0:
            raise RadosError(f"bad pgid {pgid!r} (-EINVAL)")
        deadline = _time.monotonic() + timeout
        last = "no attempt"
        while _time.monotonic() < deadline:
            osdmap = self.monc.osdmap
            pool = osdmap.pools.get(pool_id)
            if pool is None:
                raise RadosError(f"pool {pool_id} dne (-ENOENT)")
            if ps >= pool.pg_num:
                # reject immediately, like the mon's pg validation —
                # retrying a pg that cannot exist would burn the
                # whole deadline on -EAGAIN noise
                raise RadosError(f"pg {pgid} dne (-ENOENT)")
            _u, _upp, _a, primary = osdmap.pg_to_up_acting_osds(
                pool_id, ps
            )
            addr = osdmap.osd_addrs.get(primary, "")
            if primary < 0 or not addr:
                last = f"pg {pgid} has no live primary"
                _time.sleep(0.2)
                continue
            host, _, port = addr.rpartition(":")
            try:
                conn = self.messenger.connect(host, int(port))
                reply = conn.call(
                    MScrubCommand(
                        tid=self.messenger.new_tid(),
                        op=op, pgid=pgid,
                    ),
                    timeout=max(1.0, deadline - _time.monotonic()),
                )
            except (MessageError, OSError) as e:
                last = str(e)
                _time.sleep(0.2)
                continue
            if isinstance(reply, MMonCommandReply):
                if reply.rc == -11:
                    last = reply.outs
                    _time.sleep(0.2)
                    continue
                return reply
            last = f"unexpected reply {type(reply).__name__}"
            _time.sleep(0.2)
        raise RadosError(f"pg {pgid} {op} failed: {last}")

    def pg_scrub(self, pgid: str, deep: bool = False) -> str:
        """`ceph pg (deep-)scrub` — returns the primary's ack text."""
        reply = self.pg_command(
            pgid, "deep-scrub" if deep else "scrub"
        )
        if reply.rc != 0:
            raise RadosError(reply.outs)
        return reply.outs

    def pg_repair(self, pgid: str) -> str:
        """`ceph pg repair` — authoritative-copy repair of recorded
        inconsistencies, pushed through the recovery path."""
        reply = self.pg_command(pgid, "repair")
        if reply.rc != 0:
            raise RadosError(reply.outs)
        return reply.outs

    def list_inconsistent_obj(self, pgid: str) -> list[dict]:
        """`rados list-inconsistent-obj <pgid>`: the pg's persisted
        ScrubStore records (structured findings, post-hoc)."""
        reply = self.pg_command(pgid, "list-inconsistent-obj")
        if reply.rc != 0:
            raise RadosError(reply.outs)
        return json.loads(reply.outb).get("inconsistents", [])

    def open_ioctx(self, pool_name: str) -> "IoCtx":
        return IoCtx(self, self.pool_lookup(pool_name))


class _WatchDispatcher:
    """Client-side MWatchNotify delivery: run the watch callback off
    the loop thread and ack (the librados watch callback contract)."""

    def __init__(self, rados: "Rados"):
        self.rados = rados

    def ms_dispatch(self, conn, msg) -> bool:
        from ..msg import MWatchNotify, MWatchNotifyAck

        if not isinstance(msg, MWatchNotify):
            return False
        cb = self.rados._watch_cbs.get(msg.cookie)

        def deliver():
            reply = b""
            if cb is not None:
                try:
                    reply = cb(msg.payload) or b""
                except Exception:  # noqa: BLE001 — user callback
                    reply = b""
            try:
                conn.send(
                    MWatchNotifyAck(
                        tid=self.rados.messenger.new_tid(),
                        notify_id=msg.notify_id,
                        cookie=msg.cookie,
                        reply=bytes(reply),
                    )
                )
            except Exception:  # noqa: BLE001
                pass

        self.rados._pool.submit(deliver)
        return True

    def ms_handle_reset(self, conn) -> None:
        pass


class IoCtx:
    """Per-pool I/O handle (rados_ioctx_t / IoCtxImpl)."""

    def __init__(self, rados: Rados, pool_id: int):
        self.rados = rados
        self.pool_id = pool_id
        # read snapshot context (rados_ioctx_snap_set_read): 0 = head
        self.read_snap = 0
        # writer SnapContext seq (rados_ioctx_selfmanaged_snap_
        # set_write_ctx): 0 = follow the pool's snaps
        self.write_snap_seq = 0
        # rados_set_pool_full_try: mutations from this handle carry
        # OSD_FLAG_FULL_TRY, so repair/delete traffic that FREES
        # space still lands on a full OSD instead of parking on
        # backoff
        self.full_try = False
        # dmclock QoS class every op from this handle carries (the
        # mclock client-class tag; empty = the default client class)
        self.qos_class = ""

    def set_pool_full_try(self, enabled: bool = True) -> None:
        self.full_try = bool(enabled)

    def set_qos_class(self, qos: str) -> None:
        """Tag every subsequent op from this handle with a scheduler
        QoS class; primaries with a registered profile for it apply
        that (reservation, weight, limit) triple."""
        self.qos_class = str(qos)

    def _submit(self, *args, **kwargs):
        kwargs.setdefault("qos", self.qos_class)
        return self.rados.objecter.op_submit(*args, **kwargs)

    def _mut_flags(self, full_try: bool = False) -> int:
        return (
            OSD_FLAG_FULL_TRY
            if (self.full_try or full_try)
            else 0
        )

    # -- sync data ops -----------------------------------------------------
    def write_full(self, oid: str, data: bytes) -> None:
        self._submit(
            self.pool_id, oid, OSD_OP_WRITEFULL, data=bytes(data),
            snap_seq=self.write_snap_seq, flags=self._mut_flags(),
        )

    def write(self, oid: str, data: bytes, offset: int = 0) -> None:
        self._submit(
            self.pool_id, oid, OSD_OP_WRITE, offset=offset,
            data=bytes(data), snap_seq=self.write_snap_seq,
            flags=self._mut_flags(),
        )

    def append(self, oid: str, data: bytes) -> None:
        """Atomic append: the offset resolves on the primary inside
        the PG op stream (a client-side stat+write would race
        concurrent appenders)."""
        self._submit(
            self.pool_id, oid, OSD_OP_APPEND, data=bytes(data),
            snap_seq=self.write_snap_seq, flags=self._mut_flags(),
        )

    def read(
        self,
        oid: str,
        length: int = -1,
        offset: int = 0,
        snapid: int | None = None,
    ) -> bytes:
        """``snapid`` overrides the ioctx read context for ONE call
        (rbd clone parent reads pin their parent snap this way)."""
        reply = self._submit(
            self.pool_id, oid, OSD_OP_READ, offset=offset,
            length=length,
            snapid=self.read_snap if snapid is None else snapid,
        )
        return reply.data

    def remove(self, oid: str, full_try: bool = False) -> None:
        """``full_try`` lets THIS delete land on a full OSD
        (OSD_FLAG_FULL_TRY) without flipping the whole handle —
        the space-reclaim path out of OSD_FULL."""
        self._submit(
            self.pool_id, oid, OSD_OP_DELETE,
            flags=self._mut_flags(full_try),
        )

    def stat(self, oid: str) -> int:
        reply = self._submit(
            self.pool_id, oid, OSD_OP_STAT, snapid=self.read_snap
        )
        return reply.size

    # -- pool snapshots (rados_ioctx_snap_*) -------------------------------
    def _pool(self):
        return self.rados.monc.osdmap.pools[self.pool_id]

    def snap_create(self, name: str) -> int:
        pool_name = self.rados.monc.osdmap.pool_names[self.pool_id]
        reply = self.rados.monc.command(
            {"prefix": "osd pool mksnap", "pool": pool_name,
             "snap": name}
        )
        if reply.rc != 0:
            raise RadosError(reply.outs)
        out = json.loads(reply.outb)
        self.rados.monc.wait_for_epoch(out["epoch"])
        return out["snapid"]

    def snap_remove(self, name: str) -> None:
        pool_name = self.rados.monc.osdmap.pool_names[self.pool_id]
        reply = self.rados.monc.command(
            {"prefix": "osd pool rmsnap", "pool": pool_name,
             "snap": name}
        )
        if reply.rc != 0:
            raise RadosError(reply.outs)
        self.rados.monc.wait_for_epoch(json.loads(reply.outb)["epoch"])

    def snap_list(self) -> dict[int, str]:
        return dict(self._pool().snaps)

    # -- self-managed snaps (rados_ioctx_selfmanaged_snap_*) ---------------
    def set_snap_context(self, seq: int) -> None:
        """Writer SnapContext for subsequent mutations: the primary's
        make_writeable clones against THIS seq instead of the pool's
        (per-op writer snapc, PrimaryLogPG.h:632)."""
        self.write_snap_seq = int(seq)

    def selfmanaged_snap_create(self) -> int:
        """Allocate a snap id the CLIENT manages (librbd's snapshot
        pattern): the pool tracks it as live for clone resolution and
        trimming, but only writers carrying it in their snapc clone."""
        pool_name = self.rados.monc.osdmap.pool_names[self.pool_id]
        reply = self.rados.monc.command(
            {
                "prefix": "osd pool selfmanaged-snap create",
                "pool": pool_name,
            }
        )
        if reply.rc != 0:
            raise RadosError(reply.outs)
        out = json.loads(reply.outb)
        self.rados.monc.wait_for_epoch(out["epoch"])
        return out["snapid"]

    def selfmanaged_snap_remove(self, snapid: int) -> None:
        pool_name = self.rados.monc.osdmap.pool_names[self.pool_id]
        reply = self.rados.monc.command(
            {
                "prefix": "osd pool selfmanaged-snap rm",
                "pool": pool_name,
                "snapid": int(snapid),
            }
        )
        if reply.rc != 0:
            raise RadosError(reply.outs)
        self.rados.monc.wait_for_epoch(
            json.loads(reply.outb)["epoch"]
        )

    def snap_lookup(self, name: str) -> int:
        for sid, sname in self._pool().snaps.items():
            if sname == name:
                return sid
        raise RadosError(f"snap {name!r} not found (-ENOENT)")

    def snap_set_read(self, snap: int | str) -> None:
        """Route subsequent reads through a snapshot (0/"" = head)."""
        if isinstance(snap, str):
            snap = self.snap_lookup(snap) if snap else 0
        self.read_snap = int(snap)

    # -- watch/notify (rados_watch3 / rados_notify2) -----------------------
    def watch(self, oid: str, callback) -> int:
        """Register ``callback(payload) -> reply_bytes|None`` and
        return the watch handle (cookie).  The watch lingers: it is
        re-registered on every map change."""
        # cookies must be cluster-unique (the reference keys
        # watch_info by (entity, cookie)): the FULL 48-bit client id
        # occupies the cookie's high bits — two clients can never
        # share a persisted w_<cookie> record, so one client's
        # unwatch cannot erase another's failover record (a truncated
        # id birthday-collides around ~2k clients).  The low 16 bits
        # are the per-client sequence (the cookie must fit the u64
        # MOSDOp.offset wire field); when the sequence wraps past a
        # still-live older watch we skip forward rather than silently
        # clobber its callback and persisted record.
        cid_hi = int(self.rados.objecter._client_id, 16) << 16
        while True:
            cookie = cid_hi | (next(self.rados._watch_seq) & 0xFFFF)
            if cookie not in self.rados._watch_cbs:
                break
        self.rados._watch_cbs[cookie] = callback
        self._submit(
            self.pool_id, oid, OSD_OP_WATCH, offset=cookie
        )
        self.rados.objecter.linger_register(
            cookie, self.pool_id, oid
        )
        return cookie

    def unwatch(self, oid: str, cookie: int) -> None:
        self.rados.objecter.linger_unregister(cookie)
        self.rados._watch_cbs.pop(cookie, None)
        self._submit(
            self.pool_id, oid, OSD_OP_UNWATCH, offset=cookie
        )

    def notify(self, oid: str, payload: bytes = b"") -> list[dict]:
        """Notify every watcher; returns their ack records."""
        reply = self._submit(
            self.pool_id, oid, OSD_OP_NOTIFY, data=bytes(payload)
        )
        return json.loads(reply.data) if reply.data else []

    # -- xattrs ------------------------------------------------------------
    def set_xattr(self, oid: str, name: str, value: bytes) -> None:
        self._submit(
            self.pool_id, oid, OSD_OP_SETXATTR, attr=name,
            data=bytes(value), flags=self._mut_flags(),
        )

    def get_xattr(self, oid: str, name: str) -> bytes:
        reply = self._submit(
            self.pool_id, oid, OSD_OP_GETXATTR, attr=name,
            snapid=self.read_snap,
        )
        return reply.data

    # -- omap (rados_omap_* / IoCtxImpl omap ops) --------------------------
    def omap_set(self, oid: str, kv: dict[str, bytes]) -> None:
        e = Encoder()
        e.map(
            kv,
            lambda e2, k: e2.string(k),
            lambda e2, v: e2.bytes(bytes(v)),
        )
        self._submit(
            self.pool_id, oid, OSD_OP_OMAPSET, data=e.getvalue(),
            flags=self._mut_flags(),
        )

    def omap_get_vals(
        self,
        oid: str,
        start_after: str = "",
        max_return: int = -1,
        snapid: int | None = None,
    ) -> dict[str, bytes]:
        reply = self._submit(
            self.pool_id, oid, OSD_OP_OMAPGET,
            attr=start_after, length=max_return,
            snapid=self.read_snap if snapid is None else snapid,
        )
        return Decoder(reply.data).map(
            lambda d: d.string(), lambda d: d.bytes()
        )

    def omap_rm_keys(self, oid: str, keys) -> None:
        e = Encoder()
        e.list(list(keys), lambda e2, k: e2.string(k))
        self._submit(
            self.pool_id, oid, OSD_OP_OMAPRM, data=e.getvalue(),
            flags=self._mut_flags(),
        )

    def omap_clear(self, oid: str) -> None:
        self._submit(
            self.pool_id, oid, OSD_OP_OMAPCLEAR,
            flags=self._mut_flags(),
        )

    def execute(
        self, oid: str, cls: str, method: str, indata: bytes = b""
    ) -> bytes:
        """Object-class call (rados_exec / IoCtx::exec → the in-OSD
        ClassHandler dispatch).  Carries the handle's FULL_TRY flag:
        the OSD classifies CLS_WR methods as writes, so a reclaim
        class call must not park on a full OSD."""
        reply = self._submit(
            self.pool_id, oid, OSD_OP_CALL,
            attr=f"{cls}.{method}", data=bytes(indata),
            flags=self._mut_flags(),
        )
        return reply.data

    # -- listing (rados_nobjects_list*, the pgls walk) ---------------------
    def list_objects(self) -> list[str]:
        pool = self.rados.monc.osdmap.pools[self.pool_id]
        names: set[str] = set()
        for ps in range(pool.pg_num):
            pgid = f"{self.pool_id}.{ps}"
            reply = self._submit(
                self.pool_id, "", OSD_OP_LIST, pgid=pgid
            )
            names.update(reply.names)
        return sorted(names)

    # -- async (librados completions) --------------------------------------
    def aio_write_full(self, oid: str, data: bytes):
        return self.rados._pool.submit(self.write_full, oid, data)

    def aio_read(self, oid: str, length: int = -1, offset: int = 0):
        return self.rados._pool.submit(self.read, oid, length, offset)

    def aio_remove(self, oid: str):
        return self.rados._pool.submit(self.remove, oid)
