"""librados analog — the public client library
(src/librados/librados_cxx.cc, RadosClient.cc, IoCtxImpl.cc).

``Rados`` opens a cluster session (mon connect + map subscription,
the RadosClient role); ``IoCtx`` is the per-pool I/O handle with the
librados core surface: write_full/write/append/read/remove/stat,
xattrs, object listing, and aio_* variants returning
``concurrent.futures.Future`` (the librados completion model).

All data ops route through the Objecter (osdc/) to the PG primary
with retry-on-map-change; pool management routes through the monitor
command surface exactly like the reference's pool ops.
"""

from __future__ import annotations

import concurrent.futures
import json

from ..common.encoding import Decoder, Encoder
from ..mon.monitor import MonClient
from ..msg import Messenger
from ..msg.message import (
    OSD_OP_APPEND,
    OSD_OP_CALL,
    OSD_OP_DELETE,
    OSD_OP_GETXATTR,
    OSD_OP_LIST,
    OSD_OP_OMAPCLEAR,
    OSD_OP_OMAPGET,
    OSD_OP_OMAPRM,
    OSD_OP_OMAPSET,
    OSD_OP_READ,
    OSD_OP_SETXATTR,
    OSD_OP_STAT,
    OSD_OP_WRITE,
    OSD_OP_WRITEFULL,
)
from ..osdc import Objecter, ObjecterError, ObjectNotFound, RadosError

__all__ = [
    "IoCtx",
    "ObjectNotFound",
    "Rados",
    "RadosError",
]


class Rados:
    """Cluster handle (rados_t / RadosClient)."""

    def __init__(self, name: str = "client"):
        self.messenger = Messenger(name)
        self.monc = MonClient(self.messenger, whoami=-1)
        self.objecter = Objecter(self.monc, self.messenger)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=4, thread_name_prefix=f"{name}.aio"
        )
        self._connected = False

    def connect(self, mon_host: str, mon_port: int) -> "Rados":
        self.monc.connect(mon_host, mon_port)
        self._connected = True
        return self

    def connect_any(self, mon_addrs) -> "Rados":
        """Connect to the first reachable monitor of a quorum; the
        session fails over between monitors afterwards."""
        self.monc.connect_any(mon_addrs)
        self._connected = True
        return self

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)
        self.messenger.shutdown()

    # -- pool surface (rados_pool_*) ---------------------------------------
    def pool_lookup(self, name: str) -> int:
        for pool_id, pname in self.monc.osdmap.pool_names.items():
            if pname == name:
                return pool_id
        raise RadosError(f"pool {name!r} does not exist (-ENOENT)")

    def pool_list(self) -> list[str]:
        return sorted(self.monc.osdmap.pool_names.values())

    def pool_create(self, name: str, **kwargs) -> int:
        reply = self.monc.command(
            {"prefix": "osd pool create", "pool": name, **kwargs}
        )
        if reply.rc != 0:
            raise RadosError(reply.outs)
        out = json.loads(reply.outb)
        self.monc.wait_for_epoch(out["epoch"])
        return out["pool_id"]

    def pool_delete(self, name: str) -> None:
        reply = self.monc.command(
            {"prefix": "osd pool delete", "pool": name}
        )
        if reply.rc != 0:
            raise RadosError(reply.outs)

    def mon_command(self, cmd: dict):
        """Raw mon command pass-through (rados_mon_command)."""
        reply = self.monc.command(cmd)
        return reply.rc, reply.outb, reply.outs

    def open_ioctx(self, pool_name: str) -> "IoCtx":
        return IoCtx(self, self.pool_lookup(pool_name))


class IoCtx:
    """Per-pool I/O handle (rados_ioctx_t / IoCtxImpl)."""

    def __init__(self, rados: Rados, pool_id: int):
        self.rados = rados
        self.pool_id = pool_id

    # -- sync data ops -----------------------------------------------------
    def write_full(self, oid: str, data: bytes) -> None:
        self.rados.objecter.op_submit(
            self.pool_id, oid, OSD_OP_WRITEFULL, data=bytes(data)
        )

    def write(self, oid: str, data: bytes, offset: int = 0) -> None:
        self.rados.objecter.op_submit(
            self.pool_id, oid, OSD_OP_WRITE, offset=offset,
            data=bytes(data),
        )

    def append(self, oid: str, data: bytes) -> None:
        """Atomic append: the offset resolves on the primary inside
        the PG op stream (a client-side stat+write would race
        concurrent appenders)."""
        self.rados.objecter.op_submit(
            self.pool_id, oid, OSD_OP_APPEND, data=bytes(data)
        )

    def read(self, oid: str, length: int = -1, offset: int = 0) -> bytes:
        reply = self.rados.objecter.op_submit(
            self.pool_id, oid, OSD_OP_READ, offset=offset, length=length
        )
        return reply.data

    def remove(self, oid: str) -> None:
        self.rados.objecter.op_submit(
            self.pool_id, oid, OSD_OP_DELETE
        )

    def stat(self, oid: str) -> int:
        reply = self.rados.objecter.op_submit(
            self.pool_id, oid, OSD_OP_STAT
        )
        return reply.size

    # -- xattrs ------------------------------------------------------------
    def set_xattr(self, oid: str, name: str, value: bytes) -> None:
        self.rados.objecter.op_submit(
            self.pool_id, oid, OSD_OP_SETXATTR, attr=name,
            data=bytes(value),
        )

    def get_xattr(self, oid: str, name: str) -> bytes:
        reply = self.rados.objecter.op_submit(
            self.pool_id, oid, OSD_OP_GETXATTR, attr=name
        )
        return reply.data

    # -- omap (rados_omap_* / IoCtxImpl omap ops) --------------------------
    def omap_set(self, oid: str, kv: dict[str, bytes]) -> None:
        e = Encoder()
        e.map(
            kv,
            lambda e2, k: e2.string(k),
            lambda e2, v: e2.bytes(bytes(v)),
        )
        self.rados.objecter.op_submit(
            self.pool_id, oid, OSD_OP_OMAPSET, data=e.getvalue()
        )

    def omap_get_vals(
        self, oid: str, start_after: str = "", max_return: int = -1
    ) -> dict[str, bytes]:
        reply = self.rados.objecter.op_submit(
            self.pool_id, oid, OSD_OP_OMAPGET,
            attr=start_after, length=max_return,
        )
        return Decoder(reply.data).map(
            lambda d: d.string(), lambda d: d.bytes()
        )

    def omap_rm_keys(self, oid: str, keys) -> None:
        e = Encoder()
        e.list(list(keys), lambda e2, k: e2.string(k))
        self.rados.objecter.op_submit(
            self.pool_id, oid, OSD_OP_OMAPRM, data=e.getvalue()
        )

    def omap_clear(self, oid: str) -> None:
        self.rados.objecter.op_submit(
            self.pool_id, oid, OSD_OP_OMAPCLEAR
        )

    def execute(
        self, oid: str, cls: str, method: str, indata: bytes = b""
    ) -> bytes:
        """Object-class call (rados_exec / IoCtx::exec → the in-OSD
        ClassHandler dispatch)."""
        reply = self.rados.objecter.op_submit(
            self.pool_id, oid, OSD_OP_CALL,
            attr=f"{cls}.{method}", data=bytes(indata),
        )
        return reply.data

    # -- listing (rados_nobjects_list*, the pgls walk) ---------------------
    def list_objects(self) -> list[str]:
        pool = self.rados.monc.osdmap.pools[self.pool_id]
        names: set[str] = set()
        for ps in range(pool.pg_num):
            pgid = f"{self.pool_id}.{ps}"
            reply = self.rados.objecter.op_submit(
                self.pool_id, "", OSD_OP_LIST, pgid=pgid
            )
            names.update(reply.names)
        return sorted(names)

    # -- async (librados completions) --------------------------------------
    def aio_write_full(self, oid: str, data: bytes):
        return self.rados._pool.submit(self.write_full, oid, data)

    def aio_read(self, oid: str, length: int = -1, offset: int = 0):
        return self.rados._pool.submit(self.read, oid, length, offset)

    def aio_remove(self, oid: str):
        return self.rados._pool.submit(self.remove, oid)
