"""CRUSH map data model (src/crush/crush.h re-rendered as dataclasses).

Buckets carry their precomputed per-algorithm tables (straws, tree node
weights, list prefix sums) exactly as the C structs do; ``builder``
computes them.  Negative ids are buckets (-1-id indexing in the C is
replaced by a dict keyed on the real id), non-negative ids are devices.
"""

from __future__ import annotations

from dataclasses import dataclass, field

CRUSH_BUCKET_UNIFORM = 1
CRUSH_BUCKET_LIST = 2
CRUSH_BUCKET_TREE = 3
CRUSH_BUCKET_STRAW = 4
CRUSH_BUCKET_STRAW2 = 5

CRUSH_RULE_NOOP = 0
CRUSH_RULE_TAKE = 1
CRUSH_RULE_CHOOSE_FIRSTN = 2
CRUSH_RULE_CHOOSE_INDEP = 3
CRUSH_RULE_EMIT = 4
CRUSH_RULE_CHOOSELEAF_FIRSTN = 6
CRUSH_RULE_CHOOSELEAF_INDEP = 7
CRUSH_RULE_SET_CHOOSE_TRIES = 8
CRUSH_RULE_SET_CHOOSELEAF_TRIES = 9
CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES = 10
CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11
CRUSH_RULE_SET_CHOOSELEAF_VARY_R = 12
CRUSH_RULE_SET_CHOOSELEAF_STABLE = 13

CRUSH_ITEM_UNDEF = 0x7FFFFFFE
CRUSH_ITEM_NONE = 0x7FFFFFFF

# pool/rule types (rados.h)
PG_POOL_TYPE_REPLICATED = 1
PG_POOL_TYPE_ERASURE = 3


@dataclass
class Tunables:
    """crush.h:354-421; profile presets mirror CrushWrapper.h:144-210.
    Defaults are the jewel/default profile."""

    choose_local_tries: int = 0
    choose_local_fallback_tries: int = 0
    choose_total_tries: int = 50
    chooseleaf_descend_once: int = 1
    chooseleaf_vary_r: int = 1
    chooseleaf_stable: int = 1
    straw_calc_version: int = 1

    @classmethod
    def argonaut(cls):
        return cls(2, 5, 19, 0, 0, 0, 0)

    @classmethod
    def bobtail(cls):
        return cls(0, 0, 50, 1, 0, 0, 1)

    @classmethod
    def firefly(cls):
        return cls(0, 0, 50, 1, 1, 0, 1)

    @classmethod
    def hammer(cls):
        return cls(0, 0, 50, 1, 1, 0, 1)

    @classmethod
    def jewel(cls):
        return cls(0, 0, 50, 1, 1, 1, 1)


@dataclass
class Bucket:
    """One interior node.  ``id`` < 0; weights are 16.16 fixed point."""

    id: int
    type: int
    alg: int
    items: list[int] = field(default_factory=list)
    item_weights: list[int] = field(default_factory=list)
    hash: int = 0  # CRUSH_HASH_RJENKINS1
    weight: int = 0
    # straw (alg 4): per-item straw lengths, 16.16
    straws: list[int] | None = None
    # list (alg 2): prefix weight sums
    sum_weights: list[int] | None = None
    # tree (alg 3): implicit binary tree node weights; items sit at odd
    # node indices (item i at node 2i+1)
    node_weights: list[int] | None = None

    @property
    def size(self) -> int:
        return len(self.items)


@dataclass
class RuleStep:
    op: int
    arg1: int = 0
    arg2: int = 0


@dataclass
class Rule:
    """crush_rule + its mask (ruleset/type/min_size/max_size)."""

    steps: list[RuleStep]
    ruleset: int = 0
    type: int = PG_POOL_TYPE_REPLICATED
    min_size: int = 1
    max_size: int = 10


@dataclass
class ChooseArg:
    """Per-bucket straw2 override (crush.h:248-293): position-indexed
    alternative weight sets (the mgr balancer's crush-compat mode) and
    optional id remapping."""

    weight_set: list[list[int]] | None = None  # [position][item] 16.16
    ids: list[int] | None = None
