"""crush_do_rule — the exact-semantics CPU oracle (src/crush/mapper.c).

Pure function of (map, ruleno, x, weights, choose_args): no workspace,
no globals.  The retry-descent control flow of crush_choose_firstn
(mapper.c:460-648) and the breadth-first crush_choose_indep
(mapper.c:655-843) are re-derived with explicit loop flags in place of
the C gotos; every reject path advances r' identically, which is the
whole game (SURVEY.md §7 "hard parts" #2).

The C passes pointer slices (o+osize) into the choosers, so all chooser
indexing — collision scans, replica numbering, out2 slots — is relative
to the invocation's own frame.  Here each invocation gets explicit
relative lists and do_rule stitches the frames back together.

``weight`` is the 16.16 per-device reweight vector (OSD in/out state),
NOT the crush weights inside buckets.
"""

from __future__ import annotations

from .buckets import bucket_perm_choose, crush_bucket_choose
from .hashing import crush_hash32_2
from .types import (
    CRUSH_BUCKET_UNIFORM,
    CRUSH_ITEM_NONE,
    CRUSH_ITEM_UNDEF,
    CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP,
    CRUSH_RULE_EMIT,
    CRUSH_RULE_SET_CHOOSELEAF_STABLE,
    CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
    CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
    CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE,
)


def is_out(weight: list[int], item: int, x: int) -> bool:
    """Probabilistic overload rejection against the 16.16 reweight
    vector (mapper.c:424-438)."""
    if item >= len(weight):
        return True
    w = weight[item]
    if w >= 0x10000:
        return False
    if w == 0:
        return True
    return (crush_hash32_2(x, item) & 0xFFFF) >= w


def _item_type(cmap, item: int) -> int | None:
    """Type of an item; None means invalid reference."""
    if item >= 0:
        return 0
    b = cmap.buckets.get(item)
    return None if b is None else b.type


def crush_choose_firstn(
    cmap,
    bucket,
    weight,
    x: int,
    numrep: int,
    type: int,
    out: list[int],
    outpos: int,
    out_size: int,
    tries: int,
    recurse_tries: int,
    local_retries: int,
    local_fallback_retries: int,
    recurse_to_leaf: bool,
    vary_r: int,
    stable: int,
    out2: list[int] | None,
    parent_r: int,
    choose_args,
) -> int:
    """Depth-first chooser: one replica at a time, full re-descent on
    reject with r' = rep + parent_r + ftotal.  ``out``/``out2`` are
    frame-relative; returns the new outpos."""
    count = out_size
    item = 0
    for rep in range(0 if stable else outpos, numrep):
        if count <= 0:
            break
        ftotal = 0
        skip_rep = False
        retry_descent = True
        while retry_descent:
            retry_descent = False
            in_b = bucket
            flocal = 0
            retry_bucket = True
            while retry_bucket:
                retry_bucket = False
                collide = False
                reject = False
                r = rep + parent_r + ftotal

                if in_b.size == 0:
                    reject = True
                else:
                    if (
                        local_fallback_retries > 0
                        and flocal >= (in_b.size >> 1)
                        and flocal > local_fallback_retries
                    ):
                        item = bucket_perm_choose(in_b, x, r)
                    else:
                        item = crush_bucket_choose(
                            in_b, x, r, choose_args.get(in_b.id), outpos
                        )
                    if item >= cmap.max_devices:
                        skip_rep = True
                        break

                    itemtype = _item_type(cmap, item)

                    if itemtype != type:
                        if item >= 0 or itemtype is None:
                            skip_rep = True
                            break
                        in_b = cmap.buckets[item]
                        retry_bucket = True
                        continue

                    collide = item in out[:outpos]

                    if not collide and recurse_to_leaf:
                        if item < 0:
                            sub_r = r >> (vary_r - 1) if vary_r else 0
                            if (
                                crush_choose_firstn(
                                    cmap,
                                    cmap.buckets[item],
                                    weight,
                                    x,
                                    1 if stable else outpos + 1,
                                    0,
                                    out2,
                                    outpos,
                                    count,
                                    recurse_tries,
                                    0,
                                    local_retries,
                                    local_fallback_retries,
                                    False,
                                    vary_r,
                                    stable,
                                    None,
                                    sub_r,
                                    choose_args,
                                )
                                <= outpos
                            ):
                                reject = True  # didn't get a leaf
                        else:
                            out2[outpos] = item  # already a leaf

                    if not reject and not collide and itemtype == 0:
                        reject = is_out(weight, item, x)

                if reject or collide:
                    ftotal += 1
                    flocal += 1
                    if collide and flocal <= local_retries:
                        retry_bucket = True
                    elif (
                        local_fallback_retries > 0
                        and flocal <= in_b.size + local_fallback_retries
                    ):
                        retry_bucket = True
                    elif ftotal < tries:
                        retry_descent = True
                    else:
                        skip_rep = True

        if skip_rep:
            continue
        out[outpos] = item
        outpos += 1
        count -= 1
    return outpos


def crush_choose_indep(
    cmap,
    bucket,
    weight,
    x: int,
    left: int,
    numrep: int,
    type: int,
    out: list[int],
    outpos: int,
    tries: int,
    recurse_tries: int,
    recurse_to_leaf: bool,
    out2: list[int] | None,
    parent_r: int,
    choose_args,
) -> None:
    """Breadth-first positionally-stable chooser for EC: all unplaced
    positions retried per round with r' = rep + parent_r + n*ftotal;
    unfillable slots become CRUSH_ITEM_NONE."""
    endpos = outpos + left
    for rep in range(outpos, endpos):
        out[rep] = CRUSH_ITEM_UNDEF
        if out2 is not None:
            out2[rep] = CRUSH_ITEM_UNDEF

    ftotal = 0
    while left > 0 and ftotal < tries:
        for rep in range(outpos, endpos):
            if out[rep] != CRUSH_ITEM_UNDEF:
                continue
            in_b = bucket
            while True:
                r = rep + parent_r
                if (
                    in_b.alg == CRUSH_BUCKET_UNIFORM
                    and in_b.size % numrep == 0
                ):
                    r += (numrep + 1) * ftotal
                else:
                    r += numrep * ftotal

                if in_b.size == 0:
                    break

                item = crush_bucket_choose(
                    in_b, x, r, choose_args.get(in_b.id), outpos
                )
                if item >= cmap.max_devices:
                    out[rep] = CRUSH_ITEM_NONE
                    if out2 is not None:
                        out2[rep] = CRUSH_ITEM_NONE
                    left -= 1
                    break

                itemtype = _item_type(cmap, item)

                if itemtype != type:
                    if item >= 0 or itemtype is None:
                        out[rep] = CRUSH_ITEM_NONE
                        if out2 is not None:
                            out2[rep] = CRUSH_ITEM_NONE
                        left -= 1
                        break
                    in_b = cmap.buckets[item]
                    continue

                if any(out[i] == item for i in range(outpos, endpos)):
                    break  # collision

                if recurse_to_leaf:
                    if item < 0:
                        crush_choose_indep(
                            cmap,
                            cmap.buckets[item],
                            weight,
                            x,
                            1,
                            numrep,
                            0,
                            out2,
                            rep,
                            recurse_tries,
                            0,
                            False,
                            None,
                            r,
                            choose_args,
                        )
                        if out2[rep] == CRUSH_ITEM_NONE:
                            break  # placed nothing; no leaf
                    elif out2 is not None:
                        out2[rep] = item  # already a leaf

                if itemtype == 0 and is_out(weight, item, x):
                    break

                out[rep] = item
                left -= 1
                break
        ftotal += 1

    for rep in range(outpos, endpos):
        if out[rep] == CRUSH_ITEM_UNDEF:
            out[rep] = CRUSH_ITEM_NONE
        if out2 is not None and out2[rep] == CRUSH_ITEM_UNDEF:
            out2[rep] = CRUSH_ITEM_NONE


def crush_do_rule(
    cmap,
    ruleno: int,
    x: int,
    result_max: int,
    weight: list[int],
    choose_args=None,
) -> list[int]:
    """Interpret a rule program over working vectors w/o/c
    (mapper.c:900-1105).  Returns the result vector (possibly shorter
    than result_max; EC holes are CRUSH_ITEM_NONE)."""
    if ruleno < 0 or ruleno >= len(cmap.rules) or cmap.rules[ruleno] is None:
        return []
    rule = cmap.rules[ruleno]
    args = choose_args if choose_args is not None else cmap.choose_args
    t = cmap.tunables

    # choose_total_tries counted "retries" historically; +1 (mapper.c:921-925)
    choose_tries = t.choose_total_tries + 1
    choose_leaf_tries = 0
    choose_local_retries = t.choose_local_tries
    choose_local_fallback_retries = t.choose_local_fallback_tries
    vary_r = t.chooseleaf_vary_r
    stable = t.chooseleaf_stable

    result: list[int] = []
    w: list[int] = []
    wsize = 0

    for step in rule.steps:
        op = step.op
        if op == CRUSH_RULE_TAKE:
            item = step.arg1
            if (0 <= item < cmap.max_devices) or item in cmap.buckets:
                w = [item]
                wsize = 1
        elif op == CRUSH_RULE_SET_CHOOSE_TRIES:
            if step.arg1 > 0:
                choose_tries = step.arg1
        elif op == CRUSH_RULE_SET_CHOOSELEAF_TRIES:
            if step.arg1 > 0:
                choose_leaf_tries = step.arg1
        elif op == CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES:
            if step.arg1 >= 0:
                choose_local_retries = step.arg1
        elif op == CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
            if step.arg1 >= 0:
                choose_local_fallback_retries = step.arg1
        elif op == CRUSH_RULE_SET_CHOOSELEAF_VARY_R:
            if step.arg1 >= 0:
                vary_r = step.arg1
        elif op == CRUSH_RULE_SET_CHOOSELEAF_STABLE:
            if step.arg1 >= 0:
                stable = step.arg1
        elif op in (
            CRUSH_RULE_CHOOSELEAF_FIRSTN,
            CRUSH_RULE_CHOOSE_FIRSTN,
            CRUSH_RULE_CHOOSELEAF_INDEP,
            CRUSH_RULE_CHOOSE_INDEP,
        ):
            firstn = op in (
                CRUSH_RULE_CHOOSELEAF_FIRSTN,
                CRUSH_RULE_CHOOSE_FIRSTN,
            )
            if wsize == 0:
                continue
            recurse_to_leaf = op in (
                CRUSH_RULE_CHOOSELEAF_FIRSTN,
                CRUSH_RULE_CHOOSELEAF_INDEP,
            )
            o: list[int] = []
            c: list[int] = []
            osize = 0
            for i in range(wsize):
                numrep = step.arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        continue
                bucket = cmap.buckets.get(w[i])
                if bucket is None:
                    continue  # w[i] is probably CRUSH_ITEM_NONE
                # frame-relative scratch for this invocation (o+osize in C)
                avail = result_max - osize
                fo = [0] * result_max
                fc = [0] * result_max
                if firstn:
                    if choose_leaf_tries:
                        recurse_tries = choose_leaf_tries
                    elif t.chooseleaf_descend_once:
                        recurse_tries = 1
                    else:
                        recurse_tries = choose_tries
                    got = crush_choose_firstn(
                        cmap,
                        bucket,
                        weight,
                        x,
                        numrep,
                        step.arg2,
                        fo,
                        0,
                        avail,
                        choose_tries,
                        recurse_tries,
                        choose_local_retries,
                        choose_local_fallback_retries,
                        recurse_to_leaf,
                        vary_r,
                        stable,
                        fc,
                        0,
                        args,
                    )
                else:
                    got = min(numrep, avail)
                    crush_choose_indep(
                        cmap,
                        bucket,
                        weight,
                        x,
                        got,
                        numrep,
                        step.arg2,
                        fo,
                        0,
                        choose_tries,
                        choose_leaf_tries if choose_leaf_tries else 1,
                        recurse_to_leaf,
                        fc,
                        0,
                        args,
                    )
                o.extend(fo[:got])
                c.extend(fc[:got])
                osize += got

            if recurse_to_leaf:
                o = c[:osize]  # copy final leaf values to output set
            w = o
            wsize = osize
        elif op == CRUSH_RULE_EMIT:
            for i in range(wsize):
                if len(result) >= result_max:
                    break
                result.append(w[i])
            wsize = 0
    return result
