"""Bucket choosers — pure functions of (bucket, x, r) (src/crush/mapper.c).

The C versions are stateful only through the perm workspace; since the
workspace is rebuilt whenever x changes and extended deterministically
within one x, ``bucket_perm_choose`` is a pure function of (bucket, x, r)
— re-derived here without the cache (mapper.c:73-131).

All arithmetic is uint32/uint64 exact; draws use python ints (unbounded)
where the C widens to __u64/__s64.
"""

from __future__ import annotations

from .hashing import crush_hash32_3, crush_hash32_4
from .ln import crush_ln
from .types import (
    CRUSH_BUCKET_LIST,
    CRUSH_BUCKET_STRAW,
    CRUSH_BUCKET_STRAW2,
    CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_UNIFORM,
    Bucket,
    ChooseArg,
)

S64_MIN = -(1 << 63)


def bucket_perm_choose(bucket: Bucket, x: int, r: int) -> int:
    """Fisher-Yates permutation seeded by hash(x, id, step); pick slot
    r % size (mapper.c:73-131, incl. the r=0 fast path which is the
    p=0 swap of the full construction)."""
    size = bucket.size
    pr = r % size
    if pr == 0:
        s = crush_hash32_3(x, bucket.id, 0) % size
        return bucket.items[s]
    perm = list(range(size))
    for p in range(pr + 1):
        if p < size - 1:
            i = crush_hash32_3(x, bucket.id, p) % (size - p)
            if i:
                perm[p + i], perm[p] = perm[p], perm[p + i]
    return bucket.items[perm[pr]]


def bucket_uniform_choose(bucket: Bucket, x: int, r: int) -> int:
    return bucket_perm_choose(bucket, x, r)


def bucket_list_choose(bucket: Bucket, x: int, r: int) -> int:
    """Walk tail→head; item i wins with probability weight_i/sum_i
    (mapper.c:141-164)."""
    for i in range(bucket.size - 1, -1, -1):
        w = crush_hash32_4(x, bucket.items[i], r, bucket.id)
        w &= 0xFFFF
        w = (w * bucket.sum_weights[i]) >> 16
        if w < bucket.item_weights[i]:
            return bucket.items[i]
    return bucket.items[0]


def _tree_height(n: int) -> int:
    h = 0
    while (n & 1) == 0:
        h += 1
        n >>= 1
    return h


def bucket_tree_choose(bucket: Bucket, x: int, r: int) -> int:
    """Weighted descent of the implicit binary tree (mapper.c:195-222)."""
    n = len(bucket.node_weights) >> 1
    while not (n & 1):
        w = bucket.node_weights[n]
        t = (crush_hash32_4(x, n, r, bucket.id) * w) >> 32
        h = _tree_height(n)
        left = n - (1 << (h - 1))
        if t < bucket.node_weights[left]:
            n = left
        else:
            n = n + (1 << (h - 1))
    return bucket.items[n >> 1]


def bucket_straw_choose(bucket: Bucket, x: int, r: int) -> int:
    """Legacy straw: draw = hash16 * precomputed straw length; argmax
    (mapper.c:227-245)."""
    high = 0
    high_draw = 0
    for i in range(bucket.size):
        draw = crush_hash32_3(x, bucket.items[i], r) & 0xFFFF
        draw *= bucket.straws[i]
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def _draw_exponential(x: int, y: int, z: int, weight: int) -> int:
    """ln(U16)/weight in fixed point — the negative of an Exp(weight)
    sample (mapper.c:334-359); division truncates toward zero like C
    div64_s64."""
    u = crush_hash32_3(x, y, z) & 0xFFFF
    ln = crush_ln(u) - 0x1000000000000
    if ln < 0:
        return -((-ln) // weight)
    return ln // weight


def bucket_straw2_choose(
    bucket: Bucket,
    x: int,
    r: int,
    arg: ChooseArg | None = None,
    position: int = 0,
) -> int:
    """Min-of-exponentials sampling: P(item i) = w_i/Σw, fully
    independent per item (mapper.c:361-384) — this independence is what
    makes the device kernel a pure vmap+argmax."""
    weights = bucket.item_weights
    ids = bucket.items
    # empty weight_set/ids behave like none at all (the C's
    # weight_set_positions == 0 / ids_size == 0 cases)
    if arg is not None and arg.weight_set:
        pos = min(position, len(arg.weight_set) - 1)
        weights = arg.weight_set[pos]
    if arg is not None and arg.ids:
        ids = arg.ids
    high = 0
    high_draw = 0
    for i in range(bucket.size):
        if weights[i]:
            draw = _draw_exponential(x, ids[i], r, weights[i])
        else:
            draw = S64_MIN
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def crush_bucket_choose(
    bucket: Bucket,
    x: int,
    r: int,
    arg: ChooseArg | None = None,
    position: int = 0,
) -> int:
    """Dispatch on bucket.alg (mapper.c:387-418)."""
    assert bucket.size > 0
    if bucket.alg == CRUSH_BUCKET_UNIFORM:
        return bucket_uniform_choose(bucket, x, r)
    if bucket.alg == CRUSH_BUCKET_LIST:
        return bucket_list_choose(bucket, x, r)
    if bucket.alg == CRUSH_BUCKET_TREE:
        return bucket_tree_choose(bucket, x, r)
    if bucket.alg == CRUSH_BUCKET_STRAW:
        return bucket_straw_choose(bucket, x, r)
    if bucket.alg == CRUSH_BUCKET_STRAW2:
        return bucket_straw2_choose(bucket, x, r, arg, position)
    return bucket.items[0]
