"""CrushMap blob serialization (framework-native format).

The reference embeds the crush map as an opaque encoded blob inside
OSDMap encode (CrushWrapper::encode, src/crush/CrushWrapper.cc) and
ships it to every daemon/client; this module plays that role for the
framework's own wire/disk paths: a versioned little-endian format
carrying everything ``builder.CrushMap`` holds — tunables, buckets
with their per-algorithm derived tables, rules, name maps, and
choose_args.

This is NOT the reference's binary crushmap format; the
reference-compatible compiler/decompiler (crushtool -c/-d ingest of
real maps) lives in ``compiler.py``.
"""

from __future__ import annotations

from ..common.encoding import (
    Decoder,
    DecodeError,
    Encoder,
    decode_versioned,
    encode_versioned,
)
from .builder import CrushMap
from .types import Bucket, ChooseArg, Rule, RuleStep, Tunables

_VERSION = 1
_COMPAT = 1


def _enc_opt_list(e: Encoder, v: list[int] | None) -> None:
    if v is None:
        e.bool(False)
    else:
        e.bool(True)
        e.list(v, lambda e2, x: e2.s64(x))


def _dec_opt_list(d: Decoder) -> list[int] | None:
    if not d.bool():
        return None
    return d.list(lambda d2: d2.s64())


def encode_crush_map(m: CrushMap) -> bytes:
    e = Encoder()
    t = m.tunables
    for v in (
        t.choose_local_tries,
        t.choose_local_fallback_tries,
        t.choose_total_tries,
        t.chooseleaf_descend_once,
        t.chooseleaf_vary_r,
        t.chooseleaf_stable,
        t.straw_calc_version,
    ):
        e.u32(v)
    e.s32(m.max_devices)

    def enc_bucket(e2: Encoder, b: Bucket) -> None:
        e2.s32(b.id).u16(b.type).u8(b.alg).u8(b.hash).u64(b.weight)
        e2.list(b.items, lambda e3, x: e3.s32(x))
        e2.list(b.item_weights, lambda e3, x: e3.u64(x))
        _enc_opt_list(e2, b.straws)
        _enc_opt_list(e2, b.sum_weights)
        _enc_opt_list(e2, b.node_weights)

    e.list(sorted(m.buckets.values(), key=lambda b: b.id), enc_bucket)

    def enc_rule(e2: Encoder, r: Rule | None) -> None:
        if r is None:
            e2.bool(False)
            return
        e2.bool(True)
        e2.u32(r.ruleset).u32(r.type).u32(r.min_size).u32(r.max_size)
        e2.list(
            r.steps,
            lambda e3, s: e3.u32(s.op).s32(s.arg1).s32(s.arg2),
        )

    e.list(m.rules, enc_rule)
    e.map(m.type_names, lambda e2, k: e2.s32(k), lambda e2, v: e2.string(v))
    e.map(m.item_names, lambda e2, k: e2.s32(k), lambda e2, v: e2.string(v))
    e.map(m.rule_names, lambda e2, k: e2.s32(k), lambda e2, v: e2.string(v))

    def enc_choose_arg(e2: Encoder, ca: ChooseArg) -> None:
        if ca.weight_set is None:
            e2.bool(False)
        else:
            e2.bool(True)
            e2.list(
                ca.weight_set,
                lambda e3, ws: e3.list(ws, lambda e4, w: e4.u64(w)),
            )
        _enc_opt_list(e2, ca.ids)

    e.map(m.choose_args, lambda e2, k: e2.s64(k), enc_choose_arg)
    return encode_versioned(_VERSION, _COMPAT, e.getvalue())


def decode_crush_map(data: bytes) -> CrushMap:
    _version, d = decode_versioned(Decoder(data), _COMPAT)
    vals = [d.u32() for _ in range(7)]
    m = CrushMap(tunables=Tunables(*vals))
    m.max_devices = d.s32()

    def dec_bucket(d2: Decoder) -> Bucket:
        return Bucket(
            id=d2.s32(),
            type=d2.u16(),
            alg=d2.u8(),
            hash=d2.u8(),
            weight=d2.u64(),
            items=d2.list(lambda d3: d3.s32()),
            item_weights=d2.list(lambda d3: d3.u64()),
            straws=_dec_opt_list(d2),
            sum_weights=_dec_opt_list(d2),
            node_weights=_dec_opt_list(d2),
        )

    for b in d.list(dec_bucket):
        if b.id >= 0:
            raise DecodeError(f"bucket id {b.id} not negative")
        m.buckets[b.id] = b

    def dec_rule(d2: Decoder) -> Rule | None:
        if not d2.bool():
            return None
        ruleset = d2.u32()
        rtype = d2.u32()
        mn = d2.u32()
        mx = d2.u32()
        steps = d2.list(
            lambda d3: RuleStep(d3.u32(), d3.s32(), d3.s32())
        )
        return Rule(
            steps=steps, ruleset=ruleset, type=rtype,
            min_size=mn, max_size=mx,
        )

    m.rules = d.list(dec_rule)
    m.type_names = d.map(lambda d2: d2.s32(), lambda d2: d2.string())
    m.item_names = d.map(lambda d2: d2.s32(), lambda d2: d2.string())
    m.rule_names = d.map(lambda d2: d2.s32(), lambda d2: d2.string())

    def dec_choose_arg(d2: Decoder) -> ChooseArg:
        weight_set = None
        if d2.bool():
            weight_set = d2.list(
                lambda d3: d3.list(lambda d4: d4.u64())
            )
        return ChooseArg(weight_set=weight_set, ids=_dec_opt_list(d2))

    m.choose_args = d.map(lambda d2: d2.s64(), dec_choose_arg)
    m.touch()
    return m
