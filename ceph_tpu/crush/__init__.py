"""CRUSH placement for the TPU-native framework.

The reference's CRUSH core (src/crush/mapper.c, hash.c, builder.c) is a
pure integer function of (map, rule, x, weights) — re-derived here in
three tiers:

- ``mapper`` / ``buckets`` — the exact-semantics CPU oracle (pure
  Python): byte-for-byte the same placements as ``crush_do_rule``
  (verified against the compiled reference C over all bucket
  algorithms; see tests/test_crush.py).
- ``builder`` — map construction (builder.c / CrushWrapper equivalent).
- ``jaxmap`` — the batched device kernel: the whole map compiled to
  dense arrays, the rule program scalar-traced with lax control flow
  and vmapped over PGs (the ParallelPGMapper replacement; SURVEY.md
  §2.3).  Imported lazily: it enables jax x64 mode at import.
"""

from .builder import CrushMap
from .hashing import crush_hash32, crush_hash32_2, crush_hash32_3
from .ln import crush_ln
from .mapper import CRUSH_ITEM_NONE, crush_do_rule
from .types import (
    CRUSH_BUCKET_LIST,
    CRUSH_BUCKET_STRAW,
    CRUSH_BUCKET_STRAW2,
    CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_UNIFORM,
    Bucket,
    Rule,
    RuleStep,
    Tunables,
)

__all__ = [
    "CRUSH_BUCKET_LIST",
    "CRUSH_BUCKET_STRAW",
    "CRUSH_BUCKET_STRAW2",
    "CRUSH_BUCKET_TREE",
    "CRUSH_BUCKET_UNIFORM",
    "CRUSH_ITEM_NONE",
    "Bucket",
    "CrushMap",
    "Rule",
    "RuleStep",
    "Tunables",
    "crush_do_rule",
    "crush_hash32",
    "crush_hash32_2",
    "crush_hash32_3",
    "crush_ln",
]
