"""Batched CRUSH on device — the ParallelPGMapper replacement.

The reference recomputes every PG's placement by sharding pgid ranges
over a thread pool (src/osd/OSDMapMapping.h:18-156); here the whole map
compiles to dense arrays and ``crush_do_rule`` becomes a scalar-traced
function vmapped over the PG batch: one device call maps a million PGs.

Scope: all five bucket algorithms (straw2/uniform/straw/list/tree),
tunables with
choose_local_tries == choose_local_fallback_tries == 0 (true of every
profile since bobtail), rule programs of [SET_*...] TAKE CHOOSE[LEAF]
EMIT groups.  Anything else raises UnsupportedMap and callers fall back
to the exact Python oracle (ceph_tpu.crush.mapper) — the same
plugin-style split the EC backends use.

Exactness: every table lookup is a float32 one-hot matmul over
24-bit-split tables (exact in the f32 mantissa), and all fixed-point
arithmetic runs on float64 integers within the 2^53-exact range —
see CompiledMap and _crush_ln_f64.  Same r'-advancement and retry
semantics as mapper.c; verified against the oracle in
tests/test_crush_jax.py (and _crush_ln_f64 value-exact over the full
u16 domain).
"""

from __future__ import annotations

import collections
import math
from dataclasses import dataclass

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402

from .hashing import _mix_inner  # noqa: E402
from .ln import _tables as _ln_tables  # noqa: E402
from .types import (  # noqa: E402
    CRUSH_BUCKET_LIST,
    CRUSH_BUCKET_STRAW,
    CRUSH_BUCKET_STRAW2,
    CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_UNIFORM,
    CRUSH_ITEM_NONE,
    CRUSH_ITEM_UNDEF,
    CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP,
    CRUSH_RULE_EMIT,
    CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
    CRUSH_RULE_SET_CHOOSELEAF_STABLE,
    CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
    CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE,
)

MAX_DEPTH = 16  # CRUSH_MAX_DEPTH is 10; headroom is free in a fori

class UnsupportedMap(ValueError):
    """Map/rule shape outside the device kernel's scope; use the oracle."""


# -- device-side primitives ------------------------------------------------


def _hash3(a, b, c):
    """rjenkins1 arity 3 on uint32 jnp values (hash.c:48-59)."""
    h = jnp.uint32(1315423911) ^ a ^ b ^ c
    x0, y0 = jnp.uint32(231232), jnp.uint32(1232)
    a, b, h = _mix_inner(a, b, h)
    c, x, h = _mix_inner(c, x0, h)
    y, a, h = _mix_inner(y0, a, h)
    b, x, h = _mix_inner(b, x, h)
    y, c, h = _mix_inner(y, c, h)
    return h.astype(jnp.uint32)


def _hash2(a, b):
    """rjenkins1 arity 2 (hash.c:37-46)."""
    h = jnp.uint32(1315423911) ^ a ^ b
    x0, y0 = jnp.uint32(231232), jnp.uint32(1232)
    a, b, h = _mix_inner(a, b, h)
    x, a, h = _mix_inner(x0, a, h)
    b, y, h = _mix_inner(b, y0, h)
    return h.astype(jnp.uint32)


def _hash4(a, b, c, d):
    """rjenkins1 arity 4 (hash.c:61-74) — the list chooser's hash."""
    h = jnp.uint32(1315423911) ^ a ^ b ^ c ^ d
    x0, y0 = jnp.uint32(231232), jnp.uint32(1232)
    a, b, h = _mix_inner(a, b, h)
    c, d, h = _mix_inner(c, d, h)
    a, x, h = _mix_inner(a, x0, h)
    y, b, h = _mix_inner(y0, b, h)
    c, x, h = _mix_inner(c, x, h)
    y, d, h = _mix_inner(y, d, h)
    return h.astype(jnp.uint32)


def _crush_ln_f64(u, ln_tbl1, ln_tbl2):
    """2^44*log2(u+1) exactly, in float64 (mapper.c:248-290).

    The tables arrive BYTE-SPLIT in bfloat16 (3 bf16 columns per
    24-bit half, built by compile_map): a one-hot lookup of byte
    values <= 255 is exact in bf16 with f32 accumulation, and the
    native-bf16 MXU pass is several times cheaper than the f32
    HIGHEST-precision emulation — this lookup pair is the hot loop of
    every straw2 draw.  Downstream arithmetic stays on f64 integers
    < 2^53.  index2 reproduces ((x*RH) >> 48) & 0xff via the 24-bit
    split (the C's int64 wraparound only ever touches bits that the
    mod-256 discards).  Value-exact against
    ceph_tpu.crush.ln.crush_ln over the full u16 domain
    (tests/test_crush_jax.py)."""
    x = u.astype(jnp.int32) + 1
    masked = x & 0x1FFFF
    nbits = jnp.zeros_like(x)
    for shift in (16, 8, 4, 2, 1):
        step = (masked >> shift) != 0
        nbits = nbits + jnp.where(step, shift, 0)
        masked = jnp.where(step, masked >> shift, masked)
    bitlen = nbits + (masked != 0)
    shift_amt = jnp.where((x & 0x18000) == 0, 16 - bitlen, 0)
    x = x << shift_amt
    iexp = 15 - shift_amt
    k = ((x >> 8) << 1) - 256 >> 1
    oh1 = (jnp.arange(129) == k[:, None]).astype(jnp.bfloat16)
    b1 = jnp.matmul(
        oh1, ln_tbl1, preferred_element_type=jnp.float32
    )

    def recon(b, off, nbytes=3):
        """Exact byte lanes -> the value half, in f64 (f32 arithmetic
        is exact: every partial sum < 2^25)."""
        v = b[:, off]
        for i in range(1, nbytes):
            v = v * 256.0 + b[:, off + i]
        return v.astype(jnp.float64)

    rh_hi, rh_lo = recon(b1, 0, 4), recon(b1, 4)
    lh_v = recon(b1, 7) * float(1 << 24) + recon(b1, 10)
    xf = x.astype(jnp.float64)
    T = xf * rh_hi + jnp.floor(xf * rh_lo / float(1 << 24))
    index2 = jnp.mod(
        jnp.floor(T / float(1 << 24)), 256.0
    ).astype(jnp.int32)
    oh2 = (jnp.arange(256) == index2[:, None]).astype(jnp.bfloat16)
    b2 = jnp.matmul(
        oh2, ln_tbl2, preferred_element_type=jnp.float32
    )
    ll_v = recon(b2, 0) * float(1 << 24) + recon(b2, 3)
    return iexp.astype(jnp.float64) * float(1 << 44) + jnp.floor(
        (lh_v + ll_v) / 16.0
    )


# -- map compilation -------------------------------------------------------


@dataclass(frozen=True)
class CompiledMap:
    """Dense-array rendering of a CrushMap for the device kernel.

    All hot-path tables are float32, consumed through one-hot matmuls:
    dynamic gathers are pathologically slow on TPU (measured ~20 ns per
    gathered element vs ~1 ns through the MXU), and every value fits a
    float32 mantissa exactly after the 24-bit splits below, so lookups
    stay bit-exact.  Downstream arithmetic runs in float64 whose
    integer range (2^53) covers the 2^48 fixed-point ln values.
    """

    # (nb, 7*sz+3) f32:
    # items|w_hi|w_lo|straw_hi|straw_lo|sum_hi|sum_lo|size|alg|id
    # (straw columns: legacy straw lengths; sum columns: the list
    # chooser's tail sums — zero outside their algs)
    row_pack: jnp.ndarray
    # choose_args rendering (crush.h:248-293): per-position straw2
    # weight replacements + hash-id remaps, position-clamped at compile
    # time.  None when the map carries no choose_args (zero overhead).
    args_pack: jnp.ndarray | None  # (nb, P*2*sz + sz) f32: aw_hi|aw_lo|aids
    arg_positions: int  # P (max weight_set positions; 0 without args)
    types_f: jnp.ndarray  # (nb,) f32 bucket types
    bidx_f: jnp.ndarray  # (max_neg,) f32: (-1-id) -> row, -1 for gaps
    ln_tbl1: jnp.ndarray  # (129, 4) f32: rh_hi, rh_lo, lh_hi, lh_lo
    ln_tbl2: jnp.ndarray  # (256, 2) f32: ll_hi, ll_lo
    sz: int
    nb: int
    has_uniform: bool
    has_straw: bool
    has_list: bool
    has_tree: bool
    # tree buckets: (nb, 2*tree_nodes + 1) f32 nw_hi|nw_lo|start_n
    tree_pack: jnp.ndarray | None
    tree_nodes: int
    uniform_sz: int  # max uniform-bucket size (perm loop bound)
    bidx: tuple  # host-side (-1-id) -> row for TAKE resolution
    max_devices: int
    tunables: tuple  # (total_tries, descend_once, vary_r, stable)
    rules: tuple  # immutable rule description for cache keys
    # host-side structure (per row): items/sizes/types for the fast
    # path's descent-depth analysis, and the source CrushMap for the
    # exact-oracle fallback on speculation overflow
    np_items: np.ndarray
    np_sizes: np.ndarray
    np_types: np.ndarray
    np_algs: np.ndarray
    source: object
    source_mutation: int
    # structural cache key: everything the TRACED program depends on
    # except the numeric weight tables (row_pack/args_pack/tree_pack
    # are jit operands), so weights-only epoch changes reuse the
    # compiled kernel instead of paying a recompile per epoch
    skey: tuple

    def __hash__(self):
        return hash(self.skey)

    def __eq__(self, other):
        return (
            isinstance(other, CompiledMap) and self.skey == other.skey
        )


def compile_map(cmap) -> CompiledMap:
    """CrushMap -> dense arrays; raises UnsupportedMap outside scope."""
    t = cmap.tunables
    if t.choose_local_tries or t.choose_local_fallback_tries:
        raise UnsupportedMap(
            "choose_local_(fallback_)tries != 0 needs the legacy perm "
            "fallback; use the oracle"
        )
    if not cmap.buckets:
        raise UnsupportedMap("empty map")
    for b in cmap.buckets.values():
        if b.alg not in (
            CRUSH_BUCKET_STRAW2,
            CRUSH_BUCKET_UNIFORM,
            CRUSH_BUCKET_STRAW,
            CRUSH_BUCKET_LIST,
            CRUSH_BUCKET_TREE,
        ):
            raise UnsupportedMap(
                f"bucket {b.id} alg {b.alg}: unknown bucket alg"
            )
    nb = len(cmap.buckets)
    sz = max(max(b.size for b in cmap.buckets.values()), 1)
    items = np.zeros((nb, sz), dtype=np.int64)
    weights = np.zeros((nb, sz), dtype=np.int64)
    straws = np.zeros((nb, sz), dtype=np.int64)
    sums = np.zeros((nb, sz), dtype=np.int64)
    sizes = np.zeros(nb, dtype=np.int64)
    types = np.zeros(nb, dtype=np.int64)
    algs = np.zeros(nb, dtype=np.int64)
    ids = np.zeros(nb, dtype=np.int64)
    max_neg = max(-b.id for b in cmap.buckets.values())
    bidx = np.full(max_neg, -1, dtype=np.int64)
    for row, b in enumerate(
        sorted(cmap.buckets.values(), key=lambda b: -b.id)
    ):
        items[row, : b.size] = b.items
        weights[row, : b.size] = b.item_weights
        sizes[row] = b.size
        types[row] = b.type
        algs[row] = b.alg
        ids[row] = b.id
        bidx[-1 - b.id] = row
        if b.size and max(abs(i) for i in b.items) >= 1 << 24:
            raise UnsupportedMap("item id magnitude >= 2^24")
        if abs(b.id) >= 1 << 24:
            raise UnsupportedMap("bucket id magnitude >= 2^24")
        if b.weight >= 1 << 32:
            raise UnsupportedMap("bucket weight >= 2^32")
        if b.alg == CRUSH_BUCKET_STRAW:
            if not b.straws or len(b.straws) < b.size:
                raise UnsupportedMap(
                    f"straw bucket {b.id} missing straw table"
                )
            if any(s >= 1 << 32 for s in b.straws[: b.size]):
                raise UnsupportedMap("straw length >= 2^32")
            straws[row, : b.size] = b.straws[: b.size]
        if b.alg == CRUSH_BUCKET_LIST:
            if not b.sum_weights or len(b.sum_weights) < b.size:
                raise UnsupportedMap(
                    f"list bucket {b.id} missing sum_weights"
                )
            if any(s >= 1 << 32 for s in b.sum_weights[: b.size]):
                raise UnsupportedMap("list sum weight >= 2^32")
            sums[row, : b.size] = b.sum_weights[: b.size]
        if b.alg == CRUSH_BUCKET_TREE and not b.node_weights:
            raise UnsupportedMap(
                f"tree bucket {b.id} missing node_weights"
            )

    # tree buckets: per-bucket node-weight tables + start node
    has_tree = bool((algs == CRUSH_BUCKET_TREE).any())
    tree_pack = None
    tree_nodes = 0
    if has_tree:
        tree_nodes = max(
            len(b.node_weights)
            for b in cmap.buckets.values()
            if b.alg == CRUSH_BUCKET_TREE
        )
        nw = np.zeros((nb, tree_nodes), dtype=np.int64)
        start = np.zeros(nb, dtype=np.int64)
        for row, b in enumerate(
            sorted(cmap.buckets.values(), key=lambda b: -b.id)
        ):
            if b.alg != CRUSH_BUCKET_TREE:
                continue
            if any(w >= 1 << 32 for w in b.node_weights):
                raise UnsupportedMap("tree node weight >= 2^32")
            nw[row, : len(b.node_weights)] = b.node_weights
            start[row] = len(b.node_weights) >> 1
        tree_pack = np.concatenate(
            [
                (nw >> 16).astype(np.float32),
                (nw & 0xFFFF).astype(np.float32),
                start[:, None].astype(np.float32),
            ],
            axis=1,
        )

    # choose_args → dense per-position weight/id tables.  The C only
    # consults args in the straw2 chooser (crush_bucket_choose,
    # mapper.c:387-418), so args on other bucket algs are ignored, and
    # the position clamp (get_choose_arg_weights, mapper.c:311-317) is
    # baked in by replicating each bucket's last weight-set row.
    P = 0
    args_pack = None
    if cmap.choose_args:
        P = max(
            (
                len(a.weight_set)
                for a in cmap.choose_args.values()
                if a.weight_set
            ),
            default=1,
        )
        aw = np.repeat(weights[:, None, :], P, axis=1)  # (nb, P, sz)
        aids = items.copy()
        for bid, arg in cmap.choose_args.items():
            b = cmap.buckets.get(bid)
            if b is None or b.alg != CRUSH_BUCKET_STRAW2:
                continue  # the C consults args only for straw2
            row = int(np.nonzero(ids == bid)[0][0])
            # empty weight_set falls back to bucket weights (the C's
            # weight_set_positions == 0 case)
            if arg.weight_set:
                for p in range(P):
                    ws = arg.weight_set[min(p, len(arg.weight_set) - 1)]
                    if len(ws) != b.size:
                        raise UnsupportedMap(
                            f"choose_arg weight_set size {len(ws)} != "
                            f"bucket {b.id} size {b.size}"
                        )
                    if any(w >= 1 << 32 for w in ws):
                        raise UnsupportedMap("choose_arg weight >= 2^32")
                    aw[row, p, : b.size] = ws
            if arg.ids is not None:
                if len(arg.ids) != b.size:
                    raise UnsupportedMap(
                        f"choose_arg ids size {len(arg.ids)} != "
                        f"bucket {b.id} size {b.size}"
                    )
                if any(abs(i) >= 1 << 24 for i in arg.ids):
                    raise UnsupportedMap(
                        "choose_arg id magnitude >= 2^24"
                    )
                aids[row, : b.size] = arg.ids
        args_pack = np.concatenate(
            [
                (aw >> 16).reshape(nb, P * sz).astype(np.float32),
                (aw & 0xFFFF).reshape(nb, P * sz).astype(np.float32),
                aids.astype(np.float32),
            ],
            axis=1,
        )

    rules = []
    for rule in cmap.rules:
        rules.append(None if rule is None else _compile_rule(rule))

    row_pack = np.concatenate(
        [
            items.astype(np.float32),
            (weights >> 16).astype(np.float32),
            (weights & 0xFFFF).astype(np.float32),
            (straws >> 16).astype(np.float32),
            (straws & 0xFFFF).astype(np.float32),
            (sums >> 16).astype(np.float32),
            (sums & 0xFFFF).astype(np.float32),
            sizes[:, None].astype(np.float32),
            algs[:, None].astype(np.float32),
            ids[:, None].astype(np.float32),
        ],
        axis=1,
    )
    rh, lh, ll = _ln_tables()

    def _bytesplit(col, nbytes):
        """Value column -> nbytes byte columns (each bf16-exact).
        rh_hi needs FOUR bytes: RH[0] = ceil(2^55/128) = 2^48 makes
        its high half a 25-bit value."""
        return [
            (col >> (8 * i)) & 0xFF for i in range(nbytes - 1, -1, -1)
        ]

    tbl1_cols = (
        _bytesplit(rh >> 24, 4)
        + _bytesplit(rh & 0xFFFFFF, 3)
        + _bytesplit(lh >> 24, 3)
        + _bytesplit(lh & 0xFFFFFF, 3)
    )
    tbl2_cols = _bytesplit(ll >> 24, 3) + _bytesplit(ll & 0xFFFFFF, 3)
    ln_tbl1 = np.stack(tbl1_cols, axis=1).astype(np.float32)
    ln_tbl2 = np.stack(tbl2_cols, axis=1).astype(np.float32)
    skey = (
        sz,
        nb,
        cmap.max_devices,
        P,
        tree_nodes,
        items.tobytes(),
        sizes.tobytes(),
        types.tobytes(),
        algs.tobytes(),
        ids.tobytes(),
        bidx.tobytes(),
        (
            t.choose_total_tries + 1,
            t.chooseleaf_descend_once,
            t.chooseleaf_vary_r,
            t.chooseleaf_stable,
        ),
        tuple(rules),
    )
    return CompiledMap(
        row_pack=jnp.asarray(row_pack),
        args_pack=None if args_pack is None else jnp.asarray(args_pack),
        arg_positions=P,
        types_f=jnp.asarray(types.astype(np.float32)),
        bidx_f=jnp.asarray(bidx.astype(np.float32)),
        ln_tbl1=jnp.asarray(ln_tbl1, dtype=jnp.bfloat16),
        ln_tbl2=jnp.asarray(ln_tbl2, dtype=jnp.bfloat16),
        sz=sz,
        nb=nb,
        has_uniform=bool((algs == CRUSH_BUCKET_UNIFORM).any()),
        has_straw=bool((algs == CRUSH_BUCKET_STRAW).any()),
        has_list=bool((algs == CRUSH_BUCKET_LIST).any()),
        has_tree=has_tree,
        tree_pack=(
            None if tree_pack is None else jnp.asarray(tree_pack)
        ),
        tree_nodes=tree_nodes,
        uniform_sz=int(
            sizes[algs == CRUSH_BUCKET_UNIFORM].max()
        )
        if (algs == CRUSH_BUCKET_UNIFORM).any()
        else 0,
        bidx=tuple(int(v) for v in bidx),
        max_devices=cmap.max_devices,
        tunables=(
            t.choose_total_tries + 1,
            t.chooseleaf_descend_once,
            t.chooseleaf_vary_r,
            t.chooseleaf_stable,
        ),
        rules=tuple(rules),
        np_items=items,
        np_sizes=sizes,
        np_types=types,
        np_algs=algs,
        source=cmap,
        source_mutation=getattr(cmap, "mutation", 0),
        skey=skey,
    )


def _compile_rule(rule):
    """Rule -> tuple of (op, arg1, arg2) groups: [set-overrides..., take,
    choose, emit] repeated; raises UnsupportedMap on other shapes."""
    groups = []
    overrides = {}
    take = None
    choose = None
    for step in rule.steps:
        if step.op in (
            CRUSH_RULE_SET_CHOOSE_TRIES,
            CRUSH_RULE_SET_CHOOSELEAF_TRIES,
            CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
            CRUSH_RULE_SET_CHOOSELEAF_STABLE,
            CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
            CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
        ):
            if step.op in (
                CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
                CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
            ):
                if step.arg1 > 0:
                    raise UnsupportedMap("local tries override")
                continue
            # the C applies tries overrides only when > 0 and
            # vary_r/stable only when >= 0 (mapper.c:963-991)
            if step.op in (
                CRUSH_RULE_SET_CHOOSE_TRIES,
                CRUSH_RULE_SET_CHOOSELEAF_TRIES,
            ):
                if step.arg1 > 0:
                    overrides[step.op] = step.arg1
            elif step.arg1 >= 0:
                overrides[step.op] = step.arg1
        elif step.op == CRUSH_RULE_TAKE:
            take = step.arg1
        elif step.op in (
            CRUSH_RULE_CHOOSE_FIRSTN,
            CRUSH_RULE_CHOOSELEAF_FIRSTN,
            CRUSH_RULE_CHOOSE_INDEP,
            CRUSH_RULE_CHOOSELEAF_INDEP,
        ):
            if take is None or choose is not None:
                raise UnsupportedMap("rule shape: choose without take")
            choose = (step.op, step.arg1, step.arg2)
        elif step.op == CRUSH_RULE_EMIT:
            if take is None or choose is None:
                raise UnsupportedMap("rule shape: emit without choose")
            groups.append(
                (take, choose, tuple(sorted(overrides.items())))
            )
            take = choose = None
        else:
            raise UnsupportedMap(f"rule op {step.op}")
    if take is not None or choose is not None:
        raise UnsupportedMap("rule does not end with EMIT")
    return tuple(groups)


# -- the kernel ------------------------------------------------------------

# Speculation bounds for the fast firstn path.  _SPEC_TRIES extra
# retries per replica are precomputed; a lane that needs more falls
# back to the exact host oracle (flagged via the kernel's ok output).
# P(fallback) per replica is roughly p_collision^_SPEC_TRIES, so for
# any realistically-sized map the fallback never fires; tiny test maps
# hit it occasionally and stay exact through the oracle.
_SPEC_TRIES = 8
_LEAF_SPEC = 4  # max speculated chooseleaf retries (descend_once => 1)
_SPEC_BUDGET = 512  # max speculative draws per lane per rule group

_K_FOUND, _K_BAD, _K_RETRY, _K_OVER = 0, 1, 2, 3


def _descent_steps(cm: CompiledMap, start_rows, ttype: int):
    """Per-level reachable bucket sets for a descent from
    ``start_rows`` toward ``ttype``, from the static bucket graph.

    Returns (steps, found_rows) where steps[i] describes the buckets a
    descent can be drawing from at its i-th draw — the fast path
    specializes each draw round to that set (row one-hot over the set
    instead of the whole map, item vectors sized to the set's max
    bucket) — and found_rows is the set of target-type buckets the
    descent can land on (the chooseleaf domains).  Returns (None,
    None) when a cycle (or > MAX_DEPTH chain) makes the static level
    structure unbounded.  A draw that lands on a bucket of the target
    type (ttype != 0) terminates; for ttype == 0 only devices
    terminate."""
    sizes, types, items = cm.np_sizes, cm.np_types, cm.np_items
    bidx = cm.bidx
    cur = set(start_rows)
    steps = []
    found: set = set()
    while cur:
        if len(steps) >= MAX_DEPTH:
            return None, None
        rows = tuple(sorted(cur))
        steps.append(
            {
                "rows": rows,
                "sz": max(
                    (int(sizes[r]) for r in rows), default=1
                )
                or 1,
                "algs": tuple(
                    sorted({int(cm.np_algs[r]) for r in rows})
                ),
                "usz": max(
                    (
                        int(sizes[r])
                        for r in rows
                        if int(cm.np_algs[r]) == CRUSH_BUCKET_UNIFORM
                    ),
                    default=0,
                )
                or 1,
            }
        )
        nxt: set = set()
        for row in cur:
            for it in items[row, : sizes[row]]:
                it = int(it)
                if it >= 0:
                    continue  # device: terminal
                neg = -1 - it
                if neg >= len(bidx) or bidx[neg] < 0:
                    continue  # invalid item: terminal
                r2 = bidx[neg]
                if ttype != 0 and types[r2] == ttype:
                    found.add(r2)
                    continue
                nxt.add(r2)
        cur = nxt
    return steps, found


def _plan_groups(
    cm: CompiledMap, ruleno: int, result_max: int, spec_boost: int = 0
):
    """Host-side pre-pass over a rule's groups: resolve TAKE rows,
    tries/tunables, and decide per group whether the speculative fast
    path applies (firstn, acyclic bounded-depth descent, single
    choose_args position)."""
    groups = cm.rules[ruleno]
    if groups is None:
        raise UnsupportedMap(f"no rule {ruleno}")
    total_tries, descend_once, vary_r_t, stable_t = cm.tunables
    plans = []
    for take, (op, arg1, arg2), overrides in groups:
        ov = dict(overrides)
        tries = ov.get(CRUSH_RULE_SET_CHOOSE_TRIES, total_tries)
        leaf_override = ov.get(CRUSH_RULE_SET_CHOOSELEAF_TRIES, 0)
        vary_r = ov.get(CRUSH_RULE_SET_CHOOSELEAF_VARY_R, vary_r_t)
        stable = ov.get(CRUSH_RULE_SET_CHOOSELEAF_STABLE, stable_t)
        numrep = arg1 if arg1 > 0 else result_max + arg1
        if numrep <= 0:
            continue
        nslots = min(numrep, result_max)
        if take >= 0:
            raise UnsupportedMap("TAKE of a device (not a bucket)")
        if -1 - take >= len(cm.bidx):
            raise UnsupportedMap(f"TAKE of unknown bucket {take}")
        take_row = cm.bidx[-1 - take]
        if take_row < 0:
            raise UnsupportedMap(f"TAKE of unknown bucket {take}")
        firstn = op in (
            CRUSH_RULE_CHOOSE_FIRSTN,
            CRUSH_RULE_CHOOSELEAF_FIRSTN,
        )
        leaf = op in (
            CRUSH_RULE_CHOOSELEAF_FIRSTN,
            CRUSH_RULE_CHOOSELEAF_INDEP,
        )
        if firstn:
            if leaf_override:
                leaf_tries = leaf_override
            elif descend_once:
                leaf_tries = 1
            else:
                leaf_tries = tries
        else:
            leaf_tries = leaf_override if leaf_override else 1
        plan = {
            "take_row": take_row,
            "ttype": arg2,
            "numrep": numrep,
            "nslots": nslots,
            "tries": tries,
            "leaf_tries": leaf_tries,
            "vary_r": vary_r,
            "stable": stable,
            "firstn": firstn,
            "leaf": leaf,
            "fast": None,
        }
        plans.append(plan)
        # -- fast-path qualification ----------------------------------
        if not firstn or cm.arg_positions > 1:
            continue  # multi-position choose_args keeps the generic path
        if leaf and arg2 == 0:
            continue  # chooseleaf targeting devices: degenerate shape
        outer_steps, domains = _descent_steps(cm, [take_row], arg2)
        if outer_steps is None or len(outer_steps) > MAX_DEPTH - 1:
            continue
        # Adaptive speculation width: the retry probability per
        # replica is roughly numrep / (number of distinct targets), so
        # wide maps (many hosts) need only a couple of speculated
        # retries while narrow test maps need the full window.  Sized
        # so the expected oracle-fallback count stays ~10 lanes per
        # million mapped PGs.
        if arg2 == 0:
            ntargets = max(cm.max_devices, 1)
        else:
            ntargets = max(len(domains), 1)
        p_retry = min(numrep / ntargets, 0.9)
        if spec_boost:
            # caller passed a non-trivial reweight vector: is_out()
            # rejects add retry pressure the topology-derived estimate
            # cannot see, so take the full speculation window
            spec = _SPEC_TRIES
        else:
            spec = max(
                2,
                min(
                    _SPEC_TRIES,
                    math.ceil(
                        math.log(1e-5 / max(numrep, 1))
                        / math.log(max(p_retry, 1e-9))
                    )
                    - 1,
                ),
            )
        r0 = min(numrep + spec, numrep + tries - 1)
        fast = {
            "R0": r0,
            "outer_steps": outer_steps,
        }
        draws = r0 * len(outer_steps)
        if leaf:
            leaf_steps, _ = _descent_steps(cm, sorted(domains), 0)
            if leaf_steps is None or len(leaf_steps) > MAX_DEPTH - 1:
                continue
            l0 = min(leaf_tries, _LEAF_SPEC)
            pd = 1 if stable else nslots
            fast.update(
                {"leaf_steps": leaf_steps, "L0": l0, "Pd": pd}
            )
            draws += r0 * pd * l0 * len(leaf_steps)
        if draws > _SPEC_BUDGET:
            continue
        plan["fast"] = fast
    return plans


def _make_rule_fn(
    cm: CompiledMap, ruleno: int, result_max: int, spec_boost: int = 0
):
    """Build the scalar-traced do_rule for one (map, rule, result_max).

    Returns ``rule_fn(x, weightv, row_pack, args_pack, tree_pack) ->
    (result, count, ok)``.  The numeric tables are jit OPERANDS so
    weights-only epoch changes reuse the compiled kernel (keyed on
    CompiledMap.skey); ``ok`` is False for lanes whose firstn retry
    chain outran the speculation window (callers re-map those through
    the exact host oracle — see batch_do_rule).

    Two execution strategies per rule group:

    * FAST (firstn groups on acyclic maps): because crush_choose_firstn
      uses r' = rep + ftotal at EVERY level of one descent, the whole
      descent outcome is a function of r' alone — so all candidate
      descents for r' = 0..R0-1 are precomputed in D_outer batched
      draw rounds (and the chooseleaf descents likewise, indexed by the
      outer r' that chose the domain), then a while_loop replays the C
      state machine consulting the tables: its body is a handful of
      one-hot selects over R0 entries instead of bucket draws, so the
      serial chain is ~D_outer + D_leaf draw rounds, not
      numrep*(depth+retries) draws.
    * GENERIC (everything else): one flat while_loop whose every
      iteration performs exactly one bucket draw; descent levels,
      retry-descents and chooseleaf recursion are a mode register, not
      nested loops.  Under vmap all lanes advance together, so
      wall-clock per batch is the maximum lane's total draw count.
    """
    plans = _plan_groups(cm, ruleno, result_max, spec_boost)
    total_tries, descend_once, vary_r_t, stable_t = cm.tunables
    NONE = jnp.int32(CRUSH_ITEM_NONE)
    UNDEF = jnp.int32(CRUSH_ITEM_UNDEF)
    OUTER, LEAF = jnp.int32(0), jnp.int32(1)

    HIP = jax.lax.Precision.HIGHEST
    SZ, NB = cm.sz, cm.nb
    NEGB = cm.bidx_f.shape[0]
    P = cm.arg_positions
    TN = max(cm.tree_nodes, 1)

    def rule_fn(x, weightv, row_pack, args_pack, tree_pack):
        # -- primitives closing over the operand tables ----------------

        def _lookup(i, n, table):
            """One-hot matmul lookup: table row i (f32-exact), the
            TPU-native replacement for a dynamic gather."""
            oh = (jnp.arange(n) == i).astype(jnp.float32)
            return jnp.matmul(oh, table, precision=HIP)

        def load_bucket(bidx_row):
            """One row_pack lookup ->
            (ids, wf, strawf, sumf, size, alg, bid)."""
            row = _lookup(bidx_row, NB, row_pack)
            ids = jnp.round(row[:SZ]).astype(jnp.int32)

            def f64pair(base):
                return row[base : base + SZ].astype(
                    jnp.float64
                ) * 65536.0 + row[base + SZ : base + 2 * SZ].astype(
                    jnp.float64
                )

            wf = f64pair(SZ)
            strawf = f64pair(3 * SZ)
            sumf = f64pair(5 * SZ)
            size = jnp.round(row[7 * SZ]).astype(jnp.int32)
            alg = jnp.round(row[7 * SZ + 1]).astype(jnp.int32)
            bid = jnp.round(row[7 * SZ + 2]).astype(jnp.int32)
            return ids, wf, strawf, sumf, size, alg, bid

        def straw2_draw(hash_ids, ids, wf, size, x, r, szv):
            """One straw2 draw-argmax (mapper.c:361-384) over item
            vectors of length ``szv`` (the full map width for the
            generic path, the level's max bucket size for the fast
            path's specialized draw rounds).

            ``hash_ids`` feed the hash (choose_args may remap them,
            bucket_straw2_choose mapper.c:363-384); the returned item
            is always from the bucket's real ``ids``.

            draw_i = -floor(L_i/w_i) computed in float64: L < 2^48 and
            w < 2^32 are f64-exact, the quotient estimate is off by at
            most one ulp, and a multiply-compare fixup restores the
            exact floor (q*w <= L < (q+1)*w with q*w < 2^53 exact)."""
            u = (
                _hash3(
                    jnp.uint32(x),
                    hash_ids.astype(jnp.uint32),
                    jnp.uint32(r),
                )
                & jnp.uint32(0xFFFF)
            )
            L = float(1 << 48) - _crush_ln_f64(
                u, cm.ln_tbl1, cm.ln_tbl2
            )
            q0 = jnp.floor(L / jnp.where(wf > 0, wf, 1.0))
            t = q0 * wf
            q = (
                q0
                + (t + wf <= L).astype(jnp.float64)
                - (t > L).astype(jnp.float64)
            )
            draw = jnp.where(
                (wf > 0) & (jnp.arange(szv) < size), -q, -jnp.inf
            )
            am = jnp.argmax(draw)
            return jnp.sum(
                jnp.where(jnp.arange(szv) == am, ids, 0)
            ).astype(jnp.int32)

        def perm_draw(ids, size, bid, x, r, szv, uszv):
            """Uniform bucket chooser: slot r%size of the Fisher-Yates
            permutation seeded by hash(x, id, step)
            (bucket_perm_choose, mapper.c:73-131 — the r=0 fast path is
            the p=0 step of the same construction, so one loop covers
            both)."""
            size1 = jnp.maximum(size, 1)
            pr = jnp.int32(r) % size1
            # uniform buckets never exceed uszv (the uniform max of
            # the map, or of the level for specialized draws), so the
            # FY loop and slot vector are bounded by it, not the
            # map-wide max bucket size (a wide straw2 root would
            # otherwise make every draw quadratic in szv)
            usz = max(uszv, 1)
            slots = jnp.arange(usz, dtype=jnp.int32)

            def body(p, perm):
                p = jnp.int32(p)
                active = (p <= pr) & (p < size - 1)
                h = _hash3(
                    jnp.uint32(x), jnp.uint32(bid), jnp.uint32(p)
                )
                # C reduces the unsigned hash; an int32 view would flip
                # high hashes negative and change the residue
                i = (
                    h.astype(jnp.int64)
                    % jnp.maximum(size1 - p, 1).astype(jnp.int64)
                ).astype(jnp.int32)
                idx2 = p + i
                vp = jnp.sum(jnp.where(slots == p, perm, 0))
                v2 = jnp.sum(jnp.where(slots == idx2, perm, 0))
                swapped = jnp.where(
                    slots == p, v2, jnp.where(slots == idx2, vp, perm)
                )
                return jnp.where(active, swapped, perm).astype(
                    jnp.int32
                )

            perm = lax.fori_loop(0, usz, body, slots)
            s = jnp.sum(jnp.where(slots == pr, perm, 0))
            return jnp.sum(
                jnp.where(jnp.arange(szv) == s, ids, 0)
            ).astype(jnp.int32)

        def load_args(bidx_row, pos):
            """choose_args row for a bucket: position-selected straw2
            weights + hash-id remap (both equal the bucket's own tables
            for argless buckets, so one code path serves every map)."""
            arow = _lookup(bidx_row, NB, args_pack)
            poh = (
                jnp.arange(P) == jnp.clip(pos, 0, P - 1)
            ).astype(jnp.float32)
            hi = jnp.matmul(
                poh, arow[: P * SZ].reshape(P, SZ), precision=HIP
            )
            lo = jnp.matmul(
                poh,
                arow[P * SZ : 2 * P * SZ].reshape(P, SZ),
                precision=HIP,
            )
            awf = hi.astype(jnp.float64) * 65536.0 + lo.astype(
                jnp.float64
            )
            aids = jnp.round(arow[2 * P * SZ :]).astype(jnp.int32)
            return aids, awf

        def straw_draw(ids, strawf, size, x, r, szv):
            """Legacy straw chooser (bucket_straw_choose,
            mapper.c:227-245): draw_i = (hash3(x, item, r) & 0xffff) *
            straw_i, argmax with first-max-wins ties.  u16 * u32 < 2^48
            is f64-exact."""
            u = (
                _hash3(
                    jnp.uint32(x),
                    ids.astype(jnp.uint32),
                    jnp.uint32(r),
                )
                & jnp.uint32(0xFFFF)
            ).astype(jnp.float64)
            draw = jnp.where(
                jnp.arange(szv) < size, u * strawf, -jnp.inf
            )
            am = jnp.argmax(draw)  # first max, like the C's strict >
            return jnp.sum(
                jnp.where(jnp.arange(szv) == am, ids, 0)
            ).astype(jnp.int32)

        def list_draw(ids, wf, sumf, size, bid, x, r, szv):
            """List chooser (bucket_list_choose, mapper.c:141-164):
            walk tail→head, item i wins when
            (hash4(x, item, r, bucket_id) & 0xffff) * sum_i >> 16 <
            weight_i — i.e. the HIGHEST accepting index wins; items[0]
            when nobody accepts.  u16 * u32 < 2^48 and the >>16 floor
            are f64-exact."""
            w = (
                _hash4(
                    jnp.uint32(x),
                    ids.astype(jnp.uint32),
                    jnp.uint32(r),
                    bid.astype(jnp.uint32),
                )
                & jnp.uint32(0xFFFF)
            ).astype(jnp.float64)
            scaled = jnp.floor(w * sumf / 65536.0)
            accept = (scaled < wf) & (jnp.arange(szv) < size)
            idx = jnp.max(jnp.where(accept, jnp.arange(szv), -1))
            win = jnp.maximum(idx, 0)  # items[0] when none accept
            return jnp.sum(
                jnp.where(jnp.arange(szv) == win, ids, 0)
            ).astype(jnp.int32)

        def tree_draw(trow, ids, bid, x, r, szv):
            """Tree chooser (bucket_tree_choose, mapper.c:195-222):
            weighted descent of the implicit binary tree over an
            already-loaded node-weight row.  The C's
            (hash32_4 * u64 weight) >> 32 exceeds f64's 2^53 exact
            range, so it is computed as split integer arithmetic: with
            hash = h1*2^16 + h0 and A = h1*w = a1*2^16 + a0,
            t = a1 + floor((a0*2^16 + h0*w) / 2^32) — every
            intermediate stays below 2^49."""
            nwf = trow[:TN].astype(jnp.float64) * 65536.0 + trow[
                TN : 2 * TN
            ].astype(jnp.float64)
            start = jnp.round(trow[2 * TN]).astype(jnp.int32)

            def node_w(n):
                oh = (jnp.arange(TN) == n).astype(jnp.float64)
                return jnp.sum(oh * nwf)

            def body(_i, n):
                frozen = (n & 1) == 1
                w = node_w(n)
                hv = _hash4(
                    jnp.uint32(x),
                    n.astype(jnp.uint32),
                    jnp.uint32(r),
                    bid.astype(jnp.uint32),
                ).astype(jnp.float64)
                h1 = jnp.floor(hv / 65536.0)
                h0 = hv - h1 * 65536.0
                A = h1 * w
                a1 = jnp.floor(A / 65536.0)
                a0 = A - a1 * 65536.0
                t = a1 + jnp.floor(
                    (a0 * 65536.0 + h0 * w) / 4294967296.0
                )
                low = (n & -n) >> 1  # 2^(height-1)
                left = n - low
                nxt = jnp.where(t < node_w(left), left, n + low)
                return jnp.where(frozen, n, nxt).astype(jnp.int32)

            depth = max(TN.bit_length(), 1)
            n = lax.fori_loop(0, depth, body, start)
            slot = n >> 1
            return jnp.sum(
                jnp.where(jnp.arange(szv) == slot, ids, 0)
            ).astype(jnp.int32)

        def dispatch_draw(
            bidx_row, ids, wf, strawf, sumf, size, alg, bid, x, r, pos
        ):
            """crush_bucket_choose over already-loaded bucket data; the
            perm/straw/list/tree paths only compile into maps
            containing those bucket algs, the choose_args path only
            into maps that carry choose_args."""
            if args_pack is not None:
                hash_ids, awf = load_args(bidx_row, pos)
            else:
                hash_ids, awf = ids, wf
            item = straw2_draw(hash_ids, ids, awf, size, x, r, SZ)
            if cm.has_uniform:
                uni = perm_draw(
                    ids, size, bid, x, r, SZ, cm.uniform_sz
                )
                item = jnp.where(
                    alg == CRUSH_BUCKET_UNIFORM, uni, item
                )
            if cm.has_straw:
                st = straw_draw(ids, strawf, size, x, r, SZ)
                item = jnp.where(alg == CRUSH_BUCKET_STRAW, st, item)
            if cm.has_list:
                li = list_draw(ids, wf, sumf, size, bid, x, r, SZ)
                item = jnp.where(alg == CRUSH_BUCKET_LIST, li, item)
            if cm.has_tree:
                trow = _lookup(bidx_row, NB, tree_pack)
                tr = tree_draw(trow, ids, bid, x, r, SZ)
                item = jnp.where(alg == CRUSH_BUCKET_TREE, tr, item)
            return item

        def bucket_draw(bidx_row, x, r, pos):
            """Load + draw; returns (item, bucket_size)."""
            ids, wf, strawf, sumf, size, alg, bid = load_bucket(
                bidx_row
            )
            return (
                dispatch_draw(
                    bidx_row, ids, wf, strawf, sumf, size, alg, bid,
                    x, r, pos,
                ),
                size,
            )

        def row_of(item):
            """Bucket row for a (negative) item; -1 if invalid."""
            neg = -1 - item
            ok = (item < 0) & (neg < NEGB)
            row = jnp.round(
                _lookup(jnp.clip(neg, 0, None), NEGB, cm.bidx_f)
            ).astype(jnp.int32)
            return jnp.where(ok, row, -1)

        def type_of_row(nrow):
            return jnp.round(
                _lookup(jnp.maximum(nrow, 0), NB, cm.types_f)
            ).astype(jnp.int32)

        def is_out(weightv, item, x):
            """mapper.c:424-438 over the device reweight vector."""
            w = weightv[jnp.clip(item, 0, weightv.shape[0] - 1)]
            oob = item >= weightv.shape[0]
            hashed = (
                _hash2(jnp.uint32(x), jnp.uint32(item)).astype(
                    jnp.int32
                )
                & 0xFFFF
            )
            return oob | (w == 0) | ((w < 0x10000) & (hashed >= w))

        def classify(item, target_type):
            """(found, descend, hard_bad, nrow) for a drawn item
            against the level's target type (the firstn/indep descent
            checks)."""
            nrow = row_of(item)
            is_dev = item >= 0
            invalid = (~is_dev) & (nrow < 0)
            bad_dev = item >= cm.max_devices
            itype = jnp.where(is_dev, 0, type_of_row(nrow))
            found = (~bad_dev) & (~invalid) & (itype == target_type)
            hard_bad = (
                bad_dev | invalid | (is_dev & (itype != target_type))
            )
            descend = (~found) & (~hard_bad)
            return found, descend, hard_bad, nrow

        # -- fast firstn: speculative tables + table-driven machine ----

        def make_step_drawer(sinfo):
            """Specialized draw for one descent level of the fast
            path: the one-hot runs over the level's REACHABLE bucket
            set (often a single row — then no lookup at all) and item
            vectors shrink to the level's max bucket size, instead of
            the map-wide NB x SZ tables the generic path must assume.
            Returns draw(cur_row, r) -> (item, size)."""
            rows_t = sinfo["rows"]
            NS = len(rows_t)
            SZi = min(sinfo["sz"], SZ)
            algs = set(sinfo["algs"])
            idxv = jnp.asarray(rows_t, dtype=jnp.int32)

            # static gathers on the operand packs: computed once per
            # call over (NS, cols) — not per lane
            sub = row_pack[idxv, :]
            pieces = [
                sub[:, 0:SZi],
                sub[:, SZ : SZ + SZi],
                sub[:, 2 * SZ : 2 * SZ + SZi],
            ]
            ncol = 3 * SZi
            off_straw = off_sum = None
            if CRUSH_BUCKET_STRAW in algs:
                off_straw = ncol
                pieces += [
                    sub[:, 3 * SZ : 3 * SZ + SZi],
                    sub[:, 4 * SZ : 4 * SZ + SZi],
                ]
                ncol += 2 * SZi
            if CRUSH_BUCKET_LIST in algs:
                off_sum = ncol
                pieces += [
                    sub[:, 5 * SZ : 5 * SZ + SZi],
                    sub[:, 6 * SZ : 6 * SZ + SZi],
                ]
                ncol += 2 * SZi
            off_meta = ncol
            pieces.append(sub[:, 7 * SZ : 7 * SZ + 3])
            tab = jnp.concatenate(pieces, axis=1)
            if args_pack is not None:
                asub = args_pack[idxv, :]
                atab = jnp.concatenate(
                    [
                        asub[:, 0:SZi],
                        asub[:, SZ : SZ + SZi],
                        asub[:, 2 * SZ : 2 * SZ + SZi],
                    ],
                    axis=1,
                )
            if CRUSH_BUCKET_TREE in algs:
                ttab = tree_pack[idxv, :]

            def f64cols(row, a, b):
                return row[a:b].astype(jnp.float64) * 65536.0 + row[
                    a + SZi : b + SZi
                ].astype(jnp.float64)

            def draw(cur_row, r):
                if NS == 1:
                    row = tab[0]
                else:
                    oh = (idxv == cur_row).astype(jnp.float32)
                    row = jnp.matmul(oh, tab, precision=HIP)
                ids = jnp.round(row[0:SZi]).astype(jnp.int32)
                wf = f64cols(row, SZi, 2 * SZi)
                size = jnp.round(row[off_meta]).astype(jnp.int32)
                alg = jnp.round(row[off_meta + 1]).astype(jnp.int32)
                bid = jnp.round(row[off_meta + 2]).astype(jnp.int32)
                if args_pack is not None:
                    if NS == 1:
                        arow = atab[0]
                    else:
                        arow = jnp.matmul(oh, atab, precision=HIP)
                    # atab layout: aw_hi | aw_lo | aids
                    hash_ids = jnp.round(
                        arow[2 * SZi : 3 * SZi]
                    ).astype(jnp.int32)
                    awf = f64cols(arow, 0, SZi)
                else:
                    hash_ids, awf = ids, wf
                item = straw2_draw(
                    hash_ids, ids, awf, size, x, r, SZi
                )
                if CRUSH_BUCKET_UNIFORM in algs:
                    uni = perm_draw(
                        ids, size, bid, x, r, SZi, sinfo["usz"]
                    )
                    item = jnp.where(
                        alg == CRUSH_BUCKET_UNIFORM, uni, item
                    )
                if CRUSH_BUCKET_STRAW in algs:
                    strawf = f64cols(
                        row, off_straw, off_straw + SZi
                    )
                    st = straw_draw(ids, strawf, size, x, r, SZi)
                    item = jnp.where(
                        alg == CRUSH_BUCKET_STRAW, st, item
                    )
                if CRUSH_BUCKET_LIST in algs:
                    sumf = f64cols(row, off_sum, off_sum + SZi)
                    li = list_draw(
                        ids, wf, sumf, size, bid, x, r, SZi
                    )
                    item = jnp.where(
                        alg == CRUSH_BUCKET_LIST, li, item
                    )
                if CRUSH_BUCKET_TREE in algs:
                    if NS == 1:
                        trow = ttab[0]
                    else:
                        trow = jnp.matmul(oh, ttab, precision=HIP)
                    tr = tree_draw(trow, ids, bid, x, r, SZi)
                    item = jnp.where(
                        alg == CRUSH_BUCKET_TREE, tr, item
                    )
                return item, size

            return draw

        def spec_descend(steps, rows, rs, valids, target):
            """Batched candidate descents: each candidate draws with
            its own fixed r at every level (the crush_choose_firstn
            contract), one specialized draw round per level; returns
            (kind, item) per candidate."""
            kinds = jnp.where(
                valids, jnp.int32(_K_OVER), jnp.int32(_K_BAD)
            )
            items = jnp.full(rows.shape, NONE)
            tt = jnp.int32(target)
            for sinfo in steps:
                drawer = make_step_drawer(sinfo)

                def one(row, r, kind, prev_it):
                    it, bsize = drawer(row, r)
                    empty = bsize == 0
                    found, desc, hard_bad, nrow = classify(it, tt)
                    active = kind == _K_OVER
                    nk = jnp.where(
                        active,
                        jnp.where(
                            empty,
                            _K_RETRY,
                            jnp.where(
                                found,
                                _K_FOUND,
                                jnp.where(hard_bad, _K_BAD, _K_OVER),
                            ),
                        ),
                        kind,
                    ).astype(jnp.int32)
                    nit = jnp.where(active, it, prev_it)
                    nrow2 = jnp.where(
                        active & desc & ~empty, nrow, row
                    ).astype(jnp.int32)
                    return nrow2, nk, nit

                rows, kinds, items = jax.vmap(one)(
                    rows, rs, kinds, items
                )
            return kinds, items

        def fast_firstn(plan, weightv):
            f = plan["fast"]
            R0 = f["R0"]
            ttype = plan["ttype"]
            numrep, nslots = plan["numrep"], plan["nslots"]
            tries, leaf_tries = plan["tries"], plan["leaf_tries"]
            vary_r, stable = plan["vary_r"], plan["stable"]
            leaf = plan["leaf"]
            R = nslots
            rvec = jnp.arange(R0, dtype=jnp.int32)

            rows0 = jnp.full((R0,), jnp.int32(plan["take_row"]))
            kinds, items = spec_descend(
                f["outer_steps"], rows0, rvec,
                jnp.full((R0,), True), ttype,
            )
            if ttype == 0:
                oisout = jax.vmap(
                    lambda it: is_out(weightv, it, x)
                )(items) & (kinds == _K_FOUND)
            else:
                oisout = jnp.zeros((R0,), bool)

            if leaf:
                L0, Pd = f["L0"], f["Pd"]
                start_rows = jax.vmap(row_of)(items)
                lvalid = (kinds == _K_FOUND) & (items < 0)
                if vary_r:
                    sub_r = rvec >> (vary_r - 1)
                else:
                    sub_r = jnp.zeros_like(rvec)
                reps = Pd * L0
                sub_flat = jnp.repeat(sub_r, reps)
                rows_flat = jnp.repeat(start_rows, reps)
                valid_flat = jnp.repeat(lvalid, reps)
                pos_flat = jnp.tile(
                    jnp.repeat(
                        jnp.arange(Pd, dtype=jnp.int32), L0
                    ),
                    R0,
                )
                l_flat = jnp.tile(
                    jnp.arange(L0, dtype=jnp.int32), R0 * Pd
                )
                leaf_rep = (
                    jnp.zeros_like(pos_flat) if stable else pos_flat
                )
                rleaf_flat = leaf_rep + sub_flat + l_flat
                lkinds, litems = spec_descend(
                    f["leaf_steps"], rows_flat, rleaf_flat,
                    valid_flat, 0,
                )
                lisout = jax.vmap(
                    lambda it: is_out(weightv, it, x)
                )(litems) & (lkinds == _K_FOUND)

            def cond(st):
                return ~st[0]

            def body(st):
                (done, okf, rep, outpos, ftotal, lftotal, mode,
                 dom_r, domain, out, out2) = st
                in_leaf = mode == LEAF
                r = rep + ftotal
                over_r = (~in_leaf) & (r >= R0)
                ohr = jnp.arange(R0) == jnp.clip(r, 0, R0 - 1)
                k = jnp.sum(jnp.where(ohr, kinds, 0)).astype(
                    jnp.int32
                )
                it = jnp.sum(jnp.where(ohr, items, 0)).astype(
                    jnp.int32
                )
                o = (~in_leaf) & ~over_r
                o_found = o & (k == _K_FOUND)
                o_bad = o & (k == _K_BAD)
                o_retry = o & (k == _K_RETRY)
                o_over = (~in_leaf) & (over_r | (k == _K_OVER))

                collide = o_found & jnp.any(
                    (jnp.arange(R) < outpos) & (out == it)
                )
                if leaf:
                    enter_leaf = o_found & ~collide & (it < 0)
                    direct = o_found & ~collide & (it >= 0)
                else:
                    enter_leaf = jnp.bool_(False)
                    direct = o_found & ~collide
                if ttype == 0:
                    oio = jnp.any(ohr & oisout)
                    direct_out = direct & oio
                else:
                    direct_out = jnp.bool_(False)
                place_direct = direct & ~direct_out

                if leaf:
                    l = in_leaf
                    l_over_idx = lftotal >= f["L0"]
                    if stable:
                        pos_comp = jnp.int32(0)
                    else:
                        pos_comp = jnp.clip(outpos, 0, f["Pd"] - 1)
                    fidx = (
                        dom_r * (f["Pd"] * f["L0"])
                        + pos_comp * f["L0"]
                        + jnp.clip(lftotal, 0, f["L0"] - 1)
                    )
                    ohl = jnp.arange(R0 * f["Pd"] * f["L0"]) == fidx
                    lk = jnp.sum(jnp.where(ohl, lkinds, 0)).astype(
                        jnp.int32
                    )
                    lit = jnp.sum(jnp.where(ohl, litems, 0)).astype(
                        jnp.int32
                    )
                    lio = jnp.any(ohl & lisout)
                    lc = l & ~l_over_idx
                    l_found = lc & (lk == _K_FOUND)
                    l_bad = lc & (lk == _K_BAD)
                    l_empty = lc & (lk == _K_RETRY)
                    l_over = l & (l_over_idx | (lk == _K_OVER))
                    l_rej = l_found & (
                        jnp.any(
                            (jnp.arange(R) < outpos) & (out2 == lit)
                        )
                        | lio
                    )
                    l_place = l_found & ~l_rej
                    l_retry_cand = l_empty | l_rej
                    l_exhaust = l_retry_cand & (
                        lftotal + 1 >= leaf_tries
                    )
                    l_retry = l_retry_cand & ~l_exhaust
                else:
                    lit = NONE
                    l_bad = l_exhaust = l_retry = l_place = (
                        jnp.bool_(False)
                    )
                    l_over = jnp.bool_(False)

                outer_reject = (
                    o_retry | collide | direct_out | l_bad | l_exhaust
                )
                or_skip = outer_reject & (ftotal + 1 >= tries)
                or_retry = outer_reject & ~or_skip
                place = place_direct | l_place
                skip = o_bad | or_skip
                advance = place | skip
                fail = o_over | l_over

                sel = place & (jnp.arange(R) == outpos)
                out = jnp.where(
                    sel, jnp.where(l_place, domain, it), out
                )
                if leaf:
                    out2 = jnp.where(sel, lit, out2)

                new_rep = rep + advance
                new_outpos = (outpos + place).astype(jnp.int32)
                new_ftotal = jnp.where(
                    advance, 0, jnp.where(or_retry, ftotal + 1, ftotal)
                ).astype(jnp.int32)
                new_lftotal = jnp.where(
                    enter_leaf,
                    0,
                    jnp.where(l_retry, lftotal + 1, lftotal),
                ).astype(jnp.int32)
                stay_leaf = enter_leaf | l_retry
                new_mode = jnp.where(stay_leaf, LEAF, OUTER)
                new_dom_r = jnp.where(enter_leaf, r, dom_r).astype(
                    jnp.int32
                )
                new_domain = jnp.where(enter_leaf, it, domain).astype(
                    jnp.int32
                )
                new_ok = okf & ~fail
                new_done = (
                    done
                    | fail
                    | (new_rep >= numrep)
                    | (new_outpos >= nslots)
                )
                return (
                    new_done, new_ok, new_rep.astype(jnp.int32),
                    new_outpos, new_ftotal, new_lftotal, new_mode,
                    new_dom_r, new_domain, out, out2,
                )

            init = (
                jnp.bool_(numrep <= 0 or R == 0),
                jnp.bool_(True),
                jnp.int32(0), jnp.int32(0), jnp.int32(0),
                jnp.int32(0), OUTER, jnp.int32(0), jnp.int32(0),
                jnp.full((R,), NONE, dtype=jnp.int32),
                jnp.full((R,), NONE, dtype=jnp.int32),
            )
            st = lax.while_loop(cond, body, init)
            okf, outpos = st[1], st[3]
            out, out2 = st[9], st[10]
            return (out2 if leaf else out), outpos, okf

        # -- generic choosers (one draw per while_loop iteration) ------

        def choose_firstn(plan, weightv):
            """crush_choose_firstn (mapper.c:460-648) as a state
            machine.

            Registers: rep/outpos/ftotal track the C loop variables;
            mode switches between the outer descent (toward ttype) and
            the chooseleaf descent (toward a device under ``domain``);
            every reject path advances r' exactly as the C does.
            Exception to one-draw-per-iteration: empty-bucket and
            depth-exceeded transitions consume an iteration without
            using the draw.

            ``numrep`` is the C loop bound (reps keep advancing past
            skipped replicas); ``nslots`` is the count bound on actual
            placements (the C's out_size/count)."""
            take_row = plan["take_row"]
            ttype = plan["ttype"]
            numrep, nslots = plan["numrep"], plan["nslots"]
            tries, leaf_tries = plan["tries"], plan["leaf_tries"]
            vary_r, stable = plan["vary_r"], plan["stable"]
            leaf = plan["leaf"]
            R = nslots

            def cond(st):
                return ~st[0]

            def body(st):
                (done, rep, outpos, ftotal, mode, cur_row, domain,
                 lftotal, depth, out, out2) = st
                in_leaf = mode == LEAF
                leaf_rep = jnp.int32(0) if stable else outpos
                r_outer = rep + ftotal
                if vary_r:
                    sub_r = r_outer >> (vary_r - 1)
                else:
                    sub_r = jnp.int32(0)
                r = jnp.where(
                    in_leaf, leaf_rep + sub_r + lftotal, r_outer
                )

                # choose_args position: the C passes the running outpos
                # at every firstn draw (mapper.c:526-530), and the
                # chooseleaf recursion re-enters with the same outpos
                # (:578-588), so one register serves both modes
                item, bsize = bucket_draw(cur_row, x, r, outpos)
                empty = bsize == 0
                target = jnp.where(in_leaf, 0, jnp.int32(ttype))
                found, desc, hard_bad, nrow = classify(item, target)
                # depth guard: runaway descent behaves like a bad item
                too_deep = desc & (depth + 1 >= MAX_DEPTH)
                hard_bad = (~empty) & (hard_bad | too_deep)
                desc = (~empty) & desc & ~too_deep
                found = (~empty) & found

                o = ~in_leaf
                o_desc = o & desc
                o_bad = o & hard_bad
                o_found = o & found
                collide = o_found & jnp.any(
                    (jnp.arange(R) < outpos) & (out == item)
                )
                if leaf:
                    enter_leaf = o_found & ~collide & (item < 0)
                    direct = o_found & ~collide & (item >= 0)
                else:
                    enter_leaf = jnp.bool_(False)
                    direct = o_found & ~collide
                if ttype == 0:
                    direct_out = direct & is_out(weightv, item, x)
                else:
                    direct_out = jnp.bool_(False)
                place_direct = direct & ~direct_out

                l = in_leaf
                l_desc = l & desc
                l_bad = l & hard_bad
                l_found = l & found
                l_rej = l_found & (
                    jnp.any(
                        (jnp.arange(R) < outpos) & (out2 == item)
                    )
                    | is_out(weightv, item, x)
                )
                l_place = l_found & ~l_rej
                l_retry_cand = (l & empty) | l_rej
                l_exhaust = l_retry_cand & (
                    lftotal + 1 >= leaf_tries
                )
                l_retry = l_retry_cand & ~l_exhaust

                outer_reject = (
                    (o & empty)
                    | collide
                    | direct_out
                    | l_bad
                    | l_exhaust
                )
                or_skip = outer_reject & (ftotal + 1 >= tries)
                or_retry = outer_reject & ~or_skip

                place = place_direct | l_place
                skip = o_bad | or_skip
                advance = place | skip

                sel = place & (jnp.arange(R) == outpos)
                out = jnp.where(
                    sel, jnp.where(l_place, domain, item), out
                )
                if leaf:
                    out2 = jnp.where(sel, item, out2)

                new_rep = rep + advance
                new_outpos_i = outpos + place
                new_done = done | (new_rep >= numrep) | (
                    new_outpos_i >= nslots
                )
                new_outpos = new_outpos_i
                new_ftotal = jnp.where(
                    advance, 0, jnp.where(or_retry, ftotal + 1, ftotal)
                )
                new_lftotal = jnp.where(
                    enter_leaf,
                    0,
                    jnp.where(l_retry, lftotal + 1, lftotal),
                )
                stay_leaf = enter_leaf | l_desc | l_retry
                new_mode = jnp.where(stay_leaf, LEAF, OUTER)
                new_row = jnp.where(
                    o_desc | l_desc | enter_leaf,
                    nrow,
                    jnp.where(l_retry, row_of(domain), take_row),
                )
                new_domain = jnp.where(enter_leaf, item, domain)
                new_depth = jnp.where(o_desc | l_desc, depth + 1, 0)
                return (
                    new_done, new_rep, new_outpos.astype(jnp.int32),
                    new_ftotal.astype(jnp.int32), new_mode, new_row,
                    new_domain, new_lftotal.astype(jnp.int32),
                    new_depth.astype(jnp.int32), out, out2,
                )

            init = (
                jnp.bool_(numrep <= 0 or R == 0), jnp.int32(0),
                jnp.int32(0), jnp.int32(0),
                OUTER, jnp.int32(take_row), jnp.int32(0),
                jnp.int32(0), jnp.int32(0),
                jnp.full((R,), NONE, dtype=jnp.int32),
                jnp.full((R,), NONE, dtype=jnp.int32),
            )
            st = lax.while_loop(cond, body, init)
            outpos = st[2]
            out, out2 = st[9], st[10]
            return (out2 if leaf else out), outpos, jnp.bool_(True)

        def choose_indep(plan, weightv):
            """crush_choose_indep (mapper.c:655-843) as a state
            machine.

            ``slot`` scans the UNDEF positions of each round; finishing
            a slot jumps straight to the next UNDEF one, and exhausting
            them advances the round (ftotal).  r' = slot + n*ftotal at
            the outer level and slot + r_outer + n*lftotal inside
            chooseleaf, exactly the C advancement.  ``numrep`` is the
            unclamped replica count — it sets the r' stride even when
            left0 < numrep."""
            take_row = plan["take_row"]
            ttype = plan["ttype"]
            numrep, nslots = plan["numrep"], plan["nslots"]
            tries, leaf_tries = plan["tries"], plan["leaf_tries"]
            leaf = plan["leaf"]
            left0 = nslots
            R = left0

            def slot_advance(out, slot, left, ftotal):
                """Next UNDEF slot after ``slot``; wrap advances the
                round."""
                undef = out == UNDEF
                after = undef & (jnp.arange(R) > slot)
                has_after = jnp.any(after)
                nxt = jnp.where(
                    has_after, jnp.argmax(after), jnp.argmax(undef)
                ).astype(jnp.int32)
                new_ftotal = ftotal + jnp.where(has_after, 0, 1)
                done = (
                    (left <= 0)
                    | (~jnp.any(undef))
                    | (new_ftotal >= tries)
                )
                return nxt, new_ftotal, done

            def cond(st):
                return ~st[0]

            def body(st):
                (done, slot, left, ftotal, mode, cur_row, domain,
                 lftotal, depth, parent_r, out, out2) = st
                in_leaf = mode == LEAF
                ids, wf, strawf, sumf, bsize, alg, bid = load_bucket(
                    cur_row
                )
                # uniform buckets whose size divides numrep advance r
                # with stride numrep+1 (mapper.c:722-728) — per descent
                # level
                if cm.has_uniform:
                    stride = jnp.where(
                        (alg == CRUSH_BUCKET_UNIFORM)
                        & (bsize > 0)
                        & (bsize % numrep == 0),
                        numrep + 1,
                        numrep,
                    )
                else:
                    stride = jnp.int32(numrep)
                # parent_r freezes the outer r at domain-choice time
                # for the chooseleaf recursion (its nested call
                # re-bases on it)
                r = jnp.where(
                    in_leaf,
                    slot + parent_r + stride * lftotal,
                    slot + stride * ftotal,
                )

                # choose_args position: indep outer draws pass the
                # FRAME outpos — constant 0 from do_rule
                # (mapper.c:736-739) — and the leaf recursion enters
                # with outpos=rep (:790-794), so leaf draws use the
                # slot index
                pos = jnp.where(in_leaf, slot, jnp.int32(0))
                item = dispatch_draw(
                    cur_row, ids, wf, strawf, sumf, bsize, alg, bid,
                    x, r, pos,
                )
                empty = bsize == 0
                target = jnp.where(in_leaf, 0, jnp.int32(ttype))
                found, desc, hard_bad, nrow = classify(item, target)
                too_deep = desc & (depth + 1 >= MAX_DEPTH)
                hard_bad = (~empty) & (hard_bad | too_deep)
                desc = (~empty) & desc & ~too_deep
                found = (~empty) & found

                o = ~in_leaf
                o_desc = o & desc
                o_kill = o & hard_bad  # slot permanently NONE
                o_found = o & found
                collide = o_found & jnp.any(out == item)
                if leaf:
                    enter_leaf = o_found & ~collide & (item < 0)
                    direct = o_found & ~collide & (item >= 0)
                else:
                    enter_leaf = jnp.bool_(False)
                    direct = o_found & ~collide
                if ttype == 0:
                    direct_out = direct & is_out(weightv, item, x)
                else:
                    direct_out = jnp.bool_(False)
                place_direct = direct & ~direct_out

                l = in_leaf
                l_desc = l & desc
                l_fail_now = l & hard_bad  # inner NONE -> outer break
                l_found = l & found
                l_rej = l_found & is_out(weightv, item, x)
                l_place = l_found & ~l_rej
                l_retry_cand = (l & empty) | l_rej
                l_exhaust = l_retry_cand & (
                    lftotal + 1 >= leaf_tries
                )
                l_retry = l_retry_cand & ~l_exhaust

                place = place_direct | l_place
                kill = o_kill
                # break: slot stays UNDEF for a later round
                brk = (
                    (o & empty)
                    | collide
                    | direct_out
                    | l_fail_now
                    | l_exhaust
                )

                sel = jnp.arange(R) == slot
                out = jnp.where(
                    sel & place,
                    jnp.where(l_place, domain, item),
                    jnp.where(sel & kill, NONE, out),
                )
                if leaf:
                    out2 = jnp.where(
                        sel & place,
                        item,
                        jnp.where(sel & kill, NONE, out2),
                    )
                new_left = left - (place | kill).astype(jnp.int32)

                finished = place | kill | brk
                nxt, adv_ftotal, adv_done = slot_advance(
                    out, slot, new_left, ftotal
                )
                new_slot = jnp.where(finished, nxt, slot)
                new_ftotal = jnp.where(finished, adv_ftotal, ftotal)
                new_done = done | (finished & adv_done)

                stay_leaf = enter_leaf | l_desc | l_retry
                new_mode = jnp.where(
                    stay_leaf & ~finished, LEAF, OUTER
                )
                new_row = jnp.where(
                    o_desc | l_desc | enter_leaf,
                    nrow,
                    jnp.where(
                        l_retry & ~finished,
                        row_of(domain),
                        take_row,
                    ),
                )
                new_domain = jnp.where(enter_leaf, item, domain)
                new_lftotal = jnp.where(
                    enter_leaf,
                    0,
                    jnp.where(l_retry, lftotal + 1, lftotal),
                )
                new_depth = jnp.where(o_desc | l_desc, depth + 1, 0)
                new_parent_r = jnp.where(enter_leaf, r, parent_r)
                return (
                    new_done, new_slot, new_left,
                    new_ftotal.astype(jnp.int32), new_mode, new_row,
                    new_domain, new_lftotal.astype(jnp.int32),
                    new_depth.astype(jnp.int32),
                    new_parent_r.astype(jnp.int32), out, out2,
                )

            init = (
                jnp.bool_(R == 0) | jnp.bool_(tries <= 0),
                jnp.int32(0), jnp.int32(R), jnp.int32(0),
                OUTER, jnp.int32(take_row), jnp.int32(0),
                jnp.int32(0), jnp.int32(0), jnp.int32(0),
                jnp.full((R,), UNDEF, dtype=jnp.int32),
                jnp.full((R,), UNDEF, dtype=jnp.int32),
            )
            st = lax.while_loop(cond, body, init)
            out, out2 = st[10], st[11]
            out = jnp.where(out == UNDEF, NONE, out)
            out2 = jnp.where(out2 == UNDEF, NONE, out2)
            return (out2 if leaf else out), jnp.int32(R), jnp.bool_(
                True
            )

        # -- the rule program ------------------------------------------
        result = jnp.full((result_max,), NONE, dtype=jnp.int32)
        rlen = jnp.int32(0)
        okall = jnp.bool_(True)
        for plan in plans:
            if plan["fast"] is not None:
                got, n, okg = fast_firstn(plan, weightv)
            elif plan["firstn"]:
                got, n, okg = choose_firstn(plan, weightv)
            else:
                got, n, okg = choose_indep(plan, weightv)
            okall = okall & okg
            # append got[:n] to result at rlen
            for i in range(plan["nslots"]):
                slot = rlen + i
                valid = (i < n) & (slot < result_max)
                result = jnp.where(
                    valid & (jnp.arange(result_max) == slot),
                    got[i],
                    result,
                )
            rlen = jnp.minimum(rlen + n, result_max)
        return result, rlen, okall

    return rule_fn


# Kernel cache keyed on map STRUCTURE (CompiledMap.skey), not the
# CompiledMap instance: recompiling the same topology with new weights
# (the per-epoch mon/mgr pattern) reuses the jitted program and pays
# only a host→device table upload.  Bounded LRU: a long-lived daemon
# recompiling across structural epochs must not pin every old
# topology's executable (and its closed-over CompiledMap) forever.
_KERNEL_CACHE: collections.OrderedDict = collections.OrderedDict()
_KERNEL_CACHE_MAX = 64


def _kernel_cache_get(key):
    fn = _KERNEL_CACHE.get(key)
    if fn is not None:
        _KERNEL_CACHE.move_to_end(key)
    return fn


def _kernel_cache_put(key, fn):
    _KERNEL_CACHE[key] = fn
    while len(_KERNEL_CACHE) > _KERNEL_CACHE_MAX:
        _KERNEL_CACHE.popitem(last=False)


def _unpack_tables(has_args, has_tree, packs):
    """Positional operand unpacking shared by every jitted wrapper
    (the operand list omits absent args/tree packs)."""
    i = 0
    args_pack = tree_pack = None
    if has_args:
        args_pack = packs[i]
        i += 1
    if has_tree:
        tree_pack = packs[i]
    return args_pack, tree_pack


def _kernel_tables(cm: CompiledMap):
    t = [cm.row_pack]
    if cm.args_pack is not None:
        t.append(cm.args_pack)
    if cm.tree_pack is not None:
        t.append(cm.tree_pack)
    return t


def _batched(
    cm: CompiledMap, ruleno: int, result_max: int, spec_boost: int = 0
):
    key = ("xs", cm.skey, ruleno, result_max, spec_boost)
    fn = _kernel_cache_get(key)
    if fn is None:
        rf = _make_rule_fn(cm, ruleno, result_max, spec_boost)
        has_args = cm.args_pack is not None
        has_tree = cm.tree_pack is not None

        def call(xs, wv, row_pack, *packs):
            args_pack, tree_pack = _unpack_tables(
                has_args, has_tree, packs
            )
            return jax.vmap(
                lambda x: rf(x, wv, row_pack, args_pack, tree_pack)
            )(xs)

        fn = jax.jit(call)
        _kernel_cache_put(key, fn)
    return fn


def _batched_range(
    cm: CompiledMap,
    ruleno: int,
    result_max: int,
    n: int,
    packed: bool = False,
    spec_boost: int = 0,
):
    """Jitted contiguous-range variant: xs = lo + iota(n) is built ON
    DEVICE, so a bulk remap (osdmaptool --test-map-pgs shape) ships
    one scalar per call instead of an N-element host array, and calls
    pipeline without host round-trips between dispatches.  With
    ``packed`` the results ship as int16 (-32768 encodes NONE) and
    counts as uint8 — half the device→host bytes on a bulk remap."""
    key = ("rg", cm.skey, ruleno, result_max, n, packed, spec_boost)
    fn = _kernel_cache_get(key)
    if fn is None:
        rf = _make_rule_fn(cm, ruleno, result_max, spec_boost)
        has_args = cm.args_pack is not None
        has_tree = cm.tree_pack is not None

        def call(lo, wv, row_pack, *packs):
            args_pack, tree_pack = _unpack_tables(
                has_args, has_tree, packs
            )
            xs = lo + jnp.arange(n, dtype=jnp.int32)
            res, counts, ok = jax.vmap(
                lambda x: rf(x, wv, row_pack, args_pack, tree_pack)
            )(xs)
            if packed:
                res = jnp.where(
                    res == CRUSH_ITEM_NONE, jnp.int32(-32768), res
                ).astype(jnp.int16)
                counts = counts.astype(jnp.uint8)
            return res, counts, ok

        fn = jax.jit(call)
        _kernel_cache_put(key, fn)
    return fn


def apply_oracle_fallback(
    cm: CompiledMap,
    ruleno: int,
    xs,
    res,
    counts,
    ok,
    result_max: int,
    weights=None,
):
    """Re-map the lanes whose speculative retry window overflowed
    (ok == False) through the exact host oracle; returns finalized
    numpy (results, counts).  No-op (and no copy) when every lane is
    ok — the common case for any realistically-sized map.  Accepts
    the packed int16 wire form (see _batched_range) and unpacks it."""
    res = np.asarray(res)
    counts = np.asarray(counts)
    if res.dtype == np.int16:
        res32 = res.astype(np.int32)
        res32[res == -32768] = CRUSH_ITEM_NONE
        res = res32
        counts = counts.astype(np.int32)
    bad = np.nonzero(~np.asarray(ok))[0]
    if bad.size:
        if getattr(cm.source, "mutation", 0) != cm.source_mutation:
            raise RuntimeError(
                "CrushMap mutated since compile_map(): the oracle "
                "fallback would mix old-snapshot kernel results with "
                "new-map lanes — recompile the map first"
            )
        if weights is None:
            weights = np.full(
                max(cm.max_devices, 1), 0x10000, np.int32
            )
        wl = [int(w) for w in np.asarray(weights)]
        res = res.copy()
        counts = counts.copy()
        xs = np.asarray(xs)
        for i in bad:
            row = cm.source.do_rule(
                ruleno, int(xs[i]), result_max, wl
            )
            res[i, :] = CRUSH_ITEM_NONE
            res[i, : len(row)] = row
            counts[i] = len(row)
    return res, counts


def _spec_boost_for(weights) -> int:
    """1 when the reweight vector meaningfully deviates from full-in
    (is_out() rejects then drive extra retries the topology-sized
    speculation window cannot predict), else 0."""
    if weights is None:
        return 0
    w = np.asarray(weights)
    if w.size == 0:
        return 0
    frac = np.count_nonzero(w != 0x10000) / w.size
    return 1 if frac > 0.02 else 0


def batched_rule_call(cm: CompiledMap, ruleno: int, result_max: int,
                      weights):
    """The jitted batched kernel plus its packed table operands —
    the dispatch seam mesh-sharded callers (osd/sharded_mapping.py)
    go through so they never re-implement table packing or the
    speculation-boost selection.  Returns ``(fn, tables)``; call as
    ``fn(xs_dev, weight_vector, *tables)`` with ``xs_dev`` placed
    under any sharding (the kernel is lane-independent) and get the
    raw ``(res, counts, ok)`` device arrays back — finalize with
    :func:`apply_oracle_fallback`."""
    fn = _batched(cm, ruleno, result_max, _spec_boost_for(weights))
    return fn, _kernel_tables(cm)


def batch_do_rule(
    cm: CompiledMap,
    ruleno: int,
    xs,
    result_max: int,
    weights=None,
):
    """Map a batch of inputs: xs (N,) -> (results (N, result_max) int32
    padded with CRUSH_ITEM_NONE, counts (N,)) as numpy arrays.
    ``weights`` is the 16.16 device reweight vector."""
    if weights is None:
        weights = np.full(max(cm.max_devices, 1), 0x10000, np.int32)
    if isinstance(xs, jax.Array):
        # already on device (possibly mesh-sharded): leave it there
        xs_dev = xs.astype(jnp.int32)
    else:
        xs_dev = jnp.asarray(np.asarray(xs, dtype=np.int32))
    wv = jnp.asarray(weights, dtype=jnp.int32)
    res, counts, ok = _batched(
        cm, ruleno, result_max, _spec_boost_for(weights)
    )(xs_dev, wv, *_kernel_tables(cm))
    return apply_oracle_fallback(
        cm, ruleno, xs_dev, res, counts, ok, result_max, weights
    )


def batch_do_rule_range(
    cm: CompiledMap,
    ruleno: int,
    lo: int,
    n: int,
    result_max: int,
    weights=None,
    packed: bool = False,
):
    """Map the contiguous inputs [lo, lo+n): like ``batch_do_rule``
    but the input range materializes on device and the call returns
    WITHOUT blocking — callers overlap dispatch with host-side
    materialization of earlier results, then finish each chunk with
    ``apply_oracle_fallback(cm, ruleno, np.arange(lo, lo+n), *chunk,
    result_max, weights)``.  Returns (results, counts, ok) as device
    arrays.  ``packed`` ships results as int16/uint8 (halving the
    device→host bytes; apply_oracle_fallback unpacks) and requires
    every id magnitude < 32768."""
    if weights is None:
        weights = np.full(max(cm.max_devices, 1), 0x10000, np.int32)
    if packed and (
        cm.max_devices >= 32768
        or len(cm.bidx) >= 32768
        or result_max > 255
    ):
        packed = False  # ids/counts wouldn't fit the packed wire form
    wv = jnp.asarray(weights, dtype=jnp.int32)
    return _batched_range(
        cm, ruleno, result_max, n, packed, _spec_boost_for(weights)
    )(jnp.int32(lo), wv, *_kernel_tables(cm))


def make_chained_runner(
    cm: CompiledMap,
    ruleno: int,
    result_max: int,
    n: int,
    iters: int = 8,
    weights=None,
):
    """Benchmark harness: one jitted program that maps ``iters``
    consecutive n-PG ranges back-to-back ON DEVICE, consuming each
    round's results into a checksum that seeds the next round's input
    offset (so no round can be elided or overlapped away).  Returns
    ``run(lo) -> int`` which blocks until all iters*n mappings
    completed; wall-time / (iters*n) is the kernel's device-resident
    mapping rate with dispatch and host-transfer costs excluded —
    what a colocated host observes, since its PCIe transfer of the
    results is negligible next to the kernel (unlike this mount's
    development tunnel)."""
    if weights is None:
        weights = np.full(max(cm.max_devices, 1), 0x10000, np.int32)
    wv = jnp.asarray(weights, dtype=jnp.int32)
    key = ("chain", cm.skey, ruleno, result_max, n, iters)
    fn = _kernel_cache_get(key)
    if fn is None:
        rf = _make_rule_fn(cm, ruleno, result_max)
        has_args = cm.args_pack is not None
        has_tree = cm.tree_pack is not None

        def call(lo, wv, row_pack, *packs):
            args_pack, tree_pack = _unpack_tables(
                has_args, has_tree, packs
            )

            def body(i, acc):
                xs = (
                    lo
                    + acc % 7
                    + i * n
                    + jnp.arange(n, dtype=jnp.int32)
                )
                res, cnt, ok = jax.vmap(
                    lambda x: rf(
                        x, wv, row_pack, args_pack, tree_pack
                    )
                )(xs)
                return (
                    acc
                    + jnp.sum(res, dtype=jnp.int32)
                    + jnp.sum(cnt, dtype=jnp.int32)
                    + jnp.sum(ok, dtype=jnp.int32)
                ).astype(jnp.int32)

            return lax.fori_loop(0, iters, body, jnp.int32(0))

        fn = jax.jit(call)
        _kernel_cache_put(key, fn)

    tables = _kernel_tables(cm)

    def run(lo: int) -> int:
        return int(fn(jnp.int32(lo), wv, *tables))

    return run
