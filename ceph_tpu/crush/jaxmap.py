"""Batched CRUSH on device — the ParallelPGMapper replacement.

The reference recomputes every PG's placement by sharding pgid ranges
over a thread pool (src/osd/OSDMapMapping.h:18-156); here the whole map
compiles to dense arrays and ``crush_do_rule`` becomes a scalar-traced
function vmapped over the PG batch: one device call maps a million PGs.

Scope (v1): straw2 hierarchies (every bucket alg CRUSH_BUCKET_STRAW2 —
the modern default and the 10k-OSD benchmark config), tunables with
choose_local_tries == choose_local_fallback_tries == 0 (true of every
profile since bobtail), rule programs of [SET_*...] TAKE CHOOSE[LEAF]
EMIT groups.  Anything else raises UnsupportedMap and callers fall back
to the exact Python oracle (ceph_tpu.crush.mapper) — the same
plugin-style split the EC backends use.

Exactness: int64 fixed-point draws (jax_enable_x64 required — enabled
at import), identical hash/ln tables, and the same r'-advancement and
retry semantics as mapper.c; verified against the oracle in
tests/test_crush_jax.py.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402

from .hashing import _mix_inner  # noqa: E402
from .ln import _tables as _ln_tables  # noqa: E402
from .types import (  # noqa: E402
    CRUSH_BUCKET_STRAW2,
    CRUSH_ITEM_NONE,
    CRUSH_ITEM_UNDEF,
    CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP,
    CRUSH_RULE_EMIT,
    CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
    CRUSH_RULE_SET_CHOOSELEAF_STABLE,
    CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
    CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE,
)

MAX_DEPTH = 16  # CRUSH_MAX_DEPTH is 10; headroom is free in a fori

# descend status codes
_FOUND, _EMPTY, _BAD = 0, 1, 2


class UnsupportedMap(ValueError):
    """Map/rule shape outside the device kernel's scope; use the oracle."""


# -- device-side primitives ------------------------------------------------


def _hash3(a, b, c):
    """rjenkins1 arity 3 on uint32 jnp values (hash.c:48-59)."""
    h = jnp.uint32(1315423911) ^ a ^ b ^ c
    x0, y0 = jnp.uint32(231232), jnp.uint32(1232)
    a, b, h = _mix_inner(a, b, h)
    c, x, h = _mix_inner(c, x0, h)
    y, a, h = _mix_inner(y0, a, h)
    b, x, h = _mix_inner(b, x, h)
    y, c, h = _mix_inner(y, c, h)
    return h.astype(jnp.uint32)


def _hash2(a, b):
    """rjenkins1 arity 2 (hash.c:37-46)."""
    h = jnp.uint32(1315423911) ^ a ^ b
    x0, y0 = jnp.uint32(231232), jnp.uint32(1232)
    a, b, h = _mix_inner(a, b, h)
    x, a, h = _mix_inner(x0, a, h)
    b, y, h = _mix_inner(b, y0, h)
    return h.astype(jnp.uint32)


@functools.lru_cache(maxsize=1)
def _ln_consts():
    # plain numpy int64 — jnp would cache trace-scoped tracers here
    rh, lh, ll = _ln_tables()
    return rh, lh, ll


def _crush_ln(u):
    """2^44*log2(u+1) in fixed point (mapper.c:248-290), u uint32."""
    rh, lh, ll = _ln_consts()
    rh_tbl = jnp.asarray(rh, dtype=jnp.int64)
    lh_tbl = jnp.asarray(lh, dtype=jnp.int64)
    ll_tbl = jnp.asarray(ll, dtype=jnp.int64)
    x = u.astype(jnp.int64) + 1
    masked = x & 0x1FFFF
    nbits = jnp.zeros_like(x)
    for shift in (16, 8, 4, 2, 1):
        step = (masked >> shift) != 0
        nbits = nbits + jnp.where(step, shift, 0)
        masked = jnp.where(step, masked >> shift, masked)
    bitlen = nbits + (masked != 0)
    shift_amt = jnp.where((x & 0x18000) == 0, 16 - bitlen, 0)
    x = x << shift_amt
    iexpon = 15 - shift_amt
    k = ((x >> 8) << 1) - 256 >> 1
    # x*RH reaches 2^63; like the C, only the wrapped low bits feed index2
    xl64 = (x * rh_tbl[k]) >> 48
    index2 = xl64 & 0xFF
    return (iexpon << 44) + ((lh_tbl[k] + ll_tbl[index2]) >> 4)


# -- map compilation -------------------------------------------------------


@dataclass(frozen=True)
class CompiledMap:
    """Dense-array rendering of a CrushMap for the device kernel."""

    items: jnp.ndarray  # (nb, sz) int32 — bucket members (neg = bucket)
    weights: jnp.ndarray  # (nb, sz) int64 — 16.16 straw2 weights
    sizes: jnp.ndarray  # (nb,) int32
    types: jnp.ndarray  # (nb,) int32
    bidx: jnp.ndarray  # (max_neg,) int32 — (-1-id) -> bucket row, -1 gap
    max_devices: int
    tunables: tuple  # (total_tries, descend_once, vary_r, stable)
    rules: tuple  # immutable rule description for cache keys

    def __hash__(self):
        return hash((id(self.items), self.rules, self.tunables))

    def __eq__(self, other):
        return self is other


def compile_map(cmap) -> CompiledMap:
    """CrushMap -> dense arrays; raises UnsupportedMap outside scope."""
    t = cmap.tunables
    if t.choose_local_tries or t.choose_local_fallback_tries:
        raise UnsupportedMap(
            "choose_local_(fallback_)tries != 0 needs the legacy perm "
            "fallback; use the oracle"
        )
    if not cmap.buckets:
        raise UnsupportedMap("empty map")
    for b in cmap.buckets.values():
        if b.alg != CRUSH_BUCKET_STRAW2:
            raise UnsupportedMap(
                f"bucket {b.id} alg {b.alg}: device kernel is straw2-only"
            )
    if cmap.choose_args:
        raise UnsupportedMap("choose_args not yet in the device kernel")

    nb = len(cmap.buckets)
    sz = max(b.size for b in cmap.buckets.values())
    sz = max(sz, 1)
    items = np.zeros((nb, sz), dtype=np.int32)
    weights = np.zeros((nb, sz), dtype=np.int64)
    sizes = np.zeros(nb, dtype=np.int32)
    types = np.zeros(nb, dtype=np.int32)
    max_neg = max(-b.id for b in cmap.buckets.values())
    bidx = np.full(max_neg, -1, dtype=np.int32)
    for row, b in enumerate(sorted(cmap.buckets.values(), key=lambda b: -b.id)):
        items[row, : b.size] = b.items
        weights[row, : b.size] = b.item_weights
        sizes[row] = b.size
        types[row] = b.type
        bidx[-1 - b.id] = row

    rules = []
    for rule in cmap.rules:
        rules.append(None if rule is None else _compile_rule(rule))

    return CompiledMap(
        items=jnp.asarray(items),
        weights=jnp.asarray(weights),
        sizes=jnp.asarray(sizes),
        types=jnp.asarray(types),
        bidx=jnp.asarray(bidx),
        max_devices=cmap.max_devices,
        tunables=(
            t.choose_total_tries + 1,
            t.chooseleaf_descend_once,
            t.chooseleaf_vary_r,
            t.chooseleaf_stable,
        ),
        rules=tuple(rules),
    )


def _compile_rule(rule):
    """Rule -> tuple of (op, arg1, arg2) groups: [set-overrides..., take,
    choose, emit] repeated; raises UnsupportedMap on other shapes."""
    groups = []
    overrides = {}
    take = None
    choose = None
    for step in rule.steps:
        if step.op in (
            CRUSH_RULE_SET_CHOOSE_TRIES,
            CRUSH_RULE_SET_CHOOSELEAF_TRIES,
            CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
            CRUSH_RULE_SET_CHOOSELEAF_STABLE,
            CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
            CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
        ):
            if step.op in (
                CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
                CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
            ):
                if step.arg1 > 0:
                    raise UnsupportedMap("local tries override")
                continue
            overrides[step.op] = step.arg1
        elif step.op == CRUSH_RULE_TAKE:
            take = step.arg1
        elif step.op in (
            CRUSH_RULE_CHOOSE_FIRSTN,
            CRUSH_RULE_CHOOSELEAF_FIRSTN,
            CRUSH_RULE_CHOOSE_INDEP,
            CRUSH_RULE_CHOOSELEAF_INDEP,
        ):
            if take is None or choose is not None:
                raise UnsupportedMap("rule shape: choose without take")
            choose = (step.op, step.arg1, step.arg2)
        elif step.op == CRUSH_RULE_EMIT:
            if take is None or choose is None:
                raise UnsupportedMap("rule shape: emit without choose")
            groups.append(
                (take, choose, tuple(sorted(overrides.items())))
            )
            take = choose = None
        else:
            raise UnsupportedMap(f"rule op {step.op}")
    if take is not None or choose is not None:
        raise UnsupportedMap("rule does not end with EMIT")
    return tuple(groups)


# -- the kernel ------------------------------------------------------------


def _make_rule_fn(cm: CompiledMap, ruleno: int, result_max: int):
    """Build the scalar-traced do_rule for one (map, rule, result_max)."""
    groups = cm.rules[ruleno]
    if groups is None:
        raise UnsupportedMap(f"no rule {ruleno}")
    total_tries, descend_once, vary_r_t, stable_t = cm.tunables
    NONE = jnp.int32(CRUSH_ITEM_NONE)
    UNDEF = jnp.int32(CRUSH_ITEM_UNDEF)
    S64_MIN = jnp.int64(-(1 << 63))

    def straw2(bidx_row, x, r):
        """One straw2 draw-argmax (mapper.c:361-384)."""
        ids = cm.items[bidx_row]
        ws = cm.weights[bidx_row]
        slot = jnp.arange(ids.shape[0])
        u = (
            _hash3(
                jnp.uint32(x),
                ids.astype(jnp.uint32),
                jnp.uint32(r),
            ).astype(jnp.int64)
            & 0xFFFF
        )
        ln = _crush_ln(u.astype(jnp.uint32)) - jnp.int64(0x1000000000000)
        draw = jnp.where(
            ws > 0, -((-ln) // jnp.maximum(ws, 1)), S64_MIN
        )
        draw = jnp.where(slot < cm.sizes[bidx_row], draw, S64_MIN)
        return ids[jnp.argmax(draw)]

    def row_of(item):
        """Bucket row for a (negative) item; -1 if invalid."""
        neg = -1 - item
        ok = (item < 0) & (neg < cm.bidx.shape[0])
        return jnp.where(ok, cm.bidx[jnp.clip(neg, 0, None)], -1)

    def descend(start_row, x, r, ttype):
        """Walk intermediate buckets until an item of ttype
        (mapper.c firstn/indep inner descent; r is constant per level
        for straw2).  Returns (item, status)."""

        def body(_, st):
            cur_row, item, status, done = st
            empty = cm.sizes[cur_row] == 0
            nitem = straw2(cur_row, x, r)
            bad_dev = nitem >= cm.max_devices
            nrow = row_of(nitem)
            ntype = jnp.where(nitem >= 0, 0, cm.types[jnp.maximum(nrow, 0)])
            invalid = (nitem < 0) & (nrow < 0)
            found = (~empty) & (~bad_dev) & (~invalid) & (ntype == ttype)
            bad = (~empty) & (bad_dev | ((ntype != ttype) & ((nitem >= 0) | invalid)))
            nstatus = jnp.where(
                empty,
                _EMPTY,
                jnp.where(found, _FOUND, jnp.where(bad, _BAD, status)),
            )
            ndone = empty | found | bad
            keep = done
            return (
                jnp.where(keep | ndone, cur_row, nrow),
                jnp.where(keep, item, nitem),
                jnp.where(keep, status, nstatus),
                keep | ndone,
            )

        init = (start_row, jnp.int32(0), jnp.int32(_BAD), jnp.bool_(False))
        _, item, status, done = lax.fori_loop(0, MAX_DEPTH, body, init)
        return item, jnp.where(done, status, _BAD)

    def is_out(weightv, item, x):
        """mapper.c:424-438 over the device reweight vector."""
        w = weightv[jnp.clip(item, 0, weightv.shape[0] - 1)]
        oob = item >= weightv.shape[0]
        hashed = (
            _hash2(jnp.uint32(x), jnp.uint32(item)).astype(jnp.int64)
            & 0xFFFF
        )
        return oob | (w == 0) | ((w < 0x10000) & (hashed >= w))

    def leaf_firstn(domain_item, x, sub_r, out2, outpos, weightv, tries, stable):
        """Inner chooseleaf: one leaf under domain_item (the recursive
        crush_choose_firstn with numrep=1/outpos+1, type=0)."""
        rep = jnp.where(stable, 0, outpos)
        drow = row_of(domain_item)

        def cond(st):
            ftotal, _, placed, skip = st
            return (~placed) & (~skip)

        def body(st):
            ftotal, _, _, _ = st
            r = rep + sub_r + ftotal
            item, status = descend(drow, x, r, 0)
            ok = status == _FOUND
            collide = jnp.any(
                (jnp.arange(out2.shape[0]) < outpos) & (out2 == item)
            )
            rejected = ok & (collide | is_out(weightv, item, x))
            placed = ok & (~rejected)
            # EMPTY and reject both advance ftotal; BAD skips the rep
            skip = (status == _BAD) | (
                (~placed) & (ftotal + 1 >= tries)
            )
            return (ftotal + 1, item, placed, skip)

        _, item, placed, _ = lax.while_loop(
            cond, body, (jnp.int32(0), jnp.int32(0), jnp.bool_(False), jnp.bool_(False))
        )
        return item, placed

    def choose_firstn(take_row, x, numrep, ttype, leaf, weightv, tries, leaf_tries, vary_r, stable):
        """Top-level crush_choose_firstn (outpos=0 frame)."""
        out = jnp.full((numrep,), NONE, dtype=jnp.int32)
        out2 = jnp.full((numrep,), NONE, dtype=jnp.int32)
        outpos = jnp.int32(0)

        for rep in range(numrep):

            def cond(st):
                ftotal, _, _, placed, skip = st
                return (~placed) & (~skip)

            def body(st, _rep=rep):
                ftotal, _, _, _, _ = st
                r = _rep + ftotal
                item, status = descend(take_row, x, r, ttype)
                ok = status == _FOUND
                collide = ok & jnp.any(
                    (jnp.arange(numrep) < outpos) & (out == item)
                )
                reject = jnp.bool_(False)
                leaf_item = jnp.int32(0)
                if leaf:
                    sub_r = (r >> (vary_r - 1)) if vary_r else jnp.int32(0)
                    is_bucket = item < 0
                    li, got = leaf_firstn(
                        jnp.where(is_bucket, item, jnp.int32(-1)),
                        x,
                        sub_r,
                        out2,
                        outpos,
                        weightv,
                        leaf_tries,
                        stable,
                    )
                    leaf_item = jnp.where(is_bucket, li, item)
                    reject = ok & (~collide) & is_bucket & (~got)
                if ttype == 0:
                    reject = reject | (
                        ok & (~collide) & is_out(weightv, item, x)
                    )
                placed = ok & (~collide) & (~reject)
                skip = (status == _BAD) | (
                    (~placed) & (ftotal + 1 >= tries)
                )
                return (ftotal + 1, item, leaf_item, placed, skip)

            init = (
                jnp.int32(0),
                jnp.int32(0),
                jnp.int32(0),
                jnp.bool_(False),
                jnp.bool_(False),
            )
            _, item, leaf_item, placed, _ = lax.while_loop(cond, body, init)
            out = jnp.where(
                placed & (jnp.arange(numrep) == outpos), item, out
            )
            if leaf:
                out2 = jnp.where(
                    placed & (jnp.arange(numrep) == outpos), leaf_item, out2
                )
            outpos = outpos + placed.astype(jnp.int32)

        return (out2 if leaf else out), outpos

    def leaf_indep(domain_item, x, rep, parent_r, numrep, weightv, tries):
        """Inner chooseleaf indep: the recursive call with left=1 at
        slot ``rep`` (outpos=rep), so r' = rep + parent_r + n*ftotal';
        no collisions possible in a one-slot region."""
        drow = row_of(domain_item)

        def cond(st):
            ftotal, item = st
            return (item == UNDEF) & (ftotal < tries)

        def body(st):
            ftotal, _ = st
            r = rep + parent_r + numrep * ftotal
            item, status = descend(drow, x, r, 0)
            ok = (status == _FOUND) & ~is_out(weightv, item, x)
            bad = status == _BAD
            nitem = jnp.where(ok, item, jnp.where(bad, NONE, UNDEF))
            return (ftotal + 1, nitem)

        _, item = lax.while_loop(cond, body, (jnp.int32(0), UNDEF))
        return jnp.where(item == UNDEF, NONE, item)

    def choose_indep(take_row, x, left0, numrep, ttype, leaf, weightv, tries, leaf_tries):
        """Top-level crush_choose_indep (outpos=0 frame, left0 slots;
        ``numrep`` is the unclamped replica count — it sets the r'
        stride even when left0 < numrep)."""
        out = jnp.full((left0,), UNDEF, dtype=jnp.int32)
        out2 = jnp.full((left0,), UNDEF, dtype=jnp.int32)

        def cond(st):
            out, _, left, ftotal = st
            return (left > 0) & (ftotal < tries)

        def body(st):
            out, out2, left, ftotal = st
            for rep in range(left0):
                undef = out[rep] == UNDEF
                r = rep + numrep * ftotal
                item, status = descend(take_row, x, r, ttype)
                ok = status == _FOUND
                hard_bad = status == _BAD
                collide = ok & jnp.any(out == item)
                leaf_ok = jnp.bool_(True)
                leaf_item = item
                if leaf:
                    is_bucket = item < 0
                    li = leaf_indep(
                        jnp.where(is_bucket, item, jnp.int32(-1)),
                        x,
                        rep,
                        r,
                        numrep,
                        weightv,
                        leaf_tries,
                    )
                    leaf_item = jnp.where(is_bucket, li, item)
                    leaf_ok = jnp.where(is_bucket, li != NONE, True)
                outed = (
                    ok & (ttype == 0) & is_out(weightv, item, x)
                    if ttype == 0
                    else jnp.bool_(False)
                )
                place = undef & ok & (~collide) & leaf_ok & (~outed)
                kill = undef & hard_bad  # slot permanently NONE
                sel = jnp.arange(left0) == rep
                out = jnp.where(
                    sel & place, item, jnp.where(sel & kill, NONE, out)
                )
                if leaf:
                    out2 = jnp.where(
                        sel & place,
                        leaf_item,
                        jnp.where(sel & kill, NONE, out2),
                    )
                left = left - (place | kill).astype(jnp.int32)
            return (out, out2, left, ftotal + 1)

        out, out2, _, _ = lax.while_loop(
            cond, body, (out, out2, jnp.int32(left0), jnp.int32(0))
        )
        out = jnp.where(out == UNDEF, NONE, out)
        out2 = jnp.where(out2 == UNDEF, NONE, out2)
        return (out2 if leaf else out), jnp.int32(left0)

    def rule_fn(x, weightv):
        """Full do_rule for one x; returns (result, count) padded with
        NONE to result_max."""
        result = jnp.full((result_max,), NONE, dtype=jnp.int32)
        rlen = jnp.int32(0)
        for take, (op, arg1, arg2), overrides in groups:
            ov = dict(overrides)
            tries = ov.get(CRUSH_RULE_SET_CHOOSE_TRIES, total_tries)
            leaf_override = ov.get(CRUSH_RULE_SET_CHOOSELEAF_TRIES, 0)
            vary_r = ov.get(CRUSH_RULE_SET_CHOOSELEAF_VARY_R, vary_r_t)
            stable = ov.get(CRUSH_RULE_SET_CHOOSELEAF_STABLE, stable_t)
            numrep = arg1 if arg1 > 0 else result_max + arg1
            if numrep <= 0:
                continue
            # slots are bounded by result_max (the C bounds firstn by
            # count and indep by out_size); the r' stride keeps the
            # unclamped numrep
            nslots = min(numrep, result_max)
            if take >= 0:
                raise UnsupportedMap("TAKE of a device (not a bucket)")
            if -1 - take >= cm.bidx.shape[0]:
                raise UnsupportedMap(f"TAKE of unknown bucket {take}")
            take_row = int(np.asarray(cm.bidx)[-1 - take])
            if take_row < 0:
                raise UnsupportedMap(f"TAKE of unknown bucket {take}")
            firstn = op in (
                CRUSH_RULE_CHOOSE_FIRSTN,
                CRUSH_RULE_CHOOSELEAF_FIRSTN,
            )
            leaf = op in (
                CRUSH_RULE_CHOOSELEAF_FIRSTN,
                CRUSH_RULE_CHOOSELEAF_INDEP,
            )
            if firstn:
                if leaf_override:
                    leaf_tries = leaf_override
                elif descend_once:
                    leaf_tries = 1
                else:
                    leaf_tries = tries
                got, n = choose_firstn(
                    take_row, x, nslots, arg2, leaf, weightv,
                    tries, leaf_tries, vary_r, stable,
                )
            else:
                leaf_tries = leaf_override if leaf_override else 1
                got, n = choose_indep(
                    take_row, x, nslots, numrep, arg2, leaf, weightv,
                    tries, leaf_tries,
                )
            # append got[:n] to result at rlen
            for i in range(nslots):
                slot = rlen + i
                valid = (i < n) & (slot < result_max)
                result = jnp.where(
                    valid & (jnp.arange(result_max) == slot),
                    got[i],
                    result,
                )
            rlen = jnp.minimum(rlen + n, result_max)
        return result, rlen

    return rule_fn


@functools.lru_cache(maxsize=64)
def _batched(cm: CompiledMap, ruleno: int, result_max: int):
    fn = _make_rule_fn(cm, ruleno, result_max)
    return jax.jit(jax.vmap(fn, in_axes=(0, None)))


def batch_do_rule(
    cm: CompiledMap,
    ruleno: int,
    xs,
    result_max: int,
    weights=None,
):
    """Map a batch of inputs: xs (N,) -> (results (N, result_max) int32
    padded with CRUSH_ITEM_NONE, counts (N,)).  ``weights`` is the
    16.16 device reweight vector."""
    if weights is None:
        weights = np.full(max(cm.max_devices, 1), 0x10000, np.int64)
    xs = jnp.asarray(xs, dtype=jnp.int32)
    wv = jnp.asarray(weights, dtype=jnp.int64)
    return _batched(cm, ruleno, result_max)(xs, wv)
