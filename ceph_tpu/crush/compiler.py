"""CrushCompiler: reference text crushmap ⇄ CrushMap ⇄ reference binary.

Implements the REFERENCE formats so real-world maps flow in and out:

- binary: CrushWrapper::encode/decode (CrushWrapper.cc:2929/:3105) —
  CRUSH_MAGIC header, per-row buckets with alg-specific payloads,
  rules with packed masks, 32-or-64-key name maps, trailing tunables,
  device classes and choose_args maps (luminous layout);
- text: the CrushCompiler grammar (CrushCompiler.cc) — tunable lines,
  devices with classes, types, DFS-ordered bucket blocks with shadow
  ``id -N class c`` lines, rules with take/choose/set_* steps, and
  choose_args blocks.  ``decompile`` mirrors the reference's exact
  formatting (tabs, fixed-point %.3f, pos annotations) so that, like
  the reference's compile-decompile-recompile.t, text that came from a
  decompile round-trips byte-for-byte.

Decoded maps drop straight into the oracle and the device kernel: the
alg-specific payloads (straws, sum_weights, node_weights) are kept as
stored, not recomputed, exactly as the C decode does.
"""

from __future__ import annotations

import re
import struct

from .builder import CrushMap
from .types import (
    CRUSH_BUCKET_LIST,
    CRUSH_BUCKET_STRAW,
    CRUSH_BUCKET_STRAW2,
    CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_UNIFORM,
    CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP,
    CRUSH_RULE_EMIT,
    CRUSH_RULE_NOOP,
    CRUSH_RULE_SET_CHOOSELEAF_STABLE,
    CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
    CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
    CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE,
    Bucket,
    ChooseArg,
    Rule,
    RuleStep,
    Tunables,
)

CRUSH_MAGIC = 0x00010000
# (1<<uniform)|(1<<list)|(1<<straw) — crush.h CRUSH_LEGACY_ALLOWED_BUCKET_ALGS
LEGACY_ALLOWED_BUCKET_ALGS = (1 << 1) | (1 << 2) | (1 << 4)

ALG_NAMES = {
    CRUSH_BUCKET_UNIFORM: "uniform",
    CRUSH_BUCKET_LIST: "list",
    CRUSH_BUCKET_TREE: "tree",
    CRUSH_BUCKET_STRAW: "straw",
    CRUSH_BUCKET_STRAW2: "straw2",
}
ALG_IDS = {v: k for k, v in ALG_NAMES.items()}

PG_TYPE_REPLICATED = 1  # CEPH_PG_TYPE_REPLICATED
PG_TYPE_ERASURE = 3  # CEPH_PG_TYPE_ERASURE


class CrushCompilerError(ValueError):
    pass


# -- binary codec ----------------------------------------------------------


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def _unpack(self, fmt: str):
        size = struct.calcsize(fmt)
        if self.off + size > len(self.data):
            raise CrushCompilerError("truncated crushmap blob")
        (v,) = struct.unpack_from(fmt, self.data, self.off)
        self.off += size
        return v

    def u8(self):
        return self._unpack("<B")

    def u16(self):
        return self._unpack("<H")

    def u32(self):
        return self._unpack("<I")

    def s32(self):
        return self._unpack("<i")

    def s64(self):
        return self._unpack("<q")

    def string(self, n: int) -> str:
        if self.off + n > len(self.data):
            raise CrushCompilerError("truncated string")
        v = self.data[self.off : self.off + n].decode("utf-8")
        self.off += n
        return v

    @property
    def end(self) -> bool:
        return self.off >= len(self.data)


class _Writer:
    def __init__(self):
        self.parts: list[bytes] = []

    def pack(self, fmt: str, v):
        self.parts.append(struct.pack(fmt, v))

    def u8(self, v):
        self.pack("<B", v)

    def u16(self, v):
        self.pack("<H", v)

    def u32(self, v):
        self.pack("<I", v & 0xFFFFFFFF)

    def s32(self, v):
        self.pack("<i", v)

    def s64(self, v):
        self.pack("<q", v)

    def string(self, s: str):
        raw = s.encode("utf-8")
        self.u32(len(raw))
        self.parts.append(raw)

    def bytes(self) -> bytes:
        return b"".join(self.parts)


def _decode_string_map(r: _Reader) -> dict[int, str]:
    """map<int32,string> with the reference's 32-or-64-bit-key
    tolerance (decode_32_or_64_string_map, CrushWrapper.cc:3086):
    a zero 'length' means the key was 64-bit and the real length
    follows."""
    out: dict[int, str] = {}
    n = r.u32()
    for _ in range(n):
        key = r.s32()
        strlen = r.u32()
        if strlen == 0:
            strlen = r.u32()
        out[key] = r.string(strlen)
    return out


def _encode_string_map(w: _Writer, m: dict[int, str]):
    w.u32(len(m))
    for key in sorted(m):
        w.s32(key)
        w.string(m[key])


def decode_crushmap(data: bytes) -> CrushMap:
    """CrushWrapper::decode (CrushWrapper.cc:3105) over a reference
    binary crushmap blob.  Trailing sections are optional exactly as
    in the reference (legacy tunables when absent)."""
    r = _Reader(data)
    if r.u32() != CRUSH_MAGIC:
        raise CrushCompilerError("bad magic number")
    max_buckets = r.s32()
    max_rules = r.u32()
    max_devices = r.s32()

    m = CrushMap(tunables=Tunables(2, 5, 19, 0, 0, 0, 0))
    m.type_names = {}
    m.max_devices = max_devices
    # preserved so a re-encode keeps the original row/rule table sizes
    # (the reference encodes max_buckets/max_rules verbatim, including
    # trailing empty rows)
    m.binary_max_buckets = max_buckets
    m.binary_max_rules = max_rules
    row_ids: list[int | None] = []

    for _ in range(max_buckets):
        alg = r.u32()
        if alg == 0:
            row_ids.append(None)
            continue
        bid = r.s32()
        btype = r.u16()
        alg8 = r.u8()
        hash8 = r.u8()
        weight = r.u32()
        size = r.u32()
        items = [r.s32() for _ in range(size)]
        b = Bucket(
            id=bid, type=btype, alg=alg8, items=items,
            item_weights=[], hash=hash8, weight=weight,
        )
        if alg8 == CRUSH_BUCKET_UNIFORM:
            iw = r.u32()
            b.item_weights = [iw] * size
        elif alg8 == CRUSH_BUCKET_LIST:
            b.sum_weights = []
            for _ in range(size):
                b.item_weights.append(r.u32())
                b.sum_weights.append(r.u32())
        elif alg8 == CRUSH_BUCKET_TREE:
            num_nodes = r.u8()
            b.node_weights = [r.u32() for _ in range(num_nodes)]
            # item j sits at node 2j+1 (crush_calc_tree_node)
            b.item_weights = [
                b.node_weights[2 * j + 1]
                if 2 * j + 1 < num_nodes
                else 0
                for j in range(size)
            ]
        elif alg8 == CRUSH_BUCKET_STRAW:
            b.straws = []
            for _ in range(size):
                b.item_weights.append(r.u32())
                b.straws.append(r.u32())
        elif alg8 == CRUSH_BUCKET_STRAW2:
            b.item_weights = [r.u32() for _ in range(size)]
        else:
            raise CrushCompilerError(f"unknown bucket alg {alg8}")
        m.buckets[bid] = b
        row_ids.append(bid)

    for i in range(max_rules):
        if not r.u32():
            m.rules.append(None)
            continue
        length = r.u32()
        ruleset = r.u8()
        rtype = r.u8()
        min_size = r.u8()
        max_size = r.u8()
        steps = []
        for _ in range(length):
            op = r.u32()
            arg1 = r.s32()
            arg2 = r.s32()
            steps.append(RuleStep(op, arg1, arg2))
        m.rules.append(
            Rule(
                steps=steps, ruleset=ruleset, type=rtype,
                min_size=min_size, max_size=max_size,
            )
        )

    m.type_names = _decode_string_map(r)
    m.item_names = _decode_string_map(r)
    m.rule_names = _decode_string_map(r)

    t = m.tunables
    if not r.end:
        t.choose_local_tries = r.u32()
        t.choose_local_fallback_tries = r.u32()
        t.choose_total_tries = r.u32()
    if not r.end:
        t.chooseleaf_descend_once = r.u32()
    if not r.end:
        t.chooseleaf_vary_r = r.u8()
    if not r.end:
        t.straw_calc_version = r.u8()
    if not r.end:
        m.allowed_bucket_algs = r.u32()
    if not r.end:
        t.chooseleaf_stable = r.u8()
    if not r.end:
        # device classes (luminous+)
        n = r.u32()
        for _ in range(n):
            k = r.s32()
            m.class_map[k] = r.s32()
        m.class_names = {}
        n = r.u32()
        for _ in range(n):
            k = r.s32()
            m.class_names[k] = r.string(r.u32())
        n = r.u32()
        for _ in range(n):
            orig = r.s32()
            per: dict[int, int] = {}
            for _ in range(r.u32()):
                c = r.s32()
                per[c] = r.s32()
            m.class_bucket[orig] = per
    if not r.end:
        # choose_args: map<s64, per-bucket args>
        m.choose_args_maps = {}
        n_maps = r.u32()
        for _ in range(n_maps):
            key = r.s64()
            per: dict[int, ChooseArg] = {}
            n_args = r.u32()
            for _ in range(n_args):
                row = r.u32()
                if row >= len(row_ids) or row_ids[row] is None:
                    raise CrushCompilerError(
                        f"choose_arg for empty bucket row {row}"
                    )
                positions = r.u32()
                ws = None
                if positions:
                    ws = []
                    for _ in range(positions):
                        sz = r.u32()
                        ws.append([r.u32() for _ in range(sz)])
                ids_size = r.u32()
                ids = (
                    [r.s32() for _ in range(ids_size)]
                    if ids_size
                    else None
                )
                per[row_ids[row]] = ChooseArg(weight_set=ws, ids=ids)
            m.choose_args_maps[key] = per
        if m.choose_args_maps:
            # the active map: DEFAULT_CHOOSE_ARGS (-1) if present,
            # else the first (choose_args_get_with_fallback)
            active = (
                -1 if -1 in m.choose_args_maps
                else sorted(m.choose_args_maps)[0]
            )
            m.choose_args = dict(m.choose_args_maps[active])
    m.max_devices = max(
        m.max_devices,
        max(
            (i + 1 for b in m.buckets.values() for i in b.items if i >= 0),
            default=0,
        ),
    )
    m.touch()
    return m


def encode_crushmap(m: CrushMap) -> bytes:
    """CrushWrapper::encode with the modern feature set (tunables5 +
    classes + choose_args always present, like a luminous+ encode)."""
    w = _Writer()
    w.u32(CRUSH_MAGIC)
    max_buckets = max(
        max((-b for b in m.buckets), default=0),
        getattr(m, "binary_max_buckets", 0),
    )
    w.s32(max_buckets)
    nrules = max(len(m.rules), getattr(m, "binary_max_rules", 0))
    w.u32(nrules)
    w.s32(m.max_devices)

    rows: list[Bucket | None] = [None] * max_buckets
    for bid, b in m.buckets.items():
        rows[-1 - bid] = b
    for b in rows:
        if b is None:
            w.u32(0)
            continue
        w.u32(b.alg)
        w.s32(b.id)
        w.u16(b.type)
        w.u8(b.alg)
        w.u8(b.hash)
        w.u32(b.weight)
        w.u32(b.size)
        for item in b.items:
            w.s32(item)
        if b.alg == CRUSH_BUCKET_UNIFORM:
            w.u32(b.item_weights[0] if b.item_weights else 0)
        elif b.alg == CRUSH_BUCKET_LIST:
            for iw, sw in zip(b.item_weights, b.sum_weights or []):
                w.u32(iw)
                w.u32(sw)
        elif b.alg == CRUSH_BUCKET_TREE:
            nodes = b.node_weights or []
            w.u8(len(nodes))
            for nw in nodes:
                w.u32(nw)
        elif b.alg == CRUSH_BUCKET_STRAW:
            for iw, sv in zip(b.item_weights, b.straws or []):
                w.u32(iw)
                w.u32(sv)
        elif b.alg == CRUSH_BUCKET_STRAW2:
            for iw in b.item_weights:
                w.u32(iw)
        else:
            raise CrushCompilerError(f"unknown bucket alg {b.alg}")

    for i in range(nrules):
        rule = m.rules[i] if i < len(m.rules) else None
        if rule is None:
            w.u32(0)
            continue
        w.u32(1)
        w.u32(len(rule.steps))
        w.u8(rule.ruleset)
        w.u8(rule.type)
        w.u8(rule.min_size)
        w.u8(rule.max_size)
        for st in rule.steps:
            w.u32(st.op)
            w.s32(st.arg1)
            w.s32(st.arg2)

    _encode_string_map(w, m.type_names)
    _encode_string_map(w, m.item_names)
    _encode_string_map(w, m.rule_names)

    t = m.tunables
    w.u32(t.choose_local_tries)
    w.u32(t.choose_local_fallback_tries)
    w.u32(t.choose_total_tries)
    w.u32(t.chooseleaf_descend_once)
    w.u8(t.chooseleaf_vary_r)
    w.u8(t.straw_calc_version)
    w.u32(getattr(m, "allowed_bucket_algs", LEGACY_ALLOWED_BUCKET_ALGS))
    w.u8(t.chooseleaf_stable)

    # device classes
    w.u32(len(m.class_map))
    for k in sorted(m.class_map):
        w.s32(k)
        w.s32(m.class_map[k])
    w.u32(len(m.class_names))
    for k in sorted(m.class_names):
        w.s32(k)
        w.string(m.class_names[k])
    w.u32(len(m.class_bucket))
    for orig in sorted(m.class_bucket):
        w.s32(orig)
        per = m.class_bucket[orig]
        w.u32(len(per))
        for c in sorted(per):
            w.s32(c)
            w.s32(per[c])

    # choose_args
    maps = getattr(m, "choose_args_maps", None)
    if maps is None:
        maps = {-1: m.choose_args} if m.choose_args else {}
    w.u32(len(maps))
    for key in sorted(maps):
        w.s64(key)
        per = maps[key]
        live = {
            bid: a
            for bid, a in per.items()
            if (a.weight_set or a.ids)
        }
        w.u32(len(live))
        for bid in sorted(live, key=lambda b: -1 - b):
            a = live[bid]
            w.u32(-1 - bid)
            ws = a.weight_set or []
            w.u32(len(ws))
            for row in ws:
                w.u32(len(row))
                for wt in row:
                    w.u32(wt)
            ids = a.ids or []
            w.u32(len(ids))
            for i in ids:
                w.s32(i)
    return w.bytes()


# -- text: decompile -------------------------------------------------------


def _fixedpoint(v: int) -> str:
    return "%.3f" % (float(v) / float(0x10000))


def _type_name(m: CrushMap, t: int) -> str:
    name = m.type_names.get(t)
    if name is not None:
        return name
    return "device" if t == 0 else f"type{t}"


def _item_name(m: CrushMap, item: int) -> str:
    name = m.item_names.get(item)
    if name is not None:
        return name
    return f"device{item}" if item >= 0 else f"bucket{-1 - item}"


def _split_id_class(m: CrushMap, item: int) -> tuple[int, int | None]:
    """Shadow id -> (original id, class) (CrushWrapper::split_id_class)."""
    for orig, per in m.class_bucket.items():
        for c, cid in per.items():
            if cid == item:
                return orig, c
    return item, None


def decompile_crushmap(m: CrushMap) -> str:
    """CrushCompiler::decompile (CrushCompiler.cc:302): byte-compatible
    formatting, children-before-parents bucket order, shadow buckets
    folded into ``id -N class c`` lines."""
    out: list[str] = ["# begin crush map\n"]
    t = m.tunables
    if t.choose_local_tries != 2:
        out.append(f"tunable choose_local_tries {t.choose_local_tries}\n")
    if t.choose_local_fallback_tries != 5:
        out.append(
            "tunable choose_local_fallback_tries "
            f"{t.choose_local_fallback_tries}\n"
        )
    if t.choose_total_tries != 19:
        out.append(f"tunable choose_total_tries {t.choose_total_tries}\n")
    if t.chooseleaf_descend_once != 0:
        out.append(
            f"tunable chooseleaf_descend_once {t.chooseleaf_descend_once}\n"
        )
    if t.chooseleaf_vary_r != 0:
        out.append(f"tunable chooseleaf_vary_r {t.chooseleaf_vary_r}\n")
    if t.chooseleaf_stable != 0:
        out.append(f"tunable chooseleaf_stable {t.chooseleaf_stable}\n")
    if t.straw_calc_version != 0:
        out.append(f"tunable straw_calc_version {t.straw_calc_version}\n")
    allowed = getattr(m, "allowed_bucket_algs", LEGACY_ALLOWED_BUCKET_ALGS)
    if allowed != LEGACY_ALLOWED_BUCKET_ALGS:
        out.append(f"tunable allowed_bucket_algs {allowed}\n")

    out.append("\n# devices\n")
    for i in range(m.max_devices):
        name = m.item_names.get(i)
        if name is not None:
            line = f"device {i} {name}"
            if i in m.class_map and m.class_map[i] in m.class_names:
                line += f" class {m.class_names[m.class_map[i]]}"
            out.append(line + "\n")

    out.append("\n# types\n")
    # iterate the map directly (scanning i upward until every name is
    # seen would hang on a negative key a malformed blob can carry)
    if 0 not in m.type_names:
        out.append("type 0 osd\n")
    for i in sorted(m.type_names):
        out.append(f"type {i} {m.type_names[i]}\n")

    out.append("\n# buckets\n")
    shadows = {
        cid for per in m.class_bucket.values() for cid in per.values()
    }
    emitted: set[int] = set()

    def emit_bucket(bid: int):
        if bid in emitted or bid not in m.buckets:
            return
        emitted.add(bid)
        b = m.buckets[bid]
        for item in b.items:
            if item < 0:
                emit_bucket(item)
        name = m.item_names.get(bid)
        if name is not None and "~" in name:
            return  # shadow bucket: folded into id lines
        out.append(f"{_type_name(m, b.type)} {_item_name(m, bid)} {{\n")
        out.append(f"\tid {bid}\t\t# do not change unnecessarily\n")
        for c, cid in sorted(m.class_bucket.get(bid, {}).items()):
            cname = m.class_names.get(c)
            out.append(
                f"\tid {cid} class {cname}\t\t"
                "# do not change unnecessarily\n"
            )
        out.append(f"\t# weight {_fixedpoint(b.weight)}\n")
        alg_line = f"\talg {ALG_NAMES[b.alg]}"
        dopos = False
        if b.alg == CRUSH_BUCKET_UNIFORM:
            alg_line += (
                f"\t# do not change bucket size ({b.size}) unnecessarily"
            )
            dopos = True
        elif b.alg == CRUSH_BUCKET_LIST:
            alg_line += (
                "\t# add new items at the end; "
                "do not change order unnecessarily"
            )
        elif b.alg == CRUSH_BUCKET_TREE:
            alg_line += (
                "\t# do not change pos for existing items unnecessarily"
            )
            dopos = True
        out.append(alg_line + "\n")
        hname = "rjenkins1" if b.hash == 0 else f"hash{b.hash}"
        out.append(f"\thash {b.hash}\t# {hname}\n")
        for j, (item, iw) in enumerate(zip(b.items, b.item_weights)):
            line = (
                f"\titem {_item_name(m, item)} weight {_fixedpoint(iw)}"
            )
            if dopos:
                line += f" pos {j}"
            out.append(line + "\n")
        out.append("}\n")

    max_buckets = max((-b for b in m.buckets), default=0)
    for bid in range(-1, -1 - max_buckets, -1):
        if bid in shadows:
            continue
        emit_bucket(bid)

    out.append("\n# rules\n")
    for i, rule in enumerate(m.rules):
        if rule is None:
            continue
        rname = m.rule_names.get(i, f"rule{i}")
        out.append(f"rule {rname} {{\n")
        out.append(f"\tid {i}\n")
        if i != rule.ruleset:
            out.append(
                f"\t# WARNING: ruleset {rule.ruleset} != id {i}; "
                "this will not recompile to the same map\n"
            )
        if rule.type == PG_TYPE_REPLICATED:
            out.append("\ttype replicated\n")
        elif rule.type == PG_TYPE_ERASURE:
            out.append("\ttype erasure\n")
        else:
            out.append(f"\ttype {rule.type}\n")
        out.append(f"\tmin_size {rule.min_size}\n")
        out.append(f"\tmax_size {rule.max_size}\n")
        for st in rule.steps:
            if st.op == CRUSH_RULE_NOOP:
                out.append("\tstep noop\n")
            elif st.op == CRUSH_RULE_TAKE:
                orig, c = _split_id_class(m, st.arg1)
                line = f"\tstep take {_item_name(m, orig)}"
                if c is not None:
                    line += f" class {m.class_names.get(c)}"
                out.append(line + "\n")
            elif st.op == CRUSH_RULE_EMIT:
                out.append("\tstep emit\n")
            elif st.op == CRUSH_RULE_SET_CHOOSE_TRIES:
                out.append(f"\tstep set_choose_tries {st.arg1}\n")
            elif st.op == CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES:
                out.append(f"\tstep set_choose_local_tries {st.arg1}\n")
            elif st.op == CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
                out.append(
                    f"\tstep set_choose_local_fallback_tries {st.arg1}\n"
                )
            elif st.op == CRUSH_RULE_SET_CHOOSELEAF_TRIES:
                out.append(f"\tstep set_chooseleaf_tries {st.arg1}\n")
            elif st.op == CRUSH_RULE_SET_CHOOSELEAF_VARY_R:
                out.append(f"\tstep set_chooseleaf_vary_r {st.arg1}\n")
            elif st.op == CRUSH_RULE_SET_CHOOSELEAF_STABLE:
                out.append(f"\tstep set_chooseleaf_stable {st.arg1}\n")
            elif st.op in (
                CRUSH_RULE_CHOOSE_FIRSTN,
                CRUSH_RULE_CHOOSE_INDEP,
                CRUSH_RULE_CHOOSELEAF_FIRSTN,
                CRUSH_RULE_CHOOSELEAF_INDEP,
            ):
                verb = (
                    "choose"
                    if st.op
                    in (CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP)
                    else "chooseleaf"
                )
                mode = (
                    "firstn"
                    if st.op
                    in (CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSELEAF_FIRSTN)
                    else "indep"
                )
                out.append(
                    f"\tstep {verb} {mode} {st.arg1} type "
                    f"{_type_name(m, st.arg2)}\n"
                )
            else:
                out.append(f"\tstep unknown {st.op} {st.arg1} {st.arg2}\n")
        out.append("}\n")

    maps = getattr(m, "choose_args_maps", None)
    if maps is None and m.choose_args:
        maps = {-1: m.choose_args}
    if maps:
        out.append("\n# choose_args\n")
        for key in sorted(maps):
            out.append(f"choose_args {key} {{\n")
            per = maps[key]
            for bid in sorted(per, key=lambda b: -1 - b):
                a = per[bid]
                if not (a.weight_set or a.ids):
                    continue
                out.append("  {\n")
                out.append(f"    bucket_id {bid}\n")
                if a.weight_set:
                    out.append("    weight_set [\n")
                    for row in a.weight_set:
                        out.append(
                            "      [ "
                            + " ".join(_fixedpoint(v) for v in row)
                            + " ]\n"
                        )
                    out.append("    ]\n")
                if a.ids:
                    out.append(
                        "    ids [ "
                        + " ".join(str(i) for i in a.ids)
                        + " ]\n"
                    )
                out.append("  }\n")
            out.append("}\n")

    out.append("\n# end crush map\n")
    return "".join(out)


# -- text: compile ---------------------------------------------------------


def _tokens(text: str):
    """Token stream with comments stripped; braces/brackets split."""
    for raw in text.splitlines():
        line = raw.split("#", 1)[0]
        line = (
            line.replace("{", " { ")
            .replace("}", " } ")
            .replace("[", " [ ")
            .replace("]", " ] ")
        )
        yield from line.split()


def _parse_weight(tok: str) -> int:
    return int(round(float(tok) * 0x10000))


def compile_crushmap(text: str) -> CrushMap:
    """CrushCompiler::compile over the text grammar: tunables, devices
    (with classes), types, buckets (with shadow id lines), rules
    (take ... [class c], choose/chooseleaf, set_*), choose_args."""
    toks = list(_tokens(text))
    pos = 0

    def peek():
        return toks[pos] if pos < len(toks) else None

    def next_tok():
        nonlocal pos
        if pos >= len(toks):
            raise CrushCompilerError("unexpected end of crushmap text")
        tok = toks[pos]
        pos += 1
        return tok

    def expect(val):
        tok = next_tok()
        if tok != val:
            raise CrushCompilerError(f"expected {val!r}, got {tok!r}")

    m = CrushMap(tunables=Tunables(2, 5, 19, 0, 0, 0, 0))
    m.type_names = {}
    name_to_id: dict[str, int] = {}

    def resolve_item(name: str) -> int | None:
        if name in name_to_id:
            return name_to_id[name]
        # print_item_name fallbacks for nameless items: deviceN /
        # bucketN round-trip back to their ids
        mdev = re.fullmatch(r"device(\d+)", name)
        if mdev:
            dev = int(mdev.group(1))
            m.max_devices = max(m.max_devices, dev + 1)
            return dev
        mbkt = re.fullmatch(r"bucket(\d+)", name)
        if mbkt:
            bid = -1 - int(mbkt.group(1))
            return bid if bid in m.buckets else None
        return None
    # declared shadow ids: bucket name -> {class name: id}
    declared_shadows: dict[int, dict[str, int]] = {}
    pending_rules = []

    while pos < len(toks):
        tok = next_tok()
        if tok == "tunable":
            key = next_tok()
            val = int(next_tok())
            t = m.tunables
            if key == "allowed_bucket_algs":
                m.allowed_bucket_algs = val
            elif hasattr(t, key):
                setattr(t, key, val)
            else:
                raise CrushCompilerError(f"unknown tunable {key!r}")
        elif tok == "device":
            dev = int(next_tok())
            name = next_tok()
            m.item_names[dev] = name
            name_to_id[name] = dev
            m.max_devices = max(m.max_devices, dev + 1)
            if peek() == "class":
                next_tok()
                m.set_item_class(dev, next_tok())
        elif tok == "type":
            tid = int(next_tok())
            m.type_names[tid] = next_tok()
        elif tok == "rule":
            name = next_tok()
            expect("{")
            rid = None
            rtype = PG_TYPE_REPLICATED
            min_size, max_size = 1, 10
            steps: list[RuleStep] = []
            while peek() != "}":
                key = next_tok()
                if key in ("id", "ruleset"):
                    rid = int(next_tok())
                elif key == "type":
                    v = next_tok()
                    rtype = {
                        "replicated": PG_TYPE_REPLICATED,
                        "erasure": PG_TYPE_ERASURE,
                    }.get(v)
                    if rtype is None:
                        rtype = int(v)
                elif key == "min_size":
                    min_size = int(next_tok())
                elif key == "max_size":
                    max_size = int(next_tok())
                elif key == "step":
                    op = next_tok()
                    if op == "take":
                        take_name = next_tok()
                        take_class = None
                        if peek() == "class":
                            next_tok()
                            take_class = next_tok()
                        steps.append(("take", take_name, take_class))
                    elif op == "emit":
                        steps.append(RuleStep(CRUSH_RULE_EMIT))
                    elif op == "noop":
                        steps.append(RuleStep(CRUSH_RULE_NOOP))
                    elif op in ("choose", "chooseleaf"):
                        mode = next_tok()
                        num = int(next_tok())
                        expect("type")
                        tname = next_tok()
                        tid = None
                        for k, v in m.type_names.items():
                            if v == tname:
                                tid = k
                        if tid is None:
                            raise CrushCompilerError(
                                f"type {tname!r} not defined"
                            )
                        opmap = {
                            ("choose", "firstn"): CRUSH_RULE_CHOOSE_FIRSTN,
                            ("choose", "indep"): CRUSH_RULE_CHOOSE_INDEP,
                            (
                                "chooseleaf",
                                "firstn",
                            ): CRUSH_RULE_CHOOSELEAF_FIRSTN,
                            (
                                "chooseleaf",
                                "indep",
                            ): CRUSH_RULE_CHOOSELEAF_INDEP,
                        }
                        steps.append(RuleStep(opmap[op, mode], num, tid))
                    elif op.startswith("set_"):
                        opmap = {
                            "set_choose_tries": CRUSH_RULE_SET_CHOOSE_TRIES,
                            "set_choose_local_tries":
                                CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
                            "set_choose_local_fallback_tries":
                                CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
                            "set_chooseleaf_tries":
                                CRUSH_RULE_SET_CHOOSELEAF_TRIES,
                            "set_chooseleaf_vary_r":
                                CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
                            "set_chooseleaf_stable":
                                CRUSH_RULE_SET_CHOOSELEAF_STABLE,
                        }
                        if op not in opmap:
                            raise CrushCompilerError(
                                f"unknown step {op!r}"
                            )
                        steps.append(RuleStep(opmap[op], int(next_tok())))
                    else:
                        raise CrushCompilerError(f"unknown step {op!r}")
                else:
                    raise CrushCompilerError(
                        f"unknown rule field {key!r}"
                    )
            expect("}")
            pending_rules.append(
                (name, rid, rtype, min_size, max_size, steps)
            )
        elif tok == "choose_args":
            key = int(next_tok())
            expect("{")
            per: dict[int, ChooseArg] = {}
            while peek() == "{":
                next_tok()
                bid = None
                ws = None
                ids = None
                while peek() != "}":
                    field = next_tok()
                    if field == "bucket_id":
                        bid = int(next_tok())
                    elif field == "weight_set":
                        expect("[")
                        ws = []
                        while peek() == "[":
                            next_tok()
                            row = []
                            while peek() != "]":
                                row.append(_parse_weight(next_tok()))
                            expect("]")
                            ws.append(row)
                        expect("]")
                    elif field == "ids":
                        expect("[")
                        ids = []
                        while peek() != "]":
                            ids.append(int(next_tok()))
                        expect("]")
                    else:
                        raise CrushCompilerError(
                            f"unknown choose_args field {field!r}"
                        )
                expect("}")
                if bid is None:
                    raise CrushCompilerError(
                        "choose_args entry without bucket_id"
                    )
                per[bid] = ChooseArg(weight_set=ws, ids=ids)
            expect("}")
            if not hasattr(m, "choose_args_maps"):
                m.choose_args_maps = {}
            m.choose_args_maps[key] = per
        else:
            # bucket block: "<type-name> <bucket-name> { ... }"
            tname, bname = tok, next_tok()
            btype = None
            for k, v in m.type_names.items():
                if v == tname:
                    btype = k
            if btype is None:
                raise CrushCompilerError(
                    f"type {tname!r} not defined (at bucket {bname!r})"
                )
            expect("{")
            bid = None
            alg = None
            hash_ = 0
            items: list[tuple[str, int | None, int | None]] = []
            shadow_ids: dict[str, int] = {}
            while peek() != "}":
                key = next_tok()
                if key == "id":
                    v = int(next_tok())
                    if peek() == "class":
                        next_tok()
                        shadow_ids[next_tok()] = v
                    else:
                        bid = v
                elif key == "alg":
                    aname = next_tok()
                    alg = ALG_IDS.get(aname)
                    if alg is None:
                        raise CrushCompilerError(
                            f"unknown bucket alg {aname!r}"
                        )
                elif key == "hash":
                    hash_ = int(next_tok())
                elif key == "item":
                    iname = next_tok()
                    iw = None
                    ipos = None
                    while peek() in ("weight", "pos"):
                        sub = next_tok()
                        if sub == "weight":
                            iw = _parse_weight(next_tok())
                        else:
                            ipos = int(next_tok())
                    items.append((iname, iw, ipos))
                else:
                    raise CrushCompilerError(
                        f"unknown bucket field {key!r}"
                    )
            expect("}")
            if alg is None:
                raise CrushCompilerError(f"bucket {bname!r} without alg")
            if bid is None:
                bid = min(m.buckets, default=0) - 1

            def default_weight(rid_: int) -> int:
                # omitted weight defaults to the child bucket's
                # computed rollup, or 1.0 for devices
                # (CrushCompiler.cc:680-682)
                if rid_ < 0 and rid_ in m.buckets:
                    return m.buckets[rid_].weight
                return 0x10000

            # honor declared pos; unannotated items fill the unused
            # slots in declaration order (CrushCompiler.cc:723-728)
            nslots = len(items)
            for _, _, ip in items:
                if ip is not None:
                    nslots = max(nslots, ip + 1)
            slots: list[tuple[int, int] | None] = [None] * nslots
            loose: list[tuple[int, int]] = []
            for it, iw, ip in items:
                rid_ = resolve_item(it)
                if rid_ is None:
                    raise CrushCompilerError(
                        f"in bucket {bname!r} item {it!r} not defined"
                    )
                entry = (
                    rid_, iw if iw is not None else default_weight(rid_)
                )
                if ip is not None:
                    if slots[ip] is not None:
                        raise CrushCompilerError(
                            f"bucket {bname!r} pos {ip} used twice"
                        )
                    slots[ip] = entry
                else:
                    loose.append(entry)
            for i in range(nslots):
                if slots[i] is None and loose:
                    slots[i] = loose.pop(0)
            ordered = [s for s in slots if s is not None]
            if len(ordered) != len(items):
                raise CrushCompilerError(
                    f"bucket {bname!r} has pos holes"
                )
            if alg == CRUSH_BUCKET_UNIFORM and ordered:
                w0 = ordered[0][1]
                if any(w != w0 for _, w in ordered):
                    raise CrushCompilerError(
                        f"uniform bucket {bname!r} items must all "
                        "have identical weights"
                    )
            m.add_bucket(
                alg,
                btype,
                [i for i, _ in ordered],
                [w for _, w in ordered],
                id=bid,
                name=bname,
                hash=hash_,
            )
            name_to_id[bname] = bid
            if shadow_ids:
                declared_shadows[bid] = shadow_ids

    # shadow trees: reserve the declared ids, then build the clones
    if declared_shadows or m.class_map:
        for bid, per in declared_shadows.items():
            for cname, cid in per.items():
                c = m.get_class_id(cname, create=True)
                m.class_bucket.setdefault(bid, {})[c] = cid
        if any(i >= 0 for i in m.class_map):
            m.populate_classes()

    for name, rid, rtype, min_size, max_size, steps in pending_rules:
        resolved: list[RuleStep] = []
        for st in steps:
            if isinstance(st, tuple):
                _, take_name, take_class = st
                take = resolve_item(take_name)
                if take is None:
                    raise CrushCompilerError(
                        f"in rule {name!r} item {take_name!r} not defined"
                    )
                if take_class is not None:
                    c = m.get_class_id(take_class)
                    cid = m.class_bucket.get(take, {}).get(c)
                    if cid is None:
                        raise CrushCompilerError(
                            f"no shadow tree for {take_name}~{take_class}"
                        )
                    take = cid
                resolved.append(RuleStep(CRUSH_RULE_TAKE, take))
            else:
                resolved.append(st)
        if rid is None:
            rid = len(m.rules)
        if rid < len(m.rules) and m.rules[rid] is not None:
            raise CrushCompilerError(f"rule {rid} already exists")
        rule = Rule(
            steps=resolved,
            type=rtype,
            min_size=min_size,
            max_size=max_size,
        )
        m.add_rule(rule, rid)
        rule.ruleset = rid
        m.rule_names[rid] = name
    m.touch()
    return m
