"""rjenkins1 hash — the only hash CRUSH uses (src/crush/hash.c).

One numpy implementation serves scalars and batches: uint32 arithmetic
wraps naturally, so results are byte-exact against crush_hash32_* for
every arity (seed 1315423911, hash.c:24; mix rounds hash.c:12-22).

The C macro ``crush_hashmix(a, b, c)`` mutates all three of its
arguments in the caller's scope, and the x/y scratch values thread
through successive mix calls — the rebinding chains below reproduce
that dataflow exactly.

Scalars in, python int out; arrays in, uint32 arrays out.
"""

from __future__ import annotations

import functools

import numpy as np

CRUSH_HASH_RJENKINS1 = 0
CRUSH_HASH_SEED = np.uint32(1315423911)

_U32 = np.uint32
_X0 = _U32(231232)
_Y0 = _U32(1232)


def _suppress_overflow(fn):
    """uint32 wraparound is the point; one errstate per hash call."""

    @functools.wraps(fn)
    def wrapped(*args):
        with np.errstate(over="ignore"):
            return fn(*args)

    return wrapped


def _mix_inner(a, b, c):
    a = a - b
    a = a - c
    a = a ^ (c >> _U32(13))
    b = b - c
    b = b - a
    b = b ^ (a << _U32(8))
    c = c - a
    c = c - b
    c = c ^ (b >> _U32(13))
    a = a - b
    a = a - c
    a = a ^ (c >> _U32(12))
    b = b - c
    b = b - a
    b = b ^ (a << _U32(16))
    c = c - a
    c = c - b
    c = c ^ (b >> _U32(5))
    a = a - b
    a = a - c
    a = a ^ (c >> _U32(3))
    b = b - c
    b = b - a
    b = b ^ (a << _U32(10))
    c = c - a
    c = c - b
    c = c ^ (b >> _U32(15))
    return a, b, c


def _coerce(*vals):
    arrs = [np.asarray(v).astype(np.uint32) for v in vals]
    scalar = all(a.ndim == 0 for a in arrs)
    return arrs, scalar


def _ret(h, scalar):
    return int(h) if scalar else h


@_suppress_overflow
def crush_hash32(a):
    (a,), scalar = _coerce(a)
    h = CRUSH_HASH_SEED ^ a
    b = a
    b, x, h = _mix_inner(b, _X0, h)
    y, a, h = _mix_inner(_Y0, a, h)
    return _ret(h, scalar)


@_suppress_overflow
def crush_hash32_2(a, b):
    (a, b), scalar = _coerce(a, b)
    h = CRUSH_HASH_SEED ^ a ^ b
    a, b, h = _mix_inner(a, b, h)
    x, a, h = _mix_inner(_X0, a, h)
    b, y, h = _mix_inner(b, _Y0, h)
    return _ret(h, scalar)


@_suppress_overflow
def crush_hash32_3(a, b, c):
    (a, b, c), scalar = _coerce(a, b, c)
    h = CRUSH_HASH_SEED ^ a ^ b ^ c
    a, b, h = _mix_inner(a, b, h)
    c, x, h = _mix_inner(c, _X0, h)
    y, a, h = _mix_inner(_Y0, a, h)
    b, x, h = _mix_inner(b, x, h)
    y, c, h = _mix_inner(y, c, h)
    return _ret(h, scalar)


@_suppress_overflow
def crush_hash32_4(a, b, c, d):
    (a, b, c, d), scalar = _coerce(a, b, c, d)
    h = CRUSH_HASH_SEED ^ a ^ b ^ c ^ d
    a, b, h = _mix_inner(a, b, h)
    c, d, h = _mix_inner(c, d, h)
    a, x, h = _mix_inner(a, _X0, h)
    y, b, h = _mix_inner(_Y0, b, h)
    c, x, h = _mix_inner(c, x, h)
    y, d, h = _mix_inner(y, d, h)
    return _ret(h, scalar)


@_suppress_overflow
def crush_hash32_5(a, b, c, d, e):
    (a, b, c, d, e), scalar = _coerce(a, b, c, d, e)
    h = CRUSH_HASH_SEED ^ a ^ b ^ c ^ d ^ e
    a, b, h = _mix_inner(a, b, h)
    c, d, h = _mix_inner(c, d, h)
    e, x, h = _mix_inner(e, _X0, h)
    y, a, h = _mix_inner(_Y0, a, h)
    b, x, h = _mix_inner(b, x, h)
    y, c, h = _mix_inner(y, c, h)
    d, x, h = _mix_inner(d, x, h)
    y, e, h = _mix_inner(y, e, h)
    return _ret(h, scalar)


def ceph_str_hash_rjenkins(name: bytes | str) -> int:
    """Object-name hash feeding PG placement
    (src/common/ceph_hash.cc ceph_str_hash_rjenkins — Jenkins lookup2
    over the name bytes; the default pg_pool_t object_hash)."""
    if isinstance(name, str):
        name = name.encode("utf-8")
    k = name
    length = len(k)
    a = 0x9E3779B9
    b = a
    c = 0
    M = 0xFFFFFFFF

    def mix(a, b, c):
        a = (a - b - c) & M; a ^= c >> 13
        b = (b - c - a) & M; b ^= (a << 8) & M
        c = (c - a - b) & M; c ^= b >> 13
        a = (a - b - c) & M; a ^= c >> 12
        b = (b - c - a) & M; b ^= (a << 16) & M
        c = (c - a - b) & M; c ^= b >> 5
        a = (a - b - c) & M; a ^= c >> 3
        b = (b - c - a) & M; b ^= (a << 10) & M
        c = (c - a - b) & M; c ^= b >> 15
        return a, b, c

    i = 0
    rem = length
    while rem >= 12:
        a = (a + int.from_bytes(k[i : i + 4], "little")) & M
        b = (b + int.from_bytes(k[i + 4 : i + 8], "little")) & M
        c = (c + int.from_bytes(k[i + 8 : i + 12], "little")) & M
        a, b, c = mix(a, b, c)
        i += 12
        rem -= 12
    c = (c + length) & M
    tail = k[i:]
    if rem >= 11:
        c = (c + (tail[10] << 24)) & M
    if rem >= 10:
        c = (c + (tail[9] << 16)) & M
    if rem >= 9:
        c = (c + (tail[8] << 8)) & M
    if rem >= 8:
        b = (b + (tail[7] << 24)) & M
    if rem >= 7:
        b = (b + (tail[6] << 16)) & M
    if rem >= 6:
        b = (b + (tail[5] << 8)) & M
    if rem >= 5:
        b = (b + tail[4]) & M
    if rem >= 4:
        a = (a + (tail[3] << 24)) & M
    if rem >= 3:
        a = (a + (tail[2] << 16)) & M
    if rem >= 2:
        a = (a + (tail[1] << 8)) & M
    if rem >= 1:
        a = (a + tail[0]) & M
    _a, _b, c = mix(a, b, c)
    return c
