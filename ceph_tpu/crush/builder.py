"""CrushMap construction — builder.c + the CrushWrapper editing surface.

Computes the per-algorithm derived tables at insert time exactly as
crush_make_*_bucket do (src/crush/builder.c): straw lengths (v0/v1
crush_calc_straw, builder.c:431), tree node weights
(crush_make_tree_bucket, builder.c:340), list prefix sums.  Name/type
maps and add_simple_rule mirror CrushWrapper (CrushWrapper.cc
add_simple_rule_at).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .mapper import crush_do_rule
from .types import (
    CRUSH_BUCKET_LIST,
    CRUSH_BUCKET_STRAW,
    CRUSH_BUCKET_STRAW2,
    CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_UNIFORM,
    CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP,
    CRUSH_RULE_EMIT,
    CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE,
    Bucket,
    ChooseArg,
    Rule,
    RuleStep,
    Tunables,
)


def _calc_straws(weights: list[int], version: int) -> list[int]:
    """crush_calc_straw (builder.c:431-525): straw lengths such that
    P(argmax_i hash16*straw_i = i) ∝ weight_i, computed by ascending-
    weight sweep.  v1 fixes the equal-weight bookkeeping bug of v0."""
    size = len(weights)
    straws = [0] * size
    if size == 0:
        return straws
    # ascending insertion order, stable (reverse sort by weight in the C)
    order = sorted(range(size), key=lambda i: (weights[i], i))
    numleft = size
    straw = 1.0
    wbelow = 0.0
    lastw = 0.0
    i = 0
    while i < size:
        if weights[order[i]] == 0:
            straws[order[i]] = 0
            i += 1
            if version >= 1:
                numleft -= 1
            continue
        straws[order[i]] = int(straw * 0x10000)
        i += 1
        if i == size:
            break
        if version == 0 and weights[order[i]] == weights[order[i - 1]]:
            continue
        wbelow += (weights[order[i - 1]] - lastw) * numleft
        if version == 0:
            j = i
            while j < size and weights[order[j]] == weights[order[i]]:
                numleft -= 1
                j += 1
        else:
            numleft -= 1
        wnext = numleft * (weights[order[i]] - weights[order[i - 1]])
        pbelow = wbelow / (wbelow + wnext)
        straw *= (1.0 / pbelow) ** (1.0 / numleft)
        lastw = weights[order[i - 1]]
    return straws


def _calc_tree(weights: list[int]) -> list[int]:
    """Implicit-binary-tree node weights (crush_make_tree_bucket,
    builder.c:340-397): item i at node 2i+1; parents sum children."""
    size = len(weights)
    if size == 0:
        return []
    depth = 1
    t = size - 1
    while t:
        t >>= 1
        depth += 1
    num_nodes = 1 << depth
    node_weights = [0] * num_nodes
    for i, wt in enumerate(weights):
        node = (i + 1 << 1) - 1
        node_weights[node] = wt
        for _ in range(1, depth):
            # parent: flip direction bit at this height
            h = 0
            n = node
            while (n & 1) == 0:
                h += 1
                n >>= 1
            if node & (1 << (h + 1)):
                node = node - (1 << h)
            else:
                node = node + (1 << h)
            node_weights[node] += wt
    return node_weights


@dataclass
class CrushMap:
    """Editable map + query API (the CrushWrapper role)."""

    tunables: Tunables = field(default_factory=Tunables)
    buckets: dict[int, Bucket] = field(default_factory=dict)
    rules: list[Rule | None] = field(default_factory=list)
    max_devices: int = 0
    choose_args: dict[int, ChooseArg] = field(default_factory=dict)
    # device classes (CrushWrapper class_map / class_bucket)
    class_map: dict[int, int] = field(default_factory=dict)
    class_names: dict[int, str] = field(default_factory=dict)
    class_bucket: dict[int, dict[int, int]] = field(default_factory=dict)
    # name maps (CrushWrapper name_map/type_map)
    type_names: dict[int, str] = field(
        default_factory=lambda: {0: "osd", 1: "host", 2: "rack", 3: "root"}
    )
    item_names: dict[int, str] = field(default_factory=dict)
    rule_names: dict[int, str] = field(default_factory=dict)
    # Bumped by every mutator; consumers that compile the map to dense
    # device arrays (osd/mapping.py) key their cache on this so a
    # topology or weight change invalidates the compiled form.
    mutation: int = 0

    def touch(self) -> None:
        """Record a structural/weight mutation (invalidates compiled
        caches).  Call after mutating buckets/rules/tunables directly."""
        self.mutation += 1

    def set_choose_args(self, args: dict[int, ChooseArg]) -> None:
        """Install per-bucket straw2 overrides (the balancer's
        crush-compat weight-set path, CrushWrapper.h:1447) and
        invalidate compiled caches."""
        self.choose_args = dict(args)
        self.touch()

    # -- device classes (CrushWrapper class_map + shadow trees) ------------
    def get_class_id(self, name: str, create: bool = False) -> int:
        for cid, n in self.class_names.items():
            if n == name:
                return cid
        if not create:
            raise KeyError(f"device class {name!r} does not exist")
        cid = max(self.class_names, default=-1) + 1
        self.class_names[cid] = name
        return cid

    def set_item_class(self, item: int, class_name: str) -> None:
        """Tag a device with a class (CrushWrapper::set_item_class);
        shadow trees pick it up at the next populate_classes()."""
        self.class_map[item] = self.get_class_id(class_name, create=True)
        self.touch()

    def _roots(self) -> list[int]:
        """Bucket ids not referenced by any other non-shadow bucket."""
        shadows = {
            c for per in self.class_bucket.values() for c in per.values()
        }
        referenced: set[int] = set()
        for bid, b in self.buckets.items():
            if bid in shadows:
                continue
            referenced.update(i for i in b.items if i < 0)
        return [
            bid
            for bid in self.buckets
            if bid not in shadows and bid not in referenced
        ]

    def populate_classes(self) -> None:
        """(Re)build the per-class shadow hierarchies
        (CrushWrapper::populate_classes → device_class_clone,
        CrushWrapper.cc:2681): for every class and every root, a clone
        named ``<name>~<class>`` holding only that class's devices,
        with sub-bucket clones always included (possibly empty) and
        weights rolled up from the included items.  Existing clones
        keep their ids across rebuilds (the old_class_bucket reuse)."""
        live = {
            c
            for item, c in self.class_map.items()
            if item >= 0
        }
        for per in self.class_bucket.values():
            for cls, cid_clone in per.items():
                self.buckets.pop(cid_clone, None)
                if cls not in live:
                    # retired class: its clone ids stay RESERVED in
                    # class_bucket (never reallocated — a rule may
                    # still TAKE them, and the class may return) but
                    # the shadow buckets and names disappear from the
                    # map until then
                    self.item_names.pop(cid_clone, None)
        roots = self._roots()
        for cls in sorted(live):
            for root in sorted(roots, reverse=True):
                self._device_class_clone(root, cls)
        self.touch()

    def _device_class_clone(self, original_id: int, cls: int) -> int:
        existing = self.class_bucket.get(original_id, {}).get(cls)
        if existing is not None and existing in self.buckets:
            return existing
        orig = self.buckets[original_id]
        items: list[int] = []
        weights: list[int] = []
        for item, w in zip(orig.items, orig.item_weights):
            if item >= 0:
                if self.class_map.get(item) == cls:
                    items.append(item)
                    weights.append(w)
            else:
                child = self._device_class_clone(item, cls)
                items.append(child)
                weights.append(self.buckets[child].weight)
        if existing is not None:
            new_id = existing
        else:
            # like the C's used_ids set: never hand out an id reserved
            # by ANY clone (even one whose bucket is mid-rebuild)
            reserved = {
                c
                for per in self.class_bucket.values()
                for c in per.values()
            }
            new_id = min(set(self.buckets) | reserved, default=0) - 1
            while new_id in self.buckets or new_id in reserved:
                new_id -= 1
        if orig.alg == CRUSH_BUCKET_UNIFORM and weights:
            # a uniform clone keeps the per-item weight invariant
            weights = [weights[0]] * len(weights)
        self.add_bucket(
            orig.alg,
            orig.type,
            items,
            weights,
            id=new_id,
            name=(
                f"{self.item_names[original_id]}~{self.class_names[cls]}"
                if original_id in self.item_names
                else None
            ),
            hash=orig.hash,
        )
        self.class_bucket.setdefault(original_id, {})[cls] = new_id
        self.class_map[new_id] = cls
        return new_id

    def _name_to_item(self, name: str) -> int:
        for item, n in self.item_names.items():
            if n == name:
                return item
        raise KeyError(f"item {name!r} does not exist")

    def _type_id(self, name: str) -> int:
        for t, n in self.type_names.items():
            if n == name:
                return t
        raise KeyError(f"type {name!r} does not exist")

    # -- construction ------------------------------------------------------
    def add_bucket(
        self,
        alg: int,
        type: int,
        items: list[int] | None = None,
        weights: list[int] | None = None,
        id: int | None = None,
        name: str | None = None,
        hash: int = 0,
    ) -> int:
        """crush_add_bucket + crush_make_bucket: computes derived tables
        and registers the bucket.  Weights are 16.16 fixed point; device
        items must be >= 0, sub-buckets already added."""
        items = list(items or [])
        weights = list(weights or [])
        assert len(items) == len(weights)
        if alg == CRUSH_BUCKET_UNIFORM and weights:
            assert all(w == weights[0] for w in weights), (
                "uniform buckets have one item weight"
            )
        if id is None:
            id = min(self.buckets, default=0) - 1
        assert id < 0 and id not in self.buckets
        b = Bucket(
            id=id,
            type=type,
            alg=alg,
            items=items,
            item_weights=weights,
            hash=hash,
            weight=sum(weights),
        )
        if alg == CRUSH_BUCKET_LIST:
            acc, sums = 0, []
            for w in weights:
                acc += w
                sums.append(acc)
            b.sum_weights = sums
        elif alg == CRUSH_BUCKET_TREE:
            b.node_weights = _calc_tree(weights)
        elif alg == CRUSH_BUCKET_STRAW:
            b.straws = _calc_straws(
                weights, self.tunables.straw_calc_version
            )
        self.buckets[id] = b
        self.touch()
        for item in items:
            if item >= 0:
                self.max_devices = max(self.max_devices, item + 1)
        if name is not None:
            self.item_names[id] = name
        return id

    def add_rule(self, rule: Rule, ruleno: int | None = None) -> int:
        if ruleno is None:
            ruleno = len(self.rules)
        while len(self.rules) <= ruleno:
            self.rules.append(None)
        assert self.rules[ruleno] is None
        self.rules[ruleno] = rule
        rule.ruleset = ruleno
        self.touch()
        return ruleno

    def add_simple_rule(
        self,
        name: str,
        root_name: str,
        failure_domain: str = "",
        device_class: str = "",
        mode: str = "firstn",
        rule_type: int | None = None,
    ) -> int:
        """CrushWrapper::add_simple_rule_at semantics: TAKE root,
        CHOOSELEAF over the failure domain (or CHOOSE osd for a flat
        domain), EMIT; indep rules prepend SET_CHOOSELEAF_TRIES 5 and
        SET_CHOOSE_TRIES 100.  A device class resolves the TAKE to the
        class's shadow root ``<root>~<class>`` (built on demand)."""
        assert mode in ("firstn", "indep"), mode
        if device_class:
            self.get_class_id(device_class)  # must exist
            shadow = f"{root_name}~{device_class}"
            try:
                root = self._name_to_item(shadow)
            except KeyError:
                self.populate_classes()
                root = self._name_to_item(shadow)
        else:
            root = self._name_to_item(root_name)
        dtype = self._type_id(failure_domain) if failure_domain else 0
        steps: list[RuleStep] = []
        if mode == "indep":
            steps.append(RuleStep(CRUSH_RULE_SET_CHOOSELEAF_TRIES, 5))
            steps.append(RuleStep(CRUSH_RULE_SET_CHOOSE_TRIES, 100))
        steps.append(RuleStep(CRUSH_RULE_TAKE, root))
        if dtype:
            steps.append(
                RuleStep(
                    CRUSH_RULE_CHOOSELEAF_FIRSTN
                    if mode == "firstn"
                    else CRUSH_RULE_CHOOSELEAF_INDEP,
                    0,
                    dtype,
                )
            )
        else:
            steps.append(
                RuleStep(
                    CRUSH_RULE_CHOOSE_FIRSTN
                    if mode == "firstn"
                    else CRUSH_RULE_CHOOSE_INDEP,
                    0,
                    0,
                )
            )
        steps.append(RuleStep(CRUSH_RULE_EMIT))
        rule = Rule(
            steps=steps,
            type=1 if mode == "firstn" else 3,
            min_size=1 if mode == "firstn" else 3,
            max_size=10 if mode == "firstn" else 20,
        )
        ruleno = self.add_rule(rule)
        self.rule_names[ruleno] = name
        return ruleno

    # -- query -------------------------------------------------------------
    def find_rule(self, ruleset: int, type: int, size: int) -> int:
        """crush_find_rule (mapper.c:41-54)."""
        for i, r in enumerate(self.rules):
            if (
                r is not None
                and r.ruleset == ruleset
                and r.type == type
                and r.min_size <= size <= r.max_size
            ):
                return i
        return -1

    def do_rule(
        self,
        ruleno: int,
        x: int,
        result_max: int,
        weight: list[int] | None = None,
        choose_args=None,
    ) -> list[int]:
        if weight is None:
            weight = [0x10000] * self.max_devices
        return crush_do_rule(
            self, ruleno, x, result_max, weight, choose_args
        )
