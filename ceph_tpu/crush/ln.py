"""crush_ln — fixed-point 2^44*log2(x+1) (src/crush/mapper.c:248-290).

straw2 turns a 16-bit uniform hash draw u into -Exp(weight) via
ln(u)/weight in 48.16-style fixed point; exactness of every table entry
is what keeps placements byte-identical across implementations.

Three tables (src/crush/crush_ln_table.h):

- RH[k] = ceil(2^55/(128+k)), k=0..128 — reciprocal for range reduction
  (the header writes it as 2^48/(1.0+k/128)); exact, generated here.
- LH[k] = floor(2^48*log2(1+k/128)), k=0..127 — coarse log; exact,
  generated here (verified entry-for-entry against the reference
  table).  LH[128] is the out-of-range sentinel the C table carries
  (0xffff00000000, not the mathematical 2^48) — reached only for
  u=0xffff; reproduced verbatim for bit-parity.
- LL[k] ~ 2^48*log2(1+k/2^15), k=0..255 — fine log.  The published
  table does NOT match its own formula (entries deviate by up to
  ~2e-5*2^48 with no closed-form rule; empirically generated upstream),
  so it is embedded as data rather than regenerated.
"""

from __future__ import annotations

import base64
import decimal
import functools

import numpy as np

_LL_B85 = (
    "000000000001Bq!0ssI2#ZI;i2LJ#7XU<UX2><{9{fOn!3;+NCoPKn)4*&oFUa$R@5&!@ISQ~+P6"
    "#xJLp~C)K7ytkOQl)l28vp<RfWzn@9smFULmgEEApigXva-A7BLDyZ<bxc@CIA2c@Q`<^DF6Tf?b"
    "*zXEC2ui@?IQoF8}}l7a(R)G5`PoaUH5NH2?qr8dvBQH~;_uCe0xDIsgCwu76Y7Jpcdz$ZmkVKmY"
    "&$jGCvOLjV8(5Ch48MgRZ+Y^da7NdN!<wu<^hOaK4?2C51tPXGV_at0L%QUCw|6QCLEQ~&?~{dO4"
    "5R{#J2N{bP%S^xk558VWjT>t<8WNh+sU;qFBU^&`UV*mgE8brJ{W&i*HsEn8xX#fBKA`@@=YXATM"
    "pVvR!ZU6uPHQEZkaR2}S{Tk4pbN~PV44RLDcK`qYdRIwfdH?_bUQ_)<eE<Le&W)=kfB*mh;|5d+g"
    "8%>kvcC4|g#Z8mQni!IhyVZp-0CW=ivR!sWna9GjsO4v1rtbckpKVy(*@2^lmGw#<u^_<mjD0&Q>"
    "n-lng9R*Gg>|NoB#j-ol30Np8x;=STujIq5uE@YU+5Jr2qf``<d2zr~m)}aEUHms{jB1<G6r6t^f"
    "c4X(-Jfu>b%77}yg5v;Y7A3dmIAwg3PCQ}~a=xc~qF3~9xryZ`_IN*eWrzW@LLCJY~E!T<mOwmw5"
    "h#Q*>R5wc+^$N&HUO=fxu%K!iXf?JL2%>V!Z$dQ`N&;S4cGYO)t(*OVf-NB=d)&Kwi+q<7{*#H0l"
    "ME8|Y+yDRoFm#47-v9srwK8!M;s5{u>M~aI<NyEw=5#gG=Kufz!T1if>Hq)$kiO!T?EnA(Y{sy5@"
    "Bjb+Y7lu>^8f$<qMSQ8_5c6?Dr@){`2YX_8ho$$`v3p{h|gf!{r~^~jAabF0RaF2JU+6U1OWg5uU"
    "S%j2LS*8{I~2}3IPBBI$41|4FLcEe?1T$5CH%H<Ybxt5&-}Je$O1=6#)PMWPO^y7y$qPt!@&a8vy"
    "_SaifHQ9svLV#vAcqAprmY!`D|qBmn>bf7iGnCjkHe5E~Q%Dggihj-qMeECB!j4{o`_F984ms(k~"
    "aG64VpchN_KH30wsjA@2rH~|0v|3$w;I{^Ry>1OgHJ^=s#Ud$f^K>+{&cy>qRLjeE)OJ!-qMgaf-"
    "@R)_9NdW)=d=Z?4OaTA@2+yo!PXPb`snNGYQUL$}b{V}SRRI71ho-y)SOEY4@_4r7S^)q6&XB6aT"
    ">$_9G47b8U;zLCG?9UXV*vmF?0IBlW&r>IaHv5<X#oHL*BlunYykiOy&1~(Z2<rPs@JyTaRC4TL>"
    "_&^bO8VW9IHK}cL4wZNq+c(dI10c;+L&reE|Rf{`Xu$fB^siSTujIf&l;k8H}m{h5-NoM!-nnhye"
    "fqQaj<miva)tQvGwFjsXAwT?PSwkpTbzi&ltVlmP$$^6d#fmjM6(s?3ERngIX+$nyLBoB;p;V_<C"
    "Ep8)^>lNHyzq5%K^ZW274r2zl{3s;+ar~v=~faCO9s{sH2<+n&Wt^oi5P}G(gu>k-8**M?$vjG4A"
    "mE|GWwgCVDo7!Htxd8wG0~nT;ya50J<DJKKzX1RMQEc;6!T|sPWrG<s#Q^{SHsd)H$N>NV*^5;2$"
    "^ifXXMJhW%>e)a^|yGi&;bAdnmdAz(*XbgZ3u>L)&T$jh8~1X*#Q6m`F(sW+yMXp-zIMh-vIysP8"
    "nS4;Q;^uT~$NL<N*KxBikgX=K%l!wW<Jz>Hz=%E5z1i?EwG)ohPS6@Bsh-AJuUq^8o+=%FH$b_5l"
    "C@vkvy)_yGU_^O~=}`vCv|o^fNI{s900%#sm(0RjL3l>WI}1Ofm64Ygc42Lb>95@K$^2?78BY66k"
    ">3<3ZEe3>HF4*~!HSTujI5&{4K*r+s<6#@VNP$9K(7y<wQ7v(Qd8v+0TkEe5L9RdIV?oJd9Ap!sZ"
    "EcJcsBLV;b6X)T{CISEeyCguUDFOfhPMc>VECK)ksDtZdF9HAn8>KiyG6DbqsS<!8H39$tY0Sa@H"
    "v#|vqz*eQIsyOyTY&BpJpup#A0p{BKmq^&C9jWoLjnK*HRrTeMgjl;{%d6IM*;u<m_6<iOacG^8e"
    "9VLP67Y`na2>%Q33z}Wldy~R0041?u8tSR{{V4@Yoq?S^@w7TY&BpT>=0A7H0z`U;+RDf;dH%Vgd"
    "jFQ!CcsWdZ;I^8vTMXaWELUM7;9YXSfOshIaNZ2|xQ>sU8faRLAUJ7)nlbOHbXu*1a@cLD$aUv7l"
    ")c>(|cVP`$hd;$Of%dUWBegXghvqYYXf&u^lGATxAg#rKoRZ_pbhXMcqO5TJcivj=uTY&BpjRF7w"
    "A0p{BkOBYzf;dH%k^%q#Vf($AmI43(t9qjXnF0U+$z-@xoB{v<gAl#yodN&=wF{gNq5=Q_VO?Oyq"
    "yhi{RZ_pbrvd-~{MuGvsR951?-Z{+tO5W4%ANrmuL1x7f;dH%vH}1A57;8mwE_SDvm8hIxB>tG6X"
    "z0;x&i<I%mGPly#fFL_H21dzybgOrYNK*!vX*ROpjg~#sUBU_L1n}$N~TW!aoAP%K`uZZax;A&H?"
    "}c6OJT#(E<Pf#x5;Z)B*qioggqX*8%_luwyL{+5!Lo6!;|V+yVdq"
)


@functools.lru_cache(maxsize=1)
def _tables() -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(RH, LH, LL) as int64 arrays (values < 2^49 fit comfortably)."""
    rh = np.array(
        [-((-(1 << 55)) // (128 + k)) for k in range(129)], dtype=np.int64
    )
    with decimal.localcontext() as ctx:
        ctx.prec = 60
        ln2 = decimal.Decimal(2).ln()
        lh = np.array(
            [
                int(
                    (decimal.Decimal(128 + k).ln()
                     - decimal.Decimal(128).ln())
                    / ln2 * (1 << 48)
                )
                for k in range(128)
            ]
            + [0xFFFF00000000],
            dtype=np.int64,
        )
    ll = np.frombuffer(base64.b85decode(_LL_B85), dtype="<u8").astype(
        np.int64
    )
    return rh, lh, ll


def crush_ln(xin):
    """2^44*log2(x+1) for x in [0, 0xffff]; scalar int or uint32 array."""
    rh_tbl, lh_tbl, ll_tbl = _tables()
    x = np.asarray(xin).astype(np.int64) + 1
    scalar = x.ndim == 0

    # normalize into [0x8000, 0x1ffff]: shift left until bit 15/16 set
    masked = x & 0x1FFFF
    nbits = np.zeros_like(x)
    for shift in (16, 8, 4, 2, 1):  # bit_length via binary search, vectorized
        step = (masked >> shift) != 0
        nbits = nbits + np.where(step, shift, 0)
        masked = np.where(step, masked >> shift, masked)
    bitlen = nbits + (masked != 0)  # 0 for x==0 (cannot happen: x>=1)
    shift_amt = np.where((x & 0x18000) == 0, 16 - bitlen, 0)
    x = x << shift_amt
    iexpon = 15 - shift_amt

    index1 = (x >> 8) << 1
    rh = rh_tbl[(index1 - 256) >> 1]
    lh = lh_tbl[(index1 - 256) >> 1]
    # x*RH can reach 2^63 (x=0x8000, RH=2^48); like the C code, only the
    # low bits survive into index2, and int64 wraparound preserves them.
    with np.errstate(over="ignore"):
        xl64 = (x * rh) >> 48
    index2 = xl64 & 0xFF
    lh = lh + ll_tbl[index2]
    result = (iexpon << 44) + (lh >> 4)
    return int(result) if scalar else result
