"""Framework version — checked at plugin registration and reported
by the admin socket (the CEPH_GIT_NICE_VER role)."""

FRAMEWORK_VERSION = "ceph-tpu-1"
