"""Compressor plugin family (src/compressor/ — the second consumer of
the dlopen-plugin registry design, CompressionPlugin.h).

Same shape as the erasure-code registry: plugins self-register by
name, ``Compressor.create(name)`` is the factory
(Compressor::create, src/compressor/Compressor.cc), and every plugin
implements the tiny compress/decompress contract.  The reference
ships zlib/snappy/lz4/zstd/brotli (+ QAT offload); here each plugin
wraps the matching Python codec and registers only when its module
imports — exactly how the reference gates plugins on available
libraries at build time.  ``none`` (passthrough) always exists.

On-wire framing: 4-byte little-endian original length + codec bytes,
so decompress can sanity-check expansion (the reference carries the
logical length in the bluestore blob metadata instead).
"""

from __future__ import annotations

__all__ = [
    "Compressor",
    "CompressorError",
    "available",
    "create",
    "register",
]


class CompressorError(Exception):
    pass


_REGISTRY: dict[str, type["Compressor"]] = {}


def register(cls: type["Compressor"]) -> type["Compressor"]:
    _REGISTRY[cls.name] = cls
    return cls


def available() -> list[str]:
    """get_supported_compressors() role."""
    return sorted(_REGISTRY)


def create(name: str) -> "Compressor":
    """Compressor::create — factory by plugin name."""
    cls = _REGISTRY.get(name)
    if cls is None:
        raise CompressorError(
            f"unsupported compressor {name!r} (have {available()})"
        )
    return cls()


class Compressor:
    """The CompressionPlugin contract."""

    name = ""

    def _compress(self, data: bytes) -> bytes:  # pragma: no cover
        raise NotImplementedError

    def _decompress(self, data: bytes) -> bytes:  # pragma: no cover
        raise NotImplementedError

    def compress(self, data: bytes) -> bytes:
        data = bytes(data)
        return len(data).to_bytes(4, "little") + self._compress(data)

    def decompress(self, blob: bytes) -> bytes:
        if len(blob) < 4:
            raise CompressorError("short compressed blob")
        want = int.from_bytes(blob[:4], "little")
        try:
            out = self._decompress(bytes(blob[4:]))
        except Exception as e:
            raise CompressorError(f"{self.name}: {e}") from e
        if len(out) != want:
            raise CompressorError(
                f"{self.name}: length mismatch {len(out)} != {want}"
            )
        return out


@register
class NoneCompressor(Compressor):
    """Passthrough (the 'none' mode)."""

    name = "none"

    def _compress(self, data: bytes) -> bytes:
        return data

    def _decompress(self, data: bytes) -> bytes:
        return data


try:
    import zlib as _zlib

    @register
    class ZlibCompressor(Compressor):
        """ZlibCompressor.cc role."""

        name = "zlib"

        def _compress(self, data: bytes) -> bytes:
            return _zlib.compress(data, 5)

        def _decompress(self, data: bytes) -> bytes:
            return _zlib.decompress(data)

except ImportError:  # pragma: no cover
    pass


try:
    import bz2 as _bz2

    @register
    class Bz2Compressor(Compressor):
        name = "bz2"

        def _compress(self, data: bytes) -> bytes:
            return _bz2.compress(data, 5)

        def _decompress(self, data: bytes) -> bytes:
            return _bz2.decompress(data)

except ImportError:  # pragma: no cover
    pass


try:
    import lzma as _lzma

    @register
    class LzmaCompressor(Compressor):
        name = "lzma"

        def _compress(self, data: bytes) -> bytes:
            return _lzma.compress(data, preset=1)

        def _decompress(self, data: bytes) -> bytes:
            return _lzma.decompress(data)

except ImportError:  # pragma: no cover
    pass


try:
    import zstandard as _zstd

    @register
    class ZstdCompressor(Compressor):
        """ZstdCompressor.cc role."""

        name = "zstd"

        def _compress(self, data: bytes) -> bytes:
            return _zstd.ZstdCompressor(level=3).compress(data)

        def _decompress(self, data: bytes) -> bytes:
            return _zstd.ZstdDecompressor().decompress(data)

except ImportError:  # pragma: no cover
    pass


try:  # pragma: no cover — not in the baked image; gated like the rest
    import snappy as _snappy

    @register
    class SnappyCompressor(Compressor):
        name = "snappy"

        def _compress(self, data: bytes) -> bytes:
            return _snappy.compress(data)

        def _decompress(self, data: bytes) -> bytes:
            return _snappy.decompress(data)

except ImportError:
    pass


try:  # pragma: no cover
    import lz4.frame as _lz4

    @register
    class Lz4Compressor(Compressor):
        name = "lz4"

        def _compress(self, data: bytes) -> bytes:
            return _lz4.compress(data)

        def _decompress(self, data: bytes) -> bytes:
            return _lz4.decompress(data)

except ImportError:
    pass
