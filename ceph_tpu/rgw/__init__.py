"""RGW analog — an HTTP object gateway over the rados layer
(src/rgw/: the beast-frontend + rgw_rados layout, reduced to the
load-bearing architecture).

What carries over from the reference's design:

- **The gateway is a rados CLIENT daemon**: it owns no storage; every
  bucket/object operation becomes librados I/O (rgw_rados.cc's role).
- **Bucket indexes are omap objects** (the cls_rgw bucket-index
  pattern): ``bucket.index.<name>`` maps key → JSON entry
  (size/etag/mtime), so listings are key-ordered omap pages with
  marker/max-keys — exactly how S3 ListObjects pagination rides
  RocksDB in the reference.
- **A bucket directory object** (``rgw.buckets``) indexes the
  buckets themselves.
- Object payloads live at ``rgw.obj.<bucket>/<key>``; multipart-scale
  striping would ride osdc/striper.py like rbd (not wired yet).

Served surface (S3-flavored REST over http.server, the beast role):

    PUT    /<bucket>                 create bucket
    DELETE /<bucket>                 remove empty bucket
    GET    /                         ListAllMyBuckets (XML)
    PUT    /<bucket>/<key>           upload (body = object)
    GET    /<bucket>/<key>           download
    HEAD   /<bucket>/<key>           stat
    DELETE /<bucket>/<key>           remove
    GET    /<bucket>?marker=&max-keys=   ListObjects (XML, paged)

    POST   /<bucket>/<key>?uploads      initiate multipart upload
    PUT    /<bucket>/<key>?uploadId=&partNumber=   upload one part
    POST   /<bucket>/<key>?uploadId=    complete (manifest head)
    DELETE /<bucket>/<key>?uploadId=    abort

Auth (round 4): AWS SigV4-shaped request signing (rgw_auth_s3.cc
role) — users live in an omap-backed store (access key → secret),
the Authorization header carries credential scope + signed headers +
signature, the gateway recomputes the signature over the canonical
request and rejects mismatches/stale dates with 403.  Multipart
(round 4): parts land as separate rados objects; complete writes a
MANIFEST head (the reference's multipart manifest), so GET streams
part reads and the "-N" composite etag matches S3's shape.

ACLs (round 5, acl.py — src/rgw/rgw_acl.cc): buckets and objects
carry owner + grant lists (canned x-amz-acl or explicit), enforced
on EVERY op — anonymous requests match only AllUsers grants; ?acl
subresources read/write policies under READ_ACP/WRITE_ACP.
Lifecycle (round 5, lifecycle.py — src/rgw/rgw_lc.cc): per-bucket
expiration + storage-class transition rules applied by a scanning
worker; COLD transition really recompresses the payload through the
compressor registry; ``?lifecycle`` subresource round-trips configs.

STS (round 5): GetSessionToken/AssumeRole mint expiring temporary
credentials (12h cap, session creds may not re-mint) that sign
requests exactly like permanent keys.  Multisite (round 5,
multisite.py): per-zone datalog + cross-zone sync agents.

CORS (round 5): per-bucket rules (?cors subresource), OPTIONS
preflight, and Allow-Origin echo on admitted requests.

Deviations, documented: keystone absent; STS issues no role
ARNs/policies (the temp identity IS the caller); region/service
names checked only for self-consistency; single pool; lifecycle
configs are JSON on the wire (not S3's XML schema).
"""

from __future__ import annotations

import hashlib
import http.server
import json
import threading
import time
import urllib.parse
from xml.sax.saxutils import escape

from ..osdc.objecter import ObjectNotFound, RadosError
from . import acl as aclmod
from .lifecycle import LCWorker, apply_rules

__all__ = ["RGW", "RGWError", "AccessDenied", "sign_request"]


def _default_max_objs_per_shard() -> int:
    from ..common.config import SCHEMA

    return int(SCHEMA["rgw_max_objs_per_shard"].default)

BUCKETS_DIR = "rgw.buckets"
USERS_OID = "rgw.users"
LC_OID = "rgw.lc"  # lifecycle configs: bucket -> rules (lc shard role)
SKEW = 900.0  # max x-amz-date clock skew (seconds)
# storage-layer callers that bypass ACLs (internal plumbing, admin
# tools, tests of the storage logic itself) pass SYSTEM — the
# reference's system-user bypass in verify_permission
SYSTEM = "__rgw_system__"
# the multisite sync agent's identity: same bypass as SYSTEM, but its
# mutations are NOT datalogged (a mirrored write must not ping-pong
# between active-active zones; the reference short-circuits on the
# entry's source zone)
SYNC_USER = "__rgw_sync__"
DATALOG_OID = "rgw.datalog"
_DENIED = object()  # HTTP sentinel: signature rejected, 403 sent


def _hmac(key: bytes, msg: str) -> bytes:
    import hmac as hmac_mod

    return hmac_mod.new(key, msg.encode(), hashlib.sha256).digest()


def _sigv4_key(secret: str, date: str, region: str, service: str):
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def _canonical(method, path, query, amz_date, payload_sha) -> str:
    q = "&".join(
        f"{urllib.parse.quote(k, safe='')}="
        f"{urllib.parse.quote(v, safe='')}"
        for k, v in sorted(query.items())
    )
    headers = f"x-amz-content-sha256:{payload_sha}\nx-amz-date:{amz_date}\n"
    return "\n".join(
        (
            method,
            urllib.parse.quote(path),
            q,
            headers,
            "x-amz-content-sha256;x-amz-date",
            payload_sha,
        )
    )


def sign_request(
    method: str,
    path: str,
    query: dict,
    payload: bytes,
    access: str,
    secret: str,
    region: str = "default",
    amz_date: str | None = None,
) -> dict:
    """Headers for a SigV4-shaped request against the gateway (the
    client half; boto-equivalent for this reduced dialect)."""
    amz_date = amz_date or time.strftime(
        "%Y%m%dT%H%M%SZ", time.gmtime()
    )
    date = amz_date[:8]
    payload_sha = hashlib.sha256(payload).hexdigest()
    canonical = _canonical(method, path, query, amz_date, payload_sha)
    scope = f"{date}/{region}/s3/aws4_request"
    sts = "\n".join(
        (
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canonical.encode()).hexdigest(),
        )
    )
    import hmac as hmac_mod

    sig = hmac_mod.new(
        _sigv4_key(secret, date, region, "s3"), sts.encode(),
        hashlib.sha256,
    ).hexdigest()
    return {
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access}/{scope}, "
            "SignedHeaders=x-amz-content-sha256;x-amz-date, "
            f"Signature={sig}"
        ),
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_sha,
    }


class RGWError(Exception):
    pass


def _data_oid(bucket: str, key: str) -> str:
    return f"rgw.obj.{bucket}/{key}"


def _mp_oid(bucket: str) -> str:
    return f"bucket.multipart.{bucket}"


def _part_oid(bucket: str, key: str, upload_id: str, n: int) -> str:
    return f"rgw.part.{bucket}/{key}.{upload_id}.{n:05d}"


class AccessDenied(RGWError):
    pass


class RGW:
    """The gateway daemon: storage logic + embedded HTTP frontend."""

    def __init__(
        self,
        ioctx,
        auth: bool = False,
        bucket_index_shards: int = 1,
        max_objs_per_shard: int | None = None,
        name: str = "rgw",
    ):
        from .index import BucketIndex, build_rgw_perf

        self.io = ioctx
        self.server = None
        self.port = 0
        self.auth = auth
        self.name = name
        self.lc_worker = None
        self.lc_debug = False
        # sharded bucket-index plane (index.py): every index
        # read/write/list below rides it; new buckets default to
        # this many shards (1 = the legacy single-omap layout)
        self.bucket_index_shards = int(bucket_index_shards)
        self.max_objs_per_shard = (
            _default_max_objs_per_shard()
            if max_objs_per_shard is None
            else int(max_objs_per_shard)
        )
        self.perf = build_rgw_perf("rgw")
        self.index = BucketIndex(self)
        # optional mgr progress-event bridge: callable (event_id,
        # message, fraction, done) fed by the reshard state machine
        # (index.py _report_progress); None = no progress reporting
        self.progress_hook = None
        self.reshard_worker = None
        self._mgr_stop = None
        self._mgr_thread = None
        self._mgr_handle = None  # shared-services stack timer
        # set by _verify per call: was the last verified identity a
        # temporary (STS) credential?  Read immediately by the STS
        # route to refuse self-renewal (handler threads each verify
        # right before reading it, so the gap is per-thread-benign —
        # worst case a refused re-mint)
        self._last_caller_temp = False
        # bucket -> (stamp, rules); see cors_match
        self._cors_cache: dict[str, tuple] = {}
        self._datalog_lock = threading.Lock()
        # per-bucket serialization of bucket-record read-modify-
        # writes (ACL/CORS/lifecycle): two handler threads updating
        # different fields of one record would otherwise each
        # read-modify-write the whole JSON blob and silently drop
        # the other's change (the cls_rgw bucket-index op atomicity
        # the omap blob cannot give us)
        self._bucket_locks: dict[str, threading.Lock] = {}
        self._bucket_locks_guard = threading.Lock()
        # LC_OID create-on-first-use: write_full on an existing
        # object wipes its omap, so creation must be serialized or a
        # losing racer erases another bucket's freshly-set rules
        self._lc_lock = threading.Lock()
        self._datalog_seq: int | None = None

    def _bucket_lock(self, bucket: str) -> threading.Lock:
        with self._bucket_locks_guard:
            lock = self._bucket_locks.get(bucket)
            if lock is None:
                lock = self._bucket_locks[bucket] = threading.Lock()
            return lock

    # -- datalog (rgw datalog/mdlog role, feeding multisite.py) ------------
    def _log_change(self, op: str, bucket: str, key: str | None,
                    user) -> None:
        if user == SYNC_USER:
            return
        with self._datalog_lock:
            if self._datalog_seq is None:
                last = 0
                for seq, _e in self.datalog_entries(0):
                    last = seq
                self._datalog_seq = last
            self._datalog_seq += 1
            seq = self._datalog_seq
            # OMAPSET touches the object into existence; no stat dance
            self.io.omap_set(DATALOG_OID, {
                f"e{seq:016d}": json.dumps(
                    {"op": op, "bucket": bucket, "key": key}
                ).encode()
            })

    def datalog_head(self) -> int:
        with self._datalog_lock:
            if self._datalog_seq is not None:
                return self._datalog_seq
        # cold start: walk forward from the beginning once
        last = 0
        for seq, _e in self.datalog_entries(0):
            last = seq
        return last

    def datalog_entries(self, after: int = 0):
        """(seq, entry) in order for every event past ``after`` —
        PAGED from the marker (each poll costs the new entries, not
        the whole history)."""
        marker = f"e{after:016d}" if after else ""
        while True:
            try:
                vals = self.io.omap_get_vals(
                    DATALOG_OID, start_after=marker, max_return=256
                )
            except (ObjectNotFound, RadosError):
                return
            keys = sorted(k for k in vals if k.startswith("e"))
            if not keys:
                return
            for k in keys:
                yield int(k[1:]), json.loads(vals[k])
            marker = keys[-1]

    # -- users / auth (rgw_user + rgw_auth_s3 roles) -----------------------
    def _put_user_key(self, access: str, record: dict) -> None:
        try:
            self.io.stat(USERS_OID)
        except (ObjectNotFound, RadosError):
            self.io.write_full(USERS_OID, b"")
        self.io.omap_set(
            USERS_OID, {access: json.dumps(record).encode()}
        )

    def create_user(self, name: str) -> tuple[str, str]:
        """Provision a user; returns (access_key, secret_key)."""
        import os as _os

        access = _os.urandom(10).hex().upper()
        secret = _os.urandom(20).hex()
        self._put_user_key(
            access, {"name": name, "secret": secret}
        )
        return access, secret

    # -- STS (rgw_sts.cc / rgw_rest_sts.cc reduced) ------------------------
    def assume_role(
        self, user: str, duration: float = 3600.0
    ) -> tuple[str, str, float]:
        """Issue TEMPORARY credentials bound to ``user`` (the
        AssumeRole/GetSessionToken seat): a fresh access/secret pair
        that signs requests exactly like permanent keys but expires.
        Deviations: no role ARNs/policies — the temp identity IS the
        requesting user (GetSessionToken semantics), and the
        response is JSON, not STS XML."""
        import math
        import os as _os

        duration = float(duration)
        if not math.isfinite(duration) or not (
            0 < duration <= 12 * 3600
        ):
            # nan/inf would defeat the expiry compare entirely; STS
            # itself caps sessions at 12h
            raise RGWError(
                "DurationSeconds must be in (0, 43200] (-EINVAL)"
            )
        access = "TEMP" + _os.urandom(8).hex().upper()
        secret = _os.urandom(20).hex()
        expires = time.time() + duration
        self._put_user_key(access, {
            "name": user, "secret": secret, "expires": expires,
        })
        return access, secret, expires

    def _verify(self, method, path, query, headers, payload) -> str:
        """SigV4 verification; returns the user name or raises
        AccessDenied (403)."""
        authz = headers.get("Authorization", "")
        if not authz.startswith("AWS4-HMAC-SHA256 "):
            raise AccessDenied("missing SigV4 authorization")
        fields = {}
        for part in authz[len("AWS4-HMAC-SHA256 "):].split(","):
            k, _, v = part.strip().partition("=")
            fields[k] = v
        try:
            access, date, region, service, term = fields[
                "Credential"
            ].split("/")
        except (KeyError, ValueError):
            raise AccessDenied("malformed credential scope")
        amz_date = headers.get("x-amz-date", "")
        payload_sha = headers.get("x-amz-content-sha256", "")
        if service != "s3" or term != "aws4_request":
            raise AccessDenied("bad credential scope")
        if not amz_date.startswith(date):
            raise AccessDenied("credential date mismatch")
        import calendar

        try:
            then = calendar.timegm(
                time.strptime(amz_date, "%Y%m%dT%H%M%SZ")
            )
        except ValueError:
            raise AccessDenied("bad x-amz-date")
        if abs(time.time() - then) > SKEW:
            raise AccessDenied("request time too skewed")
        if hashlib.sha256(payload).hexdigest() != payload_sha:
            raise AccessDenied("payload hash mismatch")
        try:
            user = json.loads(
                self.io.omap_get_vals(USERS_OID)[access]
            )
        except (KeyError, ObjectNotFound, RadosError):
            raise AccessDenied("unknown access key")
        if "expires" in user and time.time() > float(user["expires"]):
            # expired STS credentials die hard (and get reaped so
            # the user store does not accrete dead keys)
            try:
                self.io.omap_rm_keys(USERS_OID, [access])
            except (ObjectNotFound, RadosError):
                pass
            raise AccessDenied("temporary credentials expired")
        want = sign_request(
            method, path, query, payload, access, user["secret"],
            region=region, amz_date=amz_date,
        )["Authorization"]
        import hmac as hmac_mod

        if not hmac_mod.compare_digest(want, authz):
            raise AccessDenied("signature mismatch")
        self._last_caller_temp = "expires" in user
        return user["name"]

    # -- ACL plumbing (rgw_acl.cc verify_permission seat) ------------------
    @staticmethod
    def _parse_bucket_rec(raw: bytes) -> dict:
        from .index import decode_bucket_record

        try:
            return decode_bucket_record(raw)
        except ValueError:
            # legacy record (bare ctime string): system-owned
            return {"ctime": raw.decode(), "owner": None,
                    "acl": aclmod.make_acl(None)}

    def _bucket_rec(self, bucket: str) -> dict:
        raw = self._buckets().get(bucket)
        if raw is None:
            raise RGWError(f"no bucket {bucket!r}")
        return self._parse_bucket_rec(raw)

    def _save_bucket_rec(self, bucket: str, rec: dict) -> None:
        from .index import encode_bucket_record

        self.io.omap_set(
            BUCKETS_DIR, {bucket: encode_bucket_record(rec)}
        )

    def _require(
        self,
        user,
        perm: str,
        acl: dict | None,
        bucket_owner: str | None = None,
        what: str = "",
    ) -> None:
        if user in (SYSTEM, SYNC_USER):
            return
        if not aclmod.check(acl, user, perm, bucket_owner):
            raise AccessDenied(
                f"{user or 'anonymous'} lacks {perm} on {what}"
            )

    def _require_owner(self, user, rec: dict, what: str) -> None:
        """Owner-only ops (DeleteBucket, lifecycle management): the
        caller must BE the bucket owner — an owner-less (system)
        bucket is manageable only by SYSTEM callers, and anonymous
        NEVER passes (None == None must not authorize)."""
        if user in (SYSTEM, SYNC_USER):
            return
        owner = rec.get("owner")
        if user is None or owner is None or user != owner:
            raise AccessDenied(
                f"{user or 'anonymous'} does not own {what}"
            )

    def set_bucket_acl(
        self, bucket: str, canned: str, user=SYSTEM
    ) -> None:
        with self._bucket_lock(bucket):
            rec = self._bucket_rec(bucket)
            self._require(
                user, aclmod.WRITE_ACP, rec.get("acl"),
                rec.get("owner"), bucket,
            )
            rec["acl"] = aclmod.make_acl(rec.get("owner"), canned)
            self._save_bucket_rec(bucket, rec)
        self._log_change("bucket_acl", bucket, None, user)

    def get_bucket_acl(self, bucket: str, user=SYSTEM) -> dict:
        rec = self._bucket_rec(bucket)
        self._require(
            user, aclmod.READ_ACP, rec.get("acl"),
            rec.get("owner"), bucket,
        )
        return rec.get("acl") or aclmod.make_acl(rec.get("owner"))

    def set_object_acl(
        self, bucket: str, key: str, canned: str, user=SYSTEM
    ) -> None:
        rec = self._bucket_rec(bucket)
        entry = self.stat_object(bucket, key, rec=rec)
        self._require(
            user, aclmod.WRITE_ACP, entry.get("acl"),
            rec.get("owner"), f"{bucket}/{key}",
        )
        entry["acl"] = aclmod.make_acl(entry.get("owner"), canned)
        self.index.set_entry(bucket, key, entry, rec=rec)
        self._log_change("acl", bucket, key, user)

    def get_object_acl(self, bucket: str, key: str, user=SYSTEM) -> dict:
        rec = self._bucket_rec(bucket)
        entry = self.stat_object(bucket, key)
        self._require(
            user, aclmod.READ_ACP, entry.get("acl"),
            rec.get("owner"), f"{bucket}/{key}",
        )
        return entry.get("acl") or aclmod.make_acl(
            entry.get("owner")
        )

    # -- CORS (rgw_cors.cc reduced) ----------------------------------------
    def put_bucket_cors(
        self, bucket: str, rules: list[dict], user=SYSTEM
    ) -> None:
        """Owner-gated CORS configuration: rules of
        {allowed_origins, allowed_methods, allowed_headers?,
        max_age?}; '*' wildcards origins."""
        rec = self._bucket_rec(bucket)
        self._require_owner(user, rec, bucket)
        known = {"GET", "PUT", "POST", "DELETE", "HEAD"}

        def _ok(r):
            import math

            hdrs = r.get("allowed_headers", ["*"]) if isinstance(
                r, dict
            ) else None
            age = r.get("max_age", 600) if isinstance(r, dict) else 0
            return (
                isinstance(r, dict)
                and isinstance(r.get("allowed_origins"), list)
                and r["allowed_origins"]
                and all(
                    isinstance(o, str) for o in r["allowed_origins"]
                )
                and isinstance(r.get("allowed_methods"), list)
                and r["allowed_methods"]
                and set(r["allowed_methods"]) <= known
                # headers/max_age reach ', '.join and str() in the
                # preflight reply: same list-of-strings rule
                and isinstance(hdrs, list)
                and all(isinstance(h, str) for h in hdrs)
                and isinstance(age, (int, float))
                and not isinstance(age, bool)
                and math.isfinite(age)
                and age >= 0
            )

        if not isinstance(rules, list) or not all(
            _ok(r) for r in rules
        ):
            # STRING values would pass a truthiness check and then
            # char/substring-match in cors_match ("GET" in "FORGET",
            # '*' in "*.example") — lists of strings only
            raise RGWError(
                "each CORS rule needs allowed_origins (list of "
                "strings) and allowed_methods (list from "
                "GET/PUT/POST/DELETE/HEAD)"
            )
        with self._bucket_lock(bucket):
            # re-read under the lock: the record checked above may
            # have been rewritten by a concurrent ACL update
            rec = self._bucket_rec(bucket)
            rec["cors"] = rules
            self._save_bucket_rec(bucket, rec)
        self._cors_cache.pop(bucket, None)
        self._log_change("bucket_acl", bucket, None, user)

    def get_bucket_cors(self, bucket: str, user=SYSTEM) -> list:
        rec = self._bucket_rec(bucket)
        self._require_owner(user, rec, bucket)
        return rec.get("cors", [])

    def delete_bucket_cors(self, bucket: str, user=SYSTEM) -> None:
        with self._bucket_lock(bucket):
            rec = self._bucket_rec(bucket)
            self._require_owner(user, rec, bucket)
            rec.pop("cors", None)
            self._save_bucket_rec(bucket, rec)
        self._cors_cache.pop(bucket, None)
        self._log_change("bucket_acl", bucket, None, user)

    def cors_match(
        self, bucket: str, origin: str, method: str
    ) -> dict | None:
        """First rule admitting (origin, method), else None — the
        RGWCORSConfiguration::host_name_rule walk.  Rules read
        through a short-TTL cache: the echo in _reply would
        otherwise double the bucket-record reads on every
        Origin-carrying request (config changes propagate within
        the TTL; browsers cache preflights far longer via max_age)."""
        now = time.monotonic()
        hit = self._cors_cache.get(bucket)
        if hit is not None and now - hit[0] < 5.0:
            rules = hit[1]
        else:
            try:
                rules = self._bucket_rec(bucket).get("cors", [])
            except RGWError:
                rules = []
            self._cors_cache[bucket] = (now, rules)
            if len(self._cors_cache) > 1024:
                self._cors_cache.pop(
                    next(iter(self._cors_cache))
                )
        if not rules:
            return None
        for rule in rules:
            origins = rule.get("allowed_origins", [])
            if not any(
                o == "*" or o == origin for o in origins
            ):
                continue
            if method in rule.get("allowed_methods", []):
                return rule
        return None

    # -- storage logic (rgw_rados roles) -----------------------------------
    def _buckets(self) -> dict[str, bytes]:
        try:
            return self.io.omap_get_vals(BUCKETS_DIR)
        except (ObjectNotFound, RadosError):
            return {}

    def create_bucket(
        self,
        bucket: str,
        user=SYSTEM,
        canned: str = "private",
        shards: int | None = None,
    ) -> None:
        if user is None:
            # S3: bucket creation always needs an authenticated
            # identity — there is no ACL yet to grant it
            raise AccessDenied("anonymous cannot create buckets")
        if "/" in bucket or not bucket:
            raise RGWError(f"invalid bucket name {bucket!r}")
        if bucket in self._buckets():
            raise RGWError(f"bucket {bucket!r} exists")
        owner = None if user == SYSTEM else user
        idx = self.index.create(
            bucket,
            self.bucket_index_shards if shards is None else shards,
        )
        self._save_bucket_rec(
            bucket,
            {
                "ctime": time.time(),
                "owner": owner,
                "acl": aclmod.make_acl(owner, canned),
                "index": idx,
            },
        )
        self._log_change("create_bucket", bucket, None, user)

    def delete_bucket(self, bucket: str, user=SYSTEM) -> None:
        rec = self._bucket_rec(bucket)
        # DeleteBucket is OWNER-only (S3/RGW): a public-read-write
        # WRITE grant covers objects, never the bucket itself
        self._require_owner(user, rec, bucket)
        if self.index.layout(bucket, rec).resharding():
            # deleting mid-reshard would race the migrator's record
            # reads and the cutover cleanup (the reference refuses
            # this too)
            raise RGWError(f"bucket {bucket!r} is resharding")
        # emptiness must consult EVERY shard of the current
        # generation — one empty shard proves nothing
        if self.index.any_entries(bucket, rec=rec):
            raise RGWError(f"bucket {bucket!r} not empty")
        self.index.remove_index(bucket, rec=rec)
        self.io.omap_rm_keys(BUCKETS_DIR, [bucket])
        self.io.omap_rm_keys(LC_OID, [bucket])
        self._log_change("delete_bucket", bucket, None, user)

    def put_object(
        self,
        bucket: str,
        key: str,
        data: bytes,
        user=SYSTEM,
        canned: str = "private",
    ) -> str:
        rec = self._bucket_rec(bucket)
        self._require(
            user, aclmod.WRITE, rec.get("acl"), rec.get("owner"),
            bucket,
        )
        etag = hashlib.md5(data).hexdigest()
        self._drop_object_data(bucket, key)  # stale manifest parts
        self.io.write_full(_data_oid(bucket, key), data)
        owner = None if user in (SYSTEM, None) else user
        # the index entry commits AFTER the data (the reference's
        # prepare/complete index transaction, collapsed)
        self.index.set_entry(
            bucket,
            key,
            {
                "size": len(data),
                "etag": etag,
                "mtime": time.time(),
                "owner": owner,
                "acl": aclmod.make_acl(owner, canned),
            },
            rec=rec,
        )
        self._log_change("put", bucket, key, user)
        return etag

    def get_object(self, bucket: str, key: str, user=SYSTEM) -> bytes:
        rec = self._bucket_rec(bucket)
        entry = self.stat_object(bucket, key)  # -ENOENT via index
        self._require(
            user, aclmod.READ, entry.get("acl"), rec.get("owner"),
            f"{bucket}/{key}",
        )
        if "parts" in entry:
            data = b"".join(
                self.io.read(oid) for oid in entry["parts"]
            )
        else:
            data = self.io.read(
                entry.get("data_oid") or _data_oid(bucket, key)
            )
        codec = entry.get("compression")
        if codec:
            # a lifecycle transition re-wrote the payload through the
            # compressor; reads stay transparent
            from ..compressor import create as compressor_create

            data = compressor_create(codec).decompress(data)
        if len(data) != entry["size"]:
            raise RGWError(f"{bucket}/{key}: torn object")
        return data

    def stat_object(
        self, bucket: str, key: str, rec: dict | None = None
    ) -> dict:
        """Entry lookup via ONE index shard (no longer a full-index
        read — stat cost is independent of bucket size)."""
        raw = self.index.get_entry(bucket, key, rec=rec)
        if raw is None:
            raise ObjectNotFound(f"{bucket}/{key}")
        return json.loads(raw)

    def delete_object(self, bucket: str, key: str, user=SYSTEM) -> None:
        rec = self._bucket_rec(bucket)
        self._require(
            user, aclmod.WRITE, rec.get("acl"), rec.get("owner"),
            bucket,
        )
        self.stat_object(bucket, key, rec=rec)
        self._drop_object_data(bucket, key)
        self.index.rm_entry(bucket, key, rec=rec)
        self._log_change("delete", bucket, key, user)

    # -- lifecycle (rgw_lc.cc reduced; see lifecycle.py) -------------------
    def put_bucket_lifecycle(
        self, bucket: str, rules: list[dict], user=SYSTEM
    ) -> None:
        rec = self._bucket_rec(bucket)
        # S3: only the bucket owner manages lifecycle
        self._require_owner(user, rec, bucket)
        if not isinstance(rules, list):
            raise RGWError("lifecycle config must be a rule list")
        for rule in rules:
            if not isinstance(rule, dict):
                raise RGWError("each lifecycle rule must be an object")
            if (
                "expiration_days" not in rule
                and "transition_days" not in rule
            ):
                raise RGWError("rule needs expiration or transition")
            for f in ("expiration_days", "transition_days"):
                if f in rule:
                    try:
                        float(rule[f])
                    except (TypeError, ValueError):
                        raise RGWError(f"{f} must be numeric")
            if not isinstance(rule.get("prefix", ""), str):
                raise RGWError("prefix must be a string")
        with self._bucket_lock(bucket), self._lc_lock:
            try:
                self.io.stat(LC_OID)
            except (ObjectNotFound, RadosError):
                self.io.write_full(LC_OID, b"")
            self.io.omap_set(
                LC_OID, {bucket: json.dumps(rules).encode()}
            )
        self._log_change("lifecycle", bucket, None, user)

    def get_bucket_lifecycle(self, bucket: str, user=SYSTEM) -> list:
        rec = self._bucket_rec(bucket)
        self._require_owner(user, rec, bucket)
        try:
            raw = self.io.omap_get_vals(LC_OID).get(bucket)
        except (ObjectNotFound, RadosError):
            raw = None
        return json.loads(raw) if raw else []

    def delete_bucket_lifecycle(self, bucket: str, user=SYSTEM) -> None:
        rec = self._bucket_rec(bucket)
        self._require_owner(user, rec, bucket)
        with self._bucket_lock(bucket):
            self.io.omap_rm_keys(LC_OID, [bucket])
        self._log_change("lifecycle", bucket, None, user)

    def lc_process(self, debug: bool | None = None) -> dict:
        """One scan over every configured bucket (RGWLC::process)."""
        debug = self.lc_debug if debug is None else debug
        totals = {"expired": 0, "transitioned": 0}
        try:
            configs = self.io.omap_get_vals(LC_OID)
        except (ObjectNotFound, RadosError):
            return totals
        for bucket, raw in configs.items():
            stats = apply_rules(self, bucket, json.loads(raw), debug)
            for k in totals:
                totals[k] += stats[k]
        return totals

    def _transition_object(
        self, bucket: str, key: str, storage_class: str
    ) -> None:
        """Move an object to the cold tier: recompress the payload
        (zlib — the framework's real second storage tier) and tag
        the entry.  Multipart manifests consolidate to one blob."""
        from ..compressor import create as compressor_create

        entry = self.stat_object(bucket, key)
        data = self.get_object(bucket, key, user=SYSTEM)
        comp = compressor_create("zlib")
        blob = comp.compress(data)
        old_oids = entry.pop("parts", None) or [
            entry.get("data_oid") or _data_oid(bucket, key)
        ]
        # write the cold blob to a NEW oid, flip the index entry to
        # it, THEN drop the old payload: a concurrent reader holding
        # either entry version reads a consistent (oid, entry) pair —
        # only a reader stale across the final delete sees a
        # transient miss, never a torn object
        cold_oid = _data_oid(bucket, key) + "#cold"
        self.io.write_full(cold_oid, blob)
        entry["data_oid"] = cold_oid
        entry["storage_class"] = storage_class
        entry["compression"] = "zlib"
        self.index.set_entry(bucket, key, entry)
        self._log_change("transition", bucket, key, None)
        for oid in old_oids:
            if oid == cold_oid:
                continue
            try:
                self.io.remove(oid)
            except (ObjectNotFound, RadosError):
                pass

    def start_lc(
        self, interval: float = 1.0, debug: bool = False
    ) -> None:
        """Run the lifecycle worker (RGWLC::start_processor);
        ``debug`` makes *_days count seconds (rgw_lc_debug_interval)."""
        self.lc_debug = debug
        if self.lc_worker is None:
            self.lc_worker = LCWorker(self, interval, debug)

    # -- multipart (rgw multipart manifest role) ---------------------------
    def initiate_multipart(
        self, bucket: str, key: str, user=SYSTEM
    ) -> str:
        rec = self._bucket_rec(bucket)
        self._require(
            user, aclmod.WRITE, rec.get("acl"), rec.get("owner"),
            bucket,
        )
        import os as _os

        upload_id = _os.urandom(8).hex()
        try:
            self.io.stat(_mp_oid(bucket))
        except (ObjectNotFound, RadosError):
            self.io.write_full(_mp_oid(bucket), b"")
        self.io.omap_set(
            _mp_oid(bucket),
            {f"{key}.{upload_id}": b"open"},
        )
        return upload_id

    def _mp_check(self, bucket: str, key: str, upload_id: str) -> None:
        try:
            vals = self.io.omap_get_vals(_mp_oid(bucket))
        except (ObjectNotFound, RadosError):
            vals = {}
        if f"{key}.{upload_id}" not in vals:
            raise RGWError(f"no such upload {upload_id!r}")

    def _mp_parts(
        self, bucket: str, key: str, upload_id: str
    ) -> dict[int, dict]:
        prefix = f"{key}.{upload_id}.part."
        try:
            vals = self.io.omap_get_vals(_mp_oid(bucket))
        except (ObjectNotFound, RadosError):
            vals = {}
        return {
            int(k[len(prefix):]): json.loads(v)
            for k, v in vals.items()
            if k.startswith(prefix)
        }

    def upload_part(
        self, bucket: str, key: str, upload_id: str, part: int,
        data: bytes, user=SYSTEM,
    ) -> str:
        rec = self._bucket_rec(bucket)
        self._require(
            user, aclmod.WRITE, rec.get("acl"), rec.get("owner"),
            bucket,
        )
        if not 1 <= part <= 10000:
            raise RGWError("part number out of range")
        self._mp_check(bucket, key, upload_id)
        etag = hashlib.md5(data).hexdigest()
        self.io.write_full(
            _part_oid(bucket, key, upload_id, part), data
        )
        # ONE omap key per part: concurrent part uploads (the S3
        # client default) never read-modify-write shared state
        self.io.omap_set(
            _mp_oid(bucket),
            {
                f"{key}.{upload_id}.part.{part:05d}": json.dumps(
                    {"etag": etag, "size": len(data)}
                ).encode()
            },
        )
        return etag

    def complete_multipart(
        self, bucket: str, key: str, upload_id: str, user=SYSTEM
    ) -> str:
        """Write the manifest HEAD: the object's index entry points
        at the part objects (no data copy), with the S3-shaped
        composite '-N' etag."""
        rec = self._bucket_rec(bucket)
        self._require(
            user, aclmod.WRITE, rec.get("acl"), rec.get("owner"),
            bucket,
        )
        self._mp_check(bucket, key, upload_id)
        by_num = self._mp_parts(bucket, key, upload_id)
        if not by_num:
            raise RGWError("no parts uploaded")
        parts = sorted(by_num.items())
        md5s = b"".join(
            bytes.fromhex(meta["etag"]) for _n, meta in parts
        )
        etag = (
            hashlib.md5(md5s).hexdigest() + f"-{len(parts)}"
        )
        self._drop_object_data(bucket, key)  # overwrite semantics
        owner = None if user in (SYSTEM, None) else user
        self.index.set_entry(
            bucket,
            key,
            {
                "size": sum(m["size"] for _n, m in parts),
                "etag": etag,
                "mtime": time.time(),
                "owner": owner,
                "acl": aclmod.make_acl(owner),
                "parts": [
                    _part_oid(bucket, key, upload_id, n)
                    for n, _m in parts
                ],
            },
            rec=rec,
        )
        self.io.omap_rm_keys(
            _mp_oid(bucket),
            [f"{key}.{upload_id}"]
            + [
                f"{key}.{upload_id}.part.{n:05d}"
                for n, _m in parts
            ],
        )
        self._log_change("put", bucket, key, user)
        return etag

    def abort_multipart(
        self, bucket: str, key: str, upload_id: str, user=SYSTEM
    ) -> None:
        rec = self._bucket_rec(bucket)
        self._require(
            user, aclmod.WRITE, rec.get("acl"), rec.get("owner"),
            bucket,
        )
        self._mp_check(bucket, key, upload_id)
        by_num = self._mp_parts(bucket, key, upload_id)
        for n in by_num:
            try:
                self.io.remove(_part_oid(bucket, key, upload_id, n))
            except (ObjectNotFound, RadosError):
                pass
        self.io.omap_rm_keys(
            _mp_oid(bucket),
            [f"{key}.{upload_id}"]
            + [
                f"{key}.{upload_id}.part.{n:05d}" for n in by_num
            ],
        )

    def _drop_object_data(self, bucket: str, key: str) -> None:
        """Remove an existing entry's payload (plain or manifest)."""
        try:
            entry = self.stat_object(bucket, key)
        except ObjectNotFound:
            return
        oids = entry.get("parts") or [
            entry.get("data_oid") or _data_oid(bucket, key)
        ]
        for oid in oids:
            try:
                self.io.remove(oid)
            except (ObjectNotFound, RadosError):
                pass

    def list_objects(
        self,
        bucket: str,
        marker: str = "",
        max_keys: int = 1000,
        user=SYSTEM,
    ) -> tuple[list[dict], bool]:
        """Key-ordered page after ``marker`` → (entries, truncated):
        k-way merge-sorted across the bucket's index shards —
        byte-identical to the single-omap listing (see index.py)."""
        rec = self._bucket_rec(bucket)
        self._require(
            user, aclmod.READ, rec.get("acl"), rec.get("owner"),
            bucket,
        )
        page, truncated = self.index.list_page(
            bucket, marker=marker, max_keys=max_keys, rec=rec
        )
        out = []
        for k, raw in page:
            entry = json.loads(raw)
            entry["key"] = k
            out.append(entry)
        return out, truncated

    # -- reshard admin (radosgw-admin bucket reshard roles) ----------------
    def bucket_reshard(
        self, bucket: str, num_shards: int, user=SYSTEM
    ) -> dict:
        """``bucket reshard --num-shards N``: online reshard, owner/
        system only (an index relayout is an administrative act)."""
        rec = self._bucket_rec(bucket)
        self._require_owner(user, rec, bucket)
        return self.index.reshard(bucket, num_shards)

    def reshard_status(self, bucket: str, user=SYSTEM) -> dict:
        """``reshard status --bucket B``."""
        rec = self._bucket_rec(bucket)
        self._require_owner(user, rec, bucket)
        return self.index.status(bucket)

    def reshard_list(self, user=SYSTEM) -> list[dict]:
        """``reshard list``: the pending reshard queue."""
        if user not in (SYSTEM, SYNC_USER):
            raise AccessDenied("reshard list is admin-only")
        return self.index.reshard_queue()

    def reshard_process(self, user=SYSTEM) -> int:
        """``reshard process``: drain the queue now."""
        if user not in (SYSTEM, SYNC_USER):
            raise AccessDenied("reshard process is admin-only")
        return self.index.process_reshard_queue()

    def start_reshard(self, interval: float = 2.0) -> None:
        """Run the background reshard worker (RGWReshard's
        processor thread)."""
        from .index import ReshardWorker

        if self.reshard_worker is None:
            self.reshard_worker = ReshardWorker(self, interval)

    # -- mgr telemetry (perf → MMgrReport → prometheus) --------------------
    def _mgr_report_once(self, state: dict) -> None:
        """One best-effort perf push: discover the active mgr
        through the mon (cached, slow cadence) and send an
        MMgrReport — the exact pipe every daemon's counters ride."""
        from ..msg.message import MMgrReport

        rados = self.io.rados
        now = time.monotonic()
        if state.get("addr") is None and (
            now - state.get("checked", -1e9) < 5.0
        ):
            return
        try:
            if (
                state.get("addr") is None
                or now - state.get("checked", -1e9) > 5.0
            ):
                state["checked"] = now
                rc, outb, _outs = rados.mon_command(
                    {"prefix": "mgr stat"}
                )
                active = (
                    json.loads(outb).get("active") if rc == 0 else None
                )
                addr = active["addr"] if active else None
                if addr != state.get("addr"):
                    state["addr"] = addr
                    state["conn"] = None
            if state.get("addr") is None:
                return
            conn = state.get("conn")
            if conn is None or conn.is_closed:
                host, _, port = state["addr"].rpartition(":")
                conn = state["conn"] = rados.messenger.connect(
                    host, int(port), timeout=5.0
                )
            conn.send(
                MMgrReport(
                    daemon=self.name,
                    perf=json.dumps(self.perf.dump()),
                )
            )
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            state["conn"] = None

    def start_mgr_reports(
        self,
        interval: float = 1.0,
        shared_services: bool | None = None,
    ) -> None:
        """Push ``l_rgw_index_*``/``l_rgw_reshard_*`` to the mgr on
        a timer, like an OSD's stats plane.  With ``shared_services``
        the push rides a shared-stack timer instead of a dedicated
        thread (the PR 14 treatment)."""
        if self._mgr_thread is not None or self._mgr_handle is not None:
            return
        state: dict = {}
        if shared_services:
            stack = self.io.rados.messenger._stack
            self._mgr_handle = stack.timers.every(
                interval, lambda: self._mgr_report_once(state)
            )
            return
        self._mgr_stop = threading.Event()

        def loop():
            while not self._mgr_stop.wait(interval):
                self._mgr_report_once(state)

        self._mgr_thread = threading.Thread(
            target=loop, name=f"{self.name}.mgrreport", daemon=True
        )
        self._mgr_thread.start()

    # -- HTTP frontend (the beast role) ------------------------------------
    def serve(self, port: int = 0) -> int:
        gw = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _reply(self, code, body=b"", ctype="application/xml",
                       headers=None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                headers = dict(headers or {})
                # CORS echo on EVERY response (success AND error —
                # a browser cannot read an un-echoed 403) for the
                # actual request's method; explicit headers win
                if (
                    "Access-Control-Allow-Origin" not in headers
                    and self.headers.get("Origin")
                    and self.command != "OPTIONS"
                ):
                    headers.update(self._cors_headers(
                        self._route()[0], self.command
                    ))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _err(self, code, name, msg):
                if self.command == "HEAD":
                    # HEAD responses must not carry a body or the
                    # keep-alive stream desyncs
                    self._reply(code)
                    return
                body = (
                    f"<Error><Code>{name}</Code>"
                    f"<Message>{escape(msg)}</Message></Error>"
                ).encode()
                self._reply(code, body)

            def _route(self):
                parsed = urllib.parse.urlparse(self.path)
                parts = parsed.path.strip("/").split("/", 1)
                bucket = parts[0] if parts[0] else None
                key = parts[1] if len(parts) > 1 else None
                q = dict(
                    urllib.parse.parse_qsl(
                        parsed.query, keep_blank_values=True
                    )
                )
                return bucket, key, q

            def _body(self) -> bytes:
                length = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(length) if length else b""

            def _user(self, method, payload):
                """Request identity: SYSTEM when the gateway runs
                authless, the verified user for a signed request,
                None for an ANONYMOUS one (no Authorization header —
                the ACLs decide what it may do), or _DENIED (403
                already sent) on a bad signature."""
                if not gw.auth:
                    return SYSTEM
                if not self.headers.get("Authorization"):
                    return None
                parsed = urllib.parse.urlparse(self.path)
                q = dict(
                    urllib.parse.parse_qsl(
                        parsed.query, keep_blank_values=True
                    )
                )
                try:
                    return gw._verify(
                        method, parsed.path, q,
                        {
                            k.lower() if k.lower().startswith("x-amz")
                            else k: v
                            for k, v in self.headers.items()
                        },
                        payload,
                    )
                except AccessDenied as e:
                    self._err(403, "AccessDenied", str(e))
                    return _DENIED

            def do_OPTIONS(self):  # noqa: N802
                """CORS preflight (RGWHandler preflight dispatch)."""
                bucket, _key, _q = self._route()
                origin = self.headers.get("Origin", "")
                want = self.headers.get(
                    "Access-Control-Request-Method", ""
                )
                rule = (
                    gw.cors_match(bucket, origin, want)
                    if bucket and origin and want
                    else None
                )
                if rule is None:
                    self._reply(403)
                    return
                self._reply(200, b"", headers={
                    "Access-Control-Allow-Origin": origin,
                    "Access-Control-Allow-Methods": ", ".join(
                        rule.get("allowed_methods", [])
                    ),
                    "Access-Control-Allow-Headers": ", ".join(
                        rule.get("allowed_headers", ["*"])
                    ),
                    "Access-Control-Max-Age": str(
                        rule.get("max_age", 600)
                    ),
                })

            def _cors_headers(self, bucket, method) -> dict:
                """Actual-request CORS echo: attach Allow-Origin when
                a rule admits this (Origin, method)."""
                origin = self.headers.get("Origin", "")
                if not bucket or not origin:
                    return {}
                rule = gw.cors_match(bucket, origin, method)
                if rule is None:
                    return {}
                return {"Access-Control-Allow-Origin": origin}

            def do_GET(self):  # noqa: N802
                bucket, key, q = self._route()
                user = self._user("GET", b"")
                if user is _DENIED:
                    return
                try:
                    if bucket is not None and key is None and (
                        "cors" in q
                    ):
                        self._reply(
                            200,
                            json.dumps(
                                gw.get_bucket_cors(bucket, user=user)
                            ).encode(),
                            ctype="application/json",
                        )
                    elif bucket is not None and "acl" in q:
                        policy = (
                            gw.get_bucket_acl(bucket, user=user)
                            if key is None
                            else gw.get_object_acl(
                                bucket, key, user=user
                            )
                        )
                        self._reply(
                            200, json.dumps(policy).encode(),
                            ctype="application/json",
                        )
                    elif bucket is not None and key is None and (
                        "lifecycle" in q
                    ):
                        rules = gw.get_bucket_lifecycle(
                            bucket, user=user
                        )
                        self._reply(
                            200, json.dumps(rules).encode(),
                            ctype="application/json",
                        )
                    elif bucket is None:
                        if user is None:
                            raise AccessDenied(
                                "anonymous cannot list buckets"
                            )
                        names = sorted(
                            b for b, raw in gw._buckets().items()
                            if user == SYSTEM
                            or gw._parse_bucket_rec(raw).get(
                                "owner"
                            ) == user
                        )
                        inner = "".join(
                            f"<Bucket><Name>{escape(n)}</Name></Bucket>"
                            for n in names
                        )
                        self._reply(
                            200,
                            (
                                "<ListAllMyBucketsResult><Buckets>"
                                f"{inner}</Buckets>"
                                "</ListAllMyBucketsResult>"
                            ).encode(),
                        )
                    elif key is None:
                        entries, trunc = gw.list_objects(
                            bucket,
                            marker=q.get("marker", ""),
                            max_keys=int(q.get("max-keys", 1000)),
                            user=user,
                        )
                        inner = "".join(
                            "<Contents>"
                            f"<Key>{escape(e['key'])}</Key>"
                            f"<Size>{e['size']}</Size>"
                            f"<ETag>\"{e['etag']}\"</ETag>"
                            "</Contents>"
                            for e in entries
                        )
                        self._reply(
                            200,
                            (
                                "<ListBucketResult>"
                                f"<Name>{escape(bucket)}</Name>"
                                f"<IsTruncated>{str(trunc).lower()}"
                                f"</IsTruncated>{inner}"
                                "</ListBucketResult>"
                            ).encode(),
                        )
                    else:
                        data = gw.get_object(bucket, key, user=user)
                        self._reply(
                            200, data,
                            ctype="application/octet-stream",
                        )
                except AccessDenied as e:
                    self._err(403, "AccessDenied", str(e))
                except ObjectNotFound as e:
                    self._err(404, "NoSuchKey", str(e))
                except RGWError as e:
                    self._err(404, "NoSuchBucket", str(e))

            def do_HEAD(self):  # noqa: N802
                bucket, key, _q = self._route()
                user = self._user("HEAD", b"")
                if user is _DENIED:
                    return
                try:
                    rec = gw._bucket_rec(bucket)
                    entry = gw.stat_object(bucket, key)
                    gw._require(
                        user, aclmod.READ, entry.get("acl"),
                        rec.get("owner"), f"{bucket}/{key}",
                    )
                    self._reply(
                        200, b"",
                        headers={
                            "ETag": f'"{entry["etag"]}"',
                            "X-Object-Size": str(entry["size"]),
                        },
                    )
                except AccessDenied:
                    self._reply(403)
                except (ObjectNotFound, RGWError):
                    self._reply(404)

            def do_PUT(self):  # noqa: N802
                bucket, key, q = self._route()
                body = self._body()
                user = self._user("PUT", body)
                if user is _DENIED:
                    return
                canned = self.headers.get("x-amz-acl", "private")
                try:
                    if bucket is not None and "acl" in q:
                        if key is None:
                            gw.set_bucket_acl(
                                bucket, canned, user=user
                            )
                        else:
                            gw.set_object_acl(
                                bucket, key, canned, user=user
                            )
                        self._reply(200)
                    elif bucket is not None and key is None and (
                        "cors" in q
                    ):
                        gw.put_bucket_cors(
                            bucket, json.loads(body), user=user
                        )
                        self._reply(200)
                    elif bucket is not None and key is None and (
                        "lifecycle" in q
                    ):
                        gw.put_bucket_lifecycle(
                            bucket, json.loads(body), user=user
                        )
                        self._reply(200)
                    elif key is not None and "uploadId" in q:
                        try:
                            part = int(q.get("partNumber", 0))
                        except ValueError:
                            raise RGWError("bad partNumber")
                        etag = gw.upload_part(
                            bucket, key, q["uploadId"], part, body,
                            user=user,
                        )
                        self._reply(
                            200, b"", headers={"ETag": f'"{etag}"'}
                        )
                    elif key is None:
                        gw.create_bucket(
                            bucket, user=user, canned=canned
                        )
                        self._reply(200)
                    else:
                        etag = gw.put_object(
                            bucket, key, body, user=user,
                            canned=canned,
                        )
                        self._reply(
                            200, b"", headers={"ETag": f'"{etag}"'}
                        )
                except AccessDenied as e:
                    self._err(403, "AccessDenied", str(e))
                except (ValueError, KeyError) as e:
                    self._err(400, "MalformedRequest", str(e))
                except RGWError as e:
                    self._err(409, "BucketError", str(e))

            def do_POST(self):  # noqa: N802
                bucket, key, q = self._route()
                body = self._body()
                user = self._user("POST", body)
                if user is _DENIED:
                    return
                try:
                    if bucket is None and q.get("Action") in (
                        "AssumeRole", "GetSessionToken"
                    ):
                        if user is None:
                            self._err(
                                403, "AccessDenied",
                                "STS needs an authenticated caller",
                            )
                            return
                        if gw._last_caller_temp:
                            # session credentials may not self-renew
                            # (real STS rejects this too) — a leaked
                            # short-lived key must actually die
                            self._err(
                                403, "AccessDenied",
                                "temporary credentials cannot call STS",
                            )
                            return
                        try:
                            dur = float(
                                q.get("DurationSeconds", 3600)
                            )
                        except ValueError:
                            self._err(
                                400, "MalformedRequest",
                                "bad DurationSeconds",
                            )
                            return
                        acc, sec, exp = gw.assume_role(user, dur)
                        self._reply(
                            200,
                            json.dumps({
                                "AccessKeyId": acc,
                                "SecretAccessKey": sec,
                                "Expiration": exp,
                            }).encode(),
                            ctype="application/json",
                        )
                    elif key is not None and "uploads" in q:
                        upload_id = gw.initiate_multipart(
                            bucket, key, user=user
                        )
                        self._reply(
                            200,
                            (
                                "<InitiateMultipartUploadResult>"
                                f"<Bucket>{escape(bucket)}</Bucket>"
                                f"<Key>{escape(key)}</Key>"
                                f"<UploadId>{upload_id}</UploadId>"
                                "</InitiateMultipartUploadResult>"
                            ).encode(),
                        )
                    elif key is not None and "uploadId" in q:
                        etag = gw.complete_multipart(
                            bucket, key, q["uploadId"], user=user
                        )
                        self._reply(
                            200,
                            (
                                "<CompleteMultipartUploadResult>"
                                f"<ETag>\"{etag}\"</ETag>"
                                "</CompleteMultipartUploadResult>"
                            ).encode(),
                        )
                    else:
                        self._err(400, "InvalidRequest", "bad POST")
                except AccessDenied as e:
                    self._err(403, "AccessDenied", str(e))
                except RGWError as e:
                    self._err(409, "UploadError", str(e))

            def do_DELETE(self):  # noqa: N802
                bucket, key, q = self._route()
                user = self._user("DELETE", b"")
                if user is _DENIED:
                    return
                try:
                    if key is not None and "uploadId" in q:
                        gw.abort_multipart(
                            bucket, key, q["uploadId"], user=user
                        )
                    elif key is None and "cors" in q:
                        gw.delete_bucket_cors(bucket, user=user)
                    elif key is None and "lifecycle" in q:
                        gw.delete_bucket_lifecycle(bucket, user=user)
                    elif key is None:
                        gw.delete_bucket(bucket, user=user)
                    else:
                        gw.delete_object(bucket, key, user=user)
                    self._reply(204)
                except AccessDenied as e:
                    self._err(403, "AccessDenied", str(e))
                except ObjectNotFound as e:
                    self._err(404, "NoSuchKey", str(e))
                except RGWError as e:
                    self._err(409, "BucketError", str(e))

        self.server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), Handler
        )
        self.port = self.server.server_address[1]
        threading.Thread(
            target=self.server.serve_forever,
            name="rgw.frontend",
            daemon=True,
        ).start()
        return self.port

    def shutdown(self) -> None:
        if self.lc_worker is not None:
            self.lc_worker.stop()
            self.lc_worker = None
        if self.reshard_worker is not None:
            self.reshard_worker.stop()
            self.reshard_worker = None
        if self._mgr_stop is not None:
            self._mgr_stop.set()
            self._mgr_thread.join(timeout=5)
            self._mgr_stop = None
            self._mgr_thread = None
        if self._mgr_handle is not None:
            self._mgr_handle.cancel()
            self._mgr_handle = None
        if self.server is not None:
            self.server.shutdown()
