"""RGW analog — an HTTP object gateway over the rados layer
(src/rgw/: the beast-frontend + rgw_rados layout, reduced to the
load-bearing architecture).

What carries over from the reference's design:

- **The gateway is a rados CLIENT daemon**: it owns no storage; every
  bucket/object operation becomes librados I/O (rgw_rados.cc's role).
- **Bucket indexes are omap objects** (the cls_rgw bucket-index
  pattern): ``bucket.index.<name>`` maps key → JSON entry
  (size/etag/mtime), so listings are key-ordered omap pages with
  marker/max-keys — exactly how S3 ListObjects pagination rides
  RocksDB in the reference.
- **A bucket directory object** (``rgw.buckets``) indexes the
  buckets themselves.
- Object payloads live at ``rgw.obj.<bucket>/<key>``; multipart-scale
  striping would ride osdc/striper.py like rbd (not wired yet).

Served surface (S3-flavored REST over http.server, the beast role):

    PUT    /<bucket>                 create bucket
    DELETE /<bucket>                 remove empty bucket
    GET    /                         ListAllMyBuckets (XML)
    PUT    /<bucket>/<key>           upload (body = object)
    GET    /<bucket>/<key>           download
    HEAD   /<bucket>/<key>           stat
    DELETE /<bucket>/<key>           remove
    GET    /<bucket>?marker=&max-keys=   ListObjects (XML, paged)

Deviations, documented: no auth (S3 signatures/keystone/STS), no
multipart/lifecycle/multisite, single pool.
"""

from __future__ import annotations

import hashlib
import http.server
import json
import threading
import time
import urllib.parse
from xml.sax.saxutils import escape

from ..osdc.objecter import ObjectNotFound, RadosError

__all__ = ["RGW", "RGWError"]

BUCKETS_DIR = "rgw.buckets"


class RGWError(Exception):
    pass


def _index_oid(bucket: str) -> str:
    return f"bucket.index.{bucket}"


def _data_oid(bucket: str, key: str) -> str:
    return f"rgw.obj.{bucket}/{key}"


class RGW:
    """The gateway daemon: storage logic + embedded HTTP frontend."""

    def __init__(self, ioctx):
        self.io = ioctx
        self.server = None
        self.port = 0

    # -- storage logic (rgw_rados roles) -----------------------------------
    def _buckets(self) -> dict[str, bytes]:
        try:
            return self.io.omap_get_vals(BUCKETS_DIR)
        except (ObjectNotFound, RadosError):
            return {}

    def create_bucket(self, bucket: str) -> None:
        if "/" in bucket or not bucket:
            raise RGWError(f"invalid bucket name {bucket!r}")
        if bucket in self._buckets():
            raise RGWError(f"bucket {bucket!r} exists")
        self.io.write_full(_index_oid(bucket), b"")
        self.io.omap_set(
            BUCKETS_DIR, {bucket: str(time.time()).encode()}
        )

    def delete_bucket(self, bucket: str) -> None:
        if bucket not in self._buckets():
            raise RGWError(f"no bucket {bucket!r}")
        if self.io.omap_get_vals(_index_oid(bucket), max_return=1):
            raise RGWError(f"bucket {bucket!r} not empty")
        self.io.remove(_index_oid(bucket))
        self.io.omap_rm_keys(BUCKETS_DIR, [bucket])

    def put_object(self, bucket: str, key: str, data: bytes) -> str:
        if bucket not in self._buckets():
            raise RGWError(f"no bucket {bucket!r}")
        etag = hashlib.md5(data).hexdigest()
        self.io.write_full(_data_oid(bucket, key), data)
        # the index entry commits AFTER the data (the reference's
        # prepare/complete index transaction, collapsed)
        self.io.omap_set(
            _index_oid(bucket),
            {
                key: json.dumps(
                    {
                        "size": len(data),
                        "etag": etag,
                        "mtime": time.time(),
                    }
                ).encode()
            },
        )
        return etag

    def get_object(self, bucket: str, key: str) -> bytes:
        entry = self.stat_object(bucket, key)  # -ENOENT via index
        data = self.io.read(_data_oid(bucket, key))
        if len(data) != entry["size"]:
            raise RGWError(f"{bucket}/{key}: torn object")
        return data

    def stat_object(self, bucket: str, key: str) -> dict:
        vals = self.io.omap_get_vals(_index_oid(bucket))
        if key not in vals:
            raise ObjectNotFound(f"{bucket}/{key}")
        return json.loads(vals[key])

    def delete_object(self, bucket: str, key: str) -> None:
        self.stat_object(bucket, key)
        self.io.remove(_data_oid(bucket, key))
        self.io.omap_rm_keys(_index_oid(bucket), [key])

    def list_objects(
        self, bucket: str, marker: str = "", max_keys: int = 1000
    ) -> tuple[list[dict], bool]:
        """Key-ordered page after ``marker`` → (entries, truncated):
        one omap page read, the bucket-index listing."""
        if bucket not in self._buckets():
            raise RGWError(f"no bucket {bucket!r}")
        vals = self.io.omap_get_vals(
            _index_oid(bucket), start_after=marker,
            max_return=max_keys + 1,
        )
        keys = sorted(vals)
        truncated = len(keys) > max_keys
        out = []
        for k in keys[:max_keys]:
            entry = json.loads(vals[k])
            entry["key"] = k
            out.append(entry)
        return out, truncated

    # -- HTTP frontend (the beast role) ------------------------------------
    def serve(self, port: int = 0) -> int:
        gw = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _reply(self, code, body=b"", ctype="application/xml",
                       headers=None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _err(self, code, name, msg):
                body = (
                    f"<Error><Code>{name}</Code>"
                    f"<Message>{escape(msg)}</Message></Error>"
                ).encode()
                self._reply(code, body)

            def _route(self):
                parsed = urllib.parse.urlparse(self.path)
                parts = parsed.path.strip("/").split("/", 1)
                bucket = parts[0] if parts[0] else None
                key = parts[1] if len(parts) > 1 else None
                q = dict(urllib.parse.parse_qsl(parsed.query))
                return bucket, key, q

            def do_GET(self):  # noqa: N802
                bucket, key, q = self._route()
                try:
                    if bucket is None:
                        names = sorted(gw._buckets())
                        inner = "".join(
                            f"<Bucket><Name>{escape(n)}</Name></Bucket>"
                            for n in names
                        )
                        self._reply(
                            200,
                            (
                                "<ListAllMyBucketsResult><Buckets>"
                                f"{inner}</Buckets>"
                                "</ListAllMyBucketsResult>"
                            ).encode(),
                        )
                    elif key is None:
                        entries, trunc = gw.list_objects(
                            bucket,
                            marker=q.get("marker", ""),
                            max_keys=int(q.get("max-keys", 1000)),
                        )
                        inner = "".join(
                            "<Contents>"
                            f"<Key>{escape(e['key'])}</Key>"
                            f"<Size>{e['size']}</Size>"
                            f"<ETag>\"{e['etag']}\"</ETag>"
                            "</Contents>"
                            for e in entries
                        )
                        self._reply(
                            200,
                            (
                                "<ListBucketResult>"
                                f"<Name>{escape(bucket)}</Name>"
                                f"<IsTruncated>{str(trunc).lower()}"
                                f"</IsTruncated>{inner}"
                                "</ListBucketResult>"
                            ).encode(),
                        )
                    else:
                        data = gw.get_object(bucket, key)
                        self._reply(
                            200, data,
                            ctype="application/octet-stream",
                        )
                except ObjectNotFound as e:
                    self._err(404, "NoSuchKey", str(e))
                except RGWError as e:
                    self._err(404, "NoSuchBucket", str(e))

            def do_HEAD(self):  # noqa: N802
                bucket, key, _q = self._route()
                try:
                    entry = gw.stat_object(bucket, key)
                    self._reply(
                        200, b"",
                        headers={
                            "ETag": f'"{entry["etag"]}"',
                            "X-Object-Size": str(entry["size"]),
                        },
                    )
                except (ObjectNotFound, RGWError):
                    self._reply(404)

            def do_PUT(self):  # noqa: N802
                bucket, key, _q = self._route()
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                try:
                    if key is None:
                        gw.create_bucket(bucket)
                        self._reply(200)
                    else:
                        etag = gw.put_object(bucket, key, body)
                        self._reply(
                            200, b"", headers={"ETag": f'"{etag}"'}
                        )
                except RGWError as e:
                    self._err(409, "BucketError", str(e))

            def do_DELETE(self):  # noqa: N802
                bucket, key, _q = self._route()
                try:
                    if key is None:
                        gw.delete_bucket(bucket)
                    else:
                        gw.delete_object(bucket, key)
                    self._reply(204)
                except ObjectNotFound as e:
                    self._err(404, "NoSuchKey", str(e))
                except RGWError as e:
                    self._err(409, "BucketError", str(e))

        self.server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), Handler
        )
        self.port = self.server.server_address[1]
        threading.Thread(
            target=self.server.serve_forever,
            name="rgw.frontend",
            daemon=True,
        ).start()
        return self.port

    def shutdown(self) -> None:
        if self.server is not None:
            self.server.shutdown()
