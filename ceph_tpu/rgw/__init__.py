"""RGW analog — an HTTP object gateway over the rados layer
(src/rgw/: the beast-frontend + rgw_rados layout, reduced to the
load-bearing architecture).

What carries over from the reference's design:

- **The gateway is a rados CLIENT daemon**: it owns no storage; every
  bucket/object operation becomes librados I/O (rgw_rados.cc's role).
- **Bucket indexes are omap objects** (the cls_rgw bucket-index
  pattern): ``bucket.index.<name>`` maps key → JSON entry
  (size/etag/mtime), so listings are key-ordered omap pages with
  marker/max-keys — exactly how S3 ListObjects pagination rides
  RocksDB in the reference.
- **A bucket directory object** (``rgw.buckets``) indexes the
  buckets themselves.
- Object payloads live at ``rgw.obj.<bucket>/<key>``; multipart-scale
  striping would ride osdc/striper.py like rbd (not wired yet).

Served surface (S3-flavored REST over http.server, the beast role):

    PUT    /<bucket>                 create bucket
    DELETE /<bucket>                 remove empty bucket
    GET    /                         ListAllMyBuckets (XML)
    PUT    /<bucket>/<key>           upload (body = object)
    GET    /<bucket>/<key>           download
    HEAD   /<bucket>/<key>           stat
    DELETE /<bucket>/<key>           remove
    GET    /<bucket>?marker=&max-keys=   ListObjects (XML, paged)

    POST   /<bucket>/<key>?uploads      initiate multipart upload
    PUT    /<bucket>/<key>?uploadId=&partNumber=   upload one part
    POST   /<bucket>/<key>?uploadId=    complete (manifest head)
    DELETE /<bucket>/<key>?uploadId=    abort

Auth (round 4): AWS SigV4-shaped request signing (rgw_auth_s3.cc
role) — users live in an omap-backed store (access key → secret),
the Authorization header carries credential scope + signed headers +
signature, the gateway recomputes the signature over the canonical
request and rejects mismatches/stale dates with 403.  Multipart
(round 4): parts land as separate rados objects; complete writes a
MANIFEST head (the reference's multipart manifest), so GET streams
part reads and the "-N" composite etag matches S3's shape.

Deviations, documented: keystone/STS, lifecycle, multisite, CORS and
ACLs absent; region/service names checked only for self-consistency;
single pool.
"""

from __future__ import annotations

import hashlib
import http.server
import json
import threading
import time
import urllib.parse
from xml.sax.saxutils import escape

from ..osdc.objecter import ObjectNotFound, RadosError

__all__ = ["RGW", "RGWError", "sign_request"]

BUCKETS_DIR = "rgw.buckets"
USERS_OID = "rgw.users"
SKEW = 900.0  # max x-amz-date clock skew (seconds)


def _hmac(key: bytes, msg: str) -> bytes:
    import hmac as hmac_mod

    return hmac_mod.new(key, msg.encode(), hashlib.sha256).digest()


def _sigv4_key(secret: str, date: str, region: str, service: str):
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def _canonical(method, path, query, amz_date, payload_sha) -> str:
    q = "&".join(
        f"{urllib.parse.quote(k, safe='')}="
        f"{urllib.parse.quote(v, safe='')}"
        for k, v in sorted(query.items())
    )
    headers = f"x-amz-content-sha256:{payload_sha}\nx-amz-date:{amz_date}\n"
    return "\n".join(
        (
            method,
            urllib.parse.quote(path),
            q,
            headers,
            "x-amz-content-sha256;x-amz-date",
            payload_sha,
        )
    )


def sign_request(
    method: str,
    path: str,
    query: dict,
    payload: bytes,
    access: str,
    secret: str,
    region: str = "default",
    amz_date: str | None = None,
) -> dict:
    """Headers for a SigV4-shaped request against the gateway (the
    client half; boto-equivalent for this reduced dialect)."""
    amz_date = amz_date or time.strftime(
        "%Y%m%dT%H%M%SZ", time.gmtime()
    )
    date = amz_date[:8]
    payload_sha = hashlib.sha256(payload).hexdigest()
    canonical = _canonical(method, path, query, amz_date, payload_sha)
    scope = f"{date}/{region}/s3/aws4_request"
    sts = "\n".join(
        (
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canonical.encode()).hexdigest(),
        )
    )
    import hmac as hmac_mod

    sig = hmac_mod.new(
        _sigv4_key(secret, date, region, "s3"), sts.encode(),
        hashlib.sha256,
    ).hexdigest()
    return {
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access}/{scope}, "
            "SignedHeaders=x-amz-content-sha256;x-amz-date, "
            f"Signature={sig}"
        ),
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_sha,
    }


class RGWError(Exception):
    pass


def _index_oid(bucket: str) -> str:
    return f"bucket.index.{bucket}"


def _data_oid(bucket: str, key: str) -> str:
    return f"rgw.obj.{bucket}/{key}"


def _mp_oid(bucket: str) -> str:
    return f"bucket.multipart.{bucket}"


def _part_oid(bucket: str, key: str, upload_id: str, n: int) -> str:
    return f"rgw.part.{bucket}/{key}.{upload_id}.{n:05d}"


class AccessDenied(RGWError):
    pass


class RGW:
    """The gateway daemon: storage logic + embedded HTTP frontend."""

    def __init__(self, ioctx, auth: bool = False):
        self.io = ioctx
        self.server = None
        self.port = 0
        self.auth = auth

    # -- users / auth (rgw_user + rgw_auth_s3 roles) -----------------------
    def create_user(self, name: str) -> tuple[str, str]:
        """Provision a user; returns (access_key, secret_key)."""
        import os as _os

        access = _os.urandom(10).hex().upper()
        secret = _os.urandom(20).hex()
        try:
            self.io.stat(USERS_OID)
        except (ObjectNotFound, RadosError):
            self.io.write_full(USERS_OID, b"")
        self.io.omap_set(
            USERS_OID,
            {
                access: json.dumps(
                    {"name": name, "secret": secret}
                ).encode()
            },
        )
        return access, secret

    def _verify(self, method, path, query, headers, payload) -> str:
        """SigV4 verification; returns the user name or raises
        AccessDenied (403)."""
        authz = headers.get("Authorization", "")
        if not authz.startswith("AWS4-HMAC-SHA256 "):
            raise AccessDenied("missing SigV4 authorization")
        fields = {}
        for part in authz[len("AWS4-HMAC-SHA256 "):].split(","):
            k, _, v = part.strip().partition("=")
            fields[k] = v
        try:
            access, date, region, service, term = fields[
                "Credential"
            ].split("/")
        except (KeyError, ValueError):
            raise AccessDenied("malformed credential scope")
        amz_date = headers.get("x-amz-date", "")
        payload_sha = headers.get("x-amz-content-sha256", "")
        if service != "s3" or term != "aws4_request":
            raise AccessDenied("bad credential scope")
        if not amz_date.startswith(date):
            raise AccessDenied("credential date mismatch")
        import calendar

        try:
            then = calendar.timegm(
                time.strptime(amz_date, "%Y%m%dT%H%M%SZ")
            )
        except ValueError:
            raise AccessDenied("bad x-amz-date")
        if abs(time.time() - then) > SKEW:
            raise AccessDenied("request time too skewed")
        if hashlib.sha256(payload).hexdigest() != payload_sha:
            raise AccessDenied("payload hash mismatch")
        try:
            user = json.loads(
                self.io.omap_get_vals(USERS_OID)[access]
            )
        except (KeyError, ObjectNotFound, RadosError):
            raise AccessDenied("unknown access key")
        want = sign_request(
            method, path, query, payload, access, user["secret"],
            region=region, amz_date=amz_date,
        )["Authorization"]
        import hmac as hmac_mod

        if not hmac_mod.compare_digest(want, authz):
            raise AccessDenied("signature mismatch")
        return user["name"]

    # -- storage logic (rgw_rados roles) -----------------------------------
    def _buckets(self) -> dict[str, bytes]:
        try:
            return self.io.omap_get_vals(BUCKETS_DIR)
        except (ObjectNotFound, RadosError):
            return {}

    def create_bucket(self, bucket: str) -> None:
        if "/" in bucket or not bucket:
            raise RGWError(f"invalid bucket name {bucket!r}")
        if bucket in self._buckets():
            raise RGWError(f"bucket {bucket!r} exists")
        self.io.write_full(_index_oid(bucket), b"")
        self.io.omap_set(
            BUCKETS_DIR, {bucket: str(time.time()).encode()}
        )

    def delete_bucket(self, bucket: str) -> None:
        if bucket not in self._buckets():
            raise RGWError(f"no bucket {bucket!r}")
        if self.io.omap_get_vals(_index_oid(bucket), max_return=1):
            raise RGWError(f"bucket {bucket!r} not empty")
        self.io.remove(_index_oid(bucket))
        self.io.omap_rm_keys(BUCKETS_DIR, [bucket])

    def put_object(self, bucket: str, key: str, data: bytes) -> str:
        if bucket not in self._buckets():
            raise RGWError(f"no bucket {bucket!r}")
        etag = hashlib.md5(data).hexdigest()
        self._drop_object_data(bucket, key)  # stale manifest parts
        self.io.write_full(_data_oid(bucket, key), data)
        # the index entry commits AFTER the data (the reference's
        # prepare/complete index transaction, collapsed)
        self.io.omap_set(
            _index_oid(bucket),
            {
                key: json.dumps(
                    {
                        "size": len(data),
                        "etag": etag,
                        "mtime": time.time(),
                    }
                ).encode()
            },
        )
        return etag

    def get_object(self, bucket: str, key: str) -> bytes:
        entry = self.stat_object(bucket, key)  # -ENOENT via index
        if "parts" in entry:
            data = b"".join(
                self.io.read(oid) for oid in entry["parts"]
            )
        else:
            data = self.io.read(_data_oid(bucket, key))
        if len(data) != entry["size"]:
            raise RGWError(f"{bucket}/{key}: torn object")
        return data

    def stat_object(self, bucket: str, key: str) -> dict:
        vals = self.io.omap_get_vals(_index_oid(bucket))
        if key not in vals:
            raise ObjectNotFound(f"{bucket}/{key}")
        return json.loads(vals[key])

    def delete_object(self, bucket: str, key: str) -> None:
        self.stat_object(bucket, key)
        self._drop_object_data(bucket, key)
        self.io.omap_rm_keys(_index_oid(bucket), [key])

    # -- multipart (rgw multipart manifest role) ---------------------------
    def initiate_multipart(self, bucket: str, key: str) -> str:
        if bucket not in self._buckets():
            raise RGWError(f"no bucket {bucket!r}")
        import os as _os

        upload_id = _os.urandom(8).hex()
        try:
            self.io.stat(_mp_oid(bucket))
        except (ObjectNotFound, RadosError):
            self.io.write_full(_mp_oid(bucket), b"")
        self.io.omap_set(
            _mp_oid(bucket),
            {f"{key}.{upload_id}": b"open"},
        )
        return upload_id

    def _mp_check(self, bucket: str, key: str, upload_id: str) -> None:
        try:
            vals = self.io.omap_get_vals(_mp_oid(bucket))
        except (ObjectNotFound, RadosError):
            vals = {}
        if f"{key}.{upload_id}" not in vals:
            raise RGWError(f"no such upload {upload_id!r}")

    def _mp_parts(
        self, bucket: str, key: str, upload_id: str
    ) -> dict[int, dict]:
        prefix = f"{key}.{upload_id}.part."
        try:
            vals = self.io.omap_get_vals(_mp_oid(bucket))
        except (ObjectNotFound, RadosError):
            vals = {}
        return {
            int(k[len(prefix):]): json.loads(v)
            for k, v in vals.items()
            if k.startswith(prefix)
        }

    def upload_part(
        self, bucket: str, key: str, upload_id: str, part: int,
        data: bytes,
    ) -> str:
        if not 1 <= part <= 10000:
            raise RGWError("part number out of range")
        self._mp_check(bucket, key, upload_id)
        etag = hashlib.md5(data).hexdigest()
        self.io.write_full(
            _part_oid(bucket, key, upload_id, part), data
        )
        # ONE omap key per part: concurrent part uploads (the S3
        # client default) never read-modify-write shared state
        self.io.omap_set(
            _mp_oid(bucket),
            {
                f"{key}.{upload_id}.part.{part:05d}": json.dumps(
                    {"etag": etag, "size": len(data)}
                ).encode()
            },
        )
        return etag

    def complete_multipart(
        self, bucket: str, key: str, upload_id: str
    ) -> str:
        """Write the manifest HEAD: the object's index entry points
        at the part objects (no data copy), with the S3-shaped
        composite '-N' etag."""
        self._mp_check(bucket, key, upload_id)
        by_num = self._mp_parts(bucket, key, upload_id)
        if not by_num:
            raise RGWError("no parts uploaded")
        parts = sorted(by_num.items())
        md5s = b"".join(
            bytes.fromhex(meta["etag"]) for _n, meta in parts
        )
        etag = (
            hashlib.md5(md5s).hexdigest() + f"-{len(parts)}"
        )
        self._drop_object_data(bucket, key)  # overwrite semantics
        self.io.omap_set(
            _index_oid(bucket),
            {
                key: json.dumps(
                    {
                        "size": sum(m["size"] for _n, m in parts),
                        "etag": etag,
                        "mtime": time.time(),
                        "parts": [
                            _part_oid(bucket, key, upload_id, n)
                            for n, _m in parts
                        ],
                    }
                ).encode()
            },
        )
        self.io.omap_rm_keys(
            _mp_oid(bucket),
            [f"{key}.{upload_id}"]
            + [
                f"{key}.{upload_id}.part.{n:05d}"
                for n, _m in parts
            ],
        )
        return etag

    def abort_multipart(
        self, bucket: str, key: str, upload_id: str
    ) -> None:
        self._mp_check(bucket, key, upload_id)
        by_num = self._mp_parts(bucket, key, upload_id)
        for n in by_num:
            try:
                self.io.remove(_part_oid(bucket, key, upload_id, n))
            except (ObjectNotFound, RadosError):
                pass
        self.io.omap_rm_keys(
            _mp_oid(bucket),
            [f"{key}.{upload_id}"]
            + [
                f"{key}.{upload_id}.part.{n:05d}" for n in by_num
            ],
        )

    def _drop_object_data(self, bucket: str, key: str) -> None:
        """Remove an existing entry's payload (plain or manifest)."""
        try:
            entry = self.stat_object(bucket, key)
        except ObjectNotFound:
            return
        for oid in entry.get("parts", [_data_oid(bucket, key)]):
            try:
                self.io.remove(oid)
            except (ObjectNotFound, RadosError):
                pass

    def list_objects(
        self, bucket: str, marker: str = "", max_keys: int = 1000
    ) -> tuple[list[dict], bool]:
        """Key-ordered page after ``marker`` → (entries, truncated):
        one omap page read, the bucket-index listing."""
        if bucket not in self._buckets():
            raise RGWError(f"no bucket {bucket!r}")
        vals = self.io.omap_get_vals(
            _index_oid(bucket), start_after=marker,
            max_return=max_keys + 1,
        )
        keys = sorted(vals)
        truncated = len(keys) > max_keys
        out = []
        for k in keys[:max_keys]:
            entry = json.loads(vals[k])
            entry["key"] = k
            out.append(entry)
        return out, truncated

    # -- HTTP frontend (the beast role) ------------------------------------
    def serve(self, port: int = 0) -> int:
        gw = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _reply(self, code, body=b"", ctype="application/xml",
                       headers=None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _err(self, code, name, msg):
                body = (
                    f"<Error><Code>{name}</Code>"
                    f"<Message>{escape(msg)}</Message></Error>"
                ).encode()
                self._reply(code, body)

            def _route(self):
                parsed = urllib.parse.urlparse(self.path)
                parts = parsed.path.strip("/").split("/", 1)
                bucket = parts[0] if parts[0] else None
                key = parts[1] if len(parts) > 1 else None
                q = dict(
                    urllib.parse.parse_qsl(
                        parsed.query, keep_blank_values=True
                    )
                )
                return bucket, key, q

            def _body(self) -> bytes:
                length = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(length) if length else b""

            def _authorize(self, method, payload) -> bool:
                """SigV4 gate (when the gateway runs with auth)."""
                if not gw.auth:
                    return True
                parsed = urllib.parse.urlparse(self.path)
                q = dict(
                    urllib.parse.parse_qsl(
                        parsed.query, keep_blank_values=True
                    )
                )
                try:
                    gw._verify(
                        method, parsed.path, q,
                        {
                            k.lower() if k.lower().startswith("x-amz")
                            else k: v
                            for k, v in self.headers.items()
                        },
                        payload,
                    )
                    return True
                except AccessDenied as e:
                    self._err(403, "AccessDenied", str(e))
                    return False

            def do_GET(self):  # noqa: N802
                bucket, key, q = self._route()
                if not self._authorize("GET", b""):
                    return
                try:
                    if bucket is None:
                        names = sorted(gw._buckets())
                        inner = "".join(
                            f"<Bucket><Name>{escape(n)}</Name></Bucket>"
                            for n in names
                        )
                        self._reply(
                            200,
                            (
                                "<ListAllMyBucketsResult><Buckets>"
                                f"{inner}</Buckets>"
                                "</ListAllMyBucketsResult>"
                            ).encode(),
                        )
                    elif key is None:
                        entries, trunc = gw.list_objects(
                            bucket,
                            marker=q.get("marker", ""),
                            max_keys=int(q.get("max-keys", 1000)),
                        )
                        inner = "".join(
                            "<Contents>"
                            f"<Key>{escape(e['key'])}</Key>"
                            f"<Size>{e['size']}</Size>"
                            f"<ETag>\"{e['etag']}\"</ETag>"
                            "</Contents>"
                            for e in entries
                        )
                        self._reply(
                            200,
                            (
                                "<ListBucketResult>"
                                f"<Name>{escape(bucket)}</Name>"
                                f"<IsTruncated>{str(trunc).lower()}"
                                f"</IsTruncated>{inner}"
                                "</ListBucketResult>"
                            ).encode(),
                        )
                    else:
                        data = gw.get_object(bucket, key)
                        self._reply(
                            200, data,
                            ctype="application/octet-stream",
                        )
                except ObjectNotFound as e:
                    self._err(404, "NoSuchKey", str(e))
                except RGWError as e:
                    self._err(404, "NoSuchBucket", str(e))

            def do_HEAD(self):  # noqa: N802
                bucket, key, _q = self._route()
                if not self._authorize("HEAD", b""):
                    return
                try:
                    entry = gw.stat_object(bucket, key)
                    self._reply(
                        200, b"",
                        headers={
                            "ETag": f'"{entry["etag"]}"',
                            "X-Object-Size": str(entry["size"]),
                        },
                    )
                except (ObjectNotFound, RGWError):
                    self._reply(404)

            def do_PUT(self):  # noqa: N802
                bucket, key, q = self._route()
                body = self._body()
                if not self._authorize("PUT", body):
                    return
                try:
                    if key is not None and "uploadId" in q:
                        try:
                            part = int(q.get("partNumber", 0))
                        except ValueError:
                            raise RGWError("bad partNumber")
                        etag = gw.upload_part(
                            bucket, key, q["uploadId"], part, body,
                        )
                        self._reply(
                            200, b"", headers={"ETag": f'"{etag}"'}
                        )
                    elif key is None:
                        gw.create_bucket(bucket)
                        self._reply(200)
                    else:
                        etag = gw.put_object(bucket, key, body)
                        self._reply(
                            200, b"", headers={"ETag": f'"{etag}"'}
                        )
                except RGWError as e:
                    self._err(409, "BucketError", str(e))

            def do_POST(self):  # noqa: N802
                bucket, key, q = self._route()
                body = self._body()
                if not self._authorize("POST", body):
                    return
                try:
                    if key is not None and "uploads" in q:
                        upload_id = gw.initiate_multipart(bucket, key)
                        self._reply(
                            200,
                            (
                                "<InitiateMultipartUploadResult>"
                                f"<Bucket>{escape(bucket)}</Bucket>"
                                f"<Key>{escape(key)}</Key>"
                                f"<UploadId>{upload_id}</UploadId>"
                                "</InitiateMultipartUploadResult>"
                            ).encode(),
                        )
                    elif key is not None and "uploadId" in q:
                        etag = gw.complete_multipart(
                            bucket, key, q["uploadId"]
                        )
                        self._reply(
                            200,
                            (
                                "<CompleteMultipartUploadResult>"
                                f"<ETag>\"{etag}\"</ETag>"
                                "</CompleteMultipartUploadResult>"
                            ).encode(),
                        )
                    else:
                        self._err(400, "InvalidRequest", "bad POST")
                except RGWError as e:
                    self._err(409, "UploadError", str(e))

            def do_DELETE(self):  # noqa: N802
                bucket, key, q = self._route()
                if not self._authorize("DELETE", b""):
                    return
                try:
                    if key is not None and "uploadId" in q:
                        gw.abort_multipart(bucket, key, q["uploadId"])
                    elif key is None:
                        gw.delete_bucket(bucket)
                    else:
                        gw.delete_object(bucket, key)
                    self._reply(204)
                except ObjectNotFound as e:
                    self._err(404, "NoSuchKey", str(e))
                except RGWError as e:
                    self._err(409, "BucketError", str(e))

        self.server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), Handler
        )
        self.port = self.server.server_address[1]
        threading.Thread(
            target=self.server.serve_forever,
            name="rgw.frontend",
            daemon=True,
        ).start()
        return self.port

    def shutdown(self) -> None:
        if self.server is not None:
            self.server.shutdown()
