"""RGW multisite — cross-zone asynchronous bucket/object sync
(src/rgw/rgw_sync.cc + rgw_data_sync.cc, reduced to the working
core: a per-zone DATALOG of change events and a sync agent that
tails it into another zone).

Every mutating gateway op appends a datalog entry (the reference's
datalog/mdlog shards collapsed to one ordered omap log).  A
``SyncAgent`` replicates zone A → zone B:

- **full sync** (bootstrap): with no marker recorded, every bucket
  and object copies over (data, ACLs, lifecycle configs), then the
  marker jumps to the datalog head.
- **incremental sync**: the agent tails entries after its marker —
  put/delete/acl events re-fetch the current source state and apply
  it to the destination — and persists the marker AT the
  destination zone (where the reference keeps sync status too), so
  a restarted agent resumes.

Run two agents in opposite directions for active-active (last
writer wins per object, as in the reference's merge semantics for
concurrent writes to different sites).

Deviations: one ordered log (no sharding), no metadata-vs-data log
split, no incremental-vs-full per-bucket state machine — the full
pass is idempotent re-copy."""

from __future__ import annotations

import threading
import time

from ..osdc.objecter import ObjectNotFound, RadosError
from . import SYNC_USER, SYSTEM, RGWError

MARKER_OID = "rgw.sync.markers"


class SyncAgent:
    def __init__(self, src, dst, zone: str = "secondary",
                 interval: float = 0.5):
        self.src = src  # source RGW
        self.dst = dst  # destination RGW
        self.zone = zone
        self.interval = interval
        self.full_syncs = 0
        self.entries_applied = 0
        # last swallowed sync_once failure (cleared by the next clean
        # pass) — the agent survives transient errors, but a stuck
        # bootstrap must be diagnosable from outside the thread
        self.last_error: str | None = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name=f"rgw-sync.{zone}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sync_once()
                self.last_error = None
            except Exception as e:  # noqa: BLE001 — the agent survives
                self.last_error = f"{type(e).__name__}: {e}"

    # -- marker (sync status lives at the DESTINATION) ---------------------
    def _get_marker(self) -> int | None:
        try:
            vals = self.dst.io.omap_get_vals(MARKER_OID)
        except (ObjectNotFound, RadosError):
            return None
        raw = vals.get(f"marker.{self.zone}")
        return int(raw) if raw is not None else None

    def _set_marker(self, seq: int) -> None:
        try:
            self.dst.io.stat(MARKER_OID)
        except (ObjectNotFound, RadosError):
            self.dst.io.write_full(MARKER_OID, b"")
        self.dst.io.omap_set(
            MARKER_OID, {f"marker.{self.zone}": str(seq).encode()}
        )

    # -- passes ------------------------------------------------------------
    def sync_once(self) -> int:
        marker = self._get_marker()
        if marker is None:
            head = self.src.datalog_head()
            self._full_sync()
            self._set_marker(head)
            self.full_syncs += 1
            return 0
        applied = 0
        for seq, ent in self.src.datalog_entries(after=marker):
            self._apply(ent)
            self._set_marker(seq)
            applied += 1
            self.entries_applied += 1
        return applied

    def _full_sync(self) -> None:
        for bucket in self.src._buckets():
            self._ensure_bucket(bucket)
            marker = ""
            while True:
                entries, truncated = self.src.list_objects(
                    bucket, marker=marker, max_keys=256, user=SYSTEM
                )
                for e in entries:
                    self._copy_object(bucket, e["key"])
                    marker = e["key"]
                if not truncated:
                    break

    def _ensure_bucket(self, bucket: str) -> None:
        rec = dict(self.src._bucket_rec(bucket))
        try:
            self.dst._bucket_rec(bucket)
        except RGWError:
            self.dst.create_bucket(bucket, user=SYNC_USER)
        # owner/acl + lifecycle follow the source (metadata sync).
        # NOT the index layout: each zone shards and reshards its
        # indexes independently (adopting the source's descriptor
        # would point the replica at shard objects it never wrote —
        # every previously synced entry would vanish from listings).
        # The read-modify-write runs under the destination's bucket
        # lock with the destination record re-read inside it, and
        # keeps the destination's OWN index + live reshard
        # descriptor verbatim: an unlocked save racing the reshard
        # state machine could erase a cutover mark (losing a
        # concurrent write's redo signal) or revert a freshly
        # flipped generation
        with self.dst._bucket_lock(bucket):
            drec = self.dst._bucket_rec(bucket)
            rec["index"] = drec.get("index") or {
                "gen": 0, "num_shards": 1,
            }
            rec.pop("reshard", None)
            if "reshard" in drec:
                rec["reshard"] = drec["reshard"]
            self.dst._save_bucket_rec(bucket, rec)
        rules = self.src.get_bucket_lifecycle(bucket, user=SYSTEM)
        if rules:
            self.dst.put_bucket_lifecycle(bucket, rules, user=SYNC_USER)
        else:
            # a rule deleted at the source must die at the replica
            # too, or its LC keeps expiring objects cluster-wide
            self.dst.delete_bucket_lifecycle(bucket, user=SYNC_USER)

    def _copy_object(self, bucket: str, key: str) -> None:
        try:
            data = self.src.get_object(bucket, key, user=SYSTEM)
            entry = self.src.stat_object(bucket, key)
        except (ObjectNotFound, RGWError):
            return  # raced a delete; the datalog entry will follow
        self.dst.put_object(bucket, key, data, user=SYNC_USER)
        # carry the index metadata the put reset (owner/acl).  NOT
        # storage_class: the copy lands the UNCOMPRESSED head bytes,
        # so stamping the source's COLD class would make the replica
        # claim a transition that never happened (reads would try the
        # compressed-payload path against plain bytes, and the
        # destination LC would skip the object forever); leaving the
        # class STANDARD lets the destination's own lifecycle
        # re-transition it for real
        dentry = self.dst.stat_object(bucket, key)
        for k in ("owner", "acl"):
            if k in entry:
                dentry[k] = entry[k]
        # through the index layer (the destination bucket may be
        # sharded — or mid-reshard — independently of the source)
        self.dst.index.set_entry(bucket, key, dentry)

    def _apply(self, ent: dict) -> None:
        op, bucket, key = ent["op"], ent["bucket"], ent.get("key")
        try:
            if op == "create_bucket":
                self._ensure_bucket(bucket)
            elif op == "delete_bucket":
                try:
                    self.dst.delete_bucket(bucket, user=SYNC_USER)
                except RGWError:
                    pass
            elif op in ("put", "acl", "transition"):
                self._ensure_bucket(bucket)
                self._copy_object(bucket, key)
            elif op == "delete":
                try:
                    self.dst.delete_object(bucket, key, user=SYNC_USER)
                except (ObjectNotFound, RGWError):
                    pass
            elif op in ("lifecycle", "bucket_acl"):
                self._ensure_bucket(bucket)
        except RGWError:
            pass  # destination-side hiccup; the next full pass heals
