"""Sharded bucket-index plane — hash-sharded RGW indexes, k-way
merged listings, and ONLINE dynamic resharding (src/cls/rgw/ +
src/rgw/rgw_reshard.cc roles, reduced to the load-bearing
architecture).

Why: a bucket whose index is ONE omap object serializes every index
mutation on a single PG/OSD — the classic real-Ceph hot-spot once a
bucket holds millions of objects.  The reference shards the index
over ``rgw_override_bucket_index_max_shards`` rados objects keyed by
name hash and reshards BUSY buckets online (RGWReshard).  Same
machinery here:

**Shard layout.**  A bucket's metadata record carries an ``index``
descriptor ``{"gen": G, "num_shards": N}``.  Entry ``key`` lives in
shard ``crc32(key) % N`` at oid ``bucket.index.<name>.<G>.<shard>``.
The (gen 0, 1 shard) layout keeps the LEGACY single-object oid
``bucket.index.<name>`` so pre-shard buckets (and their on-disk
indexes) read unchanged.

**Listings.**  Paged ListObjects k-way merge-sorts per-shard omap
pages (each shard iterator keeps its OWN continuation marker and
pulls successive pages lazily), so the merged page is byte-identical
to the unsharded listing: within one generation a key hashes to
exactly one shard, keys are globally unique, and the global
``marker`` / ``max-keys`` contract is preserved verbatim.

**Online reshard** (the RGWReshard state machine):

1. ``in_progress`` is marked in the bucket record (a ``reshard``
   descriptor naming target gen/shards).  From this point every
   index mutation DUAL-WRITES: current gen (authoritative) + target
   gen.
2. Migration copies gen-G entries into the gen-G+1 shard set in
   fixpoint passes — each pass re-diffs both generations and fixes
   any divergence (a copy racing a concurrent write can land a stale
   value or resurrect a deleted key; the next pass repairs it, and
   convergence needs one CLEAN pass).
3. ``cutover``: writers briefly park (retry loop against the bucket
   record) while a final clean pass runs with the write stream
   quiesced, then the record flips atomically to
   ``{"gen": G+1, "num_shards": M}`` and the reshard descriptor is
   dropped.  Old-gen shard objects are removed after the flip.

Lost-entry proof sketch: a writer writes under layout L then
RE-READS the record; if the layout changed it redoes the write under
the new layout.  So an old-gen-only write either (a) completed
before the ``in_progress`` mark — hence before the first copy pass
read its shard — or (b) observes the mark on re-read and redoes as a
dual-write.  Phantom proof: a delete under ``in_progress`` removes
the key from BOTH generations; a copy pass that raced it re-adds the
old value to the target gen, and the next fixpoint pass (old gen no
longer holds the key) removes it again — the clean-pass exit
criterion guarantees the cutover snapshot diverges nowhere.

A crash mid-reshard leaves ``in_progress`` in the record: gen G
stays authoritative, readers and listings are untouched, writers
keep dual-writing (idempotent), and re-running the reshard RESUMES
(the fixpoint passes converge from any partial state).  A crash
mid-``cutover`` is bounded by ``CUTOVER_GRACE``: writers treat a
stale cutover as ``in_progress`` (dual-write, no park) so traffic
flows until an admin restarts the reshard.

**Reshard queue** (RGWReshard's reshard log): every
``check_interval``-th mutation of a bucket counts the shard it just
wrote (a ``max_return``-bounded page read); past
``rgw_max_objs_per_shard`` the bucket is queued in the ``rgw.reshard``
omap log with a computed target shard count, drained by
``process_reshard_queue`` (or the background ``ReshardWorker``).

Migration writes the shard omaps DIRECTLY — never through
``put_object`` — so migrated entries are invisible to the multisite
datalog and replication streams ride a reshard without re-emitting
(the reference short-circuits reshard index ops the same way).

Deviations, documented: crc32 stands in for ceph_str_hash_linux; the
bucket record is the reshard state authority (no cls_rgw guards), so
one gateway process must own a bucket's reshard at a time; no
per-shard bi-log (the zone datalog stays the replication spine).
"""

from __future__ import annotations

import heapq
import json
import threading
import time
import zlib

from ..common.perf_counters import PerfCountersBuilder
from ..osdc.objecter import ObjectNotFound, RadosError

__all__ = [
    "BucketIndex",
    "ReshardWorker",
    "build_rgw_perf",
    "decode_bucket_record",
    "decode_reshard_entry",
    "encode_bucket_record",
    "encode_reshard_entry",
    "shard_of",
    "shard_oid",
]

RESHARD_OID = "rgw.reshard"  # the reshard queue/log object
RESHARD_NONE = ""
RESHARD_IN_PROGRESS = "in_progress"
RESHARD_CUTOVER = "cutover"
# a cutover older than this is a crashed resharder: writers fall back
# to dual-writing instead of parking forever
CUTOVER_GRACE = 5.0
# bounded writer park during a live cutover (well above any observed
# final-pass duration; a writer that exhausts it errors out busy,
# the reference's ERR_BUSY_RESHARDING)
_MUTATE_RETRIES = 400
_STALL_SLEEP = 0.02
_PAGE = 1024  # per-shard omap page size for full walks
_BATCH = 512  # omap_set batch size during migration


def shard_of(key: str, num_shards: int) -> int:
    """Stable name-hash shard choice (the ceph_str_hash seat)."""
    if num_shards <= 1:
        return 0
    return zlib.crc32(key.encode("utf-8")) % num_shards


def shard_oid(bucket: str, gen: int, shard: int, num_shards: int) -> str:
    """Index shard object name.  The (gen 0, 1 shard) layout keeps
    the legacy single-object name so pre-shard buckets read
    unchanged (and the unsharded fast path stays byte-compatible
    with everything ever written)."""
    if gen == 0 and num_shards <= 1:
        return f"bucket.index.{bucket}"
    return f"bucket.index.{bucket}.{gen}.{shard}"


# -- canonical encodings (dencoder-pinned) -----------------------------------
def encode_bucket_record(rec: dict) -> bytes:
    """Canonical bucket-record bytes: key-sorted, separator-minimal
    JSON so decode+re-encode is byte-stable (the dencoder corpus
    pins this shape as ``rgw_bucket_record``)."""
    return json.dumps(
        rec, sort_keys=True, separators=(",", ":")
    ).encode()


def decode_bucket_record(raw: bytes) -> dict:
    rec = json.loads(raw)
    if not isinstance(rec, dict):
        raise ValueError("bucket record is not an object")
    return rec


def encode_reshard_entry(ent: dict) -> bytes:
    """Canonical reshard-log entry bytes (``rgw_reshard_entry``)."""
    return json.dumps(
        ent, sort_keys=True, separators=(",", ":")
    ).encode()


def decode_reshard_entry(raw: bytes) -> dict:
    ent = json.loads(raw)
    if not isinstance(ent, dict):
        raise ValueError("reshard entry is not an object")
    return ent


# -- telemetry ---------------------------------------------------------------
def build_rgw_perf(name: str = "rgw"):
    """The gateway's index/reshard counter families
    (``l_rgw_index_*`` / ``l_rgw_reshard_*``), riding the same
    perf → MMgrReport → prometheus pipe as every other daemon."""
    b = PerfCountersBuilder(name)
    b.add_u64_counter(
        "l_rgw_index_ops", "index entry mutations (set/remove)"
    )
    b.add_u64_counter(
        "l_rgw_index_reads", "index entry/stat shard reads"
    )
    b.add_u64_counter(
        "l_rgw_index_list_pages",
        "per-shard omap pages pulled by merged listings",
    )
    b.add_u64_counter(
        "l_rgw_index_list_entries",
        "entries served by merged listings",
    )
    b.add_u64_counter(
        "l_rgw_index_retries",
        "mutations redone because the index layout moved underneath",
    )
    b.add_u64_counter(
        "l_rgw_index_dual_writes",
        "mutations mirrored to the reshard target generation",
    )
    b.add_u64_counter(
        "l_rgw_index_stall_waits",
        "writer park iterations while a cutover ran",
    )
    b.add_u64_gauge(
        "l_rgw_index_shards",
        "index shard count of the last bucket touched",
    )
    b.add_u64_counter(
        "l_rgw_reshard_queued",
        "buckets queued for reshard by the per-shard fill check",
    )
    b.add_u64_counter(
        "l_rgw_reshard_started", "reshards started (incl. resumes)"
    )
    b.add_u64_counter(
        "l_rgw_reshard_completed", "reshards cut over"
    )
    b.add_u64_counter(
        "l_rgw_reshard_entries_migrated",
        "entries copied/fixed into the target generation",
    )
    b.add_u64_counter(
        "l_rgw_reshard_passes", "migration fixpoint passes run"
    )
    b.add_u64_gauge(
        "l_rgw_reshard_in_progress", "reshards currently running"
    )
    return b.create_perf_counters()


class _Layout:
    """One observation of a bucket's index layout.  ``epoch()``
    captures everything a writer must re-validate after its write:
    a change means the write may have missed a generation and must
    be redone under the new layout."""

    __slots__ = (
        "gen", "num_shards", "status", "target_gen",
        "target_shards", "stamp",
    )

    def __init__(self, rec: dict):
        idx = rec.get("index") or {}
        self.gen = int(idx.get("gen", 0))
        self.num_shards = int(idx.get("num_shards", 1))
        rs = rec.get("reshard") or {}
        self.status = str(rs.get("status", RESHARD_NONE))
        self.target_gen = int(rs.get("target_gen", self.gen + 1))
        self.target_shards = int(rs.get("target_shards", 0))
        self.stamp = float(rs.get("stamp", 0.0))

    def epoch(self) -> tuple:
        return (
            self.gen, self.num_shards, self.status,
            self.target_gen, self.target_shards,
        )

    def resharding(self) -> bool:
        return self.status in (RESHARD_IN_PROGRESS, RESHARD_CUTOVER)

    def parked(self, now: float) -> bool:
        """Writers park only during a FRESH cutover; a stale one
        (crashed resharder) degrades to dual-write so traffic
        flows."""
        return (
            self.status == RESHARD_CUTOVER
            and now - self.stamp < CUTOVER_GRACE
        )


class BucketIndex:
    """The sharded-index layer every RGW index read/write/list rides
    (the cls_rgw + RGWRados::Bucket index seam)."""

    def __init__(self, rgw):
        self.rgw = rgw
        self.io = rgw.io
        # per-bucket mutation counter driving the periodic shard-fill
        # check (in-memory: the check is advisory, the queue is the
        # durable state)
        self._op_counts: dict[str, int] = {}
        self._op_counts_lock = threading.Lock()
        self.check_interval = 16

    # -- layout ------------------------------------------------------------
    def _fresh_layout(self, bucket: str) -> _Layout:
        return _Layout(self.rgw._bucket_rec(bucket))

    def layout(self, bucket: str, rec: dict | None = None) -> _Layout:
        if rec is None:
            return self._fresh_layout(bucket)
        return _Layout(rec)

    def shard_oids(
        self, bucket: str, gen: int, num_shards: int
    ) -> list[str]:
        return [
            shard_oid(bucket, gen, s, num_shards)
            for s in range(max(1, num_shards))
        ]

    def create(self, bucket: str, num_shards: int) -> dict:
        """Index descriptor + shard objects for a new bucket."""
        for oid in self.shard_oids(bucket, 0, num_shards):
            self.io.write_full(oid, b"")
        return {"gen": 0, "num_shards": int(max(1, num_shards))}

    def _touch_missing(self, oid: str) -> None:
        """Create-if-missing WITHOUT wiping omap (write_full on an
        existing object clears its keys — fatal on reshard resume)."""
        try:
            self.io.stat(oid)
        except (ObjectNotFound, RadosError):
            self.io.write_full(oid, b"")

    # -- reads -------------------------------------------------------------
    def _read_shard(self, oid: str, **kw) -> dict[str, bytes]:
        """One shard's omap page; a MISSING shard object reads as
        empty (an empty target-gen shard may never have been
        touched into existence)."""
        try:
            return self.io.omap_get_vals(oid, **kw)
        except (ObjectNotFound, RadosError):
            return {}

    def get_entry(self, bucket: str, key: str, rec: dict | None = None):
        """The entry blob for ``key`` or None — reads ONE shard of
        the current generation (the whole point: stat cost no longer
        scales with bucket size)."""
        lay = self.layout(bucket, rec)
        for _attempt in range(2):
            oid = shard_oid(
                bucket, lay.gen,
                shard_of(key, lay.num_shards), lay.num_shards,
            )
            vals = self._read_shard(oid)
            self.rgw.perf.inc("l_rgw_index_reads")
            if key in vals:
                return vals[key]
            # miss could be a cutover race: the generation this
            # layout names may have been cleaned up — retry once on
            # a FRESH record before declaring absence
            fresh = self._fresh_layout(bucket)
            if fresh.epoch() == lay.epoch():
                return None
            lay = fresh
        return None

    def _shard_pages(self, oid: str, marker: str, page: int):
        """Lazy per-shard iterator with its own continuation marker
        (the per-shard cursor the k-way merge advances)."""
        m = marker
        while True:
            try:
                vals = self.io.omap_get_vals(
                    oid, start_after=m, max_return=page
                )
            except (ObjectNotFound, RadosError):
                return
            keys = sorted(vals)
            if not keys:
                return
            self.rgw.perf.inc("l_rgw_index_list_pages")
            for k in keys:
                yield (k, vals[k])
            if len(keys) < page:
                return
            m = keys[-1]

    def list_page(
        self,
        bucket: str,
        marker: str = "",
        max_keys: int = 1000,
        rec: dict | None = None,
    ) -> tuple[list[tuple[str, bytes]], bool]:
        """Key-ordered page after ``marker`` → ([(key, raw)],
        truncated): k-way merge-sort across the current generation's
        shards.  Within a generation every key lives in exactly one
        shard, so the merged stream is EXACTLY the unsharded omap
        order — the listing contract (and its XML) is byte-identical
        to the single-object index."""
        lay = self.layout(bucket, rec)
        page = min(max(max_keys + 1, 2), _PAGE)
        for _attempt in range(3):
            merged = heapq.merge(
                *(
                    self._shard_pages(oid, marker, page)
                    for oid in self.shard_oids(
                        bucket, lay.gen, lay.num_shards
                    )
                )
            )
            out: list[tuple[str, bytes]] = []
            truncated = False
            for k, raw in merged:
                if len(out) >= max_keys:
                    truncated = True
                    break
                out.append((k, raw))
            # a cutover racing the page walk could have removed the
            # generation mid-merge (missing shards read as empty) —
            # an unchanged layout across the walk proves the page is
            # whole; a moved one re-lists under the new generation
            fresh = self._fresh_layout(bucket)
            if fresh.epoch() == lay.epoch():
                break
            lay = fresh
        self.rgw.perf.inc("l_rgw_index_list_entries", len(out))
        self.rgw.perf.set("l_rgw_index_shards", lay.num_shards)
        return out, truncated

    def entries(self, bucket: str, rec: dict | None = None):
        """Every (key, raw) of the current generation in key order
        (the LC walk / full-sync seat), paged underneath."""
        marker = ""
        while True:
            page, truncated = self.list_page(
                bucket, marker=marker, max_keys=_PAGE - 1, rec=rec
            )
            yield from page
            if not truncated or not page:
                return
            marker = page[-1][0]
            rec = None  # later pages re-read the layout

    def any_entries(self, bucket: str, rec: dict | None = None) -> bool:
        """Emptiness probe across ALL shards of the current
        generation (the delete-bucket gate: one shard being empty
        proves nothing)."""
        lay = self.layout(bucket, rec)
        return any(
            self._read_shard(oid, max_return=1)
            for oid in self.shard_oids(bucket, lay.gen, lay.num_shards)
        )

    def shard_counts(
        self, bucket: str, rec: dict | None = None
    ) -> list[int]:
        """Per-shard entry counts of the current generation (the
        ``bucket stats`` fill view the reshard threshold reasons
        about)."""
        lay = self.layout(bucket, rec)
        return [
            sum(
                1 for _kv in self._shard_pages(oid, "", _PAGE)
            )
            for oid in self.shard_oids(
                bucket, lay.gen, lay.num_shards
            )
        ]

    def count_entries(self, bucket: str, rec: dict | None = None) -> int:
        return sum(self.shard_counts(bucket, rec))

    # -- writes ------------------------------------------------------------
    def set_entry(
        self, bucket: str, key: str, entry, rec: dict | None = None
    ) -> None:
        raw = (
            entry
            if isinstance(entry, (bytes, bytearray))
            else json.dumps(entry).encode()
        )
        self._mutate(bucket, key, bytes(raw), rec)

    def rm_entry(
        self, bucket: str, key: str, rec: dict | None = None
    ) -> None:
        self._mutate(bucket, key, None, rec)

    def _apply(self, bucket: str, key: str, value, lay: _Layout) -> None:
        """One write under one observed layout: current generation
        always; the reshard target generation too while a reshard is
        live (the dual-write keeping the target convergent)."""
        targets = [
            (lay.gen, shard_of(key, lay.num_shards), lay.num_shards)
        ]
        if lay.resharding() and lay.target_shards > 0:
            targets.append(
                (
                    lay.target_gen,
                    shard_of(key, lay.target_shards),
                    lay.target_shards,
                )
            )
            self.rgw.perf.inc("l_rgw_index_dual_writes")
        for gen, shard, n in targets:
            oid = shard_oid(bucket, gen, shard, n)
            if value is None:
                try:
                    self.io.omap_rm_keys(oid, [key])
                except (ObjectNotFound, RadosError):
                    pass  # removing from a shard that never existed
            else:
                self.io.omap_set(oid, {key: value})

    def _mutate(
        self, bucket: str, key: str, value, rec: dict | None
    ) -> None:
        """The write protocol: write under the observed layout, then
        RE-READ the record; a moved layout (reshard started, cut
        over, or target changed) redoes the write so no generation
        that could become authoritative misses it."""
        lay = self.layout(bucket, rec)
        for _attempt in range(_MUTATE_RETRIES):
            if lay.parked(time.time()):
                # a live cutover quiesces writers briefly (the
                # reference's ERR_BUSY_RESHARDING retry loop,
                # server-side)
                self.rgw.perf.inc("l_rgw_index_stall_waits")
                time.sleep(_STALL_SLEEP)
                lay = self._fresh_layout(bucket)
                continue
            self._apply(bucket, key, value, lay)
            self.rgw.perf.inc("l_rgw_index_ops")
            fresh = self._fresh_layout(bucket)
            if fresh.epoch() == lay.epoch():
                if value is not None:
                    self._maybe_check_fill(bucket, key, lay)
                return
            self.rgw.perf.inc("l_rgw_index_retries")
            lay = fresh
        from . import RGWError

        raise RGWError(
            f"bucket {bucket!r} index busy resharding (-EBUSY)"
        )

    def remove_index(self, bucket: str, rec: dict | None = None) -> None:
        """Drop every shard object (both generations while a reshard
        is live) — the delete-bucket teardown."""
        lay = self.layout(bucket, rec)
        oids = set(
            self.shard_oids(bucket, lay.gen, lay.num_shards)
        )
        if lay.resharding() and lay.target_shards > 0:
            oids.update(
                self.shard_oids(
                    bucket, lay.target_gen, lay.target_shards
                )
            )
        for oid in oids:
            try:
                self.io.remove(oid)
            except (ObjectNotFound, RadosError):
                pass
        try:
            self.io.omap_rm_keys(RESHARD_OID, [bucket])
        except (ObjectNotFound, RadosError):
            pass

    # -- reshard queue (RGWReshard's reshard log) --------------------------
    def _maybe_check_fill(
        self, bucket: str, key: str, lay: _Layout
    ) -> None:
        """Every ``check_interval``-th mutation counts the shard it
        just wrote; past ``rgw_max_objs_per_shard`` the bucket joins
        the reshard queue (hash-uniform estimate for the target)."""
        thr = int(self.rgw.max_objs_per_shard)
        if thr <= 0 or lay.resharding():
            return
        with self._op_counts_lock:
            n = self._op_counts.get(bucket, 0) + 1
            self._op_counts[bucket] = n
            if n % self.check_interval:
                return
        oid = shard_oid(
            bucket, lay.gen,
            shard_of(key, lay.num_shards), lay.num_shards,
        )
        count = len(self._read_shard(oid, max_return=thr + 1))
        if count <= thr:
            return
        est_total = count * lay.num_shards
        target = max(lay.num_shards * 2, 2)
        while est_total / target > thr:
            target *= 2
        self.queue_reshard(bucket, target, reason="threshold")

    def queue_reshard(
        self, bucket: str, target_shards: int, reason: str = "admin"
    ) -> bool:
        """Add a bucket to the reshard queue; False if already
        queued (the queue is idempotent — one entry per bucket)."""
        existing = self._read_shard(RESHARD_OID)
        if bucket in existing:
            return False
        self._touch_missing(RESHARD_OID)
        ent = {
            "bucket": bucket,
            "target_shards": int(target_shards),
            "reason": reason,
            "queued_at": time.time(),
        }
        self.io.omap_set(
            RESHARD_OID, {bucket: encode_reshard_entry(ent)}
        )
        self.rgw.perf.inc("l_rgw_reshard_queued")
        return True

    def reshard_queue(self) -> list[dict]:
        return [
            decode_reshard_entry(raw)
            for _b, raw in sorted(self._read_shard(RESHARD_OID).items())
        ]

    def process_reshard_queue(self) -> int:
        """Drain the queue (the RGWReshard worker pass); returns the
        number of buckets resharded."""
        from . import RGWError

        done = 0
        for ent in self.reshard_queue():
            bucket = ent["bucket"]
            try:
                self.reshard(bucket, int(ent["target_shards"]))
                done += 1
            except RGWError:
                pass  # bucket vanished / target stale: drop the entry
            except Exception:
                # transient failure (mon blip, pool hiccup): KEEP the
                # queue entry so the next worker pass resumes the
                # reshard — dropping it would strand the bucket
                # in_progress forever (the resharding guard stops
                # the fill check from ever re-queueing it)
                raise
            try:
                self.io.omap_rm_keys(RESHARD_OID, [bucket])
            except (ObjectNotFound, RadosError):
                pass
        return done

    # -- reshard state machine ---------------------------------------------
    def status(self, bucket: str) -> dict:
        """``reshard status``: layout + live reshard descriptor."""
        rec = self.rgw._bucket_rec(bucket)
        lay = _Layout(rec)
        queued = bucket in self._read_shard(RESHARD_OID)
        return {
            "bucket": bucket,
            "gen": lay.gen,
            "num_shards": lay.num_shards,
            "status": lay.status or "idle",
            "target_gen": lay.target_gen if lay.resharding() else None,
            "target_shards": (
                lay.target_shards if lay.resharding() else None
            ),
            "queued": queued,
        }

    def _report_progress(
        self, bucket: str, target_shards: int, fraction: float,
        done: bool = False,
    ) -> None:
        """Feed the mgr progress-event plane: in-process gateways
        set ``rgw.progress_hook`` (callable (event_id, message,
        fraction, done)) — tests bridge it straight to the progress
        module; out-of-process gateways use the mgr's
        ``progress event`` command instead.  Best-effort: a broken
        hook must never fail a reshard."""
        hook = getattr(self.rgw, "progress_hook", None)
        if hook is None:
            return
        try:
            hook(
                f"reshard:{bucket}",
                f"Resharding bucket {bucket!r} to "
                f"{target_shards} shards",
                fraction,
                done,
            )
        except Exception:  # noqa: BLE001 — observability side-channel
            pass

    def _save_reshard_state(
        self, bucket: str, status: str, target_gen: int,
        target_shards: int,
    ) -> _Layout:
        with self.rgw._bucket_lock(bucket):
            rec = self.rgw._bucket_rec(bucket)
            rec["reshard"] = {
                "status": status,
                "target_gen": target_gen,
                "target_shards": target_shards,
                "stamp": time.time(),
            }
            self.rgw._save_bucket_rec(bucket, rec)
            return _Layout(rec)

    def _still_mine(self, bucket: str, lay: _Layout) -> None:
        """Abort a resharder whose layout moved underneath it: the
        record is the reshard-state authority, and a second
        resharder (admin CLI racing the background worker) that kept
        migrating against a flipped generation would read the old
        gen as empty and DELETE every migrated entry."""
        from . import RGWError

        fresh = self._fresh_layout(bucket)
        if (
            fresh.gen != lay.gen
            or fresh.num_shards != lay.num_shards
            or fresh.target_gen != lay.target_gen
            or fresh.target_shards != lay.target_shards
        ):
            raise RGWError(
                f"bucket {bucket!r} reshard superseded: layout "
                f"moved to gen {fresh.gen} x{fresh.num_shards}"
            )

    def _migrate_pass(self, bucket: str, lay: _Layout) -> int:
        """One fixpoint pass: diff the full old and new generations
        and fix every divergence.  Returns the number of fixes (0 =
        clean pass).  Writes go straight to the shard omaps — no
        datalog, no put_object: migration must be invisible to
        multisite replication."""
        old: dict[str, bytes] = {}
        for oid in self.shard_oids(bucket, lay.gen, lay.num_shards):
            for k, raw in self._shard_pages(oid, "", _PAGE):
                old[k] = raw
        new: dict[str, bytes] = {}
        for oid in self.shard_oids(
            bucket, lay.target_gen, lay.target_shards
        ):
            for k, raw in self._shard_pages(oid, "", _PAGE):
                new[k] = raw
        sets: dict[int, dict[str, bytes]] = {}
        for k, raw in old.items():
            if new.get(k) != raw:
                sets.setdefault(
                    shard_of(k, lay.target_shards), {}
                )[k] = raw
        rms: dict[int, list[str]] = {}
        for k in new.keys() - old.keys():
            rms.setdefault(
                shard_of(k, lay.target_shards), []
            ).append(k)
        diffs = 0
        for shard, kv in sets.items():
            oid = shard_oid(
                bucket, lay.target_gen, shard, lay.target_shards
            )
            items = list(kv.items())
            for i in range(0, len(items), _BATCH):
                self.io.omap_set(oid, dict(items[i : i + _BATCH]))
            diffs += len(items)
        for shard, keys in rms.items():
            oid = shard_oid(
                bucket, lay.target_gen, shard, lay.target_shards
            )
            self.io.omap_rm_keys(oid, keys)
            diffs += len(keys)
        self.rgw.perf.inc("l_rgw_reshard_passes")
        if diffs:
            self.rgw.perf.inc(
                "l_rgw_reshard_entries_migrated", diffs
            )
        return diffs

    def reshard(
        self,
        bucket: str,
        target_shards: int,
        max_passes: int = 8,
        fault_hook=None,
    ) -> dict:
        """Online reshard to ``target_shards`` (``bucket reshard``):
        mark → fixpoint migrate under live dual-writing traffic →
        brief cutover park → atomic flip → old-gen cleanup.  Resumes
        idempotently after a crash.  ``fault_hook(stage)`` is the
        crash-injection seam tests use (stages: ``marked``,
        ``migrated``, ``cutover``)."""
        from . import RGWError  # cycle-free at call time

        rec = self.rgw._bucket_rec(bucket)
        lay = _Layout(rec)
        target_shards = int(target_shards)
        if target_shards < 1:
            raise RGWError("target shard count must be >= 1")
        if lay.resharding():
            # resume: the recorded target wins (a different request
            # against a half-done reshard would orphan its entries)
            target_shards = lay.target_shards
        elif target_shards == lay.num_shards:
            raise RGWError(
                f"bucket {bucket!r} already has "
                f"{target_shards} shard(s)"
            )
        t0 = time.monotonic()
        self.rgw.perf.inc("l_rgw_reshard_started")
        self.rgw.perf.inc("l_rgw_reshard_in_progress")
        self._report_progress(bucket, target_shards, 0.0)
        try:
            lay = self._save_reshard_state(
                bucket, RESHARD_IN_PROGRESS, lay.gen + 1,
                target_shards,
            )
            for oid in self.shard_oids(
                bucket, lay.target_gen, lay.target_shards
            ):
                self._touch_missing(oid)
            if fault_hook:
                fault_hook("marked")
            entries = 0
            passes = 0
            while True:
                self._still_mine(bucket, lay)
                diffs = self._migrate_pass(bucket, lay)
                passes += 1
                entries = max(entries, diffs)
                # convergent bar: each fixpoint pass halves what can
                # remain, capped below the cutover's share
                self._report_progress(
                    bucket, target_shards,
                    min(1.0 - 0.5 ** passes, 0.9),
                )
                # exit on a CLEAN pass (at least one pass ran);
                # sustained write traffic is bounded by max_passes —
                # the cutover park quiesces the stragglers
                if diffs == 0 and passes > 1:
                    break
                if passes >= max_passes:
                    break
            if fault_hook:
                fault_hook("migrated")
            # cutover: park writers, run clean passes with the write
            # stream quiesced (a straggler that wrote pre-park is
            # caught here; one that wrote during the park redoes its
            # write against the NEW layout after the flip)
            lay = self._save_reshard_state(
                bucket, RESHARD_CUTOVER, lay.target_gen,
                lay.target_shards,
            )
            # bounded: once the cutover outlives CUTOVER_GRACE,
            # writers resume dual-writing and a pass can observe a
            # transient mid-dual-write divergence every time — but
            # each pass REPAIRS what it saw, and every protocol
            # writer either dual-wrote or redoes post-flip, so
            # flipping after a bounded number of clean-seeking
            # passes stays lossless
            for _pass in range(50):
                self._still_mine(bucket, lay)
                if not self._migrate_pass(bucket, lay):
                    break
            if fault_hook:
                fault_hook("cutover")
            old_oids = self.shard_oids(
                bucket, lay.gen, lay.num_shards
            )
            with self.rgw._bucket_lock(bucket):
                self._still_mine(bucket, lay)
                rec = self.rgw._bucket_rec(bucket)
                rec["index"] = {
                    "gen": lay.target_gen,
                    "num_shards": lay.target_shards,
                }
                rec.pop("reshard", None)
                self.rgw._save_bucket_rec(bucket, rec)
            for oid in old_oids:
                try:
                    self.io.remove(oid)
                except (ObjectNotFound, RadosError):
                    pass
            self.rgw.perf.inc("l_rgw_reshard_completed")
            self._report_progress(
                bucket, target_shards, 1.0, done=True
            )
            with self._op_counts_lock:
                self._op_counts.pop(bucket, None)
            return {
                "bucket": bucket,
                "from_shards": lay.num_shards,
                "to_shards": lay.target_shards,
                "gen": lay.target_gen,
                "entries": entries,
                "passes": passes,
                "duration_s": round(time.monotonic() - t0, 3),
            }
        finally:
            self.rgw.perf.dec("l_rgw_reshard_in_progress")


class ReshardWorker:
    """Background queue drainer (RGWReshard::process_all_logshards):
    every ``interval`` seconds, reshard whatever the fill checks
    queued."""

    def __init__(self, rgw, interval: float = 2.0):
        self.rgw = rgw
        self.interval = interval
        self.passes = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="rgw.reshard", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.rgw.index.process_reshard_queue()
            except Exception:  # noqa: BLE001 — the worker survives
                pass
            self.passes += 1

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)
