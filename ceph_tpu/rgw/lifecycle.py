"""Bucket lifecycle engine — expiration + storage-class transition
(src/rgw/rgw_lc.cc:1 reduced to its working core).

Rules per bucket (stored in the gateway's lc-config omap, the
reference's lc shard-object role):

    {"id": ..., "prefix": "logs/", "status": "Enabled",
     "expiration_days": 30}                      # delete when aged
    {"id": ..., "prefix": "", "status": "Enabled",
     "transition_days": 7, "storage_class": "COLD"}

A worker (RGW.start_lc / lc_process) scans configured buckets and
applies every enabled rule to matching keys by mtime age —
expiration deletes through the normal delete path; transition
REWRITES the object's data through the zlib compressor and tags the
index entry with the storage class (this framework's one real
second tier), with reads transparently decompressing.  Like the
reference's ``rgw_lc_debug_interval``, ``debug=True`` makes the
``*_days`` fields count SECONDS so tests age objects in real time.
"""

from __future__ import annotations

import json
import threading
import time

__all__ = ["apply_rules", "LCWorker"]


def _matches(rule: dict, key: str) -> bool:
    return rule.get("status", "Enabled") == "Enabled" and key.startswith(
        rule.get("prefix", "")
    )


def apply_rules(rgw, bucket: str, rules: list[dict], debug: bool) -> dict:
    """One lc pass over one bucket; returns {'expired': n,
    'transitioned': n} (the per-bucket RGWLC::bucket_lc_process)."""
    unit = 1.0 if debug else 86400.0
    now = time.time()
    stats = {"expired": 0, "transitioned": 0}
    try:
        # snapshot the merged sharded listing up front: the loop
        # below mutates the index it walks
        index = dict(rgw.index.entries(bucket))
    except Exception:  # noqa: BLE001 — bucket vanished mid-pass
        return stats
    for key, raw in index.items():
        entry = json.loads(raw)
        age = now - float(entry.get("mtime", now))
        for rule in rules:
            if not _matches(rule, key):
                continue
            exp = rule.get("expiration_days")
            if exp is not None and age > float(exp) * unit:
                try:
                    rgw.delete_object(bucket, key)
                    stats["expired"] += 1
                except Exception:  # noqa: BLE001 — raced a delete
                    pass
                break  # entry is gone; later rules moot
            tr = rule.get("transition_days")
            if (
                tr is not None
                and age > float(tr) * unit
                and entry.get("storage_class", "STANDARD")
                != rule.get("storage_class", "COLD")
            ):
                try:
                    rgw._transition_object(
                        bucket, key,
                        rule.get("storage_class", "COLD"),
                    )
                    stats["transitioned"] += 1
                except Exception:  # noqa: BLE001 — raced an overwrite
                    pass
                break
    return stats


class LCWorker:
    """Background scanner (RGWLC::LCWorker): every ``interval``
    seconds, walk each bucket's lifecycle config and apply it."""

    def __init__(self, rgw, interval: float, debug: bool):
        self.rgw = rgw
        self.interval = interval
        self.debug = debug
        self.passes = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="rgw.lc", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.rgw.lc_process()
            except Exception:  # noqa: BLE001 — scanner must survive
                pass
            self.passes += 1

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
