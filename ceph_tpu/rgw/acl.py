"""S3 ACLs — ownership + grants enforced on every gateway op
(src/rgw/rgw_acl.cc, rgw_acl_s3.cc reduced to the working core).

An ACL is ``{"owner": <user>, "grants": [{"grantee": g, "perms":
[...]}]}`` where a grantee is ``user:<name>``, ``ALL`` (the AllUsers
group — anonymous requests match it) or ``AUTH`` (any authenticated
user).  Permissions are the S3 five: READ, WRITE, READ_ACP,
WRITE_ACP, FULL_CONTROL.  The owner (and the bucket owner, for
objects) always has FULL_CONTROL — exactly the reference's
``RGWAccessControlPolicy::verify_permission`` short-circuit.

Canned ACLs (x-amz-acl) expand to grant lists at set time, like
rgw_acl_s3's canned-ACL table: private, public-read,
public-read-write, authenticated-read.
"""

from __future__ import annotations

READ = "READ"
WRITE = "WRITE"
READ_ACP = "READ_ACP"
WRITE_ACP = "WRITE_ACP"
FULL_CONTROL = "FULL_CONTROL"

CANNED = {
    "private": [],
    "public-read": [{"grantee": "ALL", "perms": [READ]}],
    "public-read-write": [
        {"grantee": "ALL", "perms": [READ, WRITE]}
    ],
    "authenticated-read": [{"grantee": "AUTH", "perms": [READ]}],
}


def make_acl(owner: str | None, canned: str = "private") -> dict:
    if canned not in CANNED:
        raise ValueError(f"unknown canned acl {canned!r}")
    return {"owner": owner, "grants": list(CANNED[canned])}


def check(
    acl: dict | None,
    user: str | None,
    perm: str,
    bucket_owner: str | None = None,
) -> bool:
    """Does ``user`` (None = anonymous) hold ``perm``?  Owners hold
    everything; group grants match by authentication state."""
    acl = acl or {}
    owner = acl.get("owner")
    if user is not None and user in (owner, bucket_owner):
        return True
    for grant in acl.get("grants", ()):
        g = grant["grantee"]
        if not (
            g == "ALL"
            or (g == "AUTH" and user is not None)
            or (user is not None and g == f"user:{user}")
        ):
            continue
        if perm in grant["perms"] or FULL_CONTROL in grant["perms"]:
            return True
    return False
