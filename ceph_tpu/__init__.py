"""ceph_tpu — a TPU-native storage-compute framework.

A from-scratch rebuild of the compute plane of Ceph (reference:
yangly0815/ceph @ Pacific, mounted read-only at /root/reference) designed
TPU-first in JAX/XLA/Pallas:

- ``ceph_tpu.gf``     — GF(2^w) arithmetic oracle (numpy) + table generation.
- ``ceph_tpu.ec``     — erasure-code framework: profiles, plugin registry,
  Reed-Solomon (jerasure/isa-compatible semantics), LRC, SHEC, CLAY.
- ``ceph_tpu.ops``    — TPU kernels: GF(2^8) Reed-Solomon as MXU bit-matmul
  and Pallas kernels; batched CRUSH placement kernels.
- ``ceph_tpu.crush``  — CRUSH placement: rjenkins hash, straw2, rule engine,
  map builder/compiler, tester (crushtool --test equivalent).
- ``ceph_tpu.osd``    — OSDMap model and batched PG->OSD mapping pipeline.
- ``ceph_tpu.parallel`` — device-mesh sharding of stripe/PG batches.
- ``ceph_tpu.tools``  — CLI benchmarks mirroring the reference harnesses
  (ceph_erasure_code_benchmark, crushtool --test, osdmaptool).

Byte-exactness contract: outputs must match the reference C semantics
(src/erasure-code/*, src/crush/mapper.c) chunk-for-chunk; the numpy oracle
in ``gf``/``crush`` is the executable spec, the TPU kernels are validated
against it, and a corpus harness (tools/non_regression.py) pins regressions.
"""

__version__ = "0.1.0"
