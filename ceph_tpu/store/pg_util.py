"""Shared PG-backend machinery: scrub result + per-object op ordering.

Both backends (ec_store, replicated) order client ops per object the
same way — the reference's waiting_state/waiting_reads/waiting_commit
op lists collapsed to a FIFO ticket queue — and report scrub findings
in the same shape, so the machinery lives once here.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque


class ScrubResult:
    def __init__(self):
        self.missing: list[int] = []
        self.corrupt: list[int] = []
        # faults that cannot be attributed to one shard/replica
        self.inconsistent: bool = False

    @property
    def clean(self) -> bool:
        return (
            not self.missing and not self.corrupt and not self.inconsistent
        )

    def __repr__(self):
        return (
            f"ScrubResult(missing={self.missing}, corrupt={self.corrupt}, "
            f"inconsistent={self.inconsistent})"
        )


class ObjectOpQueue:
    """Per-object FIFO tickets: ops on one object run in submission
    order; ops on different objects proceed concurrently."""

    def __init__(self):
        self._cond = threading.Condition()
        self._queues: dict[str, deque[int]] = {}
        self._tickets = itertools.count(1)

    def enter(self, name: str, on_enter=None) -> int:
        with self._cond:
            ticket = next(self._tickets)
            q = self._queues.setdefault(name, deque())
            q.append(ticket)
            if on_enter is not None:
                on_enter()
            while q[0] != ticket:
                self._cond.wait()
            return ticket

    def exit(self, name: str, ticket: int, on_exit=None):
        """Release the ticket; returns on_exit()'s result (run under
        the queue lock) so callers can hand values out of the critical
        section without closure plumbing."""
        with self._cond:
            q = self._queues[name]
            assert q[0] == ticket
            q.popleft()
            if not q:
                del self._queues[name]
            result = on_exit() if on_exit is not None else None
            self._cond.notify_all()
            return result
