"""BlockStore — the BlueStore-role extent store
(src/os/bluestore/BlueStore.cc reduced to its load-bearing design).

Where KStore keeps object data inside its snapshot+WAL stream, this
store puts data where BlueStore puts it:

- **one flat block file** (``block.dev`` — the raw-device role), with
  a first-fit **extent allocator** over 4KB units
  (src/os/bluestore/Allocator.h; the free map is rebuilt at mount by
  walking the metadata, exactly like BlueStore's allocator init from
  the FreelistManager/onode walk).
- **a KV metadata index** (the RocksDB role, src/kv/RocksDBStore.cc):
  onodes (size + xattrs + the logical→disk blob map), collection
  markers, and omap keys live in a log-structured KV — batch commits
  framed+crc'd into a WAL, periodically checkpointed, torn tails
  discarded at mount.
- **at-rest checksums verified on EVERY read**
  (BlueStore::_verify_csum): each blob records the crc32c of its
  on-disk bytes; any read that touches the blob re-verifies before
  returning, and a mismatch raises StoreError instead of returning
  rotted bytes.
- **inline compression** through the compressor plugin registry
  (CompressionPlugin.h): blobs compress on write when the codec
  actually saves space; the blob records its codec, so stores mount
  under any configuration.
- **fsck()**: walks every onode — blob extents in bounds,
  no double-allocated blocks, every checksum re-verified, omap keys
  orphan-checked (BlueStore::_fsck).

Durability ordering per transaction: data extents are written and
fsync'd to the block file FIRST, then the KV batch (onode/omap
changes) commits through the KV WAL — a crash between the two leaves
only unreferenced garbage in free space, never a committed onode
pointing at unwritten data.  Old extents are released only after the
KV commit (copy-on-write overwrites), so SIGKILL at any instant
yields either the old or the new object state.

Deviations, documented: no deferred-write path for small IO (every
write is COW), clone copies data (no shared-blob refcounting), csum
granularity is the blob (BlueStore defaults to 4KB csum chunks
inside blobs), and the KV is the framework's own WAL+checkpoint log
rather than RocksDB.
"""

from __future__ import annotations

import os
import pathlib
import threading

from ..common import lockdep
from ..common.encoding import Decoder, DecodeError, Encoder
from ..native import ceph_crc32c
from .framed_log import (
    append_frame,
    replay_frames,
    truncate_tail,
    write_checkpoint,
)
from .objectstore import (
    ObjectStore,
    StoreError,
    Transaction,
)

ALLOC_UNIT = 4096
_SEP = "\x1f"  # KV key field separator (never appears in cid/oid)
_KV_WAL = "kv.log"
_KV_SNAP = "kv.snap"
_DEV = "block.dev"
_KV_MAGIC = 0x424B5631  # "BKV1"


def _okey(cid: str, oid: str) -> str:
    return f"o{_SEP}{cid}{_SEP}{oid}"


def _ckey(cid: str) -> str:
    return f"C{_SEP}{cid}"


def _mkey(cid: str, oid: str, key: str = "") -> str:
    return f"m{_SEP}{cid}{_SEP}{oid}{_SEP}{key}"


def _round_up(n: int) -> int:
    return (n + ALLOC_UNIT - 1) // ALLOC_UNIT * ALLOC_UNIT


class _KVLog:
    """Tiny log-structured KV (the RocksDB seat): dict state, batch
    WAL with length+crc frames, checkpoint with atomic rename, torn
    tails discarded at mount."""

    def __init__(self, path: pathlib.Path, sync: bool):
        self.path = path
        self.sync = sync
        self.db: dict[str, bytes] = {}
        self._mount()
        self._wal = open(self.path / _KV_WAL, "ab")

    def _mount(self) -> None:
        snap = self.path / _KV_SNAP
        if snap.exists():
            blob = snap.read_bytes()
            if len(blob) < 4:
                raise StoreError("kv snapshot too short")
            body, crc = blob[:-4], int.from_bytes(blob[-4:], "little")
            if ceph_crc32c(0, body) != crc:
                raise StoreError("kv snapshot crc mismatch")
            d = Decoder(body)
            if d.u32() != _KV_MAGIC:
                raise StoreError("bad kv snapshot magic")
            self.db = d.map(
                lambda d2: d2.string(), lambda d2: d2.bytes()
            )
        wal = self.path / _KV_WAL
        if not wal.exists():
            return
        raw = wal.read_bytes()
        pos = 0
        for body, end in replay_frames(raw):
            try:
                d = Decoder(body)
                sets = d.map(
                    lambda d2: d2.string(), lambda d2: d2.bytes()
                )
                dels = d.list(lambda d2: d2.string())
            except DecodeError:
                break
            self.db.update(sets)
            for k in dels:
                self.db.pop(k, None)
            pos = end
        if pos < len(raw):
            truncate_tail(wal, pos)

    def commit(self, sets: dict[str, bytes], dels) -> None:
        e = Encoder()
        e.map(
            sets, lambda e2, k: e2.string(k), lambda e2, v: e2.bytes(v)
        )
        e.list(list(dels), lambda e2, k: e2.string(k))
        body = e.getvalue()
        start = self._wal.tell()
        try:
            append_frame(self._wal, body, self.sync)
        except Exception:
            # a partially-written frame must not poison the WAL: later
            # commits would land after the torn bytes and be discarded
            # by replay even though they reported success
            try:
                self._wal.truncate(start)
                self._wal.seek(start)
            except Exception:
                pass
            raise
        # ---- durable point: nothing below may raise out of commit ----
        self.db.update(sets)
        for k in dels:
            self.db.pop(k, None)
        if self._wal.tell() > 4 << 20:
            try:
                self.compact()
            except Exception:
                # compaction is an optimization; the WAL already holds
                # the committed frame — a raise here would make the
                # caller roll back extents that durable onodes
                # reference (double-allocation corruption)
                pass

    def compact(self) -> None:
        e = Encoder()
        e.u32(_KV_MAGIC)
        e.map(
            self.db,
            lambda e2, k: e2.string(k),
            lambda e2, v: e2.bytes(v),
        )
        body = e.getvalue()
        blob = body + ceph_crc32c(0, body).to_bytes(4, "little")
        write_checkpoint(self.path / _KV_SNAP, blob)
        self._wal.close()
        self._wal = open(self.path / _KV_WAL, "wb")
        if self.sync:
            os.fsync(self._wal.fileno())

    def close(self) -> None:
        if not self._wal.closed:
            self._wal.flush()
            if self.sync:
                os.fsync(self._wal.fileno())
            self._wal.close()


class _Allocator:
    """First-fit extent allocator over the block file (Allocator.h
    role): free runs in 4KB units plus a growth frontier; rebuilt at
    mount from the onode walk."""

    def __init__(self):
        self.free: list[list[int]] = []  # sorted [off, len]
        self.frontier = 0

    def allocate(self, nbytes: int) -> tuple[int, int]:
        """One contiguous extent (off, alloc_len)."""
        need = _round_up(max(nbytes, 1))
        for run in self.free:
            if run[1] >= need:
                off = run[0]
                run[0] += need
                run[1] -= need
                if run[1] == 0:
                    self.free.remove(run)
                return off, need
        off = self.frontier
        self.frontier += need
        return off, need

    def release(self, off: int, length: int) -> None:
        import bisect

        need = _round_up(max(length, 1))
        i = bisect.bisect_left(self.free, [off, need])
        # coalesce with the immediate neighbours only — the list is
        # sorted and disjoint, so nothing further can touch the run
        if i > 0 and self.free[i - 1][0] + self.free[i - 1][1] == off:
            self.free[i - 1][1] += need
            j = i - 1
        else:
            self.free.insert(i, [off, need])
            j = i
        if (
            j + 1 < len(self.free)
            and self.free[j][0] + self.free[j][1] == self.free[j + 1][0]
        ):
            self.free[j][1] += self.free[j + 1][1]
            del self.free[j + 1]

    def rebuild(self, used: list[tuple[int, int]]) -> None:
        """Free map = complement of the used extents."""
        self.free = []
        pos = 0
        frontier = 0
        for off, length in sorted(used):
            length = _round_up(length)
            if off > pos:
                self.free.append([pos, off - pos])
            pos = max(pos, off + length)
            frontier = max(frontier, off + length)
        self.frontier = frontier


class _Onode:
    """In-memory onode: size, xattrs, and the logical→disk blob map
    (sorted, non-overlapping; gaps read as zeros)."""

    __slots__ = ("size", "xattrs", "blobs")

    def __init__(self, size=0, xattrs=None, blobs=None):
        self.size = size
        self.xattrs = xattrs if xattrs is not None else {}
        # blob: [loff, llen, doff, dlen, codec, crc]
        self.blobs = blobs if blobs is not None else []

    def encode(self) -> bytes:
        e = Encoder()
        e.u64(self.size)
        e.map(
            self.xattrs,
            lambda e2, k: e2.string(k),
            lambda e2, v: e2.bytes(v),
        )
        e.u32(len(self.blobs))
        for loff, llen, doff, dlen, codec, crc in self.blobs:
            e.u64(loff).u64(llen).u64(doff).u64(dlen)
            e.string(codec)
            e.u32(crc)
        return e.getvalue()

    @classmethod
    def decode(cls, blob: bytes) -> "_Onode":
        d = Decoder(blob)
        size = d.u64()
        xattrs = d.map(lambda d2: d2.string(), lambda d2: d2.bytes())
        blobs = []
        for _ in range(d.u32()):
            blobs.append(
                [d.u64(), d.u64(), d.u64(), d.u64(), d.string(), d.u32()]
            )
        return cls(size, xattrs, blobs)

    def copy(self) -> "_Onode":
        return _Onode(
            self.size, dict(self.xattrs), [list(b) for b in self.blobs]
        )


class BlockStore(ObjectStore):
    """Extent-allocated, checksummed, optionally-compressed store."""

    def __init__(
        self,
        path: str | os.PathLike,
        sync: bool = True,
        compression: str = "none",
        min_compress: int = 4096,
    ):
        self.path = pathlib.Path(path)
        self.path.mkdir(parents=True, exist_ok=True)
        self.sync = sync
        from ..compressor import create as compressor_create

        self.compressor = compressor_create(compression)
        self._compressor_create = compressor_create
        self.min_compress = min_compress
        self._lock = lockdep.RMutex("blockstore")
        self.kv = _KVLog(self.path, sync)
        dev_path = self.path / _DEV
        if not dev_path.exists():
            dev_path.touch()
        self._dev = open(dev_path, "r+b")
        self.alloc = _Allocator()
        self._rebuild_allocator()

    def statfs(self) -> dict:
        # allocator accounting (O(free runs)): used = everything ever
        # allocated below the frontier minus the free runs — no onode
        # walk on the ~1 Hz fullness poll or the write hot path
        with self._lock:
            used = self.alloc.frontier - sum(
                run[1] for run in self.alloc.free
            )
        total = int(self.total_bytes)
        return {
            "total": total,
            "used": max(0, used),
            "avail": max(0, total - used),
        }

    def _rebuild_allocator(self) -> None:
        used = []
        for key, val in self.kv.db.items():
            if key.startswith("o" + _SEP):
                on = _Onode.decode(val)
                for _l, _ll, doff, dlen, _c, _crc in on.blobs:
                    used.append((doff, dlen))
        self.alloc.rebuild(used)

    # -- device IO ---------------------------------------------------------
    def _dev_read(self, off: int, length: int) -> bytes:
        self._dev.seek(off)
        got = self._dev.read(length)
        return got + b"\0" * (length - len(got))

    def _blob_data(self, blob, st=None) -> bytes:
        """Read + VERIFY one blob (BlueStore::_verify_csum on every
        read), decompressing as recorded.  ``st`` lets same-
        transaction reads see extents whose device write is still
        pending in the txn."""
        loff, llen, doff, dlen, codec, crc = blob
        raw = None
        if st is not None:
            for woff, wdata in st.dev_writes:
                if woff == doff:
                    raw = bytes(wdata[:dlen])
                    raw += b"\0" * (dlen - len(raw))
                    break
        if raw is None:
            raw = self._dev_read(doff, dlen)
        if ceph_crc32c(0, raw) != crc:
            raise StoreError(
                f"checksum mismatch reading extent {doff}+{dlen} "
                "(-EIO)"
            )
        if codec != "none":
            from ..compressor import CompressorError

            try:
                raw = self._compressor_create(codec).decompress(raw)
            except CompressorError as e:
                raise StoreError(f"blob decompress failed: {e}")
        return raw

    # -- transaction path --------------------------------------------------
    def queue_transaction(self, txn: Transaction) -> None:
        from .objectstore import residency_gens

        residency_gens.note_txn(self, txn)
        with self._lock:
            st = _BTxn(self)
            committed = False
            try:
                for op in txn.ops:
                    self._apply(st, op)
                # data first ...
                for off, data in st.dev_writes:
                    self._dev.seek(off)
                    self._dev.write(data)
                if st.dev_writes:
                    self._dev.flush()
                    if self.sync:
                        os.fsync(self._dev.fileno())
                # ... then metadata; a crash in between leaves only
                # unreferenced bytes in free space
                sets: dict[str, bytes] = {}
                dels: list[str] = []
                for cid in st.new_colls:
                    sets[_ckey(cid)] = b""
                for cid in st.dead_colls:
                    dels.append(_ckey(cid))
                dels.extend(st.kv_dels)
                for (cid, oid), on in st.onodes.items():
                    if on is None:
                        dels.append(_okey(cid, oid))
                    else:
                        sets[_okey(cid, oid)] = on.encode()
                for key, val in st.kv_sets.items():
                    sets[key] = val
                self.kv.commit(sets, dels)
                committed = True
            finally:
                if not committed:
                    # any failure before the KV commit — StoreError
                    # from an op, ENOSPC from the WAL append, even a
                    # malformed-tuple TypeError — must hand the fresh
                    # extents back, or every failed txn leaks space
                    # until remount
                    for off, length in st.allocated:
                        self.alloc.release(off, length)
            for off, length in st.freed:
                self.alloc.release(off, length)

    def _apply(self, st: "_BTxn", op) -> None:
        kind, cid, oid = op[0], op[1], op[2]
        if kind == "mkcoll":
            if st.coll_exists(cid):
                raise StoreError(f"collection {cid} exists (-EEXIST)")
            st.dead_colls.discard(cid)
            st.new_colls.add(cid)
        elif kind == "rmcoll":
            if not st.coll_exists(cid):
                raise StoreError(f"no collection {cid} (-ENOENT)")
            if not st.coll_empty(cid):
                raise StoreError(
                    f"collection {cid} not empty (-ENOTEMPTY)"
                )
            st.new_colls.discard(cid)
            st.dead_colls.add(cid)
        elif kind == "touch":
            st.get(cid, oid, create=True)
        elif kind == "write":
            _, _, _, offset, data = op
            self._op_write(st, cid, oid, offset, bytes(data))
        elif kind == "truncate":
            _, _, _, size = op
            self._op_truncate(st, cid, oid, size)
        elif kind == "setattr":
            _, _, _, name, value = op
            on = st.get(cid, oid)
            if on is None:
                raise StoreError(f"no object {cid}/{oid} (-ENOENT)")
            on.xattrs[name] = bytes(value)
        elif kind == "rmattr":
            _, _, _, name = op
            on = st.get(cid, oid)
            if on is None or name not in on.xattrs:
                raise StoreError(
                    f"no attr {name} on {cid}/{oid} (-ENODATA)"
                )
            del on.xattrs[name]
        elif kind == "remove":
            on = st.get(cid, oid)
            if on is None:
                raise StoreError(f"no object {cid}/{oid} (-ENOENT)")
            for b in on.blobs:
                st.freed.append((b[2], b[3]))
            st.onodes[(cid, oid)] = None
            for k in st.omap_keys(cid, oid):
                st.kv_dels.add(_mkey(cid, oid, k))
        elif kind == "omap_setkeys":
            _, _, _, kv = op
            if st.get(cid, oid) is None:
                raise StoreError(f"no object {cid}/{oid} (-ENOENT)")
            for k, v in kv.items():
                st.kv_sets[_mkey(cid, oid, k)] = bytes(v)
                st.kv_dels.discard(_mkey(cid, oid, k))
        elif kind == "omap_rmkeys":
            _, _, _, keys = op
            if st.get(cid, oid) is None:
                raise StoreError(f"no object {cid}/{oid} (-ENOENT)")
            for k in keys:
                st.kv_sets.pop(_mkey(cid, oid, k), None)
                st.kv_dels.add(_mkey(cid, oid, k))
        elif kind == "omap_clear":
            if st.get(cid, oid) is None:
                raise StoreError(f"no object {cid}/{oid} (-ENOENT)")
            for k in st.omap_keys(cid, oid):
                st.kv_sets.pop(_mkey(cid, oid, k), None)
                st.kv_dels.add(_mkey(cid, oid, k))
        elif kind == "clone":
            _, _, src_oid, dst_oid = op
            src = st.get(cid, src_oid)
            if src is None:
                raise StoreError(
                    f"no object {cid}/{src_oid} (-ENOENT)"
                )
            data = self._read_onode(st, src, 0, src.size)
            prev = st.get(cid, dst_oid)
            if prev is not None:
                for b in prev.blobs:
                    st.freed.append((b[2], b[3]))
            dst = _Onode(0, dict(src.xattrs), [])
            st.onodes[(cid, dst_oid)] = dst
            if data:
                self._write_blob(st, dst, 0, data)
            dst.size = src.size
            # omap copies too
            old_dst = set(st.omap_keys(cid, dst_oid))
            for k in old_dst:
                st.kv_sets.pop(_mkey(cid, dst_oid, k), None)
                st.kv_dels.add(_mkey(cid, dst_oid, k))
            for k in st.omap_keys(cid, src_oid):
                st.kv_sets[_mkey(cid, dst_oid, k)] = st.omap_get_one(
                    cid, src_oid, k
                )
                st.kv_dels.discard(_mkey(cid, dst_oid, k))
        else:
            raise StoreError(f"unknown op {kind}")

    def _op_write(self, st, cid, oid, offset, data) -> None:
        on = st.get(cid, oid, create=True)
        end = offset + len(data)
        if not data:
            on.size = max(on.size, offset)
            return
        overl = [
            b
            for b in on.blobs
            if b[0] < end and b[0] + b[1] > offset
        ]
        lo = min([offset] + [b[0] for b in overl])
        hi = max([end] + [b[0] + b[1] for b in overl])
        buf = bytearray(hi - lo)
        for b in overl:
            got = self._blob_data(b, st)[: b[1]]
            buf[b[0] - lo : b[0] - lo + len(got)] = got
        buf[offset - lo : end - lo] = data
        for b in overl:
            st.freed.append((b[2], b[3]))
            on.blobs.remove(b)
        self._write_blob(st, on, lo, bytes(buf))
        on.size = max(on.size, end)

    def _write_blob(self, st, on, loff, data) -> None:
        codec = "none"
        stored = data
        if (
            self.compressor.name != "none"
            and len(data) >= self.min_compress
        ):
            packed = self.compressor.compress(data)
            # only keep it when compression actually saves a block
            if len(packed) + ALLOC_UNIT <= len(data):
                stored = packed
                codec = self.compressor.name
        doff, alen = self.alloc.allocate(len(stored))
        st.allocated.append((doff, alen))
        st.dev_writes.append((doff, stored))
        on.blobs.append(
            [
                loff,
                len(data),
                doff,
                len(stored),
                codec,
                ceph_crc32c(0, stored),
            ]
        )
        on.blobs.sort(key=lambda b: b[0])

    def _op_truncate(self, st, cid, oid, size) -> None:
        on = st.get(cid, oid, create=True)
        keep = []
        for b in on.blobs:
            if b[0] >= size:
                st.freed.append((b[2], b[3]))
            elif b[0] + b[1] > size:
                b[1] = size - b[0]  # tail trimmed; extent kept
                keep.append(b)
            else:
                keep.append(b)
        on.blobs = keep
        on.size = size

    def _read_onode(self, st, on, offset, length) -> bytes:
        if length < 0:
            length = on.size - offset
        length = max(0, min(length, on.size - offset))
        if length == 0:
            return b""
        buf = bytearray(length)
        end = offset + length
        for b in on.blobs:
            if b[0] >= end or b[0] + b[1] <= offset:
                continue
            data = self._blob_data(b, st)[: b[1]]
            s = max(offset, b[0])
            e = min(end, b[0] + b[1])
            buf[s - offset : e - offset] = data[s - b[0] : e - b[0]]
        return bytes(buf)

    # -- read surface ------------------------------------------------------
    def _onode(self, cid: str, oid: str) -> _Onode:
        if _ckey(cid) not in self.kv.db:
            raise StoreError(f"no collection {cid} (-ENOENT)")
        blob = self.kv.db.get(_okey(cid, oid))
        if blob is None:
            raise StoreError(f"no object {cid}/{oid} (-ENOENT)")
        return _Onode.decode(blob)

    def read(self, cid, oid, offset=0, length=-1) -> bytes:
        with self._lock:
            on = self._onode(cid, oid)
            return self._read_onode(None, on, offset, length)

    def getattr(self, cid, oid, name) -> bytes:
        with self._lock:
            on = self._onode(cid, oid)
            if name not in on.xattrs:
                raise StoreError(f"no attr {name} (-ENODATA)")
            return on.xattrs[name]

    def stat(self, cid, oid) -> int:
        with self._lock:
            return self._onode(cid, oid).size

    def exists(self, cid, oid) -> bool:
        with self._lock:
            return _okey(cid, oid) in self.kv.db

    def list_collections(self) -> list[str]:
        with self._lock:
            p = "C" + _SEP
            return sorted(
                k[len(p):] for k in self.kv.db if k.startswith(p)
            )

    def coll_exists(self, cid: str) -> bool:
        with self._lock:
            return _ckey(cid) in self.kv.db

    def list_objects(self, cid) -> list[str]:
        with self._lock:
            if _ckey(cid) not in self.kv.db:
                raise StoreError(f"no collection {cid} (-ENOENT)")
            p = f"o{_SEP}{cid}{_SEP}"
            return sorted(
                k[len(p):] for k in self.kv.db if k.startswith(p)
            )

    def list_attrs(self, cid, oid) -> dict[str, bytes]:
        with self._lock:
            return dict(self._onode(cid, oid).xattrs)

    def omap_get(self, cid, oid) -> dict[str, bytes]:
        with self._lock:
            self._onode(cid, oid)
            p = _mkey(cid, oid)
            return {
                k[len(p):]: v
                for k, v in self.kv.db.items()
                if k.startswith(p)
            }

    def omap_get_vals(
        self, cid, oid, start_after: str = "", max_return: int = -1
    ) -> dict[str, bytes]:
        with self._lock:
            omap = self.omap_get(cid, oid)
            out: dict[str, bytes] = {}
            for k in sorted(omap):
                if start_after and k <= start_after:
                    continue
                out[k] = omap[k]
                if 0 <= max_return <= len(out):
                    break
            return out

    # -- maintenance -------------------------------------------------------
    def compact(self) -> None:
        with self._lock:
            self.kv.compact()

    def close(self) -> None:
        with self._lock:
            self.kv.close()
            if not self._dev.closed:
                self._dev.flush()
                if self.sync:
                    os.fsync(self._dev.fileno())
                self._dev.close()

    def fsck(self) -> list[str]:
        """Full consistency walk (BlueStore::_fsck): every blob's
        checksum re-verified, extents bounds- and overlap-checked,
        omap keys matched to live onodes."""
        errors: list[str] = []
        with self._lock:
            seen: list[tuple[int, int, str]] = []
            dev_size = self._dev.seek(0, 2)
            for key, val in sorted(self.kv.db.items()):
                if not key.startswith("o" + _SEP):
                    continue
                _tag, cid, oid = key.split(_SEP, 2)
                if _ckey(cid) not in self.kv.db:
                    errors.append(f"{cid}/{oid}: orphan collection")
                try:
                    on = _Onode.decode(val)
                except DecodeError as e:
                    errors.append(f"{cid}/{oid}: onode decode: {e}")
                    continue
                for b in on.blobs:
                    if b[2] + b[3] > max(dev_size, self.alloc.frontier):
                        errors.append(
                            f"{cid}/{oid}: blob extent {b[2]}+{b[3]} "
                            "out of bounds"
                        )
                        continue
                    try:
                        self._blob_data(b)
                    except StoreError as e:
                        errors.append(f"{cid}/{oid}: {e}")
                    seen.append((b[2], _round_up(b[3]), f"{cid}/{oid}"))
            seen.sort()
            for (o1, l1, n1), (o2, _l2, n2) in zip(seen, seen[1:]):
                if o1 + l1 > o2:
                    errors.append(
                        f"extent overlap: {n1} and {n2} share blocks"
                    )
            for key in self.kv.db:
                if key.startswith("m" + _SEP):
                    _tag, cid, oid, _k = key.split(_SEP, 3)
                    if _okey(cid, oid) not in self.kv.db:
                        errors.append(f"{cid}/{oid}: orphan omap key")
        return errors


class _BTxn:
    """Transaction-local shadow state (the MemStore _TxnState shape
    rendered for KV-backed onodes)."""

    def __init__(self, store: BlockStore):
        self.store = store
        self.onodes: dict[tuple[str, str], _Onode | None] = {}
        self.new_colls: set[str] = set()
        self.dead_colls: set[str] = set()
        self.kv_sets: dict[str, bytes] = {}
        self.kv_dels: set[str] = set()
        self.dev_writes: list[tuple[int, bytes]] = []
        self.allocated: list[tuple[int, int]] = []
        self.freed: list[tuple[int, int]] = []

    def coll_exists(self, cid: str) -> bool:
        if cid in self.dead_colls:
            return False
        return cid in self.new_colls or _ckey(cid) in self.store.kv.db

    def coll_empty(self, cid: str) -> bool:
        p = f"o{_SEP}{cid}{_SEP}"
        for key in self.store.kv.db:
            if key.startswith(p):
                oid = key[len(p):]
                if self.onodes.get((cid, oid), ...) is not None:
                    return False
        for (c, _oid), on in self.onodes.items():
            if c == cid and on is not None:
                return False
        return True

    def get(self, cid: str, oid: str, create: bool = False):
        key = (cid, oid)
        if key in self.onodes:
            on = self.onodes[key]
            if on is None and create:
                on = self.onodes[key] = _Onode()
            return on
        if not self.coll_exists(cid):
            raise StoreError(f"no collection {cid} (-ENOENT)")
        blob = self.store.kv.db.get(_okey(cid, oid))
        if blob is None:
            if not create:
                return None
            on = _Onode()
        else:
            on = _Onode.decode(blob)
        self.onodes[key] = on
        return on

    def omap_keys(self, cid: str, oid: str) -> list[str]:
        p = _mkey(cid, oid)
        keys = {
            k[len(p):]
            for k in self.store.kv.db
            if k.startswith(p)
        }
        for k in self.kv_sets:
            if k.startswith(p):
                keys.add(k[len(p):])
        for k in self.kv_dels:
            if k.startswith(p):
                keys.discard(k[len(p):])
        return sorted(keys)

    def omap_get_one(self, cid: str, oid: str, key: str) -> bytes:
        full = _mkey(cid, oid, key)
        if full in self.kv_sets:
            return self.kv_sets[full]
        return self.store.kv.db.get(full, b"")
