"""Shared framed-WAL/checkpoint plumbing for the persistent stores
(KStore's transaction log and BlockStore's KV log ride the same
length+crc32c framing, torn-tail replay, and atomic-rename
checkpoint — one durability-critical implementation, two users)."""

from __future__ import annotations

import os
import pathlib

from ..native import ceph_crc32c
from .objectstore import StoreError


def frame(body: bytes) -> bytes:
    """[u32 len][u32 crc32c(body)][body]."""
    return (
        len(body).to_bytes(4, "little")
        + ceph_crc32c(0, body).to_bytes(4, "little")
        + body
    )


def append_frame(f, body: bytes, sync: bool) -> None:
    """Append one frame durably.  On a partial write (ENOSPC, IO
    error) the file is truncated back to the pre-append offset so a
    half-written frame can never sit MID-log and silently hide every
    commit that lands after it from the next mount's replay."""
    start = f.tell()
    try:
        f.write(frame(body))
        f.flush()
        if sync:
            os.fsync(f.fileno())
    except OSError as e:
        try:
            f.truncate(start)
            f.flush()
        except OSError:
            pass
        raise StoreError(f"wal append failed: {e}")


def replay_frames(raw: bytes):
    """Yield (body, end_pos) for every intact frame; stops at the
    first torn/corrupt frame (the kill-mid-write tail)."""
    pos = 0
    while pos + 8 <= len(raw):
        blen = int.from_bytes(raw[pos : pos + 4], "little")
        crc = int.from_bytes(raw[pos + 4 : pos + 8], "little")
        body = raw[pos + 8 : pos + 8 + blen]
        if len(body) < blen or ceph_crc32c(0, body) != crc:
            return
        pos += 8 + blen
        yield body, pos


def truncate_tail(path: pathlib.Path, good_pos: int) -> None:
    """Drop a torn tail so future appends start clean."""
    with open(path, "r+b") as f:
        f.truncate(good_pos)


def write_checkpoint(path: pathlib.Path, blob: bytes) -> None:
    """write-temp + fsync + atomic rename (crash leaves old or new)."""
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    tmp.replace(path)
