"""Object storage layer.

``objectstore`` is the transactional store boundary
(src/os/ObjectStore.h + Transaction) with a RAM implementation
(src/os/memstore/); ``ec_store`` is the erasure-coded data plane over
it — the simplified ECBackend: full-stripe writes through the batched
encode seam, reconstructing reads, HashInfo scrub, and single-shard
recovery with minimum reads (src/osd/ECBackend.cc's read/write/
recovery paths without the messenger hop); ``wal_store`` fronts any
concrete store with a write-ahead log — group commit, deferred small
writes, crash replay (the BlueStore deferred-write role).
"""

from .ec_store import ECStore, ScrubResult
from .blockstore import BlockStore
from .kstore import KStore
from .objectstore import MemStore, ObjectStore, Transaction
from .wal_store import WALStore

__all__ = [
    "BlockStore",
    "ECStore",
    "KStore",
    "MemStore",
    "ObjectStore",
    "ScrubResult",
    "Transaction",
    "WALStore",
]
