"""ECStore — the erasure-coded data plane over per-shard object stores
(the simplified ECBackend, src/osd/ECBackend.cc).

One ObjectStore per shard plays the k+m OSDs.  Writes are full-object:
pad to stripe multiples, batch-encode through the stripe seam, land
each shard + its cumulative HashInfo crc in ONE transaction per shard
(ECTransaction::encode_and_write's shape: shard writes and hinfo
travel together).  Reads fetch the k data shards, crc-verify, and
widen to reconstruction only when one is missing or corrupt
(objects_read_and_reconstruct).  ``recover_shard`` rebuilds one shard
from its minimum read set with REAL ranged reads — for CLAY profiles
those are fractional-chunk reads (the ECUtil::decode sub-chunk
plumbing) — and falls back to a crc-verified full decode if a helper
was silently corrupt.  ``scrub`` is the per-shard crc audit of a PG
deep scrub.
"""

from __future__ import annotations

import json

import numpy as np

from ..ec import ErasureCodeProfile, registry_instance
from ..ec.interface import ErasureCodeError
from ..ec.stripe import HashInfo, StripeInfo, decode_concat, encode as stripe_encode
from ..native import ceph_crc32c
from .objectstore import MemStore, ObjectStore, StoreError, Transaction

HINFO_KEY = "hinfo_key"  # the xattr name the reference uses


class ScrubResult:
    def __init__(self):
        self.missing: list[int] = []
        self.corrupt: list[int] = []

    @property
    def clean(self) -> bool:
        return not self.missing and not self.corrupt

    def __repr__(self):
        return (
            f"ScrubResult(missing={self.missing}, corrupt={self.corrupt})"
        )


class ECStore:
    def __init__(
        self,
        plugin: str = "jerasure",
        profile: dict | None = None,
        stores: list[ObjectStore] | None = None,
        stripe_width: int | None = None,
    ):
        prof = ErasureCodeProfile(profile or {})
        self.ec = registry_instance().factory(plugin, prof)
        self.k = self.ec.get_data_chunk_count()
        self.n = self.ec.get_chunk_count()
        chunk = self.ec.get_chunk_size(
            stripe_width if stripe_width else self.k * 4096
        )
        self.sinfo = StripeInfo(self.k, self.k * chunk)
        self.stores = stores or [MemStore() for _ in range(self.n)]
        assert len(self.stores) == self.n
        self.cid = "ec_pool"
        for store in self.stores:
            try:
                store.queue_transaction(
                    Transaction().create_collection(self.cid)
                )
            except StoreError:
                pass  # already created

    # -- write path --------------------------------------------------------
    def put(self, name: str, data: bytes) -> None:
        """Full-object write: pad to stripes, batch encode, one
        transaction per shard carrying chunk bytes + hinfo."""
        logical = len(data)
        padded_len = self.sinfo.logical_to_next_stripe_offset(logical)
        padded = data + b"\0" * (padded_len - logical)
        shards = stripe_encode(self.sinfo, self.ec, padded)
        if not shards:  # zero-length object: n empty shards
            shards = {
                i: np.zeros(0, dtype=np.uint8) for i in range(self.n)
            }
        hinfo = HashInfo(self.n)
        hinfo.append(0, shards)
        meta = {
            "size": logical,
            "hashes": hinfo.cumulative_shard_hashes,
        }
        for i, store in enumerate(self.stores):
            self._write_shard(store, name, bytes(shards[i]), meta)

    def _write_shard(
        self, store: ObjectStore, name: str, shard: bytes, meta: dict
    ) -> None:
        """The one shard-write shape (remove+touch+write+hinfo in a
        single transaction), shared by put and recovery."""
        txn = Transaction()
        if store.exists(self.cid, name):
            txn.remove(self.cid, name)
        txn.touch(self.cid, name)
        txn.write(self.cid, name, 0, shard)
        txn.setattr(self.cid, name, HINFO_KEY, json.dumps(meta).encode())
        store.queue_transaction(txn)

    # -- read path ---------------------------------------------------------
    def _shard_meta(self, name: str) -> dict:
        for store in self.stores:
            try:
                return json.loads(store.getattr(self.cid, name, HINFO_KEY))
            except StoreError:
                continue
        raise ErasureCodeError(f"object {name} not found (-ENOENT)")

    def _read_verified(self, name: str, meta: dict, shard: int):
        try:
            raw = self.stores[shard].read(self.cid, name)
        except StoreError:
            return None
        if ceph_crc32c(0xFFFFFFFF, raw) != meta["hashes"][shard]:
            return None
        return np.frombuffer(raw, dtype=np.uint8)

    def _gather(
        self, name: str, meta: dict, want: set[int] | None = None
    ) -> dict[int, np.ndarray]:
        """crc-verified shard reads; corrupt/missing shards are simply
        absent, like failed shard reads."""
        shards: dict[int, np.ndarray] = {}
        for i in range(self.n) if want is None else sorted(want):
            got = self._read_verified(name, meta, i)
            if got is not None:
                shards[i] = got
        return shards

    def get(self, name: str) -> bytes:
        """Read with reconstruction
        (ECBackend::objects_read_and_reconstruct): fast path reads only
        the k data shards; any failure widens to every shard."""
        meta = self._shard_meta(name)
        if meta["size"] == 0:
            return b""
        want = {self.ec.chunk_index(i) for i in range(self.k)}
        chunks = self._gather(name, meta, want)
        if set(chunks) != want:
            # reconstruct path: top up with the shards not yet read
            chunks.update(
                self._gather(
                    name, meta, set(range(self.n)) - set(chunks)
                )
            )
        data = decode_concat(self.sinfo, self.ec, chunks)
        return bytes(data[: meta["size"]])

    # -- scrub / recovery --------------------------------------------------
    def scrub(self, name: str) -> ScrubResult:
        """Per-shard crc audit (the deep-scrub hinfo check)."""
        meta = self._shard_meta(name)
        result = ScrubResult()
        for i, store in enumerate(self.stores):
            try:
                raw = store.read(self.cid, name)
            except StoreError:
                result.missing.append(i)
                continue
            if ceph_crc32c(0xFFFFFFFF, raw) != meta["hashes"][i]:
                result.corrupt.append(i)
        return result

    def recover_shard(self, name: str, shard: int) -> int:
        """Rebuild one shard from its minimum read set and rewrite it
        (RecoveryOp: READING -> WRITING).  Reads are REAL ranged
        store reads; a failed rebuild crc (silently corrupt helper)
        falls back to a crc-verified full decode.  Returns helper
        bytes read."""
        meta = self._shard_meta(name)
        available = set()
        for i in range(self.n):
            if i == shard:
                continue
            try:
                if self.stores[i].exists(self.cid, name):
                    available.add(i)
            except StoreError:
                pass  # unreachable shard: not a helper candidate
        read_bytes = 0
        rebuilt = None
        try:
            rebuilt, read_bytes = self._repair_minimum(
                name, meta, shard, available
            )
        except (ErasureCodeError, StoreError):
            # e.g. a truncated helper (length-checked in
            # _repair_minimum); the verified path filters it by crc
            rebuilt = None
        if (
            rebuilt is None
            or ceph_crc32c(0xFFFFFFFF, bytes(rebuilt))
            != meta["hashes"][shard]
        ):
            # helper was corrupt or repair unsupported: verified path
            shards = self._gather(name, meta)
            shards.pop(shard, None)
            read_bytes += sum(len(c) for c in shards.values())
            decoded = self.ec._decode({shard}, shards)
            rebuilt = np.ascontiguousarray(decoded[shard], dtype=np.uint8)
            if (
                ceph_crc32c(0xFFFFFFFF, bytes(rebuilt))
                != meta["hashes"][shard]
            ):
                raise ErasureCodeError(
                    f"rebuilt shard {shard} fails its hinfo crc (-EIO)"
                )
        self._write_shard(
            self.stores[shard], name, bytes(rebuilt), meta
        )
        return read_bytes

    def _repair_minimum(self, name, meta, shard, available):
        """Minimum-read rebuild with ranged reads (trusting helpers,
        like the reference's repair reads — corruption is caught by the
        rebuilt-shard crc)."""
        minimum = self.ec.minimum_to_decode({shard}, available)
        chunk_len = self.sinfo.chunk_size
        lengths = {
            h: self.stores[h].stat(self.cid, name) for h in minimum
        }
        shard_len = max(lengths.values())
        short = [h for h, n in lengths.items() if n != shard_len]
        if short or shard_len % chunk_len:
            raise StoreError(
                f"helper shards truncated or misaligned: {short}"
            )
        sub_count = self.ec.get_sub_chunk_count()
        read_bytes = 0
        if sub_count > 1 and any(
            runs != [(0, sub_count)] for runs in minimum.values()
        ):
            # fractional repair, stripe by stripe (the ECUtil::decode
            # subchunk loop, src/osd/ECUtil.cc:82-116)
            nstripes = shard_len // chunk_len
            sc = chunk_len // sub_count
            parts = []
            for s in range(nstripes):
                base = s * chunk_len
                partial = {}
                for helper, runs in minimum.items():
                    segs = [
                        self.stores[helper].read(
                            self.cid, name, base + off * sc, cnt * sc
                        )
                        for off, cnt in runs
                    ]
                    buf = np.frombuffer(
                        b"".join(segs), dtype=np.uint8
                    )
                    read_bytes += len(buf)
                    partial[helper] = buf
                decoded = self.ec.decode({shard}, partial, chunk_len)
                parts.append(decoded[shard])
            return np.concatenate(parts), read_bytes
        chunks = {}
        for helper in minimum:
            raw = self.stores[helper].read(self.cid, name)
            read_bytes += len(raw)
            chunks[helper] = np.frombuffer(raw, dtype=np.uint8)
        decoded = self.ec._decode({shard}, chunks)
        return (
            np.ascontiguousarray(decoded[shard], dtype=np.uint8),
            read_bytes,
        )

    # -- fault injection (the OSDThrasher role, §4.3) ----------------------
    def lose_shard(self, name: str, shard: int) -> None:
        self.stores[shard].queue_transaction(
            Transaction().remove(self.cid, name)
        )

    def corrupt_shard(self, name: str, shard: int, offset: int = 0) -> None:
        raw = bytearray(self.stores[shard].read(self.cid, name))
        raw[offset] ^= 0xFF
        self.stores[shard].queue_transaction(
            Transaction().write(self.cid, name, 0, bytes(raw))
        )
