"""ECStore — the erasure-coded data plane over per-shard object stores
(the simplified ECBackend, src/osd/ECBackend.cc).

One ObjectStore per shard plays the k+m OSDs.  ``put`` is the
full-object write: pad to stripe multiples, batch-encode through the
stripe seam, land each shard + its cumulative HashInfo crc in ONE
transaction per shard (ECTransaction::encode_and_write's shape: shard
writes and hinfo travel together).  ``write`` is the partial-overwrite
RMW pipeline (ECBackend.cc:1858 start_rmw): a WritePlan decides which
stripes need read-modify-write, reads come from the in-flight
ExtentCache before the shards, writes per object are FIFO-ordered
(the waiting_state/waiting_reads/waiting_commit lists collapsed to a
per-object ticket queue), and only the affected stripe range is
re-encoded and range-written.  Following the reference's ec_overwrites
semantics, a partial overwrite invalidates the cumulative HashInfo
(the reference stops maintaining hinfo on overwrite-enabled pools);
scrub then verifies by re-encoding instead of per-shard crc.

Reads fetch the k data shards, crc-verify where hinfo is valid, and
widen to reconstruction when a shard is missing or corrupt
(objects_read_and_reconstruct).  ``recover_shard`` rebuilds one shard
from its minimum read set with REAL ranged reads — for CLAY profiles
those are fractional-chunk reads (the ECUtil::decode sub-chunk
plumbing) — and falls back to a crc-verified full decode if a helper
was silently corrupt.  ``scrub`` is the per-shard crc audit of a PG
deep scrub.
"""

from __future__ import annotations

import itertools
import json
import threading

import numpy as np

from ..ec import ErasureCodeProfile, registry_instance
from ..ec.interface import ErasureCodeError
from ..ec.stripe import (
    HashInfo,
    StripeInfo,
    decode_concat,
    encode as stripe_encode,
    rmw_encode,
)
from ..native import ceph_crc32c
from .objectstore import MemStore, ObjectStore, StoreError, Transaction
from .pg_util import ObjectOpQueue, ScrubResult

HINFO_KEY = "hinfo_key"  # the xattr name the reference uses


class ExtentCache:
    """In-flight/recent stripe contents per object (ExtentCache.h:120):
    sequential RMW ops on one object reuse the stripes the previous op
    just wrote instead of re-reading them from the shards.  Entries
    live only while the object has ops in flight."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stripes: dict[tuple[str, int], bytes] = {}
        self._refs: dict[str, int] = {}

    def open(self, name: str) -> None:
        with self._lock:
            self._refs[name] = self._refs.get(name, 0) + 1

    def close(self, name: str) -> None:
        with self._lock:
            self._refs[name] -= 1
            if self._refs[name] <= 0:
                del self._refs[name]
                for key in [k for k in self._stripes if k[0] == name]:
                    del self._stripes[key]

    def get(self, name: str, stripe: int) -> bytes | None:
        with self._lock:
            return self._stripes.get((name, stripe))

    def put(self, name: str, stripe: int, data: bytes) -> None:
        with self._lock:
            if name in self._refs:
                self._stripes[(name, stripe)] = data

    def invalidate(self, name: str) -> None:
        """Drop every cached stripe of ``name`` — a full-object write
        replaced the content, so queued RMW ops must re-read (the
        reference ExtentCache is repopulated by the write itself)."""
        with self._lock:
            for key in [k for k in self._stripes if k[0] == name]:
                del self._stripes[key]


class ECStore:
    def __init__(
        self,
        plugin: str = "jerasure",
        profile: dict | None = None,
        stores: list[ObjectStore] | None = None,
        stripe_width: int | None = None,
        *,
        ec=None,
        cid: str = "ec_pool",
        ensure_collections: bool = True,
    ):
        """``ec`` accepts a prebuilt codec (skipping the registry
        factory); ``cid``/``ensure_collections`` let the OSD daemon
        mount this machinery as a per-PG view over its own collection
        and remote peers (the ECBackend-under-PrimaryLogPG shape)."""
        if ec is None:
            prof = ErasureCodeProfile(profile or {})
            ec = registry_instance().factory(plugin, prof)
        self.ec = ec
        self.k = self.ec.get_data_chunk_count()
        self.n = self.ec.get_chunk_count()
        chunk = self.ec.get_chunk_size(
            stripe_width if stripe_width else self.k * 4096
        )
        self.sinfo = StripeInfo(self.k, self.k * chunk)
        self.stores = stores or [MemStore() for _ in range(self.n)]
        assert len(self.stores) == self.n
        self.cid = cid
        if ensure_collections:
            for store in self.stores:
                try:
                    store.queue_transaction(
                        Transaction().create_collection(self.cid)
                    )
                except StoreError:
                    pass  # already created (or shard unreachable)
        # RMW pipeline state: per-object FIFO tickets (the reference's
        # waiting_state/waiting_reads/waiting_commit op lists collapse
        # to "ops on one object run in submission order"; ops on
        # different objects run concurrently) + the extent cache
        self._opq = ObjectOpQueue()
        self._commit_seq = itertools.count(1)
        self.extent_cache = ExtentCache()

    # -- write path --------------------------------------------------------
    def put(self, name: str, data: bytes, trace: str = "") -> None:
        """Full-object write: pad to stripes, batch encode, one
        transaction per shard carrying chunk bytes + hinfo.  When
        shards are remote (RemoteStore sub-op proxies), ``trace``
        rides every MECSubWrite so shard daemons record the same
        span id (ECBackend.cc:886's sub-op tracing)."""
        from .remote import trace_context

        with trace_context(trace):
            self._put_inner(name, data)

    def _put_inner(self, name: str, data: bytes) -> None:
        from ..common import tracing

        logical = len(data)
        padded_len = self.sinfo.logical_to_next_stripe_offset(logical)
        padded = data + b"\0" * (padded_len - logical)
        # per-stage child spans under the ambient daemon op: the
        # device encode and the shard fan-out are the two stages a
        # slow EC write can hide in
        with tracing.span(
            "ec_encode", tags={"oid": name, "size": logical}
        ):
            shards = stripe_encode(self.sinfo, self.ec, padded)
        if not shards:  # zero-length object: n empty shards
            shards = {
                i: np.zeros(0, dtype=np.uint8) for i in range(self.n)
            }
        hinfo = HashInfo(self.n)
        hinfo.append(0, shards)
        meta = {
            "size": logical,
            "hashes": hinfo.cumulative_shard_hashes,
        }
        # full-object writes order through the same per-object ticket
        # queue as RMW writes: interleaving put's per-shard
        # transactions with a concurrent write()'s would leave shards
        # encoding two different logical states
        ticket = self._enter(name)
        try:
            with tracing.span("ec_shard_writes", tags={"oid": name}) as sp:
                for i, store in enumerate(self.stores):
                    self._write_shard(
                        store, name, bytes(shards[i]), meta
                    )
                    sp.mark_event(f"shard_{i}_applied")
        finally:
            # queued RMW ops must not reuse stripes of the replaced
            # content — even when a shard write failed partway, the
            # cached stripes no longer match what landed
            self.extent_cache.invalidate(name)
            self._exit(name, ticket)

    # -- partial-overwrite RMW pipeline ------------------------------------
    def _enter(self, name: str) -> int:
        """Queue behind in-flight ops on this object (waiting_state)."""
        return self._opq.enter(
            name, on_enter=lambda: self.extent_cache.open(name)
        )

    def _exit(self, name: str, ticket: int) -> int:
        def on_exit():
            self.extent_cache.close(name)
            return next(self._commit_seq)

        return self._opq.exit(name, ticket, on_exit=on_exit)

    def write(self, name: str, offset: int, data: bytes) -> int:
        """Partial overwrite with read-modify-write (start_rmw,
        ECBackend.cc:1858).  Returns the commit sequence number (ops on
        one object commit in submission order).

        The WritePlan: only the head/tail stripes that are partially
        covered AND hold pre-existing bytes need reading; fully-covered
        and beyond-EOF stripes encode fresh.  Reads hit the ExtentCache
        before the shards.  Per the reference's ec_overwrites
        semantics, the object's cumulative HashInfo is invalidated
        (scrub falls back to re-encode consistency checking)."""
        data = bytes(data)
        if not data:
            return 0
        sw = self.sinfo.stripe_width
        cs = self.sinfo.chunk_size
        ticket = self._enter(name)
        try:
            try:
                meta = self._shard_meta(name)
                old_size = meta["size"]
            except ErasureCodeError:
                meta = None
                old_size = 0
            if meta is not None:
                # overwriting a degraded object would auto-create
                # short zero-filled shards and lose data that is still
                # reconstructible — recover missing/truncated shards
                # first (the wait_for_degraded_object barrier before
                # ECBackend::submit_transaction)
                self._recover_degraded(name, old_size)
            def read_cached(stripes: list[int]):
                """ExtentCache first, shard reads for the rest (the
                objects_read_async_no_cache hop inside start_rmw)."""
                existing: dict[int, np.ndarray] = {}
                to_read = []
                for s in stripes:
                    cached = self.extent_cache.get(name, s)
                    if cached is not None:
                        existing[s] = np.frombuffer(
                            cached, dtype=np.uint8
                        )
                    else:
                        to_read.append(s)
                existing.update(self.read_stripes(name, to_read))
                return existing

            first, end, buf, shards = rmw_encode(
                self.sinfo, self.ec, offset, data, old_size,
                read_cached,
            )
            new_meta = {"size": max(old_size, offset + len(data))}
            blob = json.dumps(new_meta).encode()
            for i, store in enumerate(self.stores):
                # the write op auto-creates the object; no touch needed
                txn = Transaction()
                txn.write(self.cid, name, first * cs, bytes(shards[i]))
                txn.setattr(self.cid, name, HINFO_KEY, blob)
                store.queue_transaction(txn)
            for s in range(first, end):
                self.extent_cache.put(
                    name,
                    s,
                    bytes(buf[(s - first) * sw : (s - first + 1) * sw]),
                )
        except BaseException:
            # shards may hold a half-landed write; cached stripes from
            # earlier ops no longer describe what is on disk
            self.extent_cache.invalidate(name)
            raise
        finally:
            seq = self._exit(name, ticket)
        return seq

    def _recover_degraded(self, name: str, old_size: int) -> None:
        """Rebuild any missing/truncated shard before a partial
        overwrite lands range writes on it."""
        expected = (
            self.sinfo.logical_to_next_chunk_offset(old_size)
        )
        if expected == 0:
            # empty object: every shard is empty or auto-creates
            # uniformly; nothing to rebuild
            return
        for i, store in enumerate(self.stores):
            try:
                if store.stat(self.cid, name) == expected:
                    continue
            except StoreError:
                pass
            self._recover_locked(name, i)

    def read_stripes(
        self, name: str, stripes: list[int]
    ) -> dict[int, np.ndarray]:
        """Ranged stripe reads for RMW: data shards first, widening to
        reconstruction when one fails (the objects_read_async_no_cache
        hop inside start_rmw)."""
        cs = self.sinfo.chunk_size
        out: dict[int, np.ndarray] = {}
        for s in stripes:
            chunks: dict[int, np.ndarray] = {}
            want = {self.ec.chunk_index(i) for i in range(self.k)}
            for widen in (sorted(want), range(self.n)):
                for i in widen:
                    if i in chunks:
                        continue
                    try:
                        raw = self.stores[i].read(
                            self.cid, name, s * cs, cs
                        )
                    except StoreError:
                        continue
                    if len(raw) == cs:
                        chunks[i] = np.frombuffer(raw, dtype=np.uint8)
                if want <= set(chunks) or len(chunks) >= self.k:
                    break
            out[s] = decode_concat(self.sinfo, self.ec, chunks)
        return out

    def _write_shard(
        self,
        store: ObjectStore,
        name: str,
        shard: bytes,
        meta: dict,
        dev=None,
    ) -> None:
        """The one shard-write shape (remove+touch+write+hinfo in a
        single transaction), shared by put and recovery.  ``dev``
        registers an already-resident device array (a batched-decode
        output slice — device-born, zero extra transfer) instead of
        the host bytes."""
        txn = Transaction()
        if store.exists(self.cid, name):
            txn.remove(self.cid, name)
        txn.touch(self.cid, name)
        txn.write(self.cid, name, 0, shard)
        txn.setattr(self.cid, name, HINFO_KEY, json.dumps(meta).encode())
        store.queue_transaction(txn)
        # register AFTER the txn (the entry records the post-txn
        # generation; any later txn on the shard invalidates it)
        from ..ops.residency import residency_cache

        if dev is not None:
            residency_cache().put_committed(
                store, self.cid, name, dev=dev
            )
        else:
            residency_cache().put_committed(
                store, self.cid, name, data=shard
            )

    # -- read path ---------------------------------------------------------
    def _shard_meta(self, name: str) -> dict:
        for store in self.stores:
            try:
                return json.loads(store.getattr(self.cid, name, HINFO_KEY))
            except StoreError:
                continue
        raise ErasureCodeError(f"object {name} not found (-ENOENT)")

    def meta(self, name: str) -> dict:
        """Object meta ({"size", "hashes"}) from the first reachable
        shard's HashInfo xattr (raises ErasureCodeError on -ENOENT)."""
        return self._shard_meta(name)

    def size(self, name: str) -> int:
        return self._shard_meta(name)["size"]

    def _read_verified(self, name: str, meta: dict, shard: int):
        try:
            raw = self.stores[shard].read(self.cid, name)
        except StoreError:
            return None
        hashes = meta.get("hashes")
        if hashes is not None and ceph_crc32c(0xFFFFFFFF, raw) != hashes[shard]:
            return None
        return np.frombuffer(raw, dtype=np.uint8)

    def _gather(
        self, name: str, meta: dict, want: set[int] | None = None
    ) -> dict[int, np.ndarray]:
        """crc-verified shard reads; corrupt/missing shards are simply
        absent, like failed shard reads."""
        shards: dict[int, np.ndarray] = {}
        for i in range(self.n) if want is None else sorted(want):
            got = self._read_verified(name, meta, i)
            if got is not None:
                shards[i] = got
        return shards

    def get(self, name: str) -> bytes:
        """Read with reconstruction
        (ECBackend::objects_read_and_reconstruct): fast path reads only
        the k data shards; any failure widens to every shard.  Reads
        order through the per-object ticket queue so they never observe
        a half-landed multi-shard write."""
        from ..common import tracing

        ticket = self._enter(name)
        try:
            with tracing.span("ec_read", tags={"oid": name}) as sp:
                meta = self._shard_meta(name)
                if meta["size"] == 0:
                    return b""
                want = {self.ec.chunk_index(i) for i in range(self.k)}
                chunks = self._gather(name, meta, want)
                if set(chunks) != want:
                    # reconstruct path: top up with the shards not
                    # yet read
                    sp.mark_event("widen_to_reconstruct")
                    chunks.update(
                        self._gather(
                            name, meta,
                            set(range(self.n)) - set(chunks),
                        )
                    )
                sp.mark_event("shards_gathered")
                data = decode_concat(self.sinfo, self.ec, chunks)
                return bytes(data[: meta["size"]])
        finally:
            self._exit(name, ticket)

    # -- scrub / recovery --------------------------------------------------
    def scrub(self, name: str) -> ScrubResult:
        """Deep scrub: per-shard crc audit where hinfo is valid; for
        partially-overwritten objects (hinfo invalidated, matching the
        reference's ec_overwrites behavior) fall back to re-encoding
        the data shards and comparing every shard — a consistency
        check that cannot attribute the fault to one shard."""
        ticket = self._enter(name)
        try:
            return self._scrub_locked(name)
        finally:
            self._exit(name, ticket)

    def scrub_batch(self, names) -> dict[str, ScrubResult]:
        """Device-batched deep scrub of many objects: every shard of
        every object rides ONE batched crc32c call
        (ops/scrub_kernels.batch_crc32c) instead of a per-shard CPU
        crc loop; hinfo-less objects still take the per-object
        re-encode fallback.  Findings are identical to scrub() by
        construction (same hashes, same compare)."""
        from ..ops.residency import (
            residency_cache,
            scrub_trusted as _scrub_trusted,
        )
        from ..ops.scrub_kernels import batch_crc32c

        results: dict[str, ScrubResult] = {}
        raws: dict[str, dict[int, bytes]] = {}
        metas: dict[str, dict] = {}
        bufs: list[bytes] = []
        where: list[tuple[str, int]] = []
        tickets = {n: self._enter(n) for n in dict.fromkeys(names)}
        try:
            for name in tickets:
                result = results[name] = ScrubResult()
                try:
                    meta = self._shard_meta(name)
                except ErasureCodeError:
                    continue  # absent everywhere: nothing to audit
                metas[name] = meta
                raws[name] = {}
                has_hashes = meta.get("hashes") is not None
                for i, store in enumerate(self.stores):
                    if has_hashes and _scrub_trusted(store):
                        # generation-checked residency: a hit is the
                        # shard the last committed txn landed, already
                        # on device — zero-transfer digest.  Any txn
                        # since registration (overwrite, delete,
                        # injected corruption) misses and the disk
                        # read below is audited instead.  Persistent
                        # media is never served from cache (deep
                        # scrub audits its out-of-band rot).
                        buf = residency_cache().get(
                            store, self.cid, name
                        )
                        if buf is not None:
                            bufs.append(buf)
                            where.append((name, i))
                            continue
                    try:
                        raw = store.read(self.cid, name)
                    except StoreError:
                        result.missing.append(i)
                        continue
                    raws[name][i] = raw
                    if has_hashes:
                        bufs.append(raw)
                        where.append((name, i))
            if bufs:
                crcs = batch_crc32c(bufs, 0xFFFFFFFF)
                for (name, i), crc in zip(where, crcs):
                    if int(crc) != metas[name]["hashes"][i]:
                        results[name].corrupt.append(i)
            for name, meta in metas.items():
                result = results[name]
                if (
                    meta.get("hashes") is None
                    and not result.missing
                    and meta["size"]
                ):
                    # per-object re-encode fallback, same as scrub()
                    data_chunks = {
                        self.ec.chunk_index(i) for i in range(self.k)
                    }
                    logical = decode_concat(
                        self.sinfo,
                        self.ec,
                        {
                            i: np.frombuffer(
                                raws[name][i], dtype=np.uint8
                            )
                            for i in sorted(data_chunks)
                        },
                    )
                    reencoded = stripe_encode(
                        self.sinfo, self.ec, logical
                    )
                    for i in range(self.n):
                        if bytes(reencoded[i]) != raws[name][i]:
                            result.inconsistent = True
                            break
        finally:
            for name, ticket in tickets.items():
                self._exit(name, ticket)
        return results

    def _scrub_locked(self, name: str) -> ScrubResult:
        meta = self._shard_meta(name)
        result = ScrubResult()
        hashes = meta.get("hashes")
        raws: dict[int, bytes] = {}
        for i, store in enumerate(self.stores):
            try:
                raws[i] = store.read(self.cid, name)
            except StoreError:
                result.missing.append(i)
                continue
            if (
                hashes is not None
                and ceph_crc32c(0xFFFFFFFF, raws[i]) != hashes[i]
            ):
                result.corrupt.append(i)
        if hashes is None and not result.missing and meta["size"]:
            data_chunks = {
                self.ec.chunk_index(i) for i in range(self.k)
            }
            logical = decode_concat(
                self.sinfo,
                self.ec,
                {
                    i: np.frombuffer(raws[i], dtype=np.uint8)
                    for i in sorted(data_chunks)
                },
            )
            reencoded = stripe_encode(self.sinfo, self.ec, logical)
            for i in range(self.n):
                if bytes(reencoded[i]) != raws[i]:
                    result.inconsistent = True
                    break
        return result

    def recover_shard(
        self, name: str, shard: int, meta: dict | None = None
    ) -> int:
        """Rebuild one shard from its minimum read set and rewrite it
        (RecoveryOp: READING -> WRITING).  Reads are REAL ranged
        store reads; a failed rebuild crc (silently corrupt helper)
        falls back to a crc-verified full decode.  Returns helper
        bytes read."""
        ticket = self._enter(name)
        try:
            return self._recover_locked(name, shard, meta)
        finally:
            self._exit(name, ticket)

    def _recover_locked(self, name: str, shard: int, meta=None) -> int:
        rebuilt, read_bytes, meta = self.reconstruct_shard(
            name, shard, meta
        )
        self._write_shard(self.stores[shard], name, rebuilt, meta)
        return read_bytes

    def reconstruct_shard(
        self, name: str, shard: int, meta: dict | None = None
    ) -> tuple[bytes, int, dict]:
        """Rebuild one shard's bytes WITHOUT writing them — the OSD
        daemon uses this to serve recovery pulls and pushes where the
        write travels in its own logged transaction.  ``meta`` lets an
        authoritative caller pin the HashInfo (a rewinding peer may
        still hold stale hinfo).  Returns (bytes, helper_bytes_read,
        meta)."""
        if meta is None:
            meta = self._shard_meta(name)
        available = set()
        for i in range(self.n):
            if i == shard:
                continue
            try:
                if self.stores[i].exists(self.cid, name):
                    available.add(i)
            except StoreError:
                pass  # unreachable shard: not a helper candidate
        read_bytes = 0
        rebuilt = None
        hashes = meta.get("hashes")
        try:
            rebuilt, read_bytes = self._repair_minimum(
                name, meta, shard, available
            )
        except (ErasureCodeError, StoreError):
            # e.g. a truncated helper (length-checked in
            # _repair_minimum); the verified path filters it by crc
            rebuilt = None
        if rebuilt is None or (
            hashes is not None
            and ceph_crc32c(0xFFFFFFFF, bytes(rebuilt)) != hashes[shard]
        ):
            # helper was corrupt or repair unsupported: verified path
            shards = self._gather(name, meta)
            shards.pop(shard, None)
            read_bytes += sum(len(c) for c in shards.values())
            decoded = self.ec._decode({shard}, shards)
            rebuilt = np.ascontiguousarray(decoded[shard], dtype=np.uint8)
            if (
                hashes is not None
                and ceph_crc32c(0xFFFFFFFF, bytes(rebuilt))
                != hashes[shard]
            ):
                raise ErasureCodeError(
                    f"rebuilt shard {shard} fails its hinfo crc (-EIO)"
                )
        return bytes(rebuilt), read_bytes, meta

    def _repair_minimum(self, name, meta, shard, available):
        """Minimum-read rebuild with ranged reads (trusting helpers,
        like the reference's repair reads — corruption is caught by the
        rebuilt-shard crc)."""
        minimum = self.ec.minimum_to_decode({shard}, available)
        chunk_len = self.sinfo.chunk_size
        lengths = {
            h: self.stores[h].stat(self.cid, name) for h in minimum
        }
        shard_len = max(lengths.values())
        short = [h for h, n in lengths.items() if n != shard_len]
        if short or shard_len % chunk_len:
            raise StoreError(
                f"helper shards truncated or misaligned: {short}"
            )
        sub_count = self.ec.get_sub_chunk_count()
        read_bytes = 0
        if sub_count > 1 and any(
            runs != [(0, sub_count)] for runs in minimum.values()
        ):
            # fractional repair, stripe by stripe (the ECUtil::decode
            # subchunk loop, src/osd/ECUtil.cc:82-116)
            nstripes = shard_len // chunk_len
            sc = chunk_len // sub_count
            parts = []
            for s in range(nstripes):
                base = s * chunk_len
                partial = {}
                for helper, runs in minimum.items():
                    segs = [
                        self.stores[helper].read(
                            self.cid, name, base + off * sc, cnt * sc
                        )
                        for off, cnt in runs
                    ]
                    buf = np.frombuffer(
                        b"".join(segs), dtype=np.uint8
                    )
                    read_bytes += len(buf)
                    partial[helper] = buf
                decoded = self.ec.decode({shard}, partial, chunk_len)
                parts.append(decoded[shard])
            return np.concatenate(parts), read_bytes
        chunks = {}
        for helper in minimum:
            raw = self.stores[helper].read(self.cid, name)
            read_bytes += len(raw)
            chunks[helper] = np.frombuffer(raw, dtype=np.uint8)
        decoded = self.ec._decode({shard}, chunks)
        return (
            np.ascontiguousarray(decoded[shard], dtype=np.uint8),
            read_bytes,
        )

    # -- batched recovery (ROADMAP open item 2) ----------------------------
    def reconstruct_shards_batch(
        self, names, shard: int, metas: dict | None = None
    ):
        """Rebuild ONE missing shard position for MANY objects through
        a single coalesced decode-from-survivors dispatch (the
        repair-side twin of the batched write path).  Survivor reads
        honor ``minimum_to_decode`` — an LRC repair touches k_local ≪
        k helpers, and the fan-in is MEASURED in the returned stats —
        and consult the residency cache first (a survivor the encode
        path just registered rides the dispatch with zero re-upload).

        Returns (results, fallback, stats): ``results`` maps name →
        (payload, meta) where payload is host bytes or a device-born
        DeviceBuf, crc-verified against hinfo where it exists;
        ``fallback`` lists names the batched path could not serve
        (absent objects, fractional-repair profiles, short/corrupt
        helpers) — callers route those through the per-op
        :meth:`reconstruct_shard`, which widens and verifies.
        ``stats`` counts survivor fan-in: ``survivor_shards`` (helper
        shards consulted per the whole batch), ``read_bytes`` (bytes
        actually read from stores — residency hits cost zero), and
        ``residency_hits``."""
        from ..ops.residency import (
            residency_cache,
            scrub_trusted as _scrub_trusted,
        )
        from ..ec.stripe import decode_batch

        metas = metas or {}
        results: dict[str, tuple] = {}
        fallback: list[str] = []
        stats = {
            "survivor_shards": 0,
            "read_bytes": 0,
            "residency_hits": 0,
        }
        todo: list[str] = []
        sets: list[dict] = []
        obj_meta: dict[str, dict] = {}
        # a position whose store errored once this batch is DEAD for
        # the whole batch: re-probing it per object would hold the
        # caller for a full sub-op timeout PER OBJECT (a freshly
        # killed peer's session conn blocks, not refuses)
        dead_positions: set[int] = set()
        for name in dict.fromkeys(names):
            meta = metas.get(name)
            if meta is None:
                try:
                    meta = self._shard_meta(name)
                except ErasureCodeError:
                    fallback.append(name)
                    continue
            obj_meta[name] = meta
            expected = self.sinfo.logical_to_next_chunk_offset(
                meta["size"]
            )
            if expected == 0:
                results[name] = (b"", meta)
                continue
            available = set()
            for i in range(self.n):
                if i == shard or i in dead_positions:
                    continue
                try:
                    if self.stores[i].exists(self.cid, name):
                        available.add(i)
                except StoreError:
                    dead_positions.add(i)
            try:
                minimum = self.ec.minimum_to_decode(
                    {shard}, available
                )
            except ErasureCodeError:
                fallback.append(name)
                continue
            sub = self.ec.get_sub_chunk_count()
            if any(runs != [(0, sub)] for runs in minimum.values()):
                # fractional (CLAY) repair: the per-op sub-chunk
                # plumbing reads strictly less — never regress it to
                # a whole-shard batch
                fallback.append(name)
                continue
            survivors: dict[int, object] = {}
            short = False
            for pos in minimum:
                store = self.stores[pos]
                payload = None
                if _scrub_trusted(store):
                    payload = residency_cache().get(
                        store, self.cid, name, expect_len=expected
                    )
                    if payload is not None:
                        stats["residency_hits"] += 1
                if payload is None:
                    try:
                        raw = store.read(self.cid, name)
                    except StoreError:
                        dead_positions.add(pos)
                        short = True
                        break
                    if len(raw) != expected:
                        short = True
                        break
                    stats["read_bytes"] += len(raw)
                    payload = raw
                survivors[pos] = payload
            if short:
                fallback.append(name)
                continue
            stats["survivor_shards"] += len(survivors)
            todo.append(name)
            sets.append(survivors)
        if todo:
            rebuilt = decode_batch(
                self.sinfo, self.ec, sets, {shard}
            )
            for name, rec in zip(todo, rebuilt):
                meta = obj_meta[name]
                payload = rec[shard]
                hashes = meta.get("hashes")
                if hashes is not None:
                    host = (
                        payload.host()
                        if hasattr(payload, "host")
                        else bytes(payload)
                    )
                    if ceph_crc32c(0xFFFFFFFF, host) != hashes[shard]:
                        # a silently-corrupt helper: the per-op
                        # verified path filters it by crc
                        fallback.append(name)
                        continue
                results[name] = (payload, meta)
        return results, fallback, stats

    def recover_objects_batch(self, names, shard: int) -> dict:
        """Whole-PG rebuild of one dead shard position: batched
        decode-from-survivors, then one shard-write per object —
        reconstructed payloads registered device-born where the
        device path ran (the next deep scrub digests them without a
        transfer).  Objects the batched path cannot serve degrade to
        the per-op verified :meth:`recover_shard` path.  Returns the
        fan-in/throughput stats (plus ``objects``/``batched``)."""
        tickets = {n: self._enter(n) for n in dict.fromkeys(names)}
        try:
            results, fallback, stats = self.reconstruct_shards_batch(
                list(tickets), shard
            )
            for name, (payload, meta) in results.items():
                if hasattr(payload, "host"):
                    self._write_shard(
                        self.stores[shard], name, payload.host(),
                        meta, dev=payload.device(),
                    )
                else:
                    self._write_shard(
                        self.stores[shard], name, bytes(payload), meta
                    )
            recovered = 0
            for name in fallback:
                try:
                    stats["read_bytes"] += self._recover_locked(
                        name, shard
                    )
                    recovered += 1
                except (ErasureCodeError, StoreError):
                    pass  # absent everywhere / unreachable helpers
            stats["objects"] = len(results) + recovered
            stats["batched"] = len(results)
            return stats
        finally:
            for name, ticket in tickets.items():
                self._exit(name, ticket)
    def lose_shard(self, name: str, shard: int) -> None:
        self.stores[shard].queue_transaction(
            Transaction().remove(self.cid, name)
        )

    def corrupt_shard(self, name: str, shard: int, offset: int = 0) -> None:
        raw = bytearray(self.stores[shard].read(self.cid, name))
        raw[offset] ^= 0xFF
        self.stores[shard].queue_transaction(
            Transaction().write(self.cid, name, 0, bytes(raw))
        )
