"""Shard daemon + remote store proxy — the EC sub-op network boundary.

In the reference, the primary's ECBackend never touches a replica's
disk directly: sub-writes travel as MOSDECSubOpWrite and are applied
by the shard OSD's handle_sub_write (src/osd/ECBackend.cc:2106 fan-out,
:934 apply), sub-reads as MOSDECSubOpRead answered by handle_sub_read
(:1010).  This module provides both halves for the framework:

- ``ShardServer`` — a dispatcher hosting one ObjectStore; applies
  MECSubWrite transactions atomically, answers MECSubRead batches, and
  echoes MPing heartbeats (the OSD side).
- ``RemoteStore`` — an ObjectStore *proxy* over a messenger
  Connection, so the existing ECStore data plane (ec_store.py) runs
  unchanged with every shard behind a real network hop.  One sub-op
  message per transaction / read batch, exactly the reference's
  granularity.
- ``shard_daemon_main`` — stand-alone process entry
  (``python -m ceph_tpu.store.remote --port P``), used by the
  multi-process EC tests and any real deployment.
"""

from __future__ import annotations

import contextlib
import threading

import argparse
import sys
import time

from ..msg import (
    MECSubRead,
    MECSubReadReply,
    MECSubWrite,
    MECSubWriteReply,
    MPing,
    Message,
    MessageError,
    Messenger,
)
from ..common.encoding import Decoder, Encoder
from ..msg.message import (
    READ_ATTR,
    READ_ATTRS,
    READ_DATA,
    READ_EXISTS,
    READ_LIST,
    READ_OMAP,
    READ_STAT,
)
from ..msg.messenger import Connection, Dispatcher
from .objectstore import MemStore, ObjectStore, StoreError, Transaction


# ambient span id for sub-ops issued through RemoteStore: set by the
# caller (the EC daemon path wraps its shard fan-out) so every
# MECSubWrite carries the client op's trace without threading a
# parameter through the ObjectStore interface
_TRACE = threading.local()


@contextlib.contextmanager
def trace_context(trace: str):
    prev = getattr(_TRACE, "id", "")
    _TRACE.id = trace
    try:
        yield
    finally:
        _TRACE.id = prev


def current_trace() -> str:
    return getattr(_TRACE, "id", "")


class ShardServer(Dispatcher):
    """Shard-OSD dispatcher: one ObjectStore behind sub-op messages."""

    def __init__(
        self,
        store: ObjectStore | None = None,
        whoami: int = 0,
        tracker=None,
        tracer=None,
    ):
        self.store = store or MemStore()
        self.whoami = whoami
        self.tracker = tracker  # OpTracker: sub-ops record their span
        self.tracer = tracer  # common.tracing.Tracer (optional)

    def ms_dispatch(self, conn: Connection, msg: Message) -> bool:
        if isinstance(msg, MECSubWrite):
            reply = MECSubWriteReply(
                tid=msg.tid, from_osd=self.whoami
            )
            top = None
            if self.tracker is not None:
                top = self.tracker.create_op(
                    f"ec_sub_write({msg.trace})", trace=msg.trace
                )
            span = None
            if self.tracer is not None and msg.trace:
                from ..common.tracing import ROLE_SHARD

                span = self.tracer.start_span(
                    "ec_sub_write",
                    trace_id=msg.trace,
                    role=ROLE_SHARD,
                )
            try:
                self.store.queue_transaction(msg.txn)
            except StoreError as e:
                reply.ok = False
                reply.error = str(e)
            if top is not None:
                top.mark_event("applied" if reply.ok else "failed")
                top.finish()
            if span is not None:
                span.mark_event("applied" if reply.ok else "failed")
                span.finish()
            conn.send(reply)
            return True
        if isinstance(msg, MECSubRead):
            reply = MECSubReadReply(tid=msg.tid, from_osd=self.whoami)
            for kind, cid, oid, a1, a2 in msg.ops:
                try:
                    reply.results.append((True, self._read(kind, cid, oid, a1, a2)))
                except StoreError as e:
                    reply.results.append((False, str(e).encode()))
            conn.send(reply)
            return True
        if isinstance(msg, MPing) and not msg.is_reply:
            conn.send(
                MPing(
                    tid=msg.tid,
                    from_osd=self.whoami,
                    stamp=msg.stamp,
                    is_reply=True,
                )
            )
            return True
        return False

    def _read(self, kind, cid, oid, a1, a2) -> bytes:
        s = self.store
        if kind == READ_DATA:
            length = a1 >> 32
            offset = a1 & 0xFFFFFFFF
            if length == 0xFFFFFFFF:  # whole-object sentinel
                length = -1
            return s.read(cid, oid, offset, length)
        if kind == READ_ATTR:
            return s.getattr(cid, oid, a2)
        if kind == READ_STAT:
            return s.stat(cid, oid).to_bytes(8, "little")
        if kind == READ_EXISTS:
            return b"\1" if s.exists(cid, oid) else b"\0"
        if kind == READ_LIST:
            return "\0".join(s.list_objects(cid)).encode()
        if kind == READ_ATTRS:
            e = Encoder()
            e.map(
                s.list_attrs(cid, oid),
                lambda e2, k: e2.string(k),
                lambda e2, v: e2.bytes(v),
            )
            return e.getvalue()
        if kind == READ_OMAP:
            e = Encoder()
            e.map(
                s.omap_get(cid, oid),
                lambda e2, k: e2.string(k),
                lambda e2, v: e2.bytes(v),
            )
            return e.getvalue()
        raise StoreError(f"unknown read kind {kind}")


def _pack_extent(offset: int, length: int) -> int:
    """(offset, length) packed into the u64 arg1 slot; length -1 (whole
    object) is carried as the sentinel 0xFFFFFFFF.  Extents are bounded
    to 32 bits each — shard objects are chunk-sized; reject anything
    larger loudly instead of silently corrupting the packing."""
    if length < 0:
        length = 0xFFFFFFFF
    if not 0 <= offset < 1 << 32 or not 0 <= length <= 0xFFFFFFFF:
        raise StoreError(
            f"extent ({offset}, {length}) exceeds the 32-bit sub-read "
            "window"
        )
    return (length << 32) | offset


class RemoteStore(ObjectStore):
    """ObjectStore proxy: every call becomes one sub-op round trip."""

    def __init__(self, conn: Connection, timeout: float = 30.0):
        self.conn = conn
        self.timeout = timeout

    def _call(self, msg, reply_cls):
        """Sub-op round trip; a dead/unreachable shard surfaces as
        StoreError, exactly like a local IO failure, so the EC layer's
        degraded-read/recovery paths engage."""
        try:
            reply = self.conn.call(msg, timeout=self.timeout)
        except MessageError as e:
            raise StoreError(f"shard unreachable: {e}") from e
        if not isinstance(reply, reply_cls):
            raise StoreError(f"unexpected reply {type(reply).__name__}")
        return reply

    # proxy: the backing store mutates on the remote daemon; our
    # generation counter cannot see those txns, so residency entries
    # must never key on this object
    residency_local = False

    # -- write -------------------------------------------------------------
    def queue_transaction(self, txn: Transaction) -> None:
        reply = self._call(
            MECSubWrite(txn=txn, trace=current_trace()),
            MECSubWriteReply,
        )
        if not reply.ok:
            raise StoreError(reply.error)

    # -- reads -------------------------------------------------------------
    def _one(self, kind, cid, oid, a1=0, a2="") -> bytes:
        reply = self._call(
            MECSubRead(ops=[(kind, cid, oid, a1, a2)]), MECSubReadReply
        )
        if not reply.results:
            raise StoreError("empty read reply")
        ok, data = reply.results[0]
        if not ok:
            raise StoreError(data.decode())
        return data

    def read(self, cid, oid, offset=0, length=-1) -> bytes:
        return self._one(
            READ_DATA, cid, oid, _pack_extent(offset, length)
        )

    def getattr(self, cid, oid, name) -> bytes:
        return self._one(READ_ATTR, cid, oid, 0, name)

    def stat(self, cid, oid) -> int:
        return int.from_bytes(self._one(READ_STAT, cid, oid), "little")

    def exists(self, cid, oid) -> bool:
        # READ_EXISTS never fails server-side (absence is b"\\0"), so
        # any StoreError here is a transport failure and must surface —
        # a dead shard is not the same as "object absent"
        return self._one(READ_EXISTS, cid, oid) == b"\1"

    def list_objects(self, cid) -> list[str]:
        raw = self._one(READ_LIST, cid, "")
        return raw.decode().split("\0") if raw else []

    def list_attrs(self, cid, oid) -> dict[str, bytes]:
        raw = self._one(READ_ATTRS, cid, oid)
        return Decoder(raw).map(
            lambda d: d.string(), lambda d: d.bytes()
        )

    def omap_get(self, cid, oid) -> dict[str, bytes]:
        raw = self._one(READ_OMAP, cid, oid)
        return Decoder(raw).map(
            lambda d: d.string(), lambda d: d.bytes()
        )

    def ping(self, from_osd: int = -1, timeout: float = 5.0) -> float:
        """Heartbeat round trip; returns rtt seconds (raises
        MessageError when the shard is gone)."""
        t0 = time.monotonic()
        reply = self.conn.call(
            MPing(from_osd=from_osd, stamp=t0), timeout=timeout
        )
        if not isinstance(reply, MPing) or not reply.is_reply:
            raise MessageError("bad ping reply")
        return time.monotonic() - t0


def shard_daemon_main(argv=None) -> int:
    """Stand-alone shard OSD process (the ceph-osd role for one shard)."""
    p = argparse.ArgumentParser(prog="shard_daemon")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--osd-id", type=int, default=0)
    args = p.parse_args(argv)
    msgr = Messenger(name=f"osd.{args.osd_id}")
    msgr.add_dispatcher(ShardServer(whoami=args.osd_id))
    host, port = msgr.bind("127.0.0.1", args.port)
    # parent parses this line to learn the bound port
    print(f"shard_daemon ready {host}:{port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        msgr.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(shard_daemon_main())
