"""KStore — persistent ObjectStore: WAL + checkpoint over files.

The reference's default store is BlueStore (raw block device, RocksDB
metadata, its own WAL — src/os/bluestore/BlueStore.cc, 16k LoC); its
simpler sibling KStore keeps everything in the KV log.  This store
takes the KStore-class design, re-rendered for the framework:

- **write-ahead log**: every transaction is framed (length + crc32c
  over the framework transaction encoding, msg/message.py) and
  fsync'd to ``wal.log`` BEFORE the in-memory apply — commit means
  "in the WAL", exactly the ObjectStore::queue_transaction durability
  contract (src/os/ObjectStore.h:215).
- **checkpoint**: ``compact()`` snapshots the full state to
  ``snap.bin`` (write-to-temp + fsync + atomic rename) and truncates
  the WAL; crash anywhere leaves either the old or the new snapshot.
- **mount replay**: load the snapshot, then re-apply WAL entries in
  order; a torn tail (partial frame, crc mismatch — the
  kill-mid-write case) is detected and discarded, matching journal
  replay semantics.

Deviation from BlueStore, documented: no raw-block allocator, no
compression/checksum-per-blob, no RocksDB — object data lives in the
snapshot + WAL stream.  The Transaction API, atomicity, and
crash-restart behavior are the parity surface (SURVEY.md §5.4).
"""

from __future__ import annotations

import os
import pathlib
import threading

from ..common import lockdep
from ..common.encoding import Decoder, DecodeError, Encoder
from ..native import ceph_crc32c
from .framed_log import (
    append_frame,
    replay_frames,
    truncate_tail,
    write_checkpoint,
)
from .objectstore import (
    MemStore,
    StoreError,
    Transaction,
    decode_transaction,
    encode_transaction,
)

_SNAP = "snap.bin"
_WAL = "wal.log"
_SNAP_MAGIC = 0x4B53544F  # "KSTO" (v1: data + xattrs)
_SNAP_MAGIC_V2 = 0x4B535432  # "KST2" (v2: + omap)


class KStore(MemStore):
    """File-backed store; state in RAM, durability via WAL+snapshot."""

    def __init__(
        self,
        path: str | os.PathLike,
        sync: bool = True,
        compression: str = "none",
    ):
        """``compression`` names a compressor plugin for checkpoint
        blobs (the BlueStore blob-compression role at this store's
        granularity); snapshots record their codec, so a store written
        with one codec mounts under any configuration."""
        super().__init__()
        self.path = pathlib.Path(path)
        self.sync = sync
        from ..compressor import create as compressor_create

        self.compressor = compressor_create(compression)
        self.path.mkdir(parents=True, exist_ok=True)
        self._wal_lock = lockdep.Mutex("kstore.wal")
        self._mount()
        self._wal = open(self.path / _WAL, "ab")

    # -- durability plumbing ----------------------------------------------
    def queue_transaction(self, txn: Transaction) -> None:
        # validate + apply under the memstore lock, but WAL-append
        # first: an entry is only written once the ops are known to
        # apply cleanly, so we shadow-apply, then log, then commit.
        from .objectstore import residency_gens

        residency_gens.note_txn(self, txn)
        with self._lock:
            from .objectstore import _TxnState

            st = _TxnState(self)
            for op in txn.ops:
                self._apply(st, op)
            with self._wal_lock:
                e = Encoder()
                encode_transaction(e, txn)
                append_frame(self._wal, e.getvalue(), self.sync)
            self._commit(st)

    def compact(self) -> None:
        """Checkpoint: snapshot full state, truncate the WAL."""
        with self._lock:
            blob = self._snapshot()
            write_checkpoint(self.path / _SNAP, blob)
            with self._wal_lock:
                self._wal.close()
                self._wal = open(self.path / _WAL, "wb")
                if self.sync:
                    os.fsync(self._wal.fileno())

    def close(self) -> None:
        with self._wal_lock:
            if not self._wal.closed:
                self._wal.flush()
                if self.sync:
                    os.fsync(self._wal.fileno())
                self._wal.close()

    # -- snapshot format ---------------------------------------------------
    def _snapshot(self) -> bytes:
        e = Encoder()
        e.u32(_SNAP_MAGIC_V2)
        e.u32(len(self._colls))
        for cid in sorted(self._colls):
            e.string(cid)
            objs = self._colls[cid]
            e.u32(len(objs))
            for oid in sorted(objs):
                obj = objs[oid]
                e.string(oid)
                e.bytes(bytes(obj.data))
                e.map(
                    obj.xattrs,
                    lambda e2, k: e2.string(k),
                    lambda e2, v: e2.bytes(v),
                )
                e.map(
                    obj.omap,
                    lambda e2, k: e2.string(k),
                    lambda e2, v: e2.bytes(v),
                )
        body = e.getvalue()
        codec = self.compressor.name.encode()
        body = (
            len(codec).to_bytes(1, "little")
            + codec
            + self.compressor.compress(body)
        )
        return body + ceph_crc32c(0, body).to_bytes(4, "little")

    def _load_snapshot(self, blob: bytes) -> None:
        from .objectstore import _Object

        if len(blob) < 4:
            raise DecodeError("snapshot too short")
        body, crc = blob[:-4], int.from_bytes(blob[-4:], "little")
        if ceph_crc32c(0, body) != crc:
            raise DecodeError("snapshot crc mismatch")
        from ..compressor import CompressorError, create as compressor_create

        if len(body) >= 4 and int.from_bytes(
            body[:4], "little"
        ) in (_SNAP_MAGIC, _SNAP_MAGIC_V2):
            # legacy pre-compression snapshot: magic-first, raw body
            pass
        else:
            if len(body) < 1 or body[0] > 32:
                raise DecodeError("bad snapshot codec header")
            clen = body[0]
            try:
                codec = body[1 : 1 + clen].decode("ascii")
                body = compressor_create(codec).decompress(
                    body[1 + clen :]
                )
            except (CompressorError, UnicodeDecodeError) as e:
                raise DecodeError(f"snapshot decompress: {e}")
        d = Decoder(body)
        magic = d.u32()
        if magic not in (_SNAP_MAGIC, _SNAP_MAGIC_V2):
            raise DecodeError("bad snapshot magic")
        has_omap = magic == _SNAP_MAGIC_V2
        for _ in range(d.u32()):
            cid = d.string()
            coll: dict = {}
            for _ in range(d.u32()):
                oid = d.string()
                obj = _Object()
                obj.data = bytearray(d.bytes())
                obj.xattrs = d.map(
                    lambda d2: d2.string(), lambda d2: d2.bytes()
                )
                if has_omap:
                    obj.omap = d.map(
                        lambda d2: d2.string(), lambda d2: d2.bytes()
                    )
                coll[oid] = obj
            self._colls[cid] = coll

    # -- mount / replay ----------------------------------------------------
    def _mount(self) -> None:
        snap = self.path / _SNAP
        if snap.exists():
            self._load_snapshot(snap.read_bytes())
        wal = self.path / _WAL
        if not wal.exists():
            return
        raw = wal.read_bytes()
        pos = 0
        for body, end in replay_frames(raw):
            try:
                txn = decode_transaction(Decoder(body))
            except DecodeError:
                break
            try:
                super().queue_transaction(txn)
            except StoreError:
                # an entry that no longer applies cleanly (snapshot
                # already contains it and the op is not idempotent,
                # e.g. mkcoll): possible only for WAL entries logged
                # before the last compact raced a crash; skip it
                pass
            pos = end
        if pos < len(raw):
            # drop the torn tail so future appends start clean
            truncate_tail(wal, pos)
