"""PGBackend factory — pool-type dispatch
(src/osd/PGBackend.cc:571-607 build_pg_backend).

REPLICATED pools get a ReplicatedStore sized to the pool; ERASURE
pools resolve the pool's erasure-code profile through the plugin
registry (the reference looks the plugin up by the profile stored in
the OSDMap and constructs an ECBackend with the pool stripe width).
"""

from __future__ import annotations

from ..crush.types import PG_POOL_TYPE_ERASURE, PG_POOL_TYPE_REPLICATED
from .ec_store import ECStore
from .objectstore import ObjectStore
from .replicated import ReplicatedStore


class PGBackendError(ValueError):
    pass


def build_pg_backend(
    pool,
    erasure_code_profiles: dict[str, dict[str, str]] | None = None,
    stores: list[ObjectStore] | None = None,
    stripe_width: int | None = None,
):
    """Construct the backend for a PgPool (osd/osdmap.py).

    ``erasure_code_profiles`` is the OSDMap's profile table (the
    monitor-managed ``osd erasure-code-profile`` namespace); erasure
    pools must name a profile in it, exactly like the reference's
    ceph_assert(profile) path (PGBackend.cc:588-596).
    """
    if pool.type == PG_POOL_TYPE_REPLICATED:
        if stores is not None and len(stores) != pool.size:
            raise PGBackendError(
                f"pool {pool.pool_id}: {len(stores)} stores for "
                f"size={pool.size} pool"
            )
        return ReplicatedStore(stores=stores, size=pool.size)
    if pool.type == PG_POOL_TYPE_ERASURE:
        profiles = erasure_code_profiles or {}
        profile = profiles.get(pool.erasure_code_profile)
        if profile is None:
            raise PGBackendError(
                f"pool {pool.pool_id}: erasure code profile "
                f"{pool.erasure_code_profile!r} does not exist"
            )
        plugin = profile.get("plugin", "jerasure")
        return ECStore(
            plugin=plugin,
            profile={
                k: v for k, v in profile.items() if k != "plugin"
            },
            stores=stores,
            stripe_width=stripe_width,
        )
    raise PGBackendError(f"pool {pool.pool_id}: unknown type {pool.type}")
