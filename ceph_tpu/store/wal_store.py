"""WALStore — a write-ahead-log front for any concrete ObjectStore
(the BlueStore deferred-write/group-commit role, src/os/bluestore:
_deferred_queue, deferred_batch_ops, _kv_sync_thread).

The reference wins small-write latency by decoupling durability from
apply: a transaction is durable (and acked) the moment its record is
in the WAL; the data/omap apply lands later, and adjacent commits
share one fsync-equivalent barrier.  This store renders that design
over the framework's ObjectStore boundary:

- **commit = WAL append**: every transaction is validated, encoded,
  and framed into ``wal.log`` (``wal_record``: seq + crc32c over the
  transaction payload, inside the framed_log length+crc envelope).
  Small transactions (total write payload below
  ``wal_prefer_deferred_size``) ack as soon as their record's group
  barrier syncs; large ones also wait for the in-order apply (the
  BlueStore non-deferred txc still writes a WAL intent first).
- **group commit**: a dedicated WAL-writer thread drains the commit
  queue in batches of up to ``wal_max_group_txc`` records; when more
  writers are in flight than the batch has captured it holds the
  barrier open up to ``wal_flush_interval_ms`` for the stragglers, so
  N callers pay one fsync.  A solo writer never waits.
- **deferred read-through**: a read of an object whose records the
  drain has not applied yet is served by materializing the pending
  ops over the inner state (the BlueStore deferred-read contract:
  read-after-ack must observe the ack'd bytes).
- **exact replay point**: the drain appends a seq-stamp op (a setattr
  on a hidden ``_wal_meta_`` collection) to every transaction it
  applies to the inner store, so the inner state ATOMICALLY records
  the last applied seq.  Replay applies exactly the records after the
  stamp — naive re-apply from a checkpoint is NOT idempotent (a
  ``clone`` re-applied after its source moved clones the wrong
  bytes); the stamp makes replay exact, not just convergent.
- **residency binds the commit point**: ``residency_gens.note_txn``
  runs at WAL commit (before ack), not at the deferred apply — the
  generation a writer registers a device-resident payload under is
  the one its COMMIT assigned, and the drain's later inner-store
  apply bumps only the inner store's own token, so the registration
  stays valid across the deferred window.

Crash model: SIGKILL.  Completed file writes survive the process (the
page cache outlives it); replay tolerates a torn tail (framed_log)
and batch-verifies every record's payload crc on the device crc32c
kernels (ops/scrub_kernels.py) before re-applying.
"""

from __future__ import annotations

import logging
import os
import pathlib
import threading
import time

from ..common.encoding import Decoder, DecodeError, Encoder
from ..common.perf_counters import PerfCounters, PerfCountersBuilder
from ..native import ceph_crc32c
from .framed_log import (
    append_frame,
    replay_frames,
    truncate_tail,
    write_checkpoint,
)
from .objectstore import (
    MemStore,
    ObjectStore,
    StoreError,
    Transaction,
    _TxnState,
    decode_transaction,
    encode_transaction,
    residency_gens,
)

log = logging.getLogger(__name__)

_WAL = "wal.log"
_CKPT = "wal.ckpt"
_CKPT_MAGIC = 0x57414C31  # "WAL1"

# the hidden collection carrying the applied-seq stamp; filtered from
# list_collections so no OSD walk (PG load, scrub, statfs callers)
# ever sees it as user state
META_COLL = "_wal_meta_"
META_OID = "applied"
META_ATTR = "seq"


# -- wal_record / wal_checkpoint codecs (dencoder-pinned) -------------------
# The on-log record format is durable: a log written by one build must
# replay under every later one, so the layout is pinned in the
# dencoder corpus like the transaction encoding it wraps.

class WALRecord:
    __slots__ = ("seq", "crc", "payload")

    def __init__(self, seq: int, crc: int, payload: bytes):
        self.seq = seq
        self.crc = crc
        self.payload = payload


def make_wal_record(seq: int, payload: bytes) -> WALRecord:
    return WALRecord(seq, ceph_crc32c(0, payload), payload)


def encode_wal_record(e: Encoder, rec: WALRecord) -> None:
    e.u64(rec.seq)
    e.u32(rec.crc)
    e.bytes(rec.payload)


def decode_wal_record(d: Decoder) -> WALRecord:
    seq = d.u64()
    crc = d.u32()
    payload = d.bytes()
    return WALRecord(seq, crc, payload)


class WALCheckpoint:
    __slots__ = ("base_seq",)

    def __init__(self, base_seq: int):
        self.base_seq = base_seq


def encode_wal_checkpoint(e: Encoder, ck: WALCheckpoint) -> None:
    e.u32(_CKPT_MAGIC)
    e.u64(ck.base_seq)


def decode_wal_checkpoint(d: Decoder) -> WALCheckpoint:
    if d.u32() != _CKPT_MAGIC:
        raise DecodeError("bad wal checkpoint magic")
    return WALCheckpoint(d.u64())


# -- perf schema ------------------------------------------------------------

def build_wal_perf(name: str = "os_wal") -> PerfCounters:
    """The l_os_wal_* family: WAL plane accounting, riding the OSD's
    perf dump → MMgrReport → prometheus pipeline."""
    b = PerfCountersBuilder(name)
    b.add_u64_counter("l_os_wal_appends", "records committed to the WAL")
    b.add_u64_counter("l_os_wal_append_bytes", "txn payload bytes WAL'd")
    b.add_u64_counter("l_os_wal_deferred", "small txns acked at append")
    b.add_u64_counter(
        "l_os_wal_deferred_bytes", "write bytes deferred to the drain"
    )
    b.add_u64_counter("l_os_wal_barriers", "group-commit sync barriers")
    b.add_u64_avg(
        "l_os_wal_group_records",
        "records per barrier (sum/avgcount = mean group size)",
    )
    b.add_u64_counter(
        "l_os_wal_barrier_waits",
        "records that rode another caller's barrier",
    )
    b.add_u64_counter(
        "l_os_wal_reads_from_log",
        "reads served through the pending overlay (deferred read)",
    )
    b.add_u64_counter("l_os_wal_applies", "records applied to the inner store")
    b.add_u64_counter(
        "l_os_wal_apply_errors", "validated records the inner apply rejected"
    )
    b.add_u64_counter("l_os_wal_replay_records", "records re-applied at mount")
    b.add_u64_counter("l_os_wal_checkpoints", "WAL truncation checkpoints")
    b.add_u64_gauge("l_os_wal_pending_records", "committed, not yet applied")
    b.add_u64_gauge("l_os_wal_pending_bytes", "payload bytes pending apply")
    return b.create_perf_counters()


class _Pending:
    """One WAL-committed, not-yet-applied transaction."""

    __slots__ = (
        "seq", "txn", "payload", "deferred",
        "synced", "synced_ev", "applied_ev", "error",
    )

    def __init__(self, seq, txn, payload, deferred):
        self.seq = seq
        self.txn = txn
        self.payload = payload
        self.deferred = deferred
        self.synced = False
        self.synced_ev = threading.Event()
        self.applied_ev = threading.Event()
        self.error: str | None = None


class WALStore(ObjectStore):
    """WAL front over a concrete store (MemStore/KStore/BlockStore)."""

    def __init__(
        self,
        inner: ObjectStore,
        path: str | os.PathLike,
        sync: bool = True,
        prefer_deferred_size: int = 65536,
        max_group_txc: int = 32,
        flush_interval_ms: float = 0.5,
        checkpoint_bytes: int = 8 << 20,
        perf: PerfCounters | None = None,
        drain_delay: float = 0.0,
    ):
        self.inner = inner
        self.path = pathlib.Path(path)
        self.sync = sync
        self.prefer_deferred_size = int(prefer_deferred_size)
        self.max_group_txc = max(1, int(max_group_txc))
        self.flush_interval = float(flush_interval_ms) / 1000.0
        self.checkpoint_bytes = int(checkpoint_bytes)
        self.wal_perf = perf if perf is not None else build_wal_perf()
        # test hooks: slow or freeze the drain to widen the deferred
        # window deterministically
        self.drain_delay = float(drain_delay)
        self.drain_paused = False

        # scrub trust follows the backing media: an in-memory inner
        # cannot rot out-of-band, persistent media can
        self.residency_scrub_safe = inner.residency_scrub_safe
        # WAL truncation is only safe when the inner store is itself
        # durable (it persists each apply); a MemStore inner keeps the
        # full log so a remount can rebuild from empty
        self._durable_inner = hasattr(inner, "compact")

        # _state_lock orders the commit/overlay/apply seam: writers
        # validate+enqueue under it, readers materialize under it, the
        # drain applies+unpends under it (so a reader can never see a
        # record both in the overlay and in the inner store).  Lock
        # order: _state_lock -> _wal_cv and
        # _state_lock -> inner's own lock, always.
        self._state_lock = threading.Lock()
        self._drain_cv = threading.Condition(self._state_lock)
        self._pending: dict[int, _Pending] = {}
        self._by_cid: dict[str, list[int]] = {}
        self._next_seq = 1
        self._closed = False

        # group-commit plumbing
        self._wal_cv = threading.Condition()
        self._wal_q: list[_Pending] = []
        self._inflight = 0
        self._wal_bytes = 0

        self.path.mkdir(parents=True, exist_ok=True)
        self.replayed_records = self._mount()
        self._wal = open(self.path / _WAL, "ab")
        self._wal_bytes = self._wal.tell()

        self._writer_thread = threading.Thread(
            target=self._wal_writer, name="wal-writer", daemon=True
        )
        self._drain_thread = threading.Thread(
            target=self._drain_loop, name="wal-drain", daemon=True
        )
        self._writer_thread.start()
        self._drain_thread.start()

    # -- capacity passthrough ----------------------------------------------
    @property
    def total_bytes(self):
        return self.inner.total_bytes

    def statfs(self) -> dict:
        # deferred bytes are already durable in the WAL but not in the
        # inner accounting yet; the drain closes the gap within one
        # flush interval, well under the OSD's ~1 Hz poll
        return self.inner.statfs()

    # -- commit path --------------------------------------------------------
    def queue_transaction(self, txn: Transaction) -> None:
        if self._closed:
            raise StoreError("wal store is closed")
        write_bytes = sum(
            len(op[4]) for op in txn.ops if op[0] == "write"
        )
        deferred = write_bytes < self.prefer_deferred_size
        e = Encoder()
        encode_transaction(e, txn)
        payload = e.getvalue()

        with self._wal_cv:
            self._inflight += 1
        try:
            with self._state_lock:
                self._validate(txn)
                # commit-point binding: the generation this txn
                # assigns is the one the writer registers a resident
                # payload under — bound HERE, before ack, never at
                # the deferred apply
                residency_gens.note_txn(self, txn)
                seq = self._next_seq
                self._next_seq += 1
                rec = _Pending(seq, txn, payload, deferred)
                self._pending[seq] = rec
                for cid in {op[1] for op in txn.ops}:
                    self._by_cid.setdefault(cid, []).append(seq)
                self.wal_perf.inc("l_os_wal_pending_records")
                self.wal_perf.inc(
                    "l_os_wal_pending_bytes", len(payload)
                )
                # seq assignment and WAL enqueue are ONE critical
                # section (lock order: _state_lock -> _wal_cv): two
                # committers must hit _wal_q in seq order, or the
                # writer appends/fsyncs out of order and a crash can
                # leave a later-seq txn durable without the earlier
                # txn it was validated against (replay also sorts by
                # seq defensively, but the prefix it replays must be
                # seq-contiguous for history to be exact)
                with self._wal_cv:
                    if self._closed:
                        self._unpend(rec)
                        raise StoreError("wal store is closed")
                    self._wal_q.append(rec)
                    self._wal_cv.notify_all()
            rec.synced_ev.wait()
            if rec.error is None and not deferred:
                rec.applied_ev.wait()
        finally:
            with self._wal_cv:
                self._inflight -= 1
                self._wal_cv.notify_all()
        if rec.error is not None:
            raise StoreError(rec.error)
        self.wal_perf.inc("l_os_wal_appends")
        self.wal_perf.inc("l_os_wal_append_bytes", len(payload))
        if deferred:
            self.wal_perf.inc("l_os_wal_deferred")
            self.wal_perf.inc("l_os_wal_deferred_bytes", write_bytes)

    def _validate(self, txn: Transaction) -> None:
        """Shadow-apply against the effective (inner + overlay) state
        so a bad transaction fails HERE, synchronously, exactly like a
        synchronous store — never at the deferred apply, where the
        caller is long gone.  Caller holds _state_lock."""
        scratch = MemStore()
        by_cid: dict[str, set[str]] = {}
        rmcolls = set()
        for op in txn.ops:
            kind, cid = op[0], op[1]
            if cid == META_COLL:
                # the applied-seq stamp is store plumbing; a user txn
                # overwriting it would corrupt the exact-replay point
                raise StoreError(
                    f"collection {META_COLL} is reserved (-EPERM)"
                )
            oids = by_cid.setdefault(cid, set())
            if kind == "clone":
                oids.update((op[2], op[3]))
            elif kind == "rmcoll":
                rmcolls.add(cid)
            elif op[2] is not None:
                oids.add(op[2])
        for cid, oids in by_cid.items():
            self._materialize_into(
                scratch, cid, oids, full=cid in rmcolls
            )
        st = _TxnState(scratch)
        for op in txn.ops:
            scratch._apply(st, op)

    # -- group-commit writer ------------------------------------------------
    def _wal_writer(self) -> None:
        while True:
            with self._wal_cv:
                while not self._wal_q and not self._closed:
                    self._wal_cv.wait()
                if self._closed and not self._wal_q:
                    return
                batch = self._wal_q[: self.max_group_txc]
                del self._wal_q[: len(batch)]
                # hold the barrier open for stragglers: only when MORE
                # writers are in flight than this batch captured (a
                # solo writer never waits), and only while there is
                # room in the group
                while (
                    len(batch) < self.max_group_txc
                    and self._inflight > len(batch)
                    and not self._closed
                ):
                    self._wal_cv.wait(self.flush_interval)
                    if not self._wal_q:
                        break
                    room = self.max_group_txc - len(batch)
                    batch.extend(self._wal_q[:room])
                    del self._wal_q[:room]
            self._commit_batch(batch)

    def _commit_batch(self, batch: list[_Pending]) -> None:
        ok: list[_Pending] = []
        for rec in batch:
            e = Encoder()
            encode_wal_record(e, make_wal_record(rec.seq, rec.payload))
            try:
                # per-record append without fsync; one barrier below
                append_frame(self._wal, e.getvalue(), sync=False)
                self._wal_bytes += 8 + len(e.getvalue())
                ok.append(rec)
            except StoreError as err:
                self._fail_record(rec, str(err))
        if ok and self.sync:
            try:
                os.fsync(self._wal.fileno())
            except OSError as err:
                for rec in ok:
                    self._fail_record(rec, f"wal fsync failed: {err}")
                ok = []
        if not ok:
            return
        self.wal_perf.inc("l_os_wal_barriers")
        self.wal_perf.inc("l_os_wal_group_records", len(ok))
        self.wal_perf.inc("l_os_wal_barrier_waits", len(ok) - 1)
        with self._drain_cv:
            for rec in ok:
                rec.synced = True
                rec.synced_ev.set()
            self._drain_cv.notify_all()

    def _fail_record(self, rec: _Pending, error: str) -> None:
        """Un-commit a record whose append failed (ENOSPC/IO error):
        remove it from the overlay so reads stop observing it, then
        wake the caller to raise."""
        with self._state_lock:
            self._unpend(rec)
        rec.error = error
        rec.synced_ev.set()

    def _unpend(self, rec: _Pending) -> None:
        """Caller holds _state_lock."""
        if self._pending.pop(rec.seq, None) is None:
            return
        for cid in {op[1] for op in rec.txn.ops}:
            seqs = self._by_cid.get(cid)
            if seqs is not None:
                try:
                    seqs.remove(rec.seq)
                except ValueError:
                    pass
                if not seqs:
                    del self._by_cid[cid]
        self.wal_perf.dec("l_os_wal_pending_records")
        self.wal_perf.dec("l_os_wal_pending_bytes", len(rec.payload))

    # -- deferred drain -----------------------------------------------------
    def _drain_loop(self) -> None:
        while True:
            with self._drain_cv:
                rec = self._next_drainable()
                while rec is None and not self._closed:
                    self._drain_cv.wait(0.05)
                    rec = self._next_drainable()
                if rec is None and self._closed:
                    return
            if self.drain_delay:
                # test hook: widen the committed-but-unapplied window
                time.sleep(self.drain_delay)
            with self._drain_cv:
                # re-check under the lock (a racing close/unpend)
                if self._pending.get(rec.seq) is not rec:
                    continue
                self._apply_one(rec)
                self._drain_cv.notify_all()
            self._maybe_checkpoint()

    def _next_drainable(self) -> _Pending | None:
        """Lowest-seq synced pending record; None if paused or none.
        Caller holds _state_lock."""
        if self.drain_paused or not self._pending:
            return None
        seq = min(self._pending)
        rec = self._pending[seq]
        return rec if rec.synced else None

    def _apply_one(self, rec: _Pending) -> None:
        """Apply one record to the inner store, stamped with its seq,
        and drop it from the overlay — one _state_lock critical
        section, so no reader can see the record double-applied.
        Caller holds _state_lock."""
        inner_txn = Transaction()
        inner_txn.ops = list(rec.txn.ops)
        inner_txn.setattr(
            META_COLL, META_OID, META_ATTR,
            rec.seq.to_bytes(8, "little"),
        )
        try:
            self.inner.queue_transaction(inner_txn)
            self.wal_perf.inc("l_os_wal_applies")
        except StoreError as err:
            # validated at commit; an inner rejection here means the
            # inner state diverged out-of-band — count it, keep the
            # drain alive (the KStore mount-replay precedent).  A
            # non-deferred caller is still blocked on applied_ev and
            # must RAISE, not return success for bytes that never
            # landed; a deferred caller is long gone, so the best we
            # can do for its acked state is shout (the record is
            # still in the WAL and the applied stamp did not
            # advance, so a remount retries the apply)
            self.wal_perf.inc("l_os_wal_apply_errors")
            if rec.deferred:
                log.error(
                    "wal drain: apply of acked deferred txn seq=%d "
                    "failed, acked state diverged until remount "
                    "replay: %s",
                    rec.seq, err,
                )
            else:
                rec.error = f"wal apply failed: {err}"
        self._unpend(rec)
        rec.applied_ev.set()

    def _maybe_checkpoint(self) -> None:
        if not self._durable_inner:
            return
        with self._state_lock:
            if self._pending or self._wal_bytes < self.checkpoint_bytes:
                return
            # every record in the log is applied and the inner store
            # persists its own applies: compact the inner (bounds ITS
            # log too), checkpoint the replay base, start a fresh WAL
            self.inner.compact()
            base = self._next_seq - 1
            e = Encoder()
            encode_wal_checkpoint(e, WALCheckpoint(base))
            body = e.getvalue()
            write_checkpoint(
                self.path / _CKPT,
                body + ceph_crc32c(0, body).to_bytes(4, "little"),
            )
            self._wal.close()
            self._wal = open(self.path / _WAL, "wb")
            if self.sync:
                os.fsync(self._wal.fileno())
            self._wal_bytes = 0
            self.wal_perf.inc("l_os_wal_checkpoints")

    # -- mount / replay -----------------------------------------------------
    def _mount(self) -> int:
        base = 0
        ckpt = self.path / _CKPT
        if ckpt.exists():
            blob = ckpt.read_bytes()
            if len(blob) >= 4:
                body, crc = blob[:-4], int.from_bytes(blob[-4:], "little")
                if ceph_crc32c(0, body) == crc:
                    try:
                        base = decode_wal_checkpoint(Decoder(body)).base_seq
                    except DecodeError:
                        base = 0
        applied = base
        try:
            raw = self.inner.getattr(META_COLL, META_OID, META_ATTR)
            applied = max(applied, int.from_bytes(raw, "little"))
        except StoreError:
            pass
        self._ensure_meta()

        wal = self.path / _WAL
        replayed = 0
        last_seq = applied
        if wal.exists():
            raw = wal.read_bytes()
            records: list[WALRecord] = []
            ends: list[int] = []
            pos = 0
            for body, end in replay_frames(raw):
                try:
                    rec = decode_wal_record(Decoder(body))
                except DecodeError:
                    break
                records.append(rec)
                ends.append(end)
                pos = end
            # batch-verify every record's payload crc on the device
            # kernels before trusting ANY of them; a mismatch is a
            # torn record — it and everything after it are discarded
            if records:
                from ..ops.scrub_kernels import batch_crc32c

                crcs = batch_crc32c([r.payload for r in records])
                for i, rec in enumerate(records):
                    if int(crcs[i]) != rec.crc:
                        records = records[:i]
                        pos = ends[i - 1] if i else 0
                        break
            # decode-verify in log order: a crc-valid record whose
            # txn fails to decode is as fatal as a torn one — every
            # later record was validated against its effects, so
            # applying them without it would fork the replayed
            # history.  Stop there and truncate, loudly.
            decoded: list[tuple[WALRecord, Transaction | None]] = []
            for i, rec in enumerate(records):
                if rec.seq <= applied:
                    # already stamped into the inner store
                    decoded.append((rec, None))
                    continue
                try:
                    txn = decode_transaction(Decoder(rec.payload))
                except DecodeError as err:
                    self.wal_perf.inc("l_os_wal_apply_errors")
                    log.error(
                        "wal replay: record seq=%d is crc-valid but "
                        "undecodable (%s); discarding it and %d "
                        "later record(s)",
                        rec.seq, err, len(records) - i - 1,
                    )
                    records = records[:i]
                    pos = ends[i - 1] if i else 0
                    break
                decoded.append((rec, txn))
            if pos < len(raw):
                truncate_tail(wal, pos)
            # defensive: apply in seq order even if a log written by
            # an earlier build interleaved records (the commit path
            # holds seq assignment and enqueue in one critical
            # section, so a healthy log is already ordered)
            decoded.sort(key=lambda p: p[0].seq)
            for rec, txn in decoded:
                last_seq = max(last_seq, rec.seq)
                if txn is None:
                    continue
                txn.setattr(
                    META_COLL, META_OID, META_ATTR,
                    rec.seq.to_bytes(8, "little"),
                )
                try:
                    self.inner.queue_transaction(txn)
                    replayed += 1
                except StoreError:
                    self.wal_perf.inc("l_os_wal_apply_errors")
        self._next_seq = last_seq + 1
        if replayed:
            self.wal_perf.inc("l_os_wal_replay_records", replayed)
        return replayed

    def _ensure_meta(self) -> None:
        """The stamp target must exist before the first stamped apply
        (setattr requires the object)."""
        txn = Transaction()
        if not self.inner.coll_exists(META_COLL):
            txn.create_collection(META_COLL)
            txn.touch(META_COLL, META_OID)
        elif not self.inner.exists(META_COLL, META_OID):
            txn.touch(META_COLL, META_OID)
        if txn.ops:
            self.inner.queue_transaction(txn)

    # -- lifecycle ----------------------------------------------------------
    def flush(self, timeout: float = 30.0) -> bool:
        """Block until every committed record is applied (tests and
        clean shutdown; durability never depends on it)."""
        with self._drain_cv:
            return self._drain_cv.wait_for(
                lambda: not self._pending, timeout
            )

    def close(self, close_inner: bool = True) -> None:
        if self._closed:
            return
        self.flush()
        # set under _wal_cv so a committer's enqueue (which re-checks
        # _closed under the same lock) can never slip a record into
        # _wal_q after the writer thread decided to exit
        with self._wal_cv:
            self._closed = True
            self._wal_cv.notify_all()
        with self._drain_cv:
            self._drain_cv.notify_all()
        self._writer_thread.join(timeout=5.0)
        self._drain_thread.join(timeout=5.0)
        # the writer drains _wal_q before exiting; if it wedged past
        # the join timeout, fail the leftovers so no committer blocks
        # forever on synced_ev
        with self._wal_cv:
            leftovers = self._wal_q[:]
            self._wal_q.clear()
        for rec in leftovers:
            self._fail_record(rec, "wal store closed before append")
        if not self._wal.closed:
            self._wal.flush()
            if self.sync:
                os.fsync(self._wal.fileno())
            self._wal.close()
        if close_inner and hasattr(self.inner, "close"):
            self.inner.close()

    def compact(self) -> None:
        """Force a checkpoint (ignores the size threshold)."""
        self.flush()
        saved = self.checkpoint_bytes
        self.checkpoint_bytes = 0
        try:
            self._maybe_checkpoint()
        finally:
            self.checkpoint_bytes = saved

    # -- reads (deferred read-through) --------------------------------------
    def _materialize_into(
        self,
        scratch: MemStore,
        cid: str,
        oids,
        full: bool = False,
    ) -> bool:
        """Populate ``scratch`` with the effective state of ``cid``
        restricted to ``oids`` plus every object the cid's pending ops
        name: inner copies first, then the pending ops replayed in seq
        order.  ``full`` seeds every inner object name (placeholders)
        so collection-emptiness is decidable.  Returns True when the
        overlay contributed (the read counts as served-from-log).
        Caller holds _state_lock."""
        seqs = self._by_cid.get(cid, ())
        named = set(oids)
        for seq in seqs:
            for op in self._pending[seq].txn.ops:
                if op[1] != cid:
                    continue
                if op[0] == "clone":
                    named.update((op[2], op[3]))
                elif op[2] is not None:
                    named.add(op[2])
        if self.inner.coll_exists(cid):
            from .objectstore import _Object

            coll = scratch._colls.setdefault(cid, {})
            for oid in named:
                try:
                    data = self.inner.read(cid, oid)
                except StoreError:
                    continue
                o = _Object(data=bytearray(data))
                try:
                    o.xattrs = dict(self.inner.list_attrs(cid, oid))
                except StoreError:
                    pass
                try:
                    o.omap = dict(self.inner.omap_get(cid, oid))
                except StoreError:
                    pass
                coll[oid] = o
            if full:
                try:
                    for oid in self.inner.list_objects(cid):
                        if oid not in coll:
                            coll[oid] = _Object()
                except StoreError:
                    pass
        if not seqs:
            return False
        for seq in seqs:
            ops = [
                op for op in self._pending[seq].txn.ops if op[1] == cid
            ]
            st = _TxnState(scratch)
            try:
                for op in ops:
                    scratch._apply(st, op)
                scratch._commit(st)
            except StoreError:
                # a pending txn that re-validates dirty against the
                # RESTRICTED seed can only mean a materializer bug;
                # fail open to the inner state rather than wedge reads
                continue
        return True

    def _overlay_read(self, cid: str, oids, fn):
        """Run ``fn(store)`` against the effective state: the inner
        store directly when the cid has no pending records, else a
        materialized scratch."""
        if cid == META_COLL:
            # the stamp plumbing is store-internal: the whole read
            # surface presents it as absent, matching
            # list_collections/coll_exists (an empty MemStore gives
            # the exact missing-collection semantics per surface —
            # exists() -> False, read() -> -ENOENT, ...)
            return fn(MemStore())
        with self._state_lock:
            if not self._by_cid.get(cid):
                return fn(self.inner)
            scratch = MemStore()
            self._materialize_into(scratch, cid, oids)
            self.wal_perf.inc("l_os_wal_reads_from_log")
            return fn(scratch)

    def read(self, cid, oid, offset=0, length=-1) -> bytes:
        return self._overlay_read(
            cid, (oid,), lambda s: s.read(cid, oid, offset, length)
        )

    def getattr(self, cid, oid, name) -> bytes:
        return self._overlay_read(
            cid, (oid,), lambda s: s.getattr(cid, oid, name)
        )

    def stat(self, cid, oid) -> int:
        return self._overlay_read(
            cid, (oid,), lambda s: s.stat(cid, oid)
        )

    def exists(self, cid, oid) -> bool:
        return self._overlay_read(
            cid, (oid,), lambda s: s.exists(cid, oid)
        )

    def list_attrs(self, cid, oid) -> dict:
        return self._overlay_read(
            cid, (oid,), lambda s: s.list_attrs(cid, oid)
        )

    def omap_get(self, cid, oid) -> dict:
        return self._overlay_read(
            cid, (oid,), lambda s: s.omap_get(cid, oid)
        )

    def omap_get_vals(
        self, cid, oid, start_after: str = "", max_return: int = -1
    ) -> dict:
        return self._overlay_read(
            cid,
            (oid,),
            lambda s: s.omap_get_vals(cid, oid, start_after, max_return),
        )

    def list_objects(self, cid) -> list[str]:
        if cid == META_COLL:
            raise StoreError(f"no collection {cid} (-ENOENT)")
        with self._state_lock:
            seqs = self._by_cid.get(cid)
            if not seqs:
                return self.inner.list_objects(cid)
            # effective membership: inner names adjusted by the
            # pending ops' creates/removes/rmcoll
            scratch = MemStore()
            self._materialize_into(scratch, cid, (), full=True)
            self.wal_perf.inc("l_os_wal_reads_from_log")
            return scratch.list_objects(cid)

    def list_collections(self) -> list[str]:
        with self._state_lock:
            colls = set(self.inner.list_collections())
            for seqs in self._by_cid.values():
                for seq in seqs:
                    for op in self._pending[seq].txn.ops:
                        if op[0] == "mkcoll":
                            colls.add(op[1])
                        elif op[0] == "rmcoll":
                            colls.discard(op[1])
            colls.discard(META_COLL)
            return sorted(colls)

    def coll_exists(self, cid: str) -> bool:
        with self._state_lock:
            exists = self.inner.coll_exists(cid)
            for seq in self._by_cid.get(cid, ()):
                for op in self._pending[seq].txn.ops:
                    if op[0] == "mkcoll" and op[1] == cid:
                        exists = True
                    elif op[0] == "rmcoll" and op[1] == cid:
                        exists = False
            return exists and cid != META_COLL
