"""ReplicatedStore — primary-subordinate replication
(src/osd/ReplicatedBackend.cc).

One ObjectStore per replica plays the acting set.  Writes build ONE
transaction and apply it to every replica (the reference's
issue_repop → MOSDRepOp fan-out → sub_op_modify on each subordinate,
ReplicatedBackend.cc:459-546 / :975-1060); an op completes when every
replica committed, so readers ordered behind it observe all copies
identical.  Object metadata (size + whole-object crc32c data digest,
the object_info_t data_digest role) rides the same transaction as an
xattr.  Partial overwrites invalidate the digest exactly like EC
overwrites invalidate hinfo; scrub then falls back to majority
byte-comparison.

Reads serve from the primary and, on a missing/corrupt copy, fall
back to the next replica after noting the primary needs repair — the
read-path analog of the reference marking an EIO object for recovery.
``scrub`` compares every replica against the authoritative copy
(digest-verified, else majority content); ``recover_replica`` pushes
the authoritative copy onto a lost/corrupt replica (the push side of
ReplicatedBackend recovery, :2208 prep_push).

Any ObjectStore works as a replica, including RemoteStore proxies —
the multi-process tests run every subordinate behind a TCP hop.
"""

from __future__ import annotations

import json
import threading
from collections import Counter

from ..common import tracing
from ..native import ceph_crc32c
from .objectstore import MemStore, ObjectStore, StoreError, Transaction
from .pg_util import ObjectOpQueue, ScrubResult

INFO_KEY = "rinfo_key"  # object_info_t analog (size + data digest)


class ReplicatedStore:
    def __init__(self, stores: list[ObjectStore] | None = None, size: int = 3):
        self.stores = stores or [MemStore() for _ in range(size)]
        self.size = len(self.stores)
        assert self.size >= 1
        self.cid = "rep_pool"
        for store in self.stores:
            try:
                store.queue_transaction(
                    Transaction().create_collection(self.cid)
                )
            except StoreError:
                pass
        # per-object FIFO op ordering (the PG op queue collapsed)
        self._opq = ObjectOpQueue()
        # replicas flagged by read fallbacks, pending repair (the
        # read-path analog of marking an EIO object for recovery)
        self._repair_lock = threading.Lock()
        self.pending_repair: dict[str, set[int]] = {}

    # -- ordering ----------------------------------------------------------
    def _enter(self, name: str) -> int:
        return self._opq.enter(name)

    def _exit(self, name: str, ticket: int) -> None:
        self._opq.exit(name, ticket)

    def _flag_repair(self, name: str, replica: int) -> None:
        with self._repair_lock:
            self.pending_repair.setdefault(name, set()).add(replica)

    def _clear_repair(self, name: str, replica: int) -> None:
        with self._repair_lock:
            flagged = self.pending_repair.get(name)
            if flagged is not None:
                flagged.discard(replica)
                if not flagged:
                    del self.pending_repair[name]

    # -- write path --------------------------------------------------------
    def put(self, name: str, data: bytes) -> None:
        """Full-object write: one transaction per replica carrying the
        bytes and the refreshed object info (size + data digest)."""
        data = bytes(data)
        meta = {
            "size": len(data),
            "digest": ceph_crc32c(0xFFFFFFFF, data),
        }
        ticket = self._enter(name)
        try:
            # per-stage child span under the ambient daemon op (the
            # sub_op_applied stages of the replicated write)
            with tracing.span(
                "rep_put", tags={"oid": name, "size": len(data)}
            ) as sp:
                for i, store in enumerate(self.stores):
                    txn = Transaction()
                    if store.exists(self.cid, name):
                        txn.remove(self.cid, name)
                    txn.touch(self.cid, name)
                    if data:
                        txn.write(self.cid, name, 0, data)
                    txn.setattr(
                        self.cid, name, INFO_KEY,
                        json.dumps(meta).encode(),
                    )
                    store.queue_transaction(txn)
                    # register AFTER the txn (it bumped the object's
                    # generation; the entry records the post-txn gen)
                    from ..ops.residency import residency_cache

                    residency_cache().put_committed(
                        store, self.cid, name, data=data
                    )
                    sp.mark_event(f"replica_{i}_applied")
        finally:
            self._exit(name, ticket)

    def write(self, name: str, offset: int, data: bytes) -> None:
        """Partial overwrite: the same range write applied on every
        replica; the whole-object digest is invalidated (the reference
        clears data_digest on partial writes too)."""
        data = bytes(data)
        if not data:
            return
        ticket = self._enter(name)
        try:
            old = self._meta(name, default=True)
            if old["size"] or old["digest"] is not None:
                # overwriting a degraded object would auto-create
                # short zero-filled replicas that could outvote the
                # good copy in a later majority scrub — repair missing
                # or truncated replicas first (the
                # wait_for_degraded_object barrier)
                self._recover_degraded(name, old)
            meta = {
                "size": max(old["size"], offset + len(data)),
                "digest": None,
            }
            for store in self.stores:
                txn = Transaction()
                txn.write(self.cid, name, offset, data)
                txn.setattr(self.cid, name, INFO_KEY, json.dumps(meta).encode())
                store.queue_transaction(txn)
        finally:
            self._exit(name, ticket)

    def _recover_degraded(self, name: str, meta: dict) -> None:
        for i, store in enumerate(self.stores):
            try:
                if store.stat(self.cid, name) == meta["size"]:
                    continue
            except StoreError:
                pass
            self._recover_locked(name, i, meta)

    # -- read path ---------------------------------------------------------
    def _meta(self, name: str, default: bool = False) -> dict:
        for store in self.stores:
            try:
                return json.loads(store.getattr(self.cid, name, INFO_KEY))
            except StoreError:
                continue
        if default:
            return {"size": 0, "digest": None}
        raise StoreError(f"object {name} not found (-ENOENT)")

    def _read_verified(self, name: str, meta: dict, replica: int):
        try:
            raw = self.stores[replica].read(self.cid, name)
        except StoreError:
            return None
        if len(raw) != meta["size"]:
            return None
        digest = meta.get("digest")
        if digest is not None and ceph_crc32c(0xFFFFFFFF, raw) != digest:
            return None
        return raw

    def get(self, name: str) -> bytes:
        """Primary read with replica fallback on a bad copy.

        Like the reference, a read can only verify what the object
        info carries: after a partial overwrite invalidated the data
        digest, a flipped bit on the primary is invisible to reads
        (only size is checked) until scrub's majority comparison
        attributes it and recovery repairs it."""
        ticket = self._enter(name)
        try:
            with tracing.span("rep_get", tags={"oid": name}) as sp:
                meta = self._meta(name)
                for replica in range(self.size):
                    raw = self._read_verified(name, meta, replica)
                    if raw is not None:
                        return raw
                    sp.mark_event(f"replica_{replica}_fallback")
                    self._flag_repair(name, replica)
                raise StoreError(
                    f"object {name}: no verifiable replica (-EIO)"
                )
        finally:
            self._exit(name, ticket)

    # -- scrub / recovery --------------------------------------------------
    def scrub(self, name: str) -> ScrubResult:
        """Compare every replica against the authoritative copy:
        digest-verified when the digest is live, majority content
        otherwise (the reference's be_select_auth_object)."""
        ticket = self._enter(name)
        try:
            return self._scrub_locked(name)
        finally:
            self._exit(name, ticket)

    def scrub_batch(self, names) -> dict[str, ScrubResult]:
        """Device-batched deep scrub: every replica copy of every
        object checksums in ONE batched crc32c call
        (ops/scrub_kernels.batch_crc32c); digest-less objects keep
        the per-object majority-content compare.  Findings are
        identical to scrub() by construction."""
        from ..ops.residency import (
            residency_cache,
            scrub_trusted as _scrub_trusted,
        )
        from ..ops.scrub_kernels import batch_crc32c

        results: dict[str, ScrubResult] = {}
        bufs: list[bytes] = []
        where: list[tuple[str, int, int]] = []
        tickets = {n: self._enter(n) for n in dict.fromkeys(names)}
        try:
            for name in tickets:
                result = results[name] = ScrubResult()
                try:
                    meta = self._meta(name)
                except StoreError:
                    continue
                digest = meta.get("digest")
                raws: dict[int, bytes] = {}
                for i, store in enumerate(self.stores):
                    if digest is not None and _scrub_trusted(store):
                        # generation-checked residency: a hit is the
                        # payload the last committed txn landed —
                        # digest it where it already lives (no second
                        # host→device transfer); any txn since
                        # registration (including injected bit rot)
                        # misses and falls through to the disk read;
                        # persistent media is never served from cache
                        buf = residency_cache().get(
                            store, self.cid, name,
                            expect_len=meta["size"],
                        )
                        if buf is not None:
                            bufs.append(buf)
                            where.append((name, i, digest))
                            continue
                    try:
                        raws[i] = store.read(self.cid, name)
                    except StoreError:
                        result.missing.append(i)
                        continue
                    if digest is not None:
                        if len(raws[i]) != meta["size"]:
                            result.corrupt.append(i)
                        else:
                            bufs.append(raws[i])
                            where.append((name, i, digest))
                if digest is None and raws:
                    counts = Counter(raws.values())
                    auth, n = counts.most_common(1)[0]
                    if n <= len(raws) - n:
                        result.inconsistent = True
                    else:
                        result.corrupt.extend(
                            i
                            for i, raw in sorted(raws.items())
                            if raw != auth
                        )
            if bufs:
                crcs = batch_crc32c(bufs, 0xFFFFFFFF)
                for (name, i, digest), crc in zip(where, crcs):
                    if int(crc) != digest:
                        results[name].corrupt.append(i)
            for result in results.values():
                result.corrupt.sort()
        finally:
            for name, ticket in tickets.items():
                self._exit(name, ticket)
        return results

    def _scrub_locked(self, name: str) -> ScrubResult:
        meta = self._meta(name)
        result = ScrubResult()
        raws: dict[int, bytes] = {}
        for i, store in enumerate(self.stores):
            try:
                raws[i] = store.read(self.cid, name)
            except StoreError:
                result.missing.append(i)
        digest = meta.get("digest")
        if digest is not None:
            for i, raw in raws.items():
                if (
                    len(raw) != meta["size"]
                    or ceph_crc32c(0xFFFFFFFF, raw) != digest
                ):
                    result.corrupt.append(i)
        elif raws:
            # digest invalidated: majority content is authoritative
            counts = Counter(raws.values())
            auth, n = counts.most_common(1)[0]
            if n <= len(raws) - n:
                result.inconsistent = True  # no majority
            else:
                result.corrupt.extend(
                    i for i, raw in sorted(raws.items()) if raw != auth
                )
        return result

    def _authoritative(self, name: str, meta: dict) -> bytes:
        if meta.get("digest") is not None:
            for replica in range(self.size):
                raw = self._read_verified(name, meta, replica)
                if raw is not None:
                    return raw
        else:
            # dead digest: a size check cannot attribute corruption —
            # only the majority can (be_select_auth_object)
            raws = {}
            for i, store in enumerate(self.stores):
                try:
                    raws[i] = store.read(self.cid, name)
                except StoreError:
                    continue
            if raws:
                counts = Counter(raws.values())
                auth, n = counts.most_common(1)[0]
                if n > len(raws) - n:
                    return auth
        raise StoreError(
            f"object {name}: no authoritative copy (-EIO)"
        )

    def recover_replica(self, name: str, replica: int) -> int:
        """Push the authoritative copy onto one replica
        (ReplicatedBackend recovery push).  Returns bytes pushed."""
        ticket = self._enter(name)
        try:
            return self._recover_locked(name, replica, self._meta(name))
        finally:
            self._exit(name, ticket)

    def _recover_locked(self, name: str, replica: int, meta: dict) -> int:
        raw = self._authoritative(name, meta)
        txn = Transaction()
        if self.stores[replica].exists(self.cid, name):
            txn.remove(self.cid, name)
        txn.touch(self.cid, name)
        if raw:
            txn.write(self.cid, name, 0, raw)
        txn.setattr(
            self.cid, name, INFO_KEY, json.dumps(meta).encode()
        )
        self.stores[replica].queue_transaction(txn)
        self._clear_repair(name, replica)
        return len(raw)

    # -- fault injection ---------------------------------------------------
    def lose_replica(self, name: str, replica: int) -> None:
        if self.stores[replica].exists(self.cid, name):
            self.stores[replica].queue_transaction(
                Transaction().remove(self.cid, name)
            )

    def corrupt_replica(self, name: str, replica: int, offset: int = 0) -> None:
        raw = bytearray(self.stores[replica].read(self.cid, name))
        raw[offset] ^= 0xFF
        self.stores[replica].queue_transaction(
            Transaction().write(self.cid, name, 0, bytes(raw))
        )
