"""Transactional object store boundary (src/os/ObjectStore.h,
src/os/Transaction.h) with a RAM backend (src/os/memstore/).

A Transaction is an ordered op list applied atomically by
``queue_transaction`` — all or nothing, like the reference's contract
(BlueStore gets atomicity from its WAL; memstore from applying to a
per-object shadow and merging only on success).  Objects are byte
arrays with xattrs, grouped into collections.
"""

from __future__ import annotations

import copy
import threading
from dataclasses import dataclass, field

from ..common.encoding import Decoder, Encoder
from ..common import lockdep


class StoreError(Exception):
    pass


class ResidencyGens:
    """Per-(store, cid, oid) mutation generations — the invalidation
    spine of the device-payload residency cache (ops/residency.py).

    Every concrete ``queue_transaction`` notes its transaction here
    BEFORE applying, so a device-resident copy of an object registered
    at generation g can never serve a digest once ANY transaction —
    client write, recovery push, or an injected bit-rot txn — has
    named that object (its generation moved past g and the cache
    lookup misses).  Conservative by construction: a failed
    transaction still bumps, which only costs a re-upload.

    The map is bounded: on overflow the whole table clears and a
    global epoch bumps, which invalidates every outstanding residency
    entry at once (generations are (epoch, counter) pairs).
    """

    MAX_ENTRIES = 1 << 20

    def __init__(self):
        self._lock = threading.Lock()
        self._epoch = 0
        self._gens: dict[tuple, int] = {}
        self._tokens = 0
        self._tls = threading.local()

    def store_token(self, store) -> int:
        """A process-unique id for a store instance (id() can be
        recycled by the allocator after GC; this never is)."""
        tok = getattr(store, "_residency_token", None)
        if tok is None:
            with self._lock:
                tok = getattr(store, "_residency_token", None)
                if tok is None:
                    self._tokens += 1
                    tok = self._tokens
                    store._residency_token = tok
        return tok

    def note_txn(self, store, txn: "Transaction") -> None:
        tok = self.store_token(store)
        # per-THREAD record of the generations this txn assigned: the
        # writer that queued the txn registers its payload against
        # exactly these (txn_gen below), so a concurrent thread's
        # later txn — which assigns a HIGHER generation — can never
        # be absorbed into the registration (the lookup would compare
        # against the newer generation and miss).  Bounded; consumed
        # by txn_gen.
        pend = getattr(self._tls, "pending", None)
        if pend is None or len(pend) > 256:
            pend = {}
            self._tls.pending = pend
        with self._lock:
            for op in txn.ops:
                kind = op[0]
                if kind in ("mkcoll", "rmcoll"):
                    # rmcoll requires an empty collection, so every
                    # object was already bumped by its own removal
                    continue
                # clone mutates the DESTINATION object
                oid = op[3] if kind == "clone" else op[2]
                key = (tok, op[1], oid)
                self._gens[key] = self._gens.get(key, 0) + 1
                pend[key] = (self._epoch, self._gens[key])
            if len(self._gens) > self.MAX_ENTRIES:
                self._gens.clear()
                self._epoch += 1

    def txn_gen(self, store, cid: str, oid: str):
        """The generation THIS THREAD's own transaction assigned to
        (cid, oid), or None if no such txn is recorded — consumed on
        read.  Registering a payload against this (rather than the
        CURRENT generation) closes the commit-to-register window: a
        racing writer's txn lands a higher generation, so the entry
        registered here simply misses."""
        pend = getattr(self._tls, "pending", None)
        if not pend:
            return None
        return pend.pop(
            (self.store_token(store), cid, oid), None
        )

    def gen_of(self, store, cid: str, oid: str) -> tuple[int, int]:
        tok = self.store_token(store)
        with self._lock:
            return (self._epoch, self._gens.get((tok, cid, oid), 0))


# process-global: one invalidation spine, like the one JAX runtime the
# resident buffers themselves live in
residency_gens = ResidencyGens()


@dataclass
class _Object:
    data: bytearray = field(default_factory=bytearray)
    xattrs: dict[str, bytes] = field(default_factory=dict)
    # the omap: a sorted key→value namespace separate from xattrs
    # (ObjectStore.h:687 omap_get and siblings; BlueStore keeps it in
    # RocksDB — the index-style workload surface cls_log/rgw build on)
    omap: dict[str, bytes] = field(default_factory=dict)


class Transaction:
    """Ordered op list (Transaction.h's op encoding, as python ops)."""

    def __init__(self):
        self.ops: list[tuple] = []

    def create_collection(self, cid: str):
        self.ops.append(("mkcoll", cid, None))
        return self

    def touch(self, cid: str, oid: str):
        self.ops.append(("touch", cid, oid))
        return self

    def write(self, cid: str, oid: str, offset: int, data: bytes):
        self.ops.append(("write", cid, oid, offset, bytes(data)))
        return self

    def truncate(self, cid: str, oid: str, size: int):
        self.ops.append(("truncate", cid, oid, size))
        return self

    def setattr(self, cid: str, oid: str, name: str, value: bytes):
        self.ops.append(("setattr", cid, oid, name, bytes(value)))
        return self

    def rmattr(self, cid: str, oid: str, name: str):
        self.ops.append(("rmattr", cid, oid, name))
        return self

    def remove(self, cid: str, oid: str):
        self.ops.append(("remove", cid, oid))
        return self

    def omap_setkeys(self, cid: str, oid: str, kv: dict[str, bytes]):
        self.ops.append(
            ("omap_setkeys", cid, oid,
             {k: bytes(v) for k, v in kv.items()})
        )
        return self

    def omap_rmkeys(self, cid: str, oid: str, keys):
        self.ops.append(("omap_rmkeys", cid, oid, list(keys)))
        return self

    def omap_clear(self, cid: str, oid: str):
        self.ops.append(("omap_clear", cid, oid))
        return self

    def clone(self, cid: str, src_oid: str, dst_oid: str):
        """Copy src's data+xattrs+omap over dst (Transaction::clone —
        the make_writeable snap-clone primitive; each replica/shard
        clones its own LOCAL object, so no bytes ride the wire)."""
        self.ops.append(("clone", cid, src_oid, dst_oid))
        return self

    def remove_collection(self, cid: str):
        self.ops.append(("rmcoll", cid, None))
        return self


class ObjectStore:
    """The abstract boundary (ObjectStore.h): transactions in, reads
    out."""

    # advertised capacity for statfs (ObjectStore::statfs role):
    # tests shrink it to exercise full/nearfull handling; concrete
    # stores may override statfs with a cheaper accounting
    total_bytes = 1 << 30

    # device-payload residency (ops/residency.py) registers entries
    # only against stores whose mutations all flow through THIS
    # process's queue_transaction — proxies (RemoteStore) set False:
    # the backing object mutates on the remote daemon's own store,
    # which the proxy's generation counter cannot observe
    residency_local = True
    # whether DEEP SCRUB may digest a resident copy in place of a
    # media read.  Default False: on persistent media (BlockStore) a
    # byte can rot WITHOUT a transaction, and the scrub exists to
    # catch exactly that — it must read the media.  In-memory stores
    # (MemStore) set True: their read() serves the same txn-observed
    # state the generation spine tracks, so the resident copy and the
    # "media" cannot diverge out-of-band.
    residency_scrub_safe = False

    def queue_transaction(self, txn: Transaction) -> None:
        raise NotImplementedError

    def statfs(self) -> dict:
        """{total, used, avail} bytes (store_statfs_t reduced) — the
        source of the OSD's kb_used/kb_avail stat reports and the
        mon's OSD_NEARFULL/OSD_FULL checks.  Default: walk object
        sizes (callers cache; the OSD polls at ~1 Hz).  Concrete
        stores override with their own accounting (MemStore's object
        dicts, BlockStore's allocator) — the walk is the fallback
        for stores with nothing cheaper."""
        used = 0
        try:
            for cid in self.list_collections():
                for oid in self.list_objects(cid):
                    try:
                        used += self.stat(cid, oid)
                    except StoreError:
                        continue
        except StoreError:
            pass
        total = int(self.total_bytes)
        return {
            "total": total,
            "used": used,
            "avail": max(0, total - used),
        }

    def read(self, cid: str, oid: str, offset: int = 0, length: int = -1) -> bytes:
        raise NotImplementedError

    def getattr(self, cid: str, oid: str, name: str) -> bytes:
        raise NotImplementedError

    def stat(self, cid: str, oid: str) -> int:
        raise NotImplementedError

    def exists(self, cid: str, oid: str) -> bool:
        raise NotImplementedError

    def list_objects(self, cid: str) -> list[str]:
        raise NotImplementedError

    def list_collections(self) -> list[str]:
        raise NotImplementedError

    def coll_exists(self, cid: str) -> bool:
        """Collection existence (ObjectStore::collection_exists).
        Concrete stores override with an O(1) probe; the fallback
        walks the listing."""
        try:
            return cid in self.list_collections()
        except StoreError:
            return False

    def list_attrs(self, cid: str, oid: str) -> dict[str, bytes]:
        raise NotImplementedError

    def omap_get(self, cid: str, oid: str) -> dict[str, bytes]:
        """Whole omap (ObjectStore::omap_get)."""
        raise NotImplementedError

    def omap_get_vals(
        self,
        cid: str,
        oid: str,
        start_after: str = "",
        max_return: int = -1,
    ) -> dict[str, bytes]:
        """Key-ordered page after ``start_after``
        (ObjectStore::omap_get_values + iterator paging)."""
        raise NotImplementedError


class _TxnState:
    """Shadow state for one transaction: copies only the objects the
    op list names; collections created/removed are tracked as deltas."""

    __slots__ = ("store", "objects", "new_colls", "dead_colls")

    def __init__(self, store: "MemStore"):
        self.store = store
        # (cid, oid) -> _Object copy or None (= removed)
        self.objects: dict[tuple[str, str], _Object | None] = {}
        self.new_colls: set[str] = set()
        self.dead_colls: set[str] = set()

    def coll_exists(self, cid: str) -> bool:
        if cid in self.dead_colls:
            return False
        return cid in self.new_colls or cid in self.store._colls

    def get(self, cid: str, oid: str, create: bool = False):
        if not self.coll_exists(cid):
            raise StoreError(f"no collection {cid} (-ENOENT)")
        key = (cid, oid)
        if key in self.objects:
            obj = self.objects[key]
        else:
            src = self.store._colls.get(cid, {}).get(oid)
            obj = copy.deepcopy(src) if src is not None else None
            self.objects[key] = obj
        if obj is None and create:
            obj = _Object()
            self.objects[key] = obj
        return obj

    def coll_empty(self, cid: str) -> bool:
        live = set(self.store._colls.get(cid, {}))
        for (c, oid), obj in self.objects.items():
            if c != cid:
                continue
            if obj is None:
                live.discard(oid)
            else:
                live.add(oid)
        return not live


class MemStore(ObjectStore):
    """RAM ObjectStore (src/os/memstore/) with per-object
    copy-on-write transaction shadows."""

    # in-memory: read() and the resident copy cannot diverge without
    # a transaction, so scrub may digest residency (see base class)
    residency_scrub_safe = True

    def __init__(self):
        self._lock = lockdep.Mutex("memstore")
        self._colls: dict[str, dict[str, _Object]] = {}

    # -- transactions ------------------------------------------------------
    def queue_transaction(self, txn: Transaction) -> None:
        # residency invalidation BEFORE the apply: a device-resident
        # copy must stop matching the moment this txn names the object
        residency_gens.note_txn(self, txn)
        with self._lock:
            st = _TxnState(self)
            for op in txn.ops:
                self._apply(st, op)
            self._commit(st)

    def _commit(self, st: _TxnState) -> None:
        """Merge a validated shadow into live state (all ops applied
        cleanly).  Shared by the persistent store, which WAL-appends
        between validation and this merge."""
        for cid in st.dead_colls:
            self._colls.pop(cid, None)
        for cid in st.new_colls:
            self._colls.setdefault(cid, {})
        for (cid, oid), obj in st.objects.items():
            if cid in st.dead_colls or cid not in self._colls:
                continue
            if obj is None:
                self._colls[cid].pop(oid, None)
            else:
                self._colls[cid][oid] = obj

    def _apply(self, st: _TxnState, op) -> None:
        kind, cid, oid = op[0], op[1], op[2]
        if kind == "mkcoll":
            if st.coll_exists(cid):
                raise StoreError(f"collection {cid} exists (-EEXIST)")
            st.dead_colls.discard(cid)
            st.new_colls.add(cid)
            return
        if kind == "rmcoll":
            if not st.coll_exists(cid):
                raise StoreError(f"no collection {cid} (-ENOENT)")
            if not st.coll_empty(cid):
                raise StoreError(f"collection {cid} not empty (-ENOTEMPTY)")
            st.new_colls.discard(cid)
            st.dead_colls.add(cid)
            return
        if kind == "touch":
            st.get(cid, oid, create=True)
        elif kind == "write":
            _, _, _, offset, data = op
            obj = st.get(cid, oid, create=True)
            end = offset + len(data)
            if len(obj.data) < end:
                obj.data.extend(b"\0" * (end - len(obj.data)))
            obj.data[offset:end] = data
        elif kind == "truncate":
            _, _, _, size = op
            obj = st.get(cid, oid, create=True)
            if len(obj.data) > size:
                del obj.data[size:]
            else:
                obj.data.extend(b"\0" * (size - len(obj.data)))
        elif kind == "setattr":
            _, _, _, name, value = op
            obj = st.get(cid, oid)
            if obj is None:
                raise StoreError(f"no object {cid}/{oid} (-ENOENT)")
            obj.xattrs[name] = value
        elif kind == "rmattr":
            _, _, _, name = op
            obj = st.get(cid, oid)
            if obj is None or name not in obj.xattrs:
                raise StoreError(f"no attr {name} on {cid}/{oid} (-ENODATA)")
            del obj.xattrs[name]
        elif kind == "remove":
            obj = st.get(cid, oid)
            if obj is None:
                raise StoreError(f"no object {cid}/{oid} (-ENOENT)")
            st.objects[(cid, oid)] = None
        elif kind == "omap_setkeys":
            _, _, _, kv = op
            obj = st.get(cid, oid)
            if obj is None:
                raise StoreError(f"no object {cid}/{oid} (-ENOENT)")
            obj.omap.update(kv)
        elif kind == "omap_rmkeys":
            _, _, _, keys = op
            obj = st.get(cid, oid)
            if obj is None:
                raise StoreError(f"no object {cid}/{oid} (-ENOENT)")
            for k in keys:
                obj.omap.pop(k, None)
        elif kind == "omap_clear":
            obj = st.get(cid, oid)
            if obj is None:
                raise StoreError(f"no object {cid}/{oid} (-ENOENT)")
            obj.omap.clear()
        elif kind == "clone":
            _, _, src_oid, dst_oid = op
            src = st.get(cid, src_oid)
            if src is None:
                raise StoreError(
                    f"no object {cid}/{src_oid} (-ENOENT)"
                )
            dst = _Object(
                data=bytearray(src.data),
                xattrs=dict(src.xattrs),
                omap=dict(src.omap),
            )
            st.objects[(cid, dst_oid)] = dst
        else:
            raise StoreError(f"unknown op {kind}")

    # -- reads -------------------------------------------------------------
    def _get(self, cid: str, oid: str) -> _Object:
        coll = self._colls.get(cid)
        if coll is None:
            raise StoreError(f"no collection {cid} (-ENOENT)")
        obj = coll.get(oid)
        if obj is None:
            raise StoreError(f"no object {cid}/{oid} (-ENOENT)")
        return obj

    def read(self, cid, oid, offset=0, length=-1) -> bytes:
        with self._lock:
            data = self._get(cid, oid).data
            if length < 0:
                return bytes(data[offset:])
            return bytes(data[offset : offset + length])

    def getattr(self, cid, oid, name) -> bytes:
        with self._lock:
            obj = self._get(cid, oid)
            if name not in obj.xattrs:
                raise StoreError(f"no attr {name} (-ENODATA)")
            return obj.xattrs[name]

    def stat(self, cid, oid) -> int:
        with self._lock:
            return len(self._get(cid, oid).data)

    def statfs(self) -> dict:
        # one locked pass over the in-memory dicts — no per-object
        # stat() round-trips like the base-class fallback walk
        with self._lock:
            used = sum(
                len(obj.data)
                for objs in self._colls.values()
                for obj in objs.values()
            )
        total = int(self.total_bytes)
        return {
            "total": total,
            "used": used,
            "avail": max(0, total - used),
        }

    def exists(self, cid, oid) -> bool:
        with self._lock:
            return oid in self._colls.get(cid, {})

    def list_collections(self) -> list[str]:
        with self._lock:
            return sorted(self._colls)

    def coll_exists(self, cid: str) -> bool:
        with self._lock:
            return cid in self._colls

    def list_attrs(self, cid, oid) -> dict[str, bytes]:
        with self._lock:
            obj = self._colls.get(cid, {}).get(oid)
            if obj is None:
                raise StoreError(f"no object {cid}/{oid} (-ENOENT)")
            return dict(obj.xattrs)

    def list_objects(self, cid) -> list[str]:
        with self._lock:
            if cid not in self._colls:
                raise StoreError(f"no collection {cid} (-ENOENT)")
            return sorted(self._colls[cid])

    def omap_get(self, cid, oid) -> dict[str, bytes]:
        with self._lock:
            return dict(self._get(cid, oid).omap)

    def omap_get_vals(
        self, cid, oid, start_after: str = "", max_return: int = -1
    ) -> dict[str, bytes]:
        with self._lock:
            omap = self._get(cid, oid).omap
            out: dict[str, bytes] = {}
            for k in sorted(omap):
                if k <= start_after and start_after:
                    continue
                out[k] = omap[k]
                if 0 <= max_return <= len(out):
                    break
            return out


# -- transaction serialization ---------------------------------------------
# (Transaction.h's op encoding role; lives here rather than the
# messenger so the WAL (kstore) and the wire (msg) share one codec)

_TXN_OPS = {
    "mkcoll": "cs",
    "touch": "css",
    "write": "cssqb",
    "truncate": "cssq",
    "setattr": "csssb",
    "rmattr": "csss",
    "remove": "css",
    "rmcoll": "cs",
    "omap_setkeys": "cssm",
    "omap_rmkeys": "cssL",
    "omap_clear": "css",
    "clone": "csss",
}
# field codes: c=opcode string, s=str, q=int, b=bytes,
# m=str→bytes map, L=str list
# opcodes are EXPLICIT and append-only: they are a durable format
# (the KStore WAL frames transactions with them)
_OPCODES = {
    "mkcoll": 0,
    "remove": 1,
    "rmattr": 2,
    "rmcoll": 3,
    "setattr": 4,
    "touch": 5,
    "truncate": 6,
    "write": 7,
    "omap_setkeys": 8,
    "omap_rmkeys": 9,
    "omap_clear": 10,
    "clone": 11,
}
_OPNAMES = {i: name for name, i in _OPCODES.items()}


def encode_transaction(e: Encoder, txn: Transaction) -> None:
    """Serialize the ordered op list (Transaction.h op encoding role)."""
    e.u32(len(txn.ops))
    for op in txn.ops:
        name = op[0]
        spec = _TXN_OPS[name]
        e.u8(_OPCODES[name])
        for kind, val in zip(spec[1:], op[1:]):
            if kind == "s":
                e.string(val if val is not None else "")
            elif kind == "q":
                e.s64(val)
            elif kind == "b":
                e.bytes(val)
            elif kind == "m":
                e.map(
                    val,
                    lambda e2, k: e2.string(k),
                    lambda e2, v: e2.bytes(v),
                )
            elif kind == "L":
                e.list(val, lambda e2, s: e2.string(s))


def decode_transaction(d: Decoder) -> Transaction:
    txn = Transaction()
    for _ in range(d.u32()):
        name = _OPNAMES[d.u8()]
        spec = _TXN_OPS[name]
        args = []
        for kind in spec[1:]:
            if kind == "s":
                args.append(d.string())
            elif kind == "q":
                args.append(d.s64())
            elif kind == "b":
                args.append(d.bytes())
            elif kind == "m":
                args.append(
                    d.map(lambda d2: d2.string(), lambda d2: d2.bytes())
                )
            elif kind == "L":
                args.append(d.list(lambda d2: d2.string()))
        if name in ("mkcoll", "rmcoll"):
            args = args[:1]  # stored as (op, cid, None)
            txn.ops.append((name, args[0], None))
        else:
            txn.ops.append((name, *args))
    return txn
